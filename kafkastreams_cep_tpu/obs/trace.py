"""Span tracer: host-side wall spans + the device xplane trace, one API.

`SpanTracer.span("restore")` times a host block and records it into the
registry (`cep_span_seconds{span=...}` histogram + `cep_span_total`
counter), so the streams layer's poll/commit/restore sections land in the
same spine as the engine's section walls. `SpanTracer.device(log_dir)`
wraps ops.profiling.device_trace (jax.profiler xplane capture) and records
the capture wall as a span of the same name -- one call site for "time
this, and profile the device while at it".
"""
from __future__ import annotations

import contextlib
import time
from typing import Any, Iterator, Optional

from .registry import MetricsRegistry, default_registry

__all__ = ["SpanTracer"]


class SpanTracer:
    """Named wall-clock spans recorded into a MetricsRegistry."""

    def __init__(self, registry: Optional[MetricsRegistry] = None) -> None:
        self.registry = registry if registry is not None else default_registry()
        self._hist = self.registry.histogram(
            "cep_span_seconds", "Host wall per named span", labels=("span",)
        )
        self._count = self.registry.counter(
            "cep_span_total", "Completed spans", labels=("span",)
        )

    @contextlib.contextmanager
    def span(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._hist.labels(span=name).observe(time.perf_counter() - t0)
            self._count.labels(span=name).inc()

    @contextlib.contextmanager
    def device(self, log_dir: str, name: str = "device_trace") -> Iterator[Any]:
        """Capture a device xplane profile of the block AND record its wall
        as a span (the existing ops.profiling.device_trace, wrapped)."""
        from ..ops.profiling import device_trace

        with self.span(name):
            with device_trace(log_dir):
                yield
