"""IoT sensor fan-in with an idle source: the idle-timeout model.

A fleet of sensors reports into one topic family; each sensor's feed is
in order but network paths skew their arrivals, and one sensor goes dark
mid-stream (battery death, partition loss). Without idle handling, a
min-merge watermark would wait on the dark source forever and every
other sensor's records would sit in the reorder buffer until an
end-of-stream flush -- the exact failure the `IdleTimeout` generator
(time/watermarks.py) exists for: once the source has been silent past
the timeout, its watermark contribution jumps forward and the merged
clock resumes.

The query is an overheat-and-recover detector, fold-free (no exact-
replay interaction). `sensors_stream` is the seeded generator; the
record topic names the reporting sensor so per-source watermark tracking
sees the fan-in. `IDLE_SENSOR` stops emitting after `IDLE_AFTER_FRAC` of
the stream.
"""
from __future__ import annotations

import random
from typing import List

import numpy as np

from ..core.event import Event
from ..pattern.builder import QueryBuilder
from ..pattern.expressions import field
from ..pattern.pattern import Pattern, Selected

#: Per-sensor delivery delays (ms) + jitter; sensor 0 goes dark.
SENSOR_DELAYS_MS = (2, 11, 0, 6)
SENSOR_JITTER_MS = 3
REORDER_BOUND_MS = max(SENSOR_DELAYS_MS) - min(SENSOR_DELAYS_MS) + SENSOR_JITTER_MS
IDLE_SENSOR = 0
IDLE_AFTER_FRAC = 0.6

SensorEvent = dict  # {"sensor": str, "temp": float}


def sensor_event(sensor: str, temp: float) -> SensorEvent:
    return {"sensor": sensor, "temp": temp}


def sensors_pattern() -> Pattern:
    """Overheat then recover: warm -> hot spike -> cool-down, 64 ms."""
    return (
        QueryBuilder()
        .select("warm")
        .where(field("temp") > 70)
        .within(ms=64)
        .then()
        .select("hot", Selected.with_skip_til_next_match())
        .where(field("temp") > 85)
        .within(ms=64)
        .then()
        .select("cool", Selected.with_skip_til_next_match())
        .where(field("temp") < 60)
        .within(ms=64)
        .build()
    )


def sensors_schema():
    from ..ops.schema import EventSchema

    return EventSchema({"sensor": np.int32, "temp": np.float32})


def sensors_stream(
    rng: random.Random,
    n: int,
    n_sensors: int = len(SENSOR_DELAYS_MS),
    tick_ms: int = 4,
    key: str = "unit0",
) -> List[Event]:
    """Seeded fan-in feed in ARRIVAL order; sensor IDLE_SENSOR stops
    reporting after IDLE_AFTER_FRAC of the stream (idle-source case)."""
    delays = SENSOR_DELAYS_MS[:n_sensors]
    idle_from = int(n * IDLE_AFTER_FRAC)
    ts = 2_000_000
    staged = []
    for i in range(n):
        ts += rng.choice((tick_ms, tick_ms, 2 * tick_ms))
        live = [
            s for s in range(len(delays))
            if not (s == IDLE_SENSOR and i >= idle_from)
        ]
        sensor = rng.choice(live)
        # Regime-switching temperature so the three stages all fire:
        # mostly nominal, warm ramps, occasional spikes and cool-downs.
        temp = rng.choice((45.0, 55.0, 72.0, 78.0, 88.0, 92.0, 50.0))
        arrival = ts + delays[sensor] + rng.randint(0, SENSOR_JITTER_MS)
        staged.append((arrival, i, sensor, temp, ts))
    staged.sort(key=lambda t: (t[0], t[1]))
    return [
        Event(
            key,
            sensor_event(f"sensor{sensor}", temp),
            t_event,
            topic=f"sensor{sensor}",
            partition=0,
            offset=off,
        )
        for off, (_arr, _i, sensor, temp, t_event) in enumerate(staged)
    ]


def sensors_config():
    """Bench/processor config sized for lossless reorder of the fan-in."""
    from ..ops.engine import EngineConfig

    return EngineConfig(
        lanes=64, nodes=1024, matches=512, matches_per_step=16,
        nodes_per_step=32, strict_windows=True,
        reorder_capacity=256, lateness_ms=REORDER_BOUND_MS,
    )
