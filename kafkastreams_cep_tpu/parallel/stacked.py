"""Stacked multi-query driver: Q concurrent queries, one device program.

The reference attaches one processor node per query to the same topic
(reference: core/.../kstream/internals/CEPStreamImpl.java:80-93), so N
concurrent queries cost N per-record NFA walks over the same events. The
TPU-native form (SURVEY.md section 2.8 "stacked transition tables") compiles
every query into ONE table set (ops/tables.py compile_multi_query): the
event columns pack once, one begin lane per query seeds the shared lane
pool, and a single batched advance serves all queries -- the per-event cost
grows only with the union stage table and the extra live lanes, not with a
full per-query engine replication.

Matches route back to their owning query by the chain's stage-name id
(`qid_of_name_id`); per-query outputs are bit-identical to running each
query on its own engine (tests/test_stacked.py pins the equivalence).
"""
from __future__ import annotations

from typing import Any, Dict, List, Mapping, Optional, Sequence as Seq, Tuple

from ..core.event import Event
from ..core.sequence import Sequence
from ..ops.engine import EngineConfig
from ..ops.schema import EventSchema
from ..ops.tables import compile_multi_query
from .batched import BatchedDeviceNFA


class StackedQueryEngine:
    """Q queries x K keys advanced as one [T, K] device program.

    API mirrors BatchedDeviceNFA; outputs are nested per key, then per
    query name: `{key: {query_name: [Sequence, ...]}}`.
    """

    def __init__(
        self,
        named_queries: List[Tuple[str, Any]],
        keys: Seq[Any],
        schema: Optional[EventSchema] = None,
        config: Optional[EngineConfig] = None,
        mesh: Optional[Any] = None,
        engine: str = "auto",
        auto_drain: bool = True,
        drain_mode: str = "flat",
    ) -> None:
        self.query = compile_multi_query(named_queries, schema)
        self.query_names: List[str] = list(self.query.query_names or [])
        self.engine = BatchedDeviceNFA(
            self.query,
            keys=keys,
            config=config,
            mesh=mesh,
            engine=engine,
            auto_drain=auto_drain,
            drain_mode=drain_mode,
        )

    # ------------------------------------------------------------------ API
    def pack(self, events_by_key: Mapping[Any, Seq[Event]]):
        return self.engine.pack(events_by_key)

    def advance(
        self, events_by_key: Mapping[Any, Seq[Event]]
    ) -> Dict[Any, Dict[str, List[Sequence]]]:
        return self._split(self.engine.advance(events_by_key))

    def advance_packed(self, xs, decode: bool = True):
        return self._split(self.engine.advance_packed(xs, decode=decode))

    def drain(self) -> Dict[Any, Dict[str, List[Sequence]]]:
        return self._split(self.engine.drain())

    @property
    def stats(self) -> Dict[str, int]:
        return self.engine.stats

    @property
    def timings(self):
        return self.engine.timings

    def snapshot(self) -> bytes:
        return self.engine.snapshot()

    # ----------------------------------------------------------- internals
    def _split(
        self, out: Dict[Any, List[Tuple[int, Sequence]]]
    ) -> Dict[Any, Dict[str, List[Sequence]]]:
        split: Dict[Any, Dict[str, List[Sequence]]] = {}
        for key, pairs in out.items():
            per_q = split.setdefault(key, {})
            for qid, seq in pairs:
                name = (
                    self.query_names[qid]
                    if 0 <= qid < len(self.query_names)
                    else str(qid)
                )
                per_q.setdefault(name, []).append(seq)
        return split
