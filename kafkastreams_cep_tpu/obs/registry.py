"""Metrics registry: Counter/Gauge/Histogram with labels + exposition.

The one telemetry spine for all four layers (ISSUE 5): the engine
(ops/engine.py state counters via the batched driver's stats pulls), the
batched device driver (parallel/batched.py section walls, pend occupancy,
drop/overflow gauges), the key-shard layer (per-shard counter aggregation)
and the streams runtime (driver poll/commit cadence, per-query match
counts). Exposition is Prometheus 0.0.4 text (`to_prom_text`) and a
JSON-able snapshot (`snapshot`); `parse_prom_text` and
`registry_from_snapshot` close the round-trip so bench artifacts can be
validated against what the registry actually held
(scripts/check_bench_schema.py).

Design constraints:
- Pure host-side Python: nothing here may touch a device array. Device
  telemetry piggybacks on pulls the engine already performs (the fused
  [3, K] drain probe, the async ring probes, the explicit `stats` sync);
  the registry just stores what landed.
- Bounded cardinality: each metric refuses more than `max_label_sets`
  distinct label-value sets (a runaway label is an outage in disguise).
- Histograms keep both cumulative prom buckets (exposition) and a bounded
  reservoir of recent samples (host-side percentiles -- the BatchTimings
  summary path).
"""
from __future__ import annotations

import itertools
import math
import re
import threading
from typing import Any, Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "FAULT_SERIES",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "default_registry",
    "fault_series_totals",
    "next_instance_id",
    "parse_prom_text",
    "registry_from_snapshot",
]

#: The fault/robustness counter families (ISSUE 6) every health surface
#: reports together: bench.py --smoke emits them as the `faults` block
#: (all-zero in a healthy run) and scripts/check_bench_schema.py validates
#: the block against this exact key set.
FAULT_SERIES: Tuple[str, ...] = (
    "cep_faults_injected_total",
    "cep_retries_total",
    "cep_overflow_backpressure_total",
    "cep_overflow_dropped_total",
    "cep_driver_dead_letters_total",
    "cep_driver_restore_failures_total",
    "cep_checkpoint_corrupt_total",
    "cep_emit_deduped_total",
    # Event-time gate loss families (ISSUE 10, time/gate.py): records the
    # reorder stage discarded -- late beyond the watermark under
    # late_policy=drop, or reorder-buffer overflow under on_overflow=drop.
    "cep_late_dropped_total",
    "cep_reorder_overflow_dropped_total",
    # Wire-transport fault families (ISSUE 15, streams/transport.py):
    # evidence of connection damage and its recovery -- all zero on a
    # healthy loopback run, nonzero exactly when chaos (or a real
    # network) bit and the reconnect/replay machinery engaged.
    "cep_transport_retries_total",
    "cep_transport_disconnects_total",
    "cep_transport_stalls_total",
    "cep_transport_torn_frames_total",
    "cep_transport_dedup_total",
    "cep_transport_server_restarts_total",
)


def fault_series_totals(*registries: "MetricsRegistry") -> Dict[str, float]:
    """Label-summed totals of every FAULT_SERIES counter across the given
    registries (0.0 for families never registered) -- one flat dict a
    health check can assert all-zero on."""
    out: Dict[str, float] = {name: 0.0 for name in FAULT_SERIES}
    for reg in registries:
        for name in FAULT_SERIES:
            metric = reg.get(name)
            if metric is None:
                continue
            out[name] += sum(
                child.value for _lv, child in metric._sorted_children()
            )
    return out

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: Default histogram bucket upper bounds (seconds-flavored, prom-style).
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0,
)


def _escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(v: float) -> str:
    """Prom value formatting: integers render bare, +Inf as prom spells it."""
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _label_suffix(
    label_names: Tuple[str, ...], label_values: Tuple[str, ...],
    extra: Optional[Tuple[str, str]] = None,
) -> str:
    pairs = list(zip(label_names, label_values))
    if extra is not None:
        pairs.append(extra)
    if not pairs:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label_value(str(v))}"' for k, v in pairs
    )
    return "{" + inner + "}"


class _Metric:
    """One named metric family: label-set children live under it."""

    kind = "untyped"

    def __init__(
        self,
        name: str,
        help: str,
        label_names: Tuple[str, ...] = (),
        max_label_sets: int = 4096,
    ) -> None:
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in label_names:
            if not _LABEL_RE.match(ln) or ln.startswith("__"):
                raise ValueError(f"invalid label name {ln!r}")
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self.max_label_sets = max_label_sets
        self._children: Dict[Tuple[str, ...], Any] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------------- children
    def labels(self, **labels: Any) -> Any:
        """The child for one label-value set (created on first use)."""
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, "
                f"got {tuple(sorted(labels))}"
            )
        key = tuple(str(labels[ln]) for ln in self.label_names)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                if len(self._children) >= self.max_label_sets:
                    raise ValueError(
                        f"{self.name}: label cardinality exceeds "
                        f"{self.max_label_sets} distinct label sets"
                    )
                child = self._make_child()
                self._children[key] = child
            return child

    def _default_child(self) -> Any:
        """The label-less child (metrics declared without labels)."""
        if self.label_names:
            raise ValueError(
                f"{self.name} declares labels {self.label_names}; "
                "use .labels(...)"
            )
        return self.labels()

    def _make_child(self) -> Any:  # pragma: no cover - overridden
        raise NotImplementedError

    # ----------------------------------------------------------- exposition
    def _sorted_children(self) -> List[Tuple[Tuple[str, ...], Any]]:
        with self._lock:
            return sorted(self._children.items())


class _CounterChild:
    __slots__ = ("_value", "_lock")

    def __init__(self) -> None:
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value


class Counter(_Metric):
    """Monotonic counter; `inc()` on the metric hits the label-less child."""

    kind = "counter"

    def _make_child(self) -> _CounterChild:
        return _CounterChild()

    def inc(self, amount: float = 1.0) -> None:
        self._default_child().inc(amount)

    @property
    def value(self) -> float:
        return self._default_child().value


class _GaugeChild:
    __slots__ = ("_value", "_lock")

    def __init__(self) -> None:
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        return self._value


class Gauge(_Metric):
    """Free-moving gauge; `set()` on the metric hits the label-less child."""

    kind = "gauge"

    def _make_child(self) -> _GaugeChild:
        return _GaugeChild()

    def set(self, value: float) -> None:
        self._default_child().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._default_child().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default_child().dec(amount)

    @property
    def value(self) -> float:
        return self._default_child().value


class _HistogramChild:
    __slots__ = ("buckets", "bucket_counts", "sum", "count",
                 "_samples", "_reservoir", "_lock")

    def __init__(self, buckets: Tuple[float, ...], reservoir: int) -> None:
        self.buckets = buckets
        self.bucket_counts = [0] * (len(buckets) + 1)  # trailing +Inf
        self.sum = 0.0
        self.count = 0
        self._reservoir = reservoir
        self._samples: List[float] = []
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            i = 0
            for i, ub in enumerate(self.buckets):
                if v <= ub:
                    break
            else:
                i = len(self.buckets)
            self.bucket_counts[i] += 1
            self.sum += v
            self.count += 1
            self._samples.append(v)
            if len(self._samples) > self._reservoir:
                del self._samples[: len(self._samples) - self._reservoir]

    def samples(self) -> List[float]:
        with self._lock:
            return list(self._samples)

    def percentile(self, q: float) -> Optional[float]:
        """q in [0, 100] over the bounded sample reservoir (recent window);
        None before the first observation."""
        import numpy as np

        s = self.samples()
        if not s:
            return None
        return float(np.percentile(np.asarray(s), q))

    def mean(self) -> Optional[float]:
        return self.sum / self.count if self.count else None

    def cumulative_buckets(self) -> List[Tuple[float, int]]:
        """[(upper_bound, cumulative count)], ending with (+Inf, count)."""
        out: List[Tuple[float, int]] = []
        acc = 0
        for ub, c in zip(self.buckets, self.bucket_counts):
            acc += c
            out.append((ub, acc))
        out.append((math.inf, self.count))
        return out


class Histogram(_Metric):
    """Prom-style cumulative-bucket histogram + bounded sample reservoir."""

    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str,
        label_names: Tuple[str, ...] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
        reservoir: int = 1024,
        max_label_sets: int = 4096,
    ) -> None:
        super().__init__(name, help, label_names, max_label_sets)
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs:
            raise ValueError("histogram needs at least one bucket")
        self.buckets = bs
        self.reservoir = reservoir

    def _make_child(self) -> _HistogramChild:
        return _HistogramChild(self.buckets, self.reservoir)

    def observe(self, value: float) -> None:
        self._default_child().observe(value)

    def percentile(self, q: float) -> Optional[float]:
        return self._default_child().percentile(q)

    def mean(self) -> Optional[float]:
        return self._default_child().mean()

    @property
    def count(self) -> int:
        return self._default_child().count

    @property
    def sum(self) -> float:
        return self._default_child().sum


class MetricsRegistry:
    """Named metric families with get-or-create registration.

    Re-registering an existing name returns the existing family when the
    type and label names match (so a fresh BatchTimings over the same
    registry continues the same counters -- prom semantics) and raises on a
    mismatch (two subsystems fighting over one name is a bug)."""

    def __init__(self, max_label_sets: int = 4096) -> None:
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.Lock()
        self.max_label_sets = max_label_sets

    # ---------------------------------------------------------- registration
    def _get_or_create(self, cls, name: str, help: str, labels, **kwargs):
        label_names = tuple(labels)
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls) or (
                    existing.label_names != label_names
                ):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind} with labels {existing.label_names}"
                    )
                if isinstance(existing, Histogram) and "buckets" in kwargs:
                    want = tuple(sorted(float(b) for b in kwargs["buckets"]))
                    if want != existing.buckets:
                        raise ValueError(
                            f"metric {name!r} already registered with "
                            f"buckets {existing.buckets}, requested {want}"
                        )
                return existing
            metric = cls(
                name, help, label_names,
                max_label_sets=kwargs.pop("max_label_sets", self.max_label_sets),
                **kwargs,
            )
            self._metrics[name] = metric
            return metric

    def counter(
        self, name: str, help: str = "", labels: Iterable[str] = ()
    ) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(
        self, name: str, help: str = "", labels: Iterable[str] = ()
    ) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(
        self,
        name: str,
        help: str = "",
        labels: Iterable[str] = (),
        buckets: Optional[Sequence[float]] = None,
        reservoir: int = 1024,
    ) -> Histogram:
        """`buckets=None` means "don't care": get-or-create accepts the
        existing family's layout (DEFAULT_BUCKETS when creating). Explicit
        buckets must match an existing family's exactly -- two subsystems
        disagreeing on one name's layout is a bug, not a merge."""
        kwargs: Dict[str, Any] = {"reservoir": reservoir}
        if buckets is not None:
            kwargs["buckets"] = buckets
        return self._get_or_create(Histogram, name, help, labels, **kwargs)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    # ------------------------------------------------------------ exposition
    def to_prom_text(self) -> str:
        """Prometheus 0.0.4 text exposition (names and label sets sorted,
        so the output is deterministic -- golden-file testable)."""
        lines: List[str] = []
        for name in self.names():
            m = self._metrics[name]
            lines.append(f"# HELP {name} {_escape_help(m.help)}")
            lines.append(f"# TYPE {name} {m.kind}")
            for lvals, child in m._sorted_children():
                if m.kind == "histogram":
                    for ub, cum in child.cumulative_buckets():
                        suffix = _label_suffix(
                            m.label_names, lvals, ("le", _fmt(ub))
                        )
                        lines.append(f"{name}_bucket{suffix} {cum}")
                    base = _label_suffix(m.label_names, lvals)
                    lines.append(f"{name}_sum{base} {_fmt(child.sum)}")
                    lines.append(f"{name}_count{base} {child.count}")
                else:
                    suffix = _label_suffix(m.label_names, lvals)
                    lines.append(f"{name}{suffix} {_fmt(child.value)}")
        return "\n".join(lines) + ("\n" if lines else "")

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able view of every metric family and child."""
        out: Dict[str, Any] = {}
        for name in self.names():
            m = self._metrics[name]
            values: List[Dict[str, Any]] = []
            for lvals, child in m._sorted_children():
                entry: Dict[str, Any] = {
                    "labels": dict(zip(m.label_names, lvals)),
                }
                if m.kind == "histogram":
                    entry["count"] = child.count
                    entry["sum"] = child.sum
                    entry["buckets"] = {
                        _fmt(ub): cum
                        for ub, cum in child.cumulative_buckets()
                    }
                else:
                    entry["value"] = child.value
                values.append(entry)
            out[name] = {
                "type": m.kind,
                "help": m.help,
                "label_names": list(m.label_names),
                "values": values,
            }
        return out


#: Process-global default registry: the always-on spine for layers without
#: an obvious owner (host CEPProcessor, LogDriver when none is passed).
#: Engine instances default to private registries instead -- their gauges
#: are per-instance (pend occupancy, gc phase); when engines DO share a
#: registry, those gauges carry an `instance` label (next_instance_id) so
#: the series never interleave.
_DEFAULT = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return _DEFAULT


_INSTANCE_SEQ = itertools.count()


def next_instance_id() -> str:
    """Process-monotonic engine instance id for the `instance` label on
    per-instance gauges (one sequence across all engine classes, so two
    engines sharing a registry can never collide)."""
    return str(next(_INSTANCE_SEQ))


# --------------------------------------------------------------- round-trip
def registry_from_snapshot(
    snap: Mapping[str, Any], max_label_sets: Optional[int] = None
) -> MetricsRegistry:
    """Rebuild a registry holding exactly a snapshot's values (histograms
    restore buckets/sum/count; the sample reservoir is not serialized, so
    percentiles are unavailable on the rebuilt copy -- exposition only).
    `max_label_sets` overrides the rebuilt registry's cardinality bound
    (obs/merge.py uses it so a fleet-wide merge stays bounded too)."""
    reg = (
        MetricsRegistry()
        if max_label_sets is None
        else MetricsRegistry(max_label_sets=max_label_sets)
    )
    for name, fam in snap.items():
        kind = fam["type"]
        label_names = tuple(fam.get("label_names", ()))
        if kind == "histogram":
            buckets = []
            for entry in fam["values"]:
                buckets = [
                    float(b) for b in entry["buckets"] if b != "+Inf"
                ]
                break
            metric = reg.histogram(
                name, fam.get("help", ""), labels=label_names,
                buckets=buckets or DEFAULT_BUCKETS,
            )
            for entry in fam["values"]:
                child = metric.labels(**entry["labels"])
                cum_prev = 0
                per_bucket = []
                for b in sorted(
                    (float(k) for k in entry["buckets"] if k != "+Inf")
                ):
                    cum = int(entry["buckets"][_fmt(b)])
                    per_bucket.append(cum - cum_prev)
                    cum_prev = cum
                child.bucket_counts = per_bucket + [
                    int(entry["count"]) - cum_prev
                ]
                child.sum = float(entry["sum"])
                child.count = int(entry["count"])
        else:
            metric = (reg.counter if kind == "counter" else reg.gauge)(
                name, fam.get("help", ""), labels=label_names
            )
            for entry in fam["values"]:
                child = metric.labels(**entry["labels"])
                child._value = float(entry["value"])
    return reg


_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?\s+(?P<value>\S+)$"
)
_LABEL_PAIR_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_UNESCAPE_RE = re.compile(r"\\(.)")
_UNESCAPES = {"n": "\n", '"': '"', "\\": "\\"}


def _unescape_label_value(raw: str) -> str:
    """Single left-to-right pass (chained str.replace would corrupt values
    containing literal backslashes, e.g. '\\\\n' -> backslash+newline)."""
    return _UNESCAPE_RE.sub(
        lambda m: _UNESCAPES.get(m.group(1), m.group(0)), raw
    )


def _parse_value(tok: str) -> float:
    if tok == "+Inf":
        return math.inf
    if tok == "-Inf":
        return -math.inf
    return float(tok)


def parse_prom_text(text: str) -> Dict[str, Dict[Tuple[Tuple[str, str], ...], float]]:
    """Parse 0.0.4 exposition text into {sample_name: {label set: value}}.

    Histogram series appear under their exposition names (`X_bucket`,
    `X_sum`, `X_count`) -- this is the wire view, exactly what a scraper
    would ingest; scripts/check_bench_schema.py compares it against the
    JSON snapshot to prove the two expositions agree."""
    out: Dict[str, Dict[Tuple[Tuple[str, str], ...], float]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"unparseable prom line: {line!r}")
        labels: List[Tuple[str, str]] = []
        if m.group("labels"):
            for lm in _LABEL_PAIR_RE.finditer(m.group("labels")):
                labels.append(
                    (lm.group(1), _unescape_label_value(lm.group(2)))
                )
        out.setdefault(m.group("name"), {})[tuple(labels)] = _parse_value(
            m.group("value")
        )
    return out
