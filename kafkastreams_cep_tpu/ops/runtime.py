"""Host runtime around the device engine: packing, decode, pool GC.

The device kernel (ops/engine.py) runs the transition relation; this module
owns everything that stays host-side in the TPU-native design
(SURVEY.md section 7 build plan, steps 4-5):

  * event ingestion: packing a micro-batch of `Event`s into SoA columns via
    the query's EventSchema and keeping a (global index -> Event) registry
    for match materialization;
  * match construction: walking the device node pool's predecessor indices
    backwards and assembling `Sequence` objects in the oracle's order
    (the host analog of SharedVersionedBufferStoreImpl.peek,
    reference: core/.../state/internal/SharedVersionedBufferStoreImpl.java:176-201);
  * buffer GC: mark-sweep compaction of the node pool at batch boundaries,
    replacing the reference's per-traversal refcount decrements
    (the "deferred refcount deltas + periodic compaction" design,
    SURVEY.md section 7 "Refcounted buffer GC without pointers").
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

import jax.numpy as jnp

from ..core.event import Event
from ..core.sequence import MatchProvenance, Sequence, SequenceBuilder, Staged
from ..faults import injection as _flt
from ..faults.injection import CEPOverflowError, TransientFault, with_retry
from ..pattern.stages import Stages
import jax

from .engine import (
    DROP_COUNTER_KEYS,
    STATE_COUNTER_KEYS,
    WINDOW_PLANES,
    WM_NONE,
    EngineConfig,
    build_append_post,
    build_batch_fn,
    build_flush_post,
    concat_group_window,
    drain_pend,
    eval_stateless_preds,
    init_pool,
    init_state,
)
from .schema import EventSchema
from .tables import CompiledQuery, compile_query


class DeviceNFA:
    """Single-key device NFA: the accelerator counterpart of nfa/nfa.py.

    Drives the jit-compiled scan batch-by-batch while keeping the run/buffer
    state device-resident between batches; only match descriptors and (at GC
    points) the node pool cross back to the host.
    """

    #: exact-replay event-ledger bound (events per drain interval).
    REPLAY_LEDGER_MAX_EVENTS = 1 << 20

    def __init__(
        self,
        stages_or_query: Any,
        schema: Optional[EventSchema] = None,
        config: Optional[EngineConfig] = None,
        events_prune_threshold: int = 1 << 16,
        exact_replay: bool = True,
        registry: Optional[Any] = None,
    ) -> None:
        if isinstance(stages_or_query, CompiledQuery):
            self.query = stages_or_query
        else:
            assert isinstance(stages_or_query, Stages)
            self.query = compile_query(stages_or_query, schema)
        from ..obs.registry import MetricsRegistry, next_instance_id

        # Single-key engines share the batched driver's gauge naming; the
        # registry is private unless one is passed (see parallel/batched.py).
        self.metrics = registry if registry is not None else MetricsRegistry()
        self.instance_id = next_instance_id()
        self._m_state = self.metrics.gauge(
            "cep_engine_state_counter",
            "Engine state counter totals from the last stats pull "
            "(updated on the explicit stats sync, never on the advance path)",
            labels=("instance", "counter"),
        )
        self._m_dropped = self.metrics.counter(
            "cep_overflow_dropped_total",
            "Engine drop-counter deltas observed at drain boundaries "
            "(silent capacity loss made loud; see EngineConfig.on_overflow)",
            labels=("counter",),
        )
        #: Overflow-policy baselines (deltas, not totals -- restores carry
        #: historic totals that must not re-escalate).
        self._drop_base: Dict[str, int] = {}
        self.config = config if config is not None else EngineConfig()
        self._advance = build_batch_fn(self.query, self.config)
        self._append_post = jax.jit(build_append_post(self.config))
        self._flush_post = jax.jit(build_flush_post(self.query, self.config))
        # GC groups (EngineConfig.gc_group): the pend append runs every
        # advance, the mark/sweep GC only on the G-th -- node ids are
        # region-stable only through the flush's remap, so drains,
        # checkpoints and pool introspection force an early group flush.
        self.gc_group = max(int(self.config.gc_group), 1)
        self._group_ys: List[Dict[str, jnp.ndarray]] = []
        self._group_roots: List[jnp.ndarray] = []
        self.flushes = 0
        self._drain_pend = jax.jit(drain_pend)
        self.events_prune_threshold = events_prune_threshold
        self.state = init_state(self.query, self.config)
        self.pool = init_pool(self.query, self.config)
        self._events: Dict[int, Event] = {}
        self._next_gidx = 0
        self._ts_base: Optional[int] = None
        self._batches = 0
        #: Exact-replay (ops/replay.py): on a seq_collisions increment the
        #: interval since the last drain replays through the host oracle,
        #: restoring the reference's per-run fold semantics. Only active
        #: for queries that can diverge (folds present).
        from .replay import supports_replay

        self.exact_replay = exact_replay and supports_replay(self.query)
        self.replays = 0
        # None when disarmed so no dead device generation stays referenced.
        self._snap = (self.state, self.pool) if self.exact_replay else None
        self._interval_events: List[Event] = []
        self._interval_overflow = False
        self._interval_start_gidx = 0
        self._collision_base = 0

    # ------------------------------------------------------------------ API
    @property
    def runs(self) -> int:
        """Run counter -- parity with NFA.runs for conformance asserts."""
        return int(self.state["runs"])

    @property
    def n_live(self) -> int:
        """Live lane count -- parity with len(NFA.computation_stages)."""
        return int(np.sum(np.asarray(self.state["active"])))

    @property
    def stats(self) -> Dict[str, int]:
        out = {k: int(self.state[k]) for k in STATE_COUNTER_KEYS}
        # Registry gauges piggyback on this explicit pull (the advance path
        # never syncs for telemetry).
        for k, v in out.items():
            self._m_state.labels(instance=self.instance_id, counter=k).set(v)
        return out

    def match_pattern(self, event: Event) -> List[Sequence]:
        """Single-event convenience API mirroring NFA.match_pattern."""
        return self.advance([event])

    def live_runs(self) -> List[Dict[str, Any]]:
        """Queue snapshot in order: (stage name, run id, last event, version).

        The device analog of inspecting NFA.computation_stages in tests
        (reference: NFATest.assertNFA, NFATest.java:836-840).
        """
        self._flush_group()  # lane nodes may point into the group window
        active = np.asarray(self.state["active"])
        src = np.asarray(self.state["src"])
        seq = np.asarray(self.state["seq"])
        node = np.asarray(self.state["node"])
        ver = np.asarray(self.state["ver"])
        vlen = np.asarray(self.state["vlen"])
        node_event = np.asarray(self.pool["node_event"])
        out = []
        for i in range(len(active)):
            if not active[i]:
                continue
            name = self.query.name_of_id[int(self.query.name_id[src[i]])]
            last = None
            if node[i] >= 0:
                last = self._events.get(int(node_event[node[i]]))
            out.append(
                dict(
                    stage=name,
                    sequence=int(seq[i]),
                    last_event=last,
                    version=".".join(str(d) for d in ver[i][: vlen[i]]),
                )
            )
        return out

    def advance(
        self,
        events: List[Event],
        decode: bool = True,
        watermark_ms: Optional[Any] = None,
    ) -> List[Sequence]:
        """Process a micro-batch; returns completed matches in oracle order.

        decode=False defers match materialization (no device sync): matches
        accumulate in the pool's pending buffer -- GC roots, so their chains
        stay alive and id-consistent -- until `drain()`.

        `watermark_ms` (ISSUE 10) threads the event-time watermark into the
        jitted step so window expiry (`n_expired`) sweeps off event time
        instead of arrival order: a scalar (absolute ms, applied to every
        step) or a per-event sequence of absolute-ms values (None entries
        fall back to the event's own timestamp). Omitted, expiry is
        bitwise-identical to the historical arrival-order behavior.
        """
        if not events:
            return []
        xs = self._pack(events, watermark_ms=watermark_ms)
        if _flt.ACTIVE is None:
            self.state, ys = self._advance(self.state, xs)
        else:
            # `engine.device_step` transient site (see parallel/batched.py:
            # the dispatch is functional, so a bounded retry is exact).
            def _step():
                _flt.ACTIVE.fire("engine.device_step")
                return self._advance(self.state, xs)

            self.state, ys = with_retry(
                _step, site="engine.device_step",
                retry_on=(TransientFault,), registry=self.metrics,
            )
        self.state, self.pool, page_roots = self._append_post(
            self.state, self.pool, ys
        )
        self._group_ys.append({k: ys[k] for k in WINDOW_PLANES})
        self._group_roots.append(page_roots)
        if len(self._group_ys) >= self.gc_group:
            self._flush_group()
        self._batches += 1
        if self.exact_replay:
            if (
                len(self._interval_events) + len(events)
                > self.REPLAY_LEDGER_MAX_EVENTS
            ):
                if not self._interval_overflow:
                    import warnings

                    warnings.warn(
                        "exact-replay event ledger exceeded "
                        f"{self.REPLAY_LEDGER_MAX_EVENTS} events without a "
                        "drain; this interval degrades to collision "
                        "detection only",
                        RuntimeWarning,
                    )
                self._interval_overflow = True
                self._interval_events = []
                if self.config.on_overflow == "raise":
                    raise CEPOverflowError(
                        "exact-replay event ledger overflowed "
                        f"({self.REPLAY_LEDGER_MAX_EVENTS} events without a "
                        "drain); drain() more often or raise the bound"
                    )
            else:
                self._interval_events.extend(events)
        if not decode:
            return []
        return self.drain()

    def _flush_group(self) -> None:
        """Fold the accumulated group window back into the node region
        (one mark/sweep over the concatenated per-advance node planes).
        Runs on the G-th advance or early -- before anything that reads
        pool node planes (drain, live_runs, snapshot)."""
        if not self._group_ys:
            return
        ys_cat, roots_cat = concat_group_window(
            self._group_ys, self._group_roots
        )
        self._group_ys = []
        self._group_roots = []
        self.state, self.pool = self._flush_post(
            self.state, self.pool, ys_cat, roots_cat
        )
        self.flushes += 1

    def drain(self) -> List[Sequence]:
        """Decode and clear all pending matches (a device sync point).
        Forces an early group flush first (pending matches may reference
        window node ids the pool planes don't cover mid-group)."""
        self._flush_group()
        matches = self._decode_matches()
        if self.exact_replay:
            matches = self._replay_boundary(matches)
        self._prune_events()
        self._check_drop_counters(drained=matches)
        return matches

    def _check_drop_counters(self, drained: Optional[List] = None) -> None:
        """Drain-boundary overflow-policy check (EngineConfig.on_overflow):
        single-key state counters are scalars, so the pull is free at this
        sync point. Deltas land in `cep_overflow_dropped_total{counter}`;
        "raise"/"block" escalate (see parallel/batched.py for the batched
        rationale)."""
        overflow = {}
        for name in DROP_COUNTER_KEYS:
            v = int(self.state[name])
            delta = v - self._drop_base.get(name, 0)
            if delta > 0:
                overflow[name] = delta
                self._drop_base[name] = v
                self._m_dropped.labels(counter=name).inc(delta)
        if overflow and self.config.on_overflow in ("raise", "block"):
            # Drained matches ride the exception -- see parallel/batched.py.
            exc = CEPOverflowError(
                f"engine capacity overflow since the last drain: {overflow} "
                f"(policy {self.config.on_overflow!r}; size EngineConfig "
                "lanes/nodes/matches)"
            )
            exc.matches = drained if drained is not None else []
            raise exc

    def _replay_boundary(self, matches: List[Sequence]) -> List[Sequence]:
        """Drain-boundary replay hook: if any fold-divergence event fired
        since the last boundary, substitute the host oracle's matches for
        the whole interval and resync the device state from the oracle
        (ops/replay.py). Otherwise just roll the snapshot forward."""
        cur = int(self.state["seq_collisions"])
        if cur > self._collision_base and self._interval_overflow:
            import warnings

            warnings.warn(
                "fold-divergence detected but the replay ledger overflowed "
                "this interval; matches are engine-computed for it",
                RuntimeWarning,
            )
        if (
            cur > self._collision_base
            and self._interval_events
            and not self._interval_overflow
        ):
            matches = self._replay_interval(matches)
        self._collision_base = int(self.state["seq_collisions"])
        self._snap = (self.state, self.pool)
        self._interval_events = []
        self._interval_overflow = False
        self._interval_start_gidx = self._next_gidx
        return matches

    def _replay_interval(
        self, engine_matches: List[Sequence]
    ) -> List[Sequence]:
        import warnings

        from .replay import device_to_oracle, oracle_to_device

        self.replays += 1
        snap_state = {k: np.asarray(v) for k, v in self._snap[0].items()}
        snap_pool = {k: np.asarray(v) for k, v in self._snap[1].items()}
        key = self._interval_events[0].key
        ts_base = self._ts_base if self._ts_base is not None else 0
        try:
            oracle, ev_gidx = device_to_oracle(
                self.query, self.config, snap_state, snap_pool, self._events,
                ts_base, key,
            )
            matches: List[Sequence] = []
            for i, e in enumerate(self._interval_events):
                ev_gidx[e] = self._interval_start_gidx + i
                matches.extend(oracle.match_pattern(e))
        except KeyError as exc:
            # An event fell out of the registry (or a node was GC-dropped
            # under region overflow) -- in the snapshot rebuild OR in the
            # oracle feed loop: degrade to detection-only for this interval
            # rather than crashing the drain (the batched driver does the
            # same, parallel/batched.py). The degraded interval's matches
            # are engine-computed, so fold values may diverge from the
            # oracle for it (the same caveat as the seq_collisions
            # warning).
            warnings.warn(
                f"exact-replay skipped: event {exc} missing from the "
                "registry (snapshot or oracle feed); this interval's "
                "matches are engine-computed and fold values may diverge "
                "from the oracle for it"
            )
            return engine_matches
        counters = {
            k: np.asarray(self.state[k])
            for k in STATE_COUNTER_KEYS
        }
        try:
            new_state, new_pool = oracle_to_device(
                self.query, self.config, oracle, key, ev_gidx, ts_base,
                counters,
            )
            self.state = {k: jnp.asarray(v) for k, v in new_state.items()}
            self.pool = {k: jnp.asarray(v) for k, v in new_pool.items()}
        except (ValueError, KeyError) as exc:
            warnings.warn(
                f"exact-replay resync failed ({exc}); device state kept -- "
                "this interval's matches are oracle-exact but later "
                "intervals fall back to collision detection only"
            )
        return matches

    # ------------------------------------------------------------ internals
    def _pack(
        self, events: List[Event], watermark_ms: Optional[Any] = None
    ) -> Dict[str, jnp.ndarray]:
        if self._ts_base is None:
            self._ts_base = int(events[0].timestamp)
        schema = self.query.schema
        cols = schema.pack(
            [e.value for e in events],
            [e.timestamp for e in events],
            topics=[e.topic for e in events],
            ts_base=self._ts_base,
        )
        T = len(events)
        gidx = np.arange(self._next_gidx, self._next_gidx + T, dtype=np.int32)
        for i, e in enumerate(events):
            self._events[int(gidx[i])] = e
        self._next_gidx += T
        xs = {k: jnp.asarray(v) for k, v in cols.items()}
        xs["spred"] = eval_stateless_preds(self.query, cols)
        xs["gidx"] = jnp.asarray(gidx)
        xs["valid"] = jnp.ones(T, bool)
        if watermark_ms is not None:
            xs["wm"] = jnp.asarray(
                rebase_watermarks(watermark_ms, T, self._ts_base)
            )
        return xs

    def _decode_matches(self) -> List[Sequence]:
        count = int(self.pool["pend_count"])
        if count == 0:
            if int(self.pool["pend_pos"]) > 0:
                self.pool = self._drain_pend(self.pool)  # reclaim hole pages
            return []
        # pend_pos is the dense per-key occupancy count: valid ids in
        # [0, pend_pos) are in emission order, and the only -1 holes are
        # entries a GC nulled under region overflow (dead chains).
        pos = int(self.pool["pend_pos"])
        pend = np.asarray(self.pool["pend"])[:pos]
        pend = pend[pend >= 0]
        node_event = np.asarray(self.pool["node_event"])
        node_name = np.asarray(self.pool["node_name"])
        node_pred = np.asarray(self.pool["node_pred"])

        native = self._native_decoder()
        if native is not None:
            out = native.decode_matches(
                np.asarray([len(pend)], np.int32),
                pend[None, :],
                node_event[None, :],
                node_name[None, :],
                node_pred[None, :],
                self.query.name_of_id,
                self._events,
                Staged,
                Sequence,
            )[0]
        else:
            chains = decode_chains(pend, node_name, node_event, node_pred)
            # Empty chains = pend entries whose nodes were GC-dropped under
            # region overflow (node_drops counts them).
            out = [
                materialize_sequence(chain, self.query.name_of_id, self._events)
                for chain in chains
                if chain
            ]
        self.pool = self._drain_pend(self.pool)
        return out

    def _native_decoder(self):
        """The C match decoder module, or None (cached; test-overridable)."""
        from ..native import cached_decoder

        return cached_decoder(self)

    # --------------------------------------------------------- checkpointing
    def snapshot(self) -> bytes:
        """Serialize the full engine state to bytes (device arrays pulled as
        raw typed frames + the host event registry). The device analog of
        the reference's per-record NFAStates externalization
        (CEPProcessor.java:144-147), taken at batch granularity. Forces an
        early group flush first: the accumulated node window lives outside
        the serialized pool (gc_phase is always 0 in a snapshot)."""
        self._flush_group()
        from ..state.serde import (
            _Writer,
            MAGIC,
            encode_array_tree,
            encode_event_registry,
            seal_frame,
        )

        w = _Writer()
        w._buf.write(MAGIC)
        w.blob(encode_array_tree({k: np.asarray(v) for k, v in self.state.items()}))
        w.blob(encode_array_tree({k: np.asarray(v) for k, v in self.pool.items()}))
        w.blob(encode_event_registry(self._events))
        w.i64(self._next_gidx)
        w.i64(self._ts_base if self._ts_base is not None else -1)
        w.i64(self._batches)
        return seal_frame(w.getvalue())

    @classmethod
    def restore(
        cls,
        stages_or_query: Any,
        data: bytes,
        schema: Optional[EventSchema] = None,
        config: Optional[EngineConfig] = None,
    ) -> "DeviceNFA":
        """Rebuild a DeviceNFA from `snapshot()` bytes in a fresh object
        graph (query recompiled by the caller, stages never serialized --
        the ComputationStageSerde.java:56-66 contract)."""
        from ..state.serde import (
            _Reader,
            decode_array_tree,
            decode_event_registry,
            open_frame,
            read_magic,
            upgrade_checkpoint_trees,
        )

        dev = cls(stages_or_query, schema=schema, config=config)
        r = _Reader(open_frame(data))
        read_magic(r)
        tree = decode_array_tree(r.blob())
        pool_tree = decode_array_tree(r.blob())
        upgrade_checkpoint_trees(tree, pool_tree)
        dev.state = {k: jnp.asarray(v) for k, v in tree.items()}
        dev.pool = {k: jnp.asarray(v) for k, v in pool_tree.items()}
        dev._events = decode_event_registry(r.blob())
        dev._next_gidx = r.i64()
        ts_base = r.i64()
        dev._ts_base = None if ts_base < 0 else ts_base
        dev._batches = r.i64()
        if dev.exact_replay:
            dev._snap = (dev.state, dev.pool)
            dev._interval_start_gidx = dev._next_gidx
            dev._collision_base = int(dev.state["seq_collisions"])
        dev._drop_base = {k: int(dev.state[k]) for k in DROP_COUNTER_KEYS}
        return dev

    def _prune_events(self) -> None:
        """Bound the host event registry: keep only pool-referenced events.

        Runs after the post-advance GC compacted the pool, so the single
        `node_event` pull is the only host transfer -- and only once the
        registry outgrows its threshold (a pull is a sync point).
        """
        if len(self._events) <= self.events_prune_threshold:
            return
        live = np.asarray(self.pool["node_event"])
        live_gidx = set(int(g) for g in live[live >= 0])
        self._events = {g: e for g, e in self._events.items() if g in live_gidx}


def rebase_watermarks(
    watermark_ms: Any, n: int, ts_base: int
) -> np.ndarray:
    """Absolute-ms watermark(s) -> rebased i32 "wm" column of shape [n].

    Accepts a scalar (broadcast to every step) or a per-event sequence;
    None entries (and a None scalar) fall back to WM_NONE, which the step's
    max(ts, wm) clock reduces to the event's own timestamp. Values clamp
    into i32 so a huge watermark (end-of-stream flush) compares identically
    to "expire everything expirable"."""
    lo, hi = int(WM_NONE), 2**31 - 1
    if np.isscalar(watermark_ms) or watermark_ms is None:
        seq = [watermark_ms] * n
    else:
        seq = list(watermark_ms)
        if len(seq) != n:
            raise ValueError(
                f"watermark sequence length {len(seq)} != batch length {n}"
            )
    out = np.empty(n, np.int32)
    for i, w in enumerate(seq):
        if w is None:
            out[i] = WM_NONE
        else:
            out[i] = int(min(max(int(w) - ts_base, lo), hi))
    return out


def decode_chains(
    start_nodes: np.ndarray,
    node_name: np.ndarray,
    node_event: np.ndarray,
    node_pred: np.ndarray,
) -> List[List[Tuple[int, int]]]:
    """Vectorized predecessor walk: all match chains at once.

    Replaces the per-match, per-node Python walk with one NumPy gather per
    chain *depth* level (the host analog of the reference's peek loop,
    SharedVersionedBufferStoreImpl.java:176-201). Returns, per start node,
    the chain as (stage-name-id, event-gidx) pairs oldest-first.
    """
    n = len(start_nodes)
    cur = start_nodes.astype(np.int64)
    midx = np.arange(n)
    levels: List[Tuple[np.ndarray, np.ndarray, np.ndarray]] = []
    while True:
        live = cur >= 0
        if not live.any():
            break
        li = cur[live]
        levels.append((midx[live], node_name[li], node_event[li]))
        nxt = np.full_like(cur, -1)
        nxt[live] = node_pred[li]
        cur = nxt

    chains: List[List[Tuple[int, int]]] = [[] for _ in range(n)]
    for m_ids, names_l, gidxs in reversed(levels):
        for m, nm, g in zip(m_ids.tolist(), names_l.tolist(), gidxs.tolist()):
            if g < 0:
                # Dropped put (node-pool overflow routed to the trash slot):
                # the chain is truncated; node_drops already counts it.
                continue
            chains[m].append((nm, g))
    return chains


def sequence_provenance(
    seq: Sequence, query: str = "q", trigger: str = "drain"
) -> MatchProvenance:
    """Derive one match's lineage from its materialized Sequence.

    The Sequence IS the pulled chain table made host-real (stage groups in
    traversal order, events oldest-first within a group), so every field
    here is a pure host-side read -- no device pull, no extra sync:
    stage path and Dewey-style version-path depth from the group walk
    (DeweyVersion.add_stage appends one digit per stage entered), chain
    depth from the hop count, and the window span from the first/last
    events' source-log coordinates. Offsets follow the Event contract's
    ((topic, partition, offset) / timestamp fallback) order; the TIMESTAMP
    span is taken over raw event time instead (ISSUE 10): behind a reorder
    stage an out-of-order source's log order no longer tracks event time,
    and the provenance window must report the event-time span the match
    actually covered, not the arrival span."""
    events = [e for staged in seq.matched for e in staged.events]
    first = min(events) if events else None
    last = max(events) if events else None
    ts = [e.timestamp for e in events]
    return MatchProvenance(
        query=query,
        trigger=trigger,
        stage_path=tuple(s.stage for s in seq.matched),
        chain_depth=len(events),
        branch_depth=len(seq.matched),
        first_offset=first.offset if first is not None else -1,
        last_offset=last.offset if last is not None else -1,
        first_timestamp=min(ts) if ts else -1,
        last_timestamp=max(ts) if ts else -1,
    )


def materialize_sequence(
    chain: List[Tuple[int, int]],
    name_of_id: List[str],
    events: Dict[int, Event],
) -> Sequence:
    """Build a host `Sequence` from an oldest-first (name-id, gidx) chain.

    Equivalent to SequenceBuilder().add(...) per node, but grouped first so
    each stage sorts once instead of per-add -- decode materializes every
    match of a drain, so this is the drain's hottest Python loop."""
    # Group by the stage NAME string, not name_id: ids are keyed by
    # (name, type), so e.g. a begin-position one_or_more compiles to a
    # BEGIN-typed and a NORMAL-typed stage sharing one name whose nodes
    # must land in one group (as SequenceBuilder merges them).
    groups: Dict[str, List[Event]] = {}
    order: List[str] = []
    for name_id, gidx in chain:
        name = name_of_id[name_id]
        lst = groups.get(name)
        if lst is None:
            lst = groups[name] = []
            order.append(name)
        lst.append(events[gidx])
    matched: List[Staged] = []
    for name in order:
        evs = groups[name]
        # Staged's sorted(set(...)) normalization costs Python-level
        # __hash__/__lt__ per element -- the decode hot spot. It can be
        # skipped exactly when the group is provably already normalized
        # under the Event contract (identity AND order are offset-based
        # within one (topic, partition)): all events share one
        # (topic, partition) and offsets strictly increase.
        first = evs[0]
        topic = first.topic
        partition = first.partition
        prev = None
        normalized = True
        for e in evs:
            if (
                e.topic != topic
                or e.partition != partition
                or (prev is not None and e.offset <= prev)
            ):
                normalized = False
                break
            prev = e.offset
        if normalized:
            st = Staged.__new__(Staged)
            st.stage = name
            st._events = evs
            matched.append(st)
        else:
            matched.append(Staged(name, evs))
    return Sequence(matched)
