"""Chain-flattened drain: differential, D2H accounting, overlapped decode.

The default drain path (parallel/batched.py, drain_mode="flat") walks every
pending match's predecessor chain ON DEVICE (ops/engine.build_chain_flatten)
and pulls one dense [3, Mb, Cb, K] table sized by true match volume; the
pool-pull drain (drain_mode="pool") remains the semantic reference. This
module pins:

  * flat == pool bitwise (same matches, same order, same fold values) on
    branching/fold/window patterns and random streams, through BOTH decode
    paths (native C and the Python reference), including capacity-pressure
    and exact-replay-boundary cases;
  * drain D2H volume scales with match count, not node-pool capacity (the
    acceptance contract: no node-pool plane pulls on the flat drain path);
  * the overlapped (worker-thread) decode never drops or reorders matches
    across drain boundaries;
  * the region-pressure guard gates on the probed TRUE cursor and backs
    off after a no-op drain (ADVICE r5 medium: no no-op-sync loop on
    match-free streams).
"""
import random

import pytest

import jax

from kafkastreams_cep_tpu import Event, QueryBuilder, Selected, compile_pattern
from kafkastreams_cep_tpu.ops.engine import EngineConfig
from kafkastreams_cep_tpu.parallel import BatchedDeviceNFA
from kafkastreams_cep_tpu.pattern.expressions import agg, value

TS = 1_000_000
CONFIG = EngineConfig(lanes=64, nodes=512, matches=128)


def branching_pattern():
    """skip-till-any + one_or_more + fold: variable-depth chains, branching,
    shared chain prefixes -- the shapes the flatten walk must reproduce."""
    return (
        QueryBuilder()
        .select("first")
        .where(value() == "A")
        .fold("cnt", agg("cnt", default=0) + 1)
        .then()
        .select("second", Selected.with_skip_til_any_match())
        .one_or_more()
        .where(value() == "C")
        .then()
        .select("latest")
        .where(value() == "D")
        .build()
    )


def abc_pattern():
    return (
        QueryBuilder()
        .select("a").where(value() == "A")
        .then().select("b").where(value() == "B")
        .then().select("c").where(value() == "C")
        .build()
    )


def letter_stream(seed, n, key=None):
    rng = random.Random(seed)
    return [
        Event(key or f"k{seed}", rng.choice("ABCD"), TS + i, "t", 0, i)
        for i in range(n)
    ]


def drive(pattern, streams, splits, config, drain_mode, native=True):
    """Advance ragged batches, decoding each; returns per-key match lists
    and the engine (for stats / byte accounting)."""
    keys = list(streams)
    bat = BatchedDeviceNFA(
        compile_pattern(pattern), keys=keys, config=config,
        drain_mode=drain_mode,
    )
    if not native:
        bat._native_dec = None  # force the Python reference decode
    got = {k: [] for k in keys}
    for lo, hi in splits:
        chunk = {k: evs[lo:hi] for k, evs in streams.items() if evs[lo:hi]}
        if not chunk:
            continue
        for k, seqs in bat.advance(chunk).items():
            got[k].extend(seqs)
    return got, bat


@pytest.mark.parametrize("seed", range(5))
def test_flat_equals_pool(seed):
    """flat == pool across random streams, mid-stream drains included --
    same matches, same order, same fold values (Sequence equality covers
    the full materialized content)."""
    pattern = branching_pattern()
    streams = {
        f"k{i}": letter_stream(1000 * seed + i, 14 + 3 * i) for i in range(4)
    }
    splits = [(0, 5), (5, 9), (9, 100)]
    want, bp = drive(pattern, streams, splits, CONFIG, "pool")
    got, bf = drive(pattern, streams, splits, CONFIG, "flat")
    assert got == want
    assert bf.stats == bp.stats
    # The flat path pulled real data and accounted for it.
    if sum(len(v) for v in want.values()):
        assert bf.drain_pull_bytes > 0


@pytest.mark.parametrize("seed", [1, 3])
def test_flat_equals_pool_python_decode(seed):
    """Same contract through the Python reference decoders (the native C
    module disabled on both sides)."""
    pattern = branching_pattern()
    streams = {
        f"k{i}": letter_stream(2000 * seed + i, 14 + 3 * i) for i in range(3)
    }
    splits = [(0, 6), (6, 100)]
    want, _ = drive(pattern, streams, splits, CONFIG, "pool", native=False)
    got, _ = drive(pattern, streams, splits, CONFIG, "flat", native=False)
    assert got == want


def test_flat_native_equals_python_decode():
    """The C flat decoder and the Python flat reference agree bit for bit
    (both decode the same flattened table)."""
    pattern = branching_pattern()
    streams = {f"k{i}": letter_stream(77 + i, 16) for i in range(3)}
    splits = [(0, 7), (7, 100)]
    want, _ = drive(pattern, streams, splits, CONFIG, "flat", native=False)
    got, _ = drive(pattern, streams, splits, CONFIG, "flat", native=True)
    assert got == want


def test_flat_equals_pool_capacity_pressure():
    """Under node-region overflow (node_drops > 0) both paths must degrade
    IDENTICALLY: dead chains decode to nothing on each, drop counters
    match, and surviving matches agree."""
    pattern = branching_pattern()
    config = EngineConfig(lanes=64, nodes=48, matches=128, matches_per_step=16)
    streams = {f"k{i}": letter_stream(500 + i, 40) for i in range(2)}
    splits = [(0, 14), (14, 27), (27, 100)]
    want, bp = drive(pattern, streams, splits, config, "pool")
    got, bf = drive(pattern, streams, splits, config, "flat")
    assert bf.stats == bp.stats
    assert got == want


def test_flat_equals_pool_replay_boundary():
    """Exact-replay boundaries (fold-divergence recovery, ops/replay.py)
    ride the drain path: on a collision-prone pattern the flat and pool
    engines must still agree exactly -- and with the host oracle."""
    from kafkastreams_cep_tpu import NFA, AggregatesStore, SharedVersionedBuffer

    rng = random.Random(50_072)
    pattern = (
        QueryBuilder()
        .select("s0").where(value() == "A")
        .then().select("s1", Selected.with_skip_til_any_match())
        .one_or_more().where(value() == "B")
        .fold("cnt", agg("cnt", default=0) + 1)
        .then().select("s2").where(
            (value() == "C") & (agg("cnt", default=0) <= 2)
        )
        .build()
    )
    keys = ["kA", "kB"]
    streams = {}
    for key in keys:
        ts = 1000
        events = []
        for i in range(20):
            ts += rng.choice([0, 1, 1, 2])
            events.append(Event(key, rng.choice("ABCD"), ts, "t", 0, i))
        streams[key] = events

    stages = compile_pattern(pattern)
    expected = {}
    for key in keys:
        oracle = NFA.build(stages, AggregatesStore(), SharedVersionedBuffer())
        acc = []
        for e in streams[key]:
            acc.extend(oracle.match_pattern(e))
        expected[key] = acc

    config = EngineConfig(lanes=256, nodes=2048, matches=1024,
                          matches_per_step=128)
    splits = [(0, 5), (5, 10), (10, 15), (15, 100)]
    want, _ = drive(pattern, streams, splits, config, "pool")
    got, _ = drive(pattern, streams, splits, config, "flat")
    assert got == want
    for k in keys:
        assert got[k] == expected[k], f"key {k} diverged from the oracle"


def test_drain_bytes_scale_with_matches_not_nodes():
    """The acceptance contract: flat-drain D2H volume is the flattened
    table + the [3, K] probe ONLY -- growing the node pool must not change
    the pulled bytes, while more matches must."""
    pattern = abc_pattern()
    splits = [(0, 100)]

    def bytes_for(nodes, n_events):
        streams = {
            k: [
                Event(k, "ABC"[i % 3], TS + i, "t", 0, i)
                for i in range(n_events)
            ]
            for k in ("k0", "k1")
        }
        config = EngineConfig(lanes=8, nodes=nodes, matches=256,
                              matches_per_step=4)
        got, bat = drive(pattern, streams, splits, config, "flat")
        assert sum(len(v) for v in got.values()) == 2 * (n_events // 3)
        return bat.last_drain_bytes

    small = bytes_for(nodes=256, n_events=12)
    large_pool = bytes_for(nodes=2048, n_events=12)
    assert small == large_pool > 0  # 8x the node capacity, same pull
    more_matches = bytes_for(nodes=256, n_events=48)
    assert more_matches > small  # volume tracks match count


def test_overlapped_decode_never_drops_or_reorders():
    """Auto-drains hand their pulls to the decode worker mid-stream; the
    final drain joins. Nothing may be lost, duplicated, or reordered
    relative to an engine whose ring is big enough to never auto-drain."""
    pattern = abc_pattern()
    keys = ["k0", "k1"]
    n_batches, T = 30, 6
    streams = {k: [
        Event(k, "ABC"[i % 3], TS + i, "t", 0, i)
        for i in range(T * n_batches)
    ] for k in keys}

    def run(matches_ring):
        config = EngineConfig(lanes=8, nodes=256, matches=matches_ring,
                              matches_per_step=4)
        bat = BatchedDeviceNFA(
            compile_pattern(pattern), keys=keys, config=config,
        )
        for b in range(n_batches):
            bat.advance_packed(
                bat.pack({k: s[b * T:(b + 1) * T] for k, s in streams.items()}),
                decode=False,
            )
        out = bat.drain()
        return out, bat

    out_small, bat_small = run(48)    # forces mid-stream threaded drains
    out_big, _ = run(4096)            # single terminal drain
    assert bat_small.stats["match_drops"] == 0
    assert out_small == out_big
    expect = T * n_batches // 3
    assert {k: len(v) for k, v in out_small.items()} == {
        k: expect for k in keys
    }


def test_region_pressure_guard_gates_on_probed_cursor():
    """ADVICE r5 medium: the region-pressure drain must gate on the
    freshest PROBED true cursor, not the worst-case occupancy bound --
    a match-free stream with high region fill must never fire a no-op
    sync drain -- and must back off after a drain that pulled nothing."""
    pattern = abc_pattern()
    config = EngineConfig(lanes=8, nodes=64, matches=256, matches_per_step=4)
    bat = BatchedDeviceNFA(
        compile_pattern(pattern), keys=["k0"], config=config,
    )
    pulls = []
    orig_pull = bat._pull_raw

    def counting_pull(**kw):
        pulls.append(1)
        return orig_pull(**kw)

    bat._pull_raw = counting_pull
    noise = {"k0": [Event("k0", "D", TS + i, "t", 0, i) for i in range(4)]}
    bat.advance_packed(bat.pack(noise), decode=False)
    jax.block_until_ready(bat.state["n_events"])

    # Force the failure-mode observation: high fill, TRUE cursor 0 (the
    # old guard's occ bound would be nonzero here and fire every advance).
    bat._pos_probes.clear()
    bat._pos_obs = (bat._pend_accum, 0, config.nodes)  # fill = 100%
    noise2 = {"k0": [Event("k0", "D", TS + 10 + i, "t", 0, i + 4) for i in range(4)]}
    bat.advance_packed(bat.pack(noise2), decode=False)
    assert not pulls, "region-pressure drain fired with nothing pending"

    # A probed real match + high fill DOES fire...
    bat._pos_probes.clear()
    bat._pos_obs = (bat._pend_accum, 1, config.nodes)
    noise3 = {"k0": [Event("k0", "D", TS + 20 + i, "t", 0, i + 8) for i in range(4)]}
    bat.advance_packed(bat.pack(noise3), decode=False)
    assert len(pulls) == 1
    # ...and a pull that found nothing (the probe had aged) arms the
    # backoff: the same stale observation no longer re-fires.
    assert bat._region_backoff
    bat._pos_probes.clear()
    bat._pos_obs = (bat._pend_accum, 1, config.nodes)
    noise4 = {"k0": [Event("k0", "D", TS + 30 + i, "t", 0, i + 12) for i in range(4)]}
    bat.advance_packed(bat.pack(noise4), decode=False)
    assert len(pulls) == 1, "backoff must suppress the region trigger"


def test_pin_interval_crossed_with_flat_drain():
    """The bench's flagship combination (bench.py skip_any8_batched runs
    pin_interval=True with drain_mode="flat") was previously covered only
    one axis at a time. Under interval pinning the drain-side compaction
    must still re-derive the EXACT pend closure (the pinned bitmap
    over-approximates by design), so pin x {flat, pool} x precise-walk
    must all agree across a mid-stream drain boundary -- same matches,
    same order, same fold values -- with zero drops at this sizing."""
    pattern = branching_pattern()
    keys = [f"k{i}" for i in range(3)]
    streams = {
        k: letter_stream(4000 + i, 24) for i, k in enumerate(keys)
    }

    def run(pin, mode):
        config = EngineConfig(
            lanes=64, nodes=1024, matches=256, matches_per_step=16,
            pin_interval=pin,
        )
        bat = BatchedDeviceNFA(
            compile_pattern(pattern), keys=keys, config=config,
            drain_mode=mode,
        )
        got = {k: [] for k in keys}
        # Three undrained advances (pins must keep the pending chains
        # alive across those GC passes), a mid-stream drain boundary,
        # three more, then the final drain.
        for b in range(6):
            bat.advance_packed(
                bat.pack({k: s[b * 4:(b + 1) * 4] for k, s in streams.items()}),
                decode=False,
            )
            if b == 2:
                for k, seqs in bat.drain().items():
                    got[k].extend(seqs)
        for k, seqs in bat.drain().items():
            got[k].extend(seqs)
        st = bat.stats
        assert st["node_drops"] == 0 and st["match_drops"] == 0, (pin, mode)
        return got

    want = run(False, "pool")  # precise walks + the semantic reference pull
    assert run(True, "flat") == want   # the bench combination
    assert run(True, "pool") == want
    assert run(False, "flat") == want


def test_flat_drain_stacked_queries():
    """Stacked multi-query attribution (qid routing) through the flat
    table: flat == pool on a 2-query stack."""
    from kafkastreams_cep_tpu.parallel import StackedQueryEngine

    def q(letters):
        qb = QueryBuilder()
        b = qb.select(f"{letters}-0").where(value() == letters[0])
        for j, ch in enumerate(letters[1:], start=1):
            b = b.then().select(f"{letters}-{j}").where(value() == ch)
        return b.build()

    config = EngineConfig(lanes=16, nodes=256, matches=64,
                          matches_per_step=8)
    streams = {f"k{i}": letter_stream(900 + i, 18) for i in range(2)}

    def run(mode):
        eng = StackedQueryEngine(
            [("abc", q("ABC")), ("bcd", q("BCD"))],
            keys=list(streams),
            config=config,
            drain_mode=mode,
        )
        got = {}
        for lo, hi in ((0, 7), (7, 100)):
            chunk = {k: s[lo:hi] for k, s in streams.items()}
            for k, per_q in eng.advance(chunk).items():
                for name, seqs in per_q.items():
                    got.setdefault(k, {}).setdefault(name, []).extend(seqs)
        return got

    assert run("flat") == run("pool")
