"""Store builders: assemble the typed query stores with durability toggles.

Re-design of the reference builder layer
(reference: core/.../cep/state/internal/builder/AbstractStoreBuilder.java:52-71,
BufferStoreBuilder.java:49-53, NFAStoreBuilder.java:58-64,
AggregatesStoreBuilder.java:46-50, and state/QueryStoreBuilders.java:50-96).
Each builder stacks an in-memory KV store with optional change-logging
(appending to a `RecordLog` changelog topic, the Kafka-role transport) and
optional write-back caching, then hands the stack to the typed store
facade. `QueryStoreBuilders` compiles the pattern exactly once
(QueryStoreBuilders.java:50-56) and shares the compiled stages between the
three builders' codecs and the processor.

Changelog topics follow the reference naming
(README.md:350-355): `<app-id>-<store-name>-changelog` where the store name
is `<query>-streamscep-{states,matched,aggregates}`.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

from ..pattern.compiler import ensure_stages
from ..pattern.stages import Stages
from .aggregates import AggregatesStore
from .buffer import BufferStore, SharedVersionedBuffer
from .naming import aggregates_store, event_buffer_store, nfa_states_store
from .nfa_store import NFAStore
from .serde import CheckpointCodec
from .store import (
    CachingKeyValueStore,
    ChangeLoggingKeyValueStore,
    InMemoryKeyValueStore,
    StateStore,
    WrappedStateStore,
)


def changelog_topic(app_id: str, store_name: str) -> str:
    """`<app-id>-<store-name>-changelog` (reference README.md:350-355)."""
    return f"{app_id}-{store_name}-changelog"


class AbstractStoreBuilder:
    """Base builder: logging/caching toggles (AbstractStoreBuilder.java:52-71).

    Logging defaults on, caching off -- the reference's defaults
    (AbstractStoreBuilder.java:36)."""

    def __init__(self, name: str) -> None:
        self.name = name
        self.logging_enabled = True
        self.caching_enabled = False

    def with_logging_enabled(self) -> "AbstractStoreBuilder":
        self.logging_enabled = True
        return self

    def with_logging_disabled(self) -> "AbstractStoreBuilder":
        self.logging_enabled = False
        return self

    def with_caching_enabled(self) -> "AbstractStoreBuilder":
        self.caching_enabled = True
        return self

    def with_caching_disabled(self) -> "AbstractStoreBuilder":
        self.caching_enabled = False
        return self

    # -- serdes bound by the concrete builders ------------------------------
    def _value_serde(self) -> Optional[Tuple[Callable, Callable]]:
        return None  # pickle default

    def _key_serde(self) -> Optional[Tuple[Callable, Callable]]:
        return None  # pickle default

    def build_kv(
        self, log: Optional[Any] = None, app_id: str = "app"
    ) -> StateStore:
        """The wrapped KV stack: memory [-> change-logging] [-> caching]."""
        store: StateStore = InMemoryKeyValueStore(self.name)
        if self.logging_enabled and log is not None:
            store = ChangeLoggingKeyValueStore(
                store,
                log,
                changelog_topic(app_id, self.name),
                key_serde=self._key_serde(),
                value_serde=self._value_serde(),
            )
        if self.caching_enabled:
            store = CachingKeyValueStore(store)
        return store

    def build(self, log: Optional[Any] = None, app_id: str = "app"):
        raise NotImplementedError


class NFAStoreBuilder(AbstractStoreBuilder):
    """Per-key NFA snapshot store builder (NFAStoreBuilder.java:58-64):
    values are `NFAStates` framed by the run-queue codec (stages re-linked
    by id against the recompiled query)."""

    def __init__(self, query_name: str, codec: CheckpointCodec) -> None:
        super().__init__(nfa_states_store(query_name))
        self.codec = codec

    def _value_serde(self):
        return (self.codec.encode_nfa_states, self.codec.decode_nfa_states)

    def build(self, log: Optional[Any] = None, app_id: str = "app") -> NFAStore:
        return NFAStore(backing=self.build_kv(log, app_id))


class BufferStoreBuilder(AbstractStoreBuilder):
    """Shared versioned buffer store builder (BufferStoreBuilder.java:49-53):
    values are whole per-key lineage buffers framed by the buffer codec."""

    def __init__(self, query_name: str, codec: CheckpointCodec) -> None:
        super().__init__(event_buffer_store(query_name))
        self.codec = codec

    def _value_serde(self):
        return (self.codec.encode_buffer, self.codec.decode_buffer)

    def build(self, log: Optional[Any] = None, app_id: str = "app") -> BufferStore:
        return BufferStore(backing=self.build_kv(log, app_id))


class AggregatesStoreBuilder(AbstractStoreBuilder):
    """Fold-register store builder (AggregatesStoreBuilder.java:46-50):
    keys are (record key, aggregate name, run id) tuples, values opaque
    user fold states (pickle, the Kryo-fallback analog)."""

    def __init__(self, query_name: str) -> None:
        super().__init__(aggregates_store(query_name))

    def build(
        self, log: Optional[Any] = None, app_id: str = "app"
    ) -> AggregatesStore:
        return AggregatesStore(backing=self.build_kv(log, app_id))


class QueryStoreBuilders:
    """Compile the pattern once, hand out the three store builders
    (QueryStoreBuilders.java:50-96)."""

    def __init__(
        self,
        query_name: str,
        pattern_or_stages: Any,
        strict_windows: bool = False,
    ) -> None:
        self.stages: Stages = ensure_stages(pattern_or_stages)
        self.query_name = query_name
        self.codec = CheckpointCodec(self.stages, strict_windows=strict_windows)
        self.nfa = NFAStoreBuilder(query_name, self.codec)
        self.buffer = BufferStoreBuilder(query_name, self.codec)
        self.aggregates = AggregatesStoreBuilder(query_name)

    def build_all(
        self, log: Optional[Any] = None, app_id: str = "app"
    ) -> Dict[str, Any]:
        """The three typed stores keyed by store name."""
        return {
            self.nfa.name: self.nfa.build(log, app_id),
            self.buffer.name: self.buffer.build(log, app_id),
            self.aggregates.name: self.aggregates.build(log, app_id),
        }


def restore_store(typed_store: Any) -> int:
    """Replay a typed store's changelog (if its KV stack has one) into the
    bottom store; returns records applied. The restore bypasses the logging
    layer so replay does not re-append (the reference's restore path does
    the same via the restore consumer). Stores owning their own restore
    protocol (the device-runtime checkpoint store) delegate to it."""
    restore_cl = getattr(typed_store, "restore_from_changelog", None)
    if restore_cl is not None:
        return restore_cl()
    kv = getattr(typed_store, "_kv", None)
    n = 0
    while kv is not None:
        if isinstance(kv, ChangeLoggingKeyValueStore):
            n += kv.restore()
        kv = kv.inner if isinstance(kv, WrappedStateStore) else None
    return n
