"""Bytes-level checkpoint codecs for the host stores and device state.

Re-design of the reference serde package
(reference: core/.../cep/state/internal/serde/ComputationStageSerde.java:56-155,
NFAStateValueSerde.java:79-152, MatchedEventSerde.java:86-118,
KryoSerDe.java:37-121): engine-owned structure is framed explicitly
(length-prefixed fields, stages referenced **by id** against the recompiled
query -- stages themselves are never stored, ComputationStageSerde.java:56-66),
while user keys/values go through pluggable serdes exactly as the reference
routes them through Kryo/user serdes. The default serde is pickle (the
Python analog of the reference's Kryo fallback).

Device state (ops/runtime.py, parallel/batched.py) serializes as raw typed
array frames (name, dtype, shape, C-order bytes) plus the host-side event
registry -- restorable into a fresh process with only the pattern + config.
"""
from __future__ import annotations

import io
import pickle
import struct
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..core.dewey import DeweyVersion
from ..core.event import Event
from ..pattern.stages import Stage, Stages
from .aggregates import AggregatesStore
from .buffer import BufferNode, BufferStore, SharedVersionedBuffer
from .nfa_store import NFAStates, NFAStore

MAGIC = b"KCT5"  # format tag + version (5: interval pinning -- pool carries
                 # pend_min, state carries per-lane chain roots; 4: paged
                 # pend ring; 3: batched leaves key-axis-last)
#: still-readable prior versions: missing leaves are synthesized on load
#: (`upgrade_pool_tree` / `upgrade_state_tree`).
COMPAT_MAGIC = (b"KCT3", b"KCT4")


class CheckpointError(ValueError):
    """A checkpoint payload failed validation: truncated frame, trailing
    garbage, bad magic, or CRC mismatch. Subclasses ValueError so callers
    of the pre-typed decoders keep working; new code should catch this."""


# ---------------------------------------------------------------------------
# CRC32C (Castagnoli) integrity frames
# ---------------------------------------------------------------------------
#: Seal marker for CRC-framed checkpoint payloads. Payloads themselves
#: always begin with a KCT* magic, so the marker can never collide with a
#: legacy (unsealed) checkpoint -- `open_frame` stays backward compatible.
CRC_MARKER = b"KCRC"
_CRC_HEADER = struct.Struct("<IQ")  # crc32c, payload length


def _crc32c_tables() -> List[List[int]]:
    """Slicing-by-8 tables for the Castagnoli polynomial (reflected
    0x82F63B78) -- pure Python, ~8 bytes per loop iteration."""
    t0 = []
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ 0x82F63B78 if c & 1 else c >> 1
        t0.append(c)
    tables = [t0]
    for _ in range(7):
        prev = tables[-1]
        tables.append([t0[prev[i] & 0xFF] ^ (prev[i] >> 8) for i in range(256)])
    return tables


_CRC_TABLES = _crc32c_tables()

try:  # C extension when the environment has one; identical polynomial,
    # init, and xor-out, so frames sealed either way verify either way.
    from google_crc32c import extend as _native_crc32c_extend
except ImportError:  # pragma: no cover - depends on the environment
    _native_crc32c_extend = None


def crc32c(data: bytes, crc: int = 0) -> int:
    """CRC-32C (Castagnoli) of `data` -- the checksum RocksDB/Kafka use for
    their block/record frames; crc32c(b"123456789") == 0xE3069283."""
    if _native_crc32c_extend is not None:
        return _native_crc32c_extend(crc, data)
    t0, t1, t2, t3, t4, t5, t6, t7 = _CRC_TABLES
    crc ^= 0xFFFFFFFF
    n = len(data)
    mv = memoryview(data)
    i = 0
    end8 = n - (n % 8)
    while i < end8:
        lo = crc ^ int.from_bytes(mv[i : i + 4], "little")
        hi = int.from_bytes(mv[i + 4 : i + 8], "little")
        crc = (
            t7[lo & 0xFF]
            ^ t6[(lo >> 8) & 0xFF]
            ^ t5[(lo >> 16) & 0xFF]
            ^ t4[(lo >> 24) & 0xFF]
            ^ t3[hi & 0xFF]
            ^ t2[(hi >> 8) & 0xFF]
            ^ t1[(hi >> 16) & 0xFF]
            ^ t0[(hi >> 24) & 0xFF]
        )
        i += 8
    while i < n:
        crc = (crc >> 8) ^ t0[(crc ^ data[i]) & 0xFF]
        i += 1
    return crc ^ 0xFFFFFFFF


def seal_frame(payload: bytes) -> bytes:
    """Wrap a checkpoint payload in a CRC32C frame:
    [KCRC][u32 crc][u64 len][payload]."""
    return CRC_MARKER + _CRC_HEADER.pack(crc32c(payload), len(payload)) + payload


def open_frame(data: bytes) -> bytes:
    """Unwrap (and verify) a sealed frame; legacy unsealed payloads pass
    through untouched (they begin with a KCT* magic, never KCRC). Raises
    `CheckpointError` on truncation, length mismatch, or CRC mismatch."""
    if data[:4] != CRC_MARKER:
        return data  # legacy unsealed checkpoint
    if len(data) < 4 + _CRC_HEADER.size:
        raise CheckpointError("truncated checkpoint CRC header")
    crc, length = _CRC_HEADER.unpack_from(data, 4)
    payload = data[4 + _CRC_HEADER.size :]
    if len(payload) != length:
        raise CheckpointError(
            f"checkpoint frame length mismatch (header {length}, "
            f"payload {len(payload)})"
        )
    if crc32c(payload) != crc:
        raise CheckpointError("checkpoint CRC32C mismatch (corrupt payload)")
    return payload


def read_magic(r: "_Reader") -> int:
    """Consume and validate the 4-byte format tag; returns its version."""
    tag = r._read(4)
    if tag == MAGIC:
        return int(MAGIC[3:].decode())
    if tag in COMPAT_MAGIC:
        return int(tag[3:].decode())
    raise CheckpointError("bad checkpoint magic")


def upgrade_pool_tree(pool: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Upgrade a KCT3 engine pool in place: synthesize the paged-ring
    cursor (`pend_pos` = one past the last occupied slot -- KCT3 rings are
    compact prefixes) and the `pinned` bitmap (the pend-reachable closure,
    re-walked host-side so pending chains survive the next GC)."""
    if "pend_pos" in pool:
        return pool
    pend = np.asarray(pool["pend"])
    pred = np.asarray(pool["node_pred"])
    B = pred.shape[0]
    valid = pend >= 0

    def closure(pend_k: np.ndarray, pred_k: np.ndarray) -> np.ndarray:
        pinned = np.zeros(B, bool)
        cur = pend_k[(pend_k >= 0) & (pend_k < B)]
        while cur.size:
            cur = np.unique(cur)
            new = cur[~pinned[cur]]
            if new.size == 0:
                break
            pinned[new] = True
            nxt = pred_k[new]
            cur = nxt[(nxt >= 0) & (nxt < B)]
        return pinned

    if pend.ndim == 1:
        pos = int(valid.nonzero()[0].max()) + 1 if valid.any() else 0
        pool["pend_pos"] = np.asarray(pos, np.int32)
        pool["pinned"] = closure(pend, pred)
    else:  # batched: key axis last ([M, K] ring, [B, K] pool)
        M, K = pend.shape
        pos = np.where(valid.any(0), M - np.argmax(valid[::-1], 0), 0)
        pool["pend_pos"] = pos.astype(np.int32)
        pinned = np.zeros((B, K), bool)
        for k in range(K):
            pinned[:, k] = closure(pend[:, k], pred[:, k])
        pool["pinned"] = pinned
    return pool


#: `pend_min` sentinel (engine._PEND_MIN_NONE): no pending match.
_PEND_MIN_NONE = np.int32(2**31 - 1)


def _chain_roots(node: np.ndarray, pred: np.ndarray) -> np.ndarray:
    """Follow predecessor pointers host-side: the chain root of each
    lane's last node (vectorized pointer-jumping; -1 stays -1)."""
    root = node.astype(np.int32).copy()
    while True:
        live = root >= 0
        if not live.any():
            break
        nxt = np.where(live, pred[np.clip(root, 0, None)], -1)
        step = live & (nxt >= 0)
        if not step.any():
            break
        root = np.where(step, nxt, root)
    return root


def upgrade_checkpoint_trees(
    state: Dict[str, np.ndarray], pool: Dict[str, np.ndarray]
) -> None:
    """Upgrade KCT3/KCT4 trees in place to the KCT5 schema: synthesize the
    pool's `pend_min` (min pinned node id -- pinned IS the pend-reachable
    set, whose minimum bounds every pending chain) and the state's
    per-lane chain roots (a host-side predecessor walk)."""
    upgrade_pool_tree(pool)
    if "pend_min" not in pool:
        pinned = np.asarray(pool["pinned"])
        any_pin = pinned.any(axis=0)
        first = np.argmax(pinned, axis=0).astype(np.int32)
        pool["pend_min"] = np.where(any_pin, first, _PEND_MIN_NONE).astype(
            np.int32
        )
    if "root" not in state:
        node = np.asarray(state["node"])
        pred = np.asarray(pool["node_pred"])
        if node.ndim == 1:
            state["root"] = _chain_roots(node, pred)
        else:  # [R, K] lanes over [B, K] pools
            R, K = node.shape
            root = np.empty((R, K), np.int32)
            for k in range(K):
                root[:, k] = _chain_roots(node[:, k], pred[:, k])
            state["root"] = root
    if "gc_phase" not in state:
        # GC groups (EngineConfig.gc_group): pre-group checkpoints carry no
        # group-phase scalar; snapshots always flush the group window
        # first, so 0 is exact, not approximate.
        state["gc_phase"] = np.zeros_like(np.asarray(state["runs"], np.int32))


def _default_serialize(obj: Any) -> bytes:
    return pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)


def _default_deserialize(data: bytes) -> Any:
    return pickle.loads(data)


class _Writer:
    def __init__(self) -> None:
        self._buf = io.BytesIO()

    def u8(self, v: int) -> None:
        self._buf.write(struct.pack("<B", v))

    def i32(self, v: int) -> None:
        self._buf.write(struct.pack("<i", v))

    def i64(self, v: int) -> None:
        self._buf.write(struct.pack("<q", v))

    def blob(self, data: bytes) -> None:
        self._buf.write(struct.pack("<I", len(data)))
        self._buf.write(data)

    def text(self, s: str) -> None:
        self.blob(s.encode("utf-8"))

    def getvalue(self) -> bytes:
        return self._buf.getvalue()


class _Reader:
    def __init__(self, data: bytes) -> None:
        self._data = data
        self._buf = io.BytesIO(data)

    def _read(self, n: int) -> bytes:
        out = self._buf.read(n)
        if len(out) != n:
            raise CheckpointError("truncated checkpoint frame")
        return out

    def expect_end(self) -> None:
        """Every decode entry point must consume its payload exactly:
        trailing garbage means a framing bug or a corrupt/foreign blob,
        and silently ignoring it hides both."""
        pos = self._buf.tell()
        if pos != len(self._data):
            raise CheckpointError(
                f"checkpoint frame carries {len(self._data) - pos} trailing "
                "byte(s) past the decoded payload"
            )

    def u8(self) -> int:
        return struct.unpack("<B", self._read(1))[0]

    def i32(self) -> int:
        return struct.unpack("<i", self._read(4))[0]

    def i64(self) -> int:
        return struct.unpack("<q", self._read(8))[0]

    def blob(self) -> bytes:
        (n,) = struct.unpack("<I", self._read(4))
        return self._read(n)

    def text(self) -> str:
        return self.blob().decode("utf-8")


class CheckpointCodec:
    """Codec bound to one compiled query (stages re-linked by index).

    The stage table must be the same compile output shape on encode and
    decode -- the reference makes the same assumption when it rebuilds
    stages from ids against the recompiled pattern
    (ComputationStageSerde.java:90-101).
    """

    def __init__(
        self,
        stages: Stages,
        serialize: Callable[[Any], bytes] = _default_serialize,
        deserialize: Callable[[bytes], Any] = _default_deserialize,
        strict_windows: bool = False,
    ) -> None:
        self.stages = stages
        self._stage_list: List[Stage] = list(stages)
        self._index_of: Dict[int, int] = {
            id(s): i for i, s in enumerate(self._stage_list)
        }
        self._ser = serialize
        self._de = deserialize
        self.strict_windows = strict_windows

    # ---------------------------------------------------------------- events
    def _put_event(self, w: _Writer, event: Optional[Event]) -> None:
        if event is None:
            w.u8(0)
            return
        w.u8(1)
        w.blob(self._ser(event.key))
        w.blob(self._ser(event.value))
        w.i64(event.timestamp)
        w.text(event.topic)
        w.i32(event.partition)
        w.i64(event.offset)

    def _get_event(self, r: _Reader) -> Optional[Event]:
        if r.u8() == 0:
            return None
        key = self._de(r.blob())
        value = self._de(r.blob())
        ts = r.i64()
        topic = r.text()
        partition = r.i32()
        offset = r.i64()
        return Event(key, value, ts, topic, partition, offset)

    # ---------------------------------------------------------------- stages
    def _stage_ref(self, stage: Stage) -> Tuple[int, int]:
        """(compiled index, epsilon-target index | -1) for a runtime stage."""
        idx = self._index_of.get(id(stage))
        if idx is not None:
            return idx, -1
        # Synthesized epsilon: identity is a compiled stage (same id/name),
        # target is its single PROCEED edge.
        target = stage.edges[0].target
        tgt_idx = self._index_of.get(id(target))
        src_idx = next(
            (
                i
                for i, s in enumerate(self._stage_list)
                if s.id == stage.id and s.name == stage.name and s.type == stage.type
            ),
            None,
        )
        if src_idx is None or tgt_idx is None:
            raise ValueError(f"stage {stage!r} does not belong to this query")
        return src_idx, tgt_idx

    def _resolve_stage(self, idx: int, eps_target: int) -> Stage:
        stage = self._stage_list[idx]
        if eps_target < 0:
            return stage
        target = self._stage_list[eps_target]
        eps = Stage.new_epsilon(stage, target)
        if self.strict_windows:
            eps.window_ms = (
                target.window_ms if target.window_ms != -1 else stage.window_ms
            )
        return eps

    # ------------------------------------------------------------- NFAStates
    def encode_nfa_states(self, snap: NFAStates) -> bytes:
        """Frame: run queue (stage ids + versions + embedded last events),
        runs counter, offset high-water marks
        (NFAStateValueSerde.java:79-116)."""
        w = _Writer()
        w._buf.write(MAGIC)
        w.i32(len(snap.computation_stages))
        for cs in snap.computation_stages:
            src, eps = self._stage_ref(cs.stage)
            w.i32(src)
            w.i32(eps)
            w.i32(len(cs.version.digits))
            for d in cs.version.digits:
                w.i32(d)
            w.i64(cs.sequence)
            w.i64(cs.timestamp)
            w.u8(1 if cs.is_branching else 0)
            w.u8(1 if cs.is_ignored else 0)
            w.i64(cs.last_node if cs.last_node is not None else -1)
            self._put_event(w, cs.last_event)
        w.i64(snap.runs)
        w.i32(len(snap.latest_offsets))
        for topic, offset in snap.latest_offsets.items():
            w.text(topic)
            w.i64(offset)
        return seal_frame(w.getvalue())

    def decode_nfa_states(self, data: bytes) -> NFAStates:
        from ..nfa.nfa import ComputationStage

        r = _Reader(open_frame(data))
        read_magic(r)
        n = r.i32()
        queue = []
        for _ in range(n):
            src = r.i32()
            eps = r.i32()
            digits = tuple(r.i32() for _ in range(r.i32()))
            sequence = r.i64()
            timestamp = r.i64()
            is_branching = bool(r.u8())
            is_ignored = bool(r.u8())
            last_node = r.i64()
            last_event = self._get_event(r)
            queue.append(
                ComputationStage(
                    stage=self._resolve_stage(src, eps),
                    version=DeweyVersion(digits),
                    sequence=sequence,
                    last_event=last_event,
                    timestamp=timestamp,
                    is_branching=is_branching,
                    is_ignored=is_ignored,
                    last_node=None if last_node < 0 else last_node,
                )
            )
        runs = r.i64()
        offsets = {}
        for _ in range(r.i32()):
            topic = r.text()
            offsets[topic] = r.i64()
        r.expect_end()
        return NFAStates(queue, runs, offsets)

    # ---------------------------------------------------------------- buffer
    def encode_buffer(self, buffer: SharedVersionedBuffer) -> bytes:
        """Node frame: id, stage name, embedded event, parent id
        (MatchedEventSerde.java:86-118 analog, minus refcounts -- reclamation
        is mark-sweep here)."""
        w = _Writer()
        w._buf.write(MAGIC)
        w.i64(buffer._next_id)
        w.i32(len(buffer._nodes))
        for node_id, node in buffer._nodes.items():
            w.i64(node_id)
            w.text(node.stage_name)
            self._put_event(w, node.event)
            w.i64(node.parent if node.parent is not None else -1)
        return seal_frame(w.getvalue())

    def decode_buffer(self, data: bytes) -> SharedVersionedBuffer:
        r = _Reader(open_frame(data))
        read_magic(r)
        buffer: SharedVersionedBuffer = SharedVersionedBuffer()
        buffer._next_id = r.i64()
        n = r.i32()
        for _ in range(n):
            node_id = r.i64()
            stage_name = r.text()
            event = self._get_event(r)
            parent = r.i64()
            buffer._nodes[node_id] = BufferNode(
                stage_name, event, None if parent < 0 else parent
            )
        r.expect_end()
        return buffer

    # ------------------------------------------------------------ aggregates
    def encode_aggregates(self, store: AggregatesStore) -> bytes:
        """(record key, name, run id) -> value frames
        (AggregateKeySerde.java:107-121 analog)."""
        w = _Writer()
        w._buf.write(MAGIC)
        entries = list(store.items())
        w.i32(len(entries))
        for (key, name, sequence), value in entries:
            w.blob(self._ser(key))
            w.text(name)
            w.i64(sequence)
            w.blob(self._ser(value))
        return seal_frame(w.getvalue())

    def decode_aggregates(self, data: bytes) -> AggregatesStore:
        r = _Reader(open_frame(data))
        read_magic(r)
        store = AggregatesStore()
        for _ in range(r.i32()):
            key = self._de(r.blob())
            name = r.text()
            sequence = r.i64()
            value = self._de(r.blob())
            store.put(key, name, sequence, value)
        r.expect_end()
        return store

    # ---------------------------------------------------- query-level stores
    def encode_query_stores(
        self,
        nfa_store: NFAStore,
        buffers: BufferStore,
        aggregates: AggregatesStore,
    ) -> bytes:
        """One checkpoint blob for a query's three stores -- the changelog
        record equivalent (README.md:350-355 store naming scheme)."""
        w = _Writer()
        w._buf.write(MAGIC)
        nfa_entries = list(nfa_store.items())
        w.i32(len(nfa_entries))
        for key, snap in nfa_entries:
            w.blob(self._ser(key))
            w.blob(self.encode_nfa_states(snap))
        buf_entries = list(buffers.items())
        w.i32(len(buf_entries))
        for key, buffer in buf_entries:
            w.blob(self._ser(key))
            w.blob(self.encode_buffer(buffer))
        w.blob(self.encode_aggregates(aggregates))
        return seal_frame(w.getvalue())

    def decode_query_stores(
        self, data: bytes
    ) -> Tuple[NFAStore, BufferStore, AggregatesStore]:
        r = _Reader(open_frame(data))
        read_magic(r)
        nfa_store = NFAStore()
        for _ in range(r.i32()):
            key = self._de(r.blob())
            nfa_store.put(key, self.decode_nfa_states(r.blob()))
        buffers = BufferStore()
        for _ in range(r.i32()):
            key = self._de(r.blob())
            buffers.set_for_key(key, self.decode_buffer(r.blob()))
        aggregates = self.decode_aggregates(r.blob())
        r.expect_end()
        return nfa_store, buffers, aggregates


# ---------------------------------------------------------------------------
# Device state frames
# ---------------------------------------------------------------------------
def encode_array_tree(
    tree: Dict[str, Any],
    serialize: Callable[[Any], bytes] = _default_serialize,
) -> bytes:
    """Raw typed frames for a flat dict of arrays (the device state dict)."""
    w = _Writer()
    w._buf.write(MAGIC)
    w.i32(len(tree))
    for name in sorted(tree):
        arr = np.asarray(tree[name])
        w.text(name)
        w.text(str(arr.dtype))
        w.i32(arr.ndim)
        for dim in arr.shape:
            w.i64(dim)
        w.blob(arr.tobytes(order="C"))
    return seal_frame(w.getvalue())


def decode_array_tree(data: bytes) -> Dict[str, np.ndarray]:
    r = _Reader(open_frame(data))
    if r._read(4) != MAGIC:
        raise CheckpointError("bad checkpoint magic")
    out: Dict[str, np.ndarray] = {}
    for _ in range(r.i32()):
        name = r.text()
        dtype = np.dtype(r.text())
        shape = tuple(r.i64() for _ in range(r.i32()))
        raw = r.blob()
        out[name] = np.frombuffer(raw, dtype=dtype).reshape(shape).copy()
    r.expect_end()
    return out


class ShapeRestoreError(CheckpointError):
    """Cross-shape restore refused: the snapshot's LIVE occupancy does
    not fit the target shape. Raised instead of silently truncating --
    dropping live lanes/nodes/pending matches on restore would be
    silent state loss dressed up as a resize."""


def check_restore_capacity(
    state: Dict[str, Any],
    pool: Dict[str, Any],
    *,
    lanes: int,
    nodes: int,
    matches: int,
    where: str = "restore",
) -> None:
    """Refuse loudly when a snapshot's live occupancy exceeds the target
    capacity (`ShapeRestoreError`). The checks lean on the engine's
    compaction invariants: GC folds live nodes to the region prefix
    `[0, node_count)` and the pend ring is a dense prefix
    `[0, pend_pos)`, so prefix extents bound every live id."""
    problems = []
    active = np.asarray(state["active"])
    if active.ndim >= 1 and active.shape[0] > lanes:
        # Lanes are NOT compacted to a prefix: any live run in a lane
        # beyond the target extent blocks the shrink.
        lane_live = active.reshape(active.shape[0], -1).any(axis=1)
        if bool(lane_live[lanes:].any()):
            top = int(np.nonzero(lane_live)[0].max())
            problems.append(f"live run in lane {top} >= target lanes {lanes}")
    node_count = np.asarray(pool["node_count"])
    if int(node_count.max(initial=0)) > nodes:
        problems.append(
            f"node_count {int(node_count.max(initial=0))} > target nodes {nodes}"
        )
    pend_pos = np.asarray(pool["pend_pos"])
    if int(pend_pos.max(initial=0)) > matches:
        problems.append(
            f"pend_pos {int(pend_pos.max(initial=0))} > target matches {matches}"
        )
    # Defensive id bound: every stored node id (match chains, run
    # cursors, predecessor links) must address the target region.
    max_id = -1
    for tree, name in ((state, "node"), (state, "root"),
                       (pool, "node_pred"), (pool, "pend")):
        arr = np.asarray(tree[name])
        if arr.size:
            max_id = max(max_id, int(arr.max()))
    if max_id >= nodes:
        problems.append(f"stored node id {max_id} >= target nodes {nodes}")
    if problems:
        raise ShapeRestoreError(
            f"{where}: snapshot does not fit target shape "
            f"(lanes={lanes}, nodes={nodes}, matches={matches}): "
            + "; ".join(problems)
        )


def graft_array_tree(
    src: Dict[str, Any], target: Dict[str, np.ndarray]
) -> Dict[str, np.ndarray]:
    """Paste `src` leaves into freshly initialized `target` leaves,
    slicing every axis to the common extent (in place; returns target).

    Correct for the device trees because capacity pads carry init values
    (node planes -1, pend ring -1, pinned False) and the live content is
    compacted to axis prefixes -- callers gate on
    `check_restore_capacity` first so nothing live is ever cut."""
    for name, dst in target.items():
        if name not in src:
            continue
        arr = np.asarray(src[name])
        if arr.ndim != dst.ndim:
            raise ShapeRestoreError(
                f"graft: leaf {name!r} rank mismatch "
                f"({arr.ndim} vs {dst.ndim})"
            )
        sl = tuple(
            slice(0, min(a, b)) for a, b in zip(arr.shape, dst.shape)
        )
        dst[sl] = arr[sl].astype(dst.dtype, copy=False)
    return target


def encode_event_registry(
    events: Dict[int, Event],
    serialize: Callable[[Any], bytes] = _default_serialize,
) -> bytes:
    codec = _EventOnly(serialize, _default_deserialize)
    w = _Writer()
    w._buf.write(MAGIC)
    w.i32(len(events))
    for gidx, event in events.items():
        w.i64(gidx)
        codec._put_event(w, event)
    return seal_frame(w.getvalue())


def decode_event_registry(
    data: bytes,
    deserialize: Callable[[bytes], Any] = _default_deserialize,
) -> Dict[int, Event]:
    codec = _EventOnly(_default_serialize, deserialize)
    r = _Reader(open_frame(data))
    if r._read(4) != MAGIC:
        raise CheckpointError("bad checkpoint magic")
    out: Dict[int, Event] = {}
    for _ in range(r.i32()):
        gidx = r.i64()
        out[gidx] = codec._get_event(r)
    r.expect_end()
    return out


class _EventOnly(CheckpointCodec):
    """Event framing without a stage table (device registries)."""

    def __init__(self, serialize, deserialize) -> None:
        self._ser = serialize
        self._de = deserialize


# ---------------------------------------------------------------------------
# Event-time gate frames (ISSUE 10)
# ---------------------------------------------------------------------------
#: Wrapper tag for processor snapshots that carry event-time state
#: alongside the legacy payload. Distinct from every KCT* magic, so
#: `split_event_time` discriminates new and old formats unambiguously
#: (old snapshots restore with a fresh gate -- replay rebuilds it).
ET_MAGIC = b"KCW1"


def encode_event_time_state(
    state: Dict[str, Any],
    serialize: Callable[[Any], bytes] = _default_serialize,
) -> bytes:
    """Seal an EventTimeGate.snapshot_state() dict: watermark-generator
    kind + state, the monotone release clock, forced/observed marks, the
    arrival sequence, every key's buffered (seq, Event) entries in
    (ts, seq) order, and the late side output. Crash recovery restores the
    reorder buffer and watermark CONSISTENTLY with the engine snapshot the
    same commit wrote (streams/device_processor.py snapshot/restore)."""
    codec = _EventOnly(serialize, _default_deserialize)
    w = _Writer()
    w._buf.write(MAGIC)
    w.text(state["gen_kind"])
    w.blob(pickle.dumps(state["gen_state"], protocol=pickle.HIGHEST_PROTOCOL))
    clocks = state["clocks"]
    w.i32(len(clocks))
    for key in clocks:
        w.blob(pickle.dumps(key, protocol=pickle.HIGHEST_PROTOCOL))
        w.i64(clocks[key])
    w.i64(state["forced_wm"])
    w.i64(state["max_seen"])
    w.i64(state["seq"])
    buffers = state["buffers"]
    w.i32(len(buffers))
    for key in buffers:
        w.blob(pickle.dumps(key, protocol=pickle.HIGHEST_PROTOCOL))
        entries = buffers[key]
        w.i32(len(entries))
        for _ts, seq, ev in entries:
            w.i64(seq)
            codec._put_event(w, ev)
    late = state["late"]
    w.i32(len(late))
    for ev in late:
        codec._put_event(w, ev)
    # Arrival high-water marks (host runtime): the arrival-side dedup
    # marks MUST restore atomically with the gate contents they guard --
    # a durable mark over a volatile buffer silently loses the buffered
    # records on crash (the device runtime snapshots its marks in the
    # same processor blob instead; it passes {} here).
    w.blob(
        pickle.dumps(state.get("hwm", {}), protocol=pickle.HIGHEST_PROTOCOL)
    )
    return seal_frame(w.getvalue())


def decode_event_time_state(
    data: bytes,
    deserialize: Callable[[bytes], Any] = _default_deserialize,
) -> Dict[str, Any]:
    codec = _EventOnly(_default_serialize, deserialize)
    r = _Reader(open_frame(data))
    read_magic(r)
    out: Dict[str, Any] = {
        "gen_kind": r.text(),
        "gen_state": pickle.loads(r.blob()),
    }
    clocks: Dict[Any, int] = {}
    for _ in range(r.i32()):
        ck = pickle.loads(r.blob())
        clocks[ck] = r.i64()
    out["clocks"] = clocks
    out["forced_wm"] = r.i64()
    out["max_seen"] = r.i64()
    out["seq"] = r.i64()
    buffers: Dict[Any, list] = {}
    for _ in range(r.i32()):
        key = pickle.loads(r.blob())
        entries = []
        for _ in range(r.i32()):
            seq = r.i64()
            ev = codec._get_event(r)
            entries.append((ev.timestamp, seq, ev))
        buffers[key] = entries
    out["buffers"] = buffers
    out["late"] = [codec._get_event(r) for _ in range(r.i32())]
    out["hwm"] = pickle.loads(r.blob())  # cep: serde-ok(arrival HWMs are consumed by CEPProcessor.restore, not the gate; the device runtime encodes {})
    r.expect_end()
    return out


def wrap_event_time(inner: bytes, gate_bytes: bytes) -> bytes:
    """Wrap a processor snapshot with its event-time gate frame."""
    w = _Writer()
    w._buf.write(ET_MAGIC)
    w.blob(inner)
    w.blob(gate_bytes)
    return seal_frame(w.getvalue())


def split_event_time(data: bytes) -> Tuple[bytes, Optional[bytes]]:
    """(inner snapshot, gate frame | None): inverse of wrap_event_time.

    Legacy snapshots (no wrapper) pass through untouched with gate None,
    so pre-event-time checkpoints keep restoring."""
    payload = open_frame(data)
    if not payload.startswith(ET_MAGIC):
        return data, None
    r = _Reader(payload)
    if r._read(4) != ET_MAGIC:  # pragma: no cover - startswith guarded
        raise CheckpointError("bad event-time wrapper magic")
    inner = r.blob()
    gate = r.blob()
    r.expect_end()
    return inner, gate


# ---------------------------------------------------------------------------
# Shard checkpoints (ISSUE 16: live migration)
# ---------------------------------------------------------------------------
#: Format tag for a self-contained movable shard: everything a successor
#: driver on another broker needs to resume a fenced shard mid-stream --
#: consumer positions, per-broker transport sessions (the idempotent-
#: producer identity, so server-side dedup spans the move), and per-query
#: store/emission/event-time state. Distinct from KCT*/KCW1 so a shard
#: frame can never be mistaken for an engine or gate snapshot.
SHARD_MAGIC = b"KSH1"


def encode_shard_checkpoint(shard: Dict[str, Any]) -> bytes:
    """Seal one shard's movable state. Schema (all keys required):

    - ``shard_id``: str -- the shard's stable name (also the app-id salt
      for its changelog topics).
    - ``group``: str -- the shard driver's consumer group.
    - ``positions``: {(topic, partition): pos} -- committed consumer
      positions at the fence point (`LogDriver.positions()`).
    - ``sessions``: {broker_label: (session_bytes, seq)} -- per-broker
      `SocketRecordLog.session_state()`; the successor client adopts
      both so the broker's seq->offset dedup table keeps covering
      appends issued before the move.
    - ``queries``: {qname: {"runtime": str, "stores": bytes | None,
      "sink_pos": {topic: pos}, "event_time": bytes | None}} -- the
      store snapshot (host: `CheckpointCodec.encode_query_stores`;
      device: `processor.snapshot()`), the EmissionGate watermark, and
      the sealed event-time gate frame.
    """
    w = _Writer()
    w._buf.write(SHARD_MAGIC)
    w.text(shard["shard_id"])
    w.text(shard["group"])
    positions = shard["positions"]
    w.i32(len(positions))
    for (topic, partition) in sorted(positions):
        w.text(topic)
        w.i32(int(partition))
        w.i64(int(positions[(topic, partition)]))
    sessions = shard["sessions"]
    w.i32(len(sessions))
    for label in sorted(sessions):
        session, seq = sessions[label]
        w.text(str(label))
        w.blob(bytes(session))
        w.i64(int(seq))
    queries = shard["queries"]
    w.i32(len(queries))
    for qname in sorted(queries):
        q = queries[qname]
        w.text(qname)
        w.text(q["runtime"])
        stores = q.get("stores")
        w.u8(0 if stores is None else 1)
        if stores is not None:
            w.blob(stores)
        sink_pos = q.get("sink_pos") or {}
        w.i32(len(sink_pos))
        for topic in sorted(sink_pos):
            w.text(topic)
            w.i64(int(sink_pos[topic]))
        gate = q.get("event_time")
        w.u8(0 if gate is None else 1)
        if gate is not None:
            w.blob(gate)
    return seal_frame(w.getvalue())


def decode_shard_checkpoint(data: bytes) -> Dict[str, Any]:
    """Inverse of `encode_shard_checkpoint`; raises `CheckpointError` on
    a corrupt frame or a non-shard payload."""
    r = _Reader(open_frame(data))
    if r._read(4) != SHARD_MAGIC:
        raise CheckpointError("bad shard checkpoint magic")
    out: Dict[str, Any] = {
        "shard_id": r.text(),
        "group": r.text(),
    }
    positions: Dict[Tuple[str, int], int] = {}
    for _ in range(r.i32()):
        topic = r.text()
        partition = r.i32()
        positions[(topic, partition)] = r.i64()
    out["positions"] = positions
    sessions: Dict[str, Tuple[bytes, int]] = {}
    for _ in range(r.i32()):
        label = r.text()
        session = r.blob()
        sessions[label] = (session, r.i64())
    out["sessions"] = sessions
    queries: Dict[str, Dict[str, Any]] = {}
    for _ in range(r.i32()):
        qname = r.text()
        q: Dict[str, Any] = {"runtime": r.text()}
        q["stores"] = r.blob() if r.u8() else None
        sink_pos: Dict[str, int] = {}
        for _ in range(r.i32()):
            topic = r.text()
            sink_pos[topic] = r.i64()
        q["sink_pos"] = sink_pos
        q["event_time"] = r.blob() if r.u8() else None
        queries[qname] = q
    out["queries"] = queries
    r.expect_end()
    return out
