"""Query compiler: Stages -> packed transition tables + traced closures.

The host compiler (pattern/compiler.py) produces the NFA stage graph; this
module lowers it for the device engine (ops/engine.py):

  * per-stage edge slots packed into dense int32 arrays (a stage has at most
    one consuming edge BEGIN|TAKE, one IGNORE, one PROCEED|SKIP_PROCEED --
    guaranteed by the construction rules, StagesFactory.java:101-169);
  * predicates deduplicated into a list of jax-traceable closures evaluated
    against (event columns, fold registers) -- each predicate becomes one
    fused vector op per micro-batch step instead of the reference's per-edge
    virtual call (NFA.java:371-384);
  * fold updates per stage lowered the same way;
  * stages grouped by (name, type) into buffer-key name ids (the Matched key
    identity, state/internal/Matched.java:21-34);
  * string constants in expressions tokenized via the EventSchema.

The epsilon-PROCEED descent is not a table: the engine unrolls it to the
static stage count (SURVEY.md section 7, "Recursive epsilon-evaluation").
"""
from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..pattern.expressions import (
    AggRef,
    BinOp,
    BoolOp,
    Const,
    Env,
    Expr,
    Field,
    Key,
    NotOp,
    Timestamp,
    TopicIs,
    TrueExpr,
    Value,
)
from ..pattern.stages import EdgeOperation, Stage, Stages, StateType
from .schema import EventSchema

# consume ops
OP_NONE, OP_BEGIN, OP_TAKE = 0, 1, 2
# proceed kinds
PR_NONE, PR_PROCEED, PR_SKIP = 0, 1, 2


class DeviceEnv(Env):
    """Expression environment over device columns + per-run registers.

    `event` is a dict of scalar (per-step) column values; registers are
    [R, A]-shaped so predicate results broadcast over run lanes.
    """

    def __init__(
        self,
        event: Dict[str, Any],
        regs: Any,
        regs_set: Any,
        agg_slots: Dict[str, int],
        defaults: Dict[str, float],
    ) -> None:
        self._event = event
        self._regs = regs
        self._regs_set = regs_set
        self._agg_slots = agg_slots
        self._defaults = defaults

    def field(self, name: str) -> Any:
        return self._event[f"f:{name}"]

    def value(self) -> Any:
        return self._event["f:"]

    def key(self) -> Any:
        raise NotImplementedError("key() is not available in device predicates")

    def timestamp(self) -> Any:
        return self._event["ts"]

    def topic_is(self, topic_code: Any) -> Any:
        return self._event["topic"] == topic_code

    def agg(self, name: str, default: Any = None) -> Any:
        import jax.numpy as jnp

        slot = self._agg_slots.get(name)
        if slot is None:
            # No fold ever writes this register: the host oracle's store
            # lookup always misses and yields the default (States.getOrElse,
            # state/States.java:70-73), so the device reads a constant.
            fallback = default if default is not None else self._defaults.get(name, 0)
            return jnp.asarray(fallback, jnp.float32)
        val = self._regs[..., slot]
        is_set = self._regs_set[..., slot]
        fallback = default if default is not None else self._defaults.get(name, 0)
        return jnp.where(is_set, val, jnp.asarray(fallback, dtype=val.dtype))

    def true(self) -> Any:
        return True


def _encode_consts(expr: Expr, schema: EventSchema) -> Expr:
    """Rebuild the tree with string constants tokenized for the device."""
    if isinstance(expr, Const):
        return Const(schema.encode_const(expr.value))
    if isinstance(expr, TopicIs):
        return TopicIs(schema.topic_id(expr.topic))  # type: ignore[arg-type]
    if isinstance(expr, BinOp):
        return BinOp(
            _encode_consts(expr.left, schema), _encode_consts(expr.right, schema),
            expr.op, expr.sym,
        )
    if isinstance(expr, BoolOp):
        return BoolOp(
            _encode_consts(expr.left, schema), _encode_consts(expr.right, schema), expr.kind
        )
    if isinstance(expr, NotOp):
        return NotOp(_encode_consts(expr.inner, schema))
    return expr


@dataclass
class CompiledQuery:
    """Device-ready form of one compiled pattern query."""

    schema: EventSchema
    n_stages: int
    n_preds: int
    n_aggs: int
    max_depth: int  # epsilon-chain unroll depth

    # Per-stage tables, shape [S] (numpy; moved to device by the engine).
    consume_op: np.ndarray      # OP_NONE | OP_BEGIN | OP_TAKE
    consume_pred: np.ndarray    # predicate id (-1 none)
    consume_target: np.ndarray  # target stage id (-1 none)
    ignore_pred: np.ndarray     # predicate id (-1 none)
    proceed_kind: np.ndarray    # PR_NONE | PR_PROCEED | PR_SKIP
    proceed_pred: np.ndarray
    proceed_target: np.ndarray
    window_ms: np.ndarray       # i64, -1 none (int64 so >24.8-day windows
                                # don't overflow; see ADVICE r1)
    name_id: np.ndarray         # buffer-key identity (name, type) id
    pure_name_id: np.ndarray    # name-only id (stage-cross detection,
                                # NFA.java:343-349 compares getName())
    is_begin: np.ndarray        # bool
    is_final: np.ndarray        # bool
    #: stage is a pure forwarder: single PROCEED edge
    #: (ComputationStage.isForwarding, ComputationStage.java:134-140)
    is_fwd: np.ndarray = dc_field(default_factory=lambda: np.zeros(0, bool))
    #: forwarding stage whose PROCEED target is $final
    fwd_final: np.ndarray = dc_field(default_factory=lambda: np.zeros(0, bool))
    #: per-predicate: reads fold registers (must be evaluated per run lane)
    pred_stateful: np.ndarray = dc_field(default_factory=lambda: np.zeros(0, bool))

    #: predicate closures: fn(DeviceEnv) -> bool array broadcast over runs
    predicates: List[Callable[[DeviceEnv], Any]] = dc_field(default_factory=list)
    #: per stage: list of (agg slot, update closure fn(DeviceEnv, current)->val)
    folds: List[List[Tuple[int, Callable]]] = dc_field(default_factory=list)
    agg_slots: Dict[str, int] = dc_field(default_factory=dict)
    agg_defaults: Dict[str, float] = dc_field(default_factory=dict)
    name_of_id: List[str] = dc_field(default_factory=list)
    begin_stage: int = 0
    #: the host stage graph this query was lowered from, retained so the
    #: exact-replay path (ops/replay.py) can rebuild a host oracle and the
    #: device stage ids map back to Stage objects (stage_list[i]).
    host_stages: Optional[Stages] = None
    stage_list: List[Stage] = dc_field(default_factory=list)
    #: multi-query stacking (compile_multi_query): one begin lane per
    #: stacked query, and per-name-id query attribution for match routing.
    #: None for ordinary single-query compiles.
    begin_stages: Optional[List[int]] = None
    qid_of_name_id: Optional[np.ndarray] = None
    query_names: Optional[List[str]] = None


def compile_query(stages: Stages, schema: Optional[EventSchema] = None) -> CompiledQuery:
    """Lower a compiled stage graph into device tables.

    Requires every predicate and fold to be expression-based
    (device_compilable); raises ValueError otherwise with the offending
    stage named, directing users to the host path.
    """
    schema = schema if schema is not None else EventSchema()
    stage_list: List[Stage] = list(stages)
    n = len(stage_list)
    index_of = {id(s): i for i, s in enumerate(stage_list)}

    consume_op = np.zeros(n, np.int32)
    consume_pred = np.full(n, -1, np.int32)
    consume_target = np.full(n, -1, np.int32)
    ignore_pred = np.full(n, -1, np.int32)
    proceed_kind = np.zeros(n, np.int32)
    proceed_pred = np.full(n, -1, np.int32)
    proceed_target = np.full(n, -1, np.int32)
    window_ms = np.full(n, -1, np.int64)
    name_id = np.zeros(n, np.int32)
    pure_name_id = np.zeros(n, np.int32)
    is_begin = np.zeros(n, bool)
    is_final = np.zeros(n, bool)

    predicates: List[Callable] = []
    pred_stateful: List[bool] = []
    pred_ids: Dict[int, int] = {}
    name_ids: Dict[Tuple[str, StateType], int] = {}
    name_of_id: List[str] = []
    pure_name_ids: Dict[str, int] = {}
    agg_slots: Dict[str, int] = {}
    agg_defaults: Dict[str, float] = {}
    folds: List[List[Tuple[int, Callable]]] = [[] for _ in range(n)]

    def pred_id(predicate) -> int:
        key = id(predicate)
        got = pred_ids.get(key)
        if got is not None:
            return got
        expr = predicate.expr()
        if expr is None:
            raise ValueError(
                "predicate is not device-compilable (closure-based); use "
                "expression predicates (field()/agg()/value()) or the host path"
            )
        stateful = bool(expr.aggs())
        expr = _encode_consts(expr, schema)
        pid = len(predicates)

        def run(env: DeviceEnv, _e=expr) -> Any:
            return _e.evaluate(env)

        predicates.append(run)
        pred_stateful.append(stateful)
        pred_ids[key] = pid
        return pid

    begin_stage = -1
    for i, stage in enumerate(stage_list):
        key = (stage.name, stage.type)
        if key not in name_ids:
            name_ids[key] = len(name_of_id)
            name_of_id.append(stage.name)
        name_id[i] = name_ids[key]
        if stage.name not in pure_name_ids:
            pure_name_ids[stage.name] = len(pure_name_ids)
        pure_name_id[i] = pure_name_ids[stage.name]
        window_ms[i] = stage.window_ms
        is_begin[i] = stage.is_begin
        is_final[i] = stage.is_final
        if stage.is_begin and begin_stage < 0:
            begin_stage = i

        for aggregator in stage.aggregates:
            if aggregator.name not in agg_slots:
                agg_slots[aggregator.name] = len(agg_slots)
                agg_defaults[aggregator.name] = (
                    float(aggregator.initial) if aggregator.initial is not None else 0.0
                )
            if aggregator.expression is None:
                raise ValueError(
                    f"fold {aggregator.name!r} on stage {stage.name!r} is not "
                    "device-compilable (callable-based); use expression folds"
                )
            expr = _encode_consts(aggregator.expression, schema)
            slot = agg_slots[aggregator.name]

            def update(env: DeviceEnv, _e=expr) -> Any:
                return _e.evaluate(env)

            folds[i].append((slot, update))

        for edge in stage.edges:
            op = edge.operation
            if op in (EdgeOperation.BEGIN, EdgeOperation.TAKE):
                consume_op[i] = OP_BEGIN if op == EdgeOperation.BEGIN else OP_TAKE
                consume_pred[i] = pred_id(edge.predicate)
                consume_target[i] = index_of[id(edge.target)]
            elif op == EdgeOperation.IGNORE:
                ignore_pred[i] = pred_id(edge.predicate)
            else:
                proceed_kind[i] = (
                    PR_PROCEED if op == EdgeOperation.PROCEED else PR_SKIP
                )
                proceed_pred[i] = pred_id(edge.predicate)
                proceed_target[i] = index_of[id(edge.target)]

    # A stage is a pure forwarder iff its only edge is a PROCEED
    # (ComputationStage.isForwarding); runtime epsilon states are forwarders
    # by construction, so depth below bounds the live descent chain.
    is_fwd = (
        (consume_op == OP_NONE) & (ignore_pred < 0) & (proceed_kind == PR_PROCEED)
    )
    fwd_final = np.zeros(n, bool)
    for i in range(n):
        if is_fwd[i] and proceed_target[i] >= 0:
            fwd_final[i] = bool(is_final[proceed_target[i]])

    # Epsilon-descent unroll depth: 1 level for the run's own (possibly
    # synthesized-epsilon) stage plus the longest static PROCEED/SKIP_PROCEED
    # chain reachable from any stage (SURVEY.md section 7, "Recursive
    # epsilon-evaluation": max depth is static).
    chain = [0] * n
    def _chain(i: int, seen: Tuple[int, ...] = ()) -> int:
        if proceed_kind[i] == PR_NONE or proceed_target[i] < 0:
            return 1
        tgt = int(proceed_target[i])
        if tgt in seen:  # defensive: construction rules never build cycles
            return 1
        return 1 + _chain(tgt, seen + (i,))
    for i in range(n):
        chain[i] = _chain(i)
    max_depth = 1 + max(chain) if n else 1

    return CompiledQuery(
        schema=schema,
        n_stages=n,
        n_preds=len(predicates),
        n_aggs=max(1, len(agg_slots)),
        max_depth=max_depth,
        consume_op=consume_op,
        consume_pred=consume_pred,
        consume_target=consume_target,
        ignore_pred=ignore_pred,
        proceed_kind=proceed_kind,
        proceed_pred=proceed_pred,
        proceed_target=proceed_target,
        window_ms=window_ms,
        name_id=name_id,
        pure_name_id=pure_name_id,
        is_begin=is_begin,
        is_final=is_final,
        is_fwd=is_fwd,
        fwd_final=fwd_final,
        pred_stateful=np.asarray(pred_stateful, bool),
        predicates=predicates,
        folds=folds,
        agg_slots=agg_slots,
        agg_defaults=agg_defaults,
        name_of_id=name_of_id,
        begin_stage=begin_stage,
        host_stages=stages,
        stage_list=stage_list,
    )


def compile_multi_query(
    named_queries: List[Tuple[str, Any]],
    schema: Optional[EventSchema] = None,
) -> CompiledQuery:
    """Stack Q compiled queries into ONE device table set (SURVEY.md §2.8
    "multiple concurrent queries = stacked transition tables").

    The reference runs N independent processor nodes over one topic
    (reference: core/.../kstream/internals/CEPStreamImpl.java:80-93), so N
    queries cost N per-record NFA walks. Here the per-query stage tables
    concatenate with offset stage/predicate/name/register ids, one begin
    lane per query seeds the shared lane pool, and a single device advance
    serves every query -- the event columns are packed once and the kernel's
    unrolled lookups span the union stage table.

    All queries must share one event schema (they observe the same packed
    columns -- pass `schema`, or let one be created here); aggregate fold
    names must be distinct across queries (each register slot is one fold
    cell; a cross-query name collision raises). Match routing back to the
    owning query rides `qid_of_name_id` (chains never span queries).
    """
    from ..pattern.compiler import compile_pattern as _compile_pattern
    from ..pattern.pattern import Pattern

    if not named_queries:
        raise ValueError("compile_multi_query needs at least one query")
    shared_schema = schema if schema is not None else EventSchema()
    names: List[str] = []
    compiled: List[CompiledQuery] = []
    for qname, q in named_queries:
        names.append(str(qname))
        if isinstance(q, CompiledQuery):
            if q.schema is not shared_schema:
                raise ValueError(
                    "stacked CompiledQuery must be compiled against the "
                    "shared schema object (pass Stages/Pattern instead)"
                )
            compiled.append(q)
        elif isinstance(q, Stages):
            compiled.append(compile_query(q, shared_schema))
        elif isinstance(q, Pattern):
            compiled.append(compile_query(_compile_pattern(q), shared_schema))
        else:
            raise TypeError(f"cannot stack {type(q).__name__}")

    agg_slots: Dict[str, int] = {}
    agg_defaults: Dict[str, float] = {}
    predicates: List[Callable] = []
    pred_stateful: List[bool] = []
    name_of_id: List[str] = []
    qid_of_name: List[int] = []
    folds: List[List[Tuple[int, Callable]]] = []
    begin_stages: List[int] = []
    stage_list: List[Stage] = []

    tabs: Dict[str, List[np.ndarray]] = {
        k: []
        for k in (
            "consume_op", "consume_pred", "consume_target", "ignore_pred",
            "proceed_kind", "proceed_pred", "proceed_target", "window_ms",
            "name_id", "pure_name_id", "is_begin", "is_final", "is_fwd",
            "fwd_final",
        )
    }
    stage_off = 0
    pure_off = 0
    for qi, cq in enumerate(compiled):
        pred_off = len(predicates)
        name_off = len(name_of_id)
        agg_off = len(agg_slots)

        def off_ids(t: np.ndarray, off: int) -> np.ndarray:
            return np.where(t >= 0, t + off, t).astype(t.dtype)

        tabs["consume_op"].append(cq.consume_op)
        tabs["consume_pred"].append(off_ids(cq.consume_pred, pred_off))
        tabs["consume_target"].append(off_ids(cq.consume_target, stage_off))
        tabs["ignore_pred"].append(off_ids(cq.ignore_pred, pred_off))
        tabs["proceed_kind"].append(cq.proceed_kind)
        tabs["proceed_pred"].append(off_ids(cq.proceed_pred, pred_off))
        tabs["proceed_target"].append(off_ids(cq.proceed_target, stage_off))
        tabs["window_ms"].append(cq.window_ms)
        tabs["name_id"].append(cq.name_id + name_off)
        tabs["pure_name_id"].append(cq.pure_name_id + pure_off)
        tabs["is_begin"].append(cq.is_begin)
        tabs["is_final"].append(cq.is_final)
        tabs["is_fwd"].append(cq.is_fwd)
        tabs["fwd_final"].append(cq.fwd_final)

        predicates.extend(cq.predicates)
        pred_stateful.extend(bool(b) for b in cq.pred_stateful)
        name_of_id.extend(cq.name_of_id)
        qid_of_name.extend([qi] * len(cq.name_of_id))
        for agg_name, slot in cq.agg_slots.items():
            if agg_name in agg_slots:
                raise ValueError(
                    f"aggregate name {agg_name!r} appears in more than one "
                    "stacked query; fold registers are per-name cells -- "
                    "rename the fold in one of the queries"
                )
            agg_slots[agg_name] = agg_off + slot
            agg_defaults[agg_name] = cq.agg_defaults.get(agg_name, 0.0)
        for stage_folds in cq.folds:
            folds.append([(agg_off + slot, fn) for slot, fn in stage_folds])
        begin_stages.append(stage_off + cq.begin_stage)
        stage_list.extend(cq.stage_list)

        stage_off += cq.n_stages
        pure_off += int(cq.pure_name_id.max()) + 1 if cq.n_stages else 0

    return CompiledQuery(
        schema=shared_schema,
        n_stages=stage_off,
        n_preds=len(predicates),
        n_aggs=max(1, len(agg_slots)),
        max_depth=max(cq.max_depth for cq in compiled),
        consume_op=np.concatenate(tabs["consume_op"]),
        consume_pred=np.concatenate(tabs["consume_pred"]),
        consume_target=np.concatenate(tabs["consume_target"]),
        ignore_pred=np.concatenate(tabs["ignore_pred"]),
        proceed_kind=np.concatenate(tabs["proceed_kind"]),
        proceed_pred=np.concatenate(tabs["proceed_pred"]),
        proceed_target=np.concatenate(tabs["proceed_target"]),
        window_ms=np.concatenate(tabs["window_ms"]),
        name_id=np.concatenate(tabs["name_id"]),
        pure_name_id=np.concatenate(tabs["pure_name_id"]),
        is_begin=np.concatenate(tabs["is_begin"]),
        is_final=np.concatenate(tabs["is_final"]),
        is_fwd=np.concatenate(tabs["is_fwd"]),
        fwd_final=np.concatenate(tabs["fwd_final"]),
        pred_stateful=np.asarray(pred_stateful, bool),
        predicates=predicates,
        folds=folds,
        agg_slots=agg_slots,
        agg_defaults=agg_defaults,
        name_of_id=name_of_id,
        begin_stage=begin_stages[0],
        # Exact-replay needs ONE host stage graph; a stacked query keeps
        # detection-only semantics (ops/replay.py supports_replay -> False).
        host_stages=None,
        stage_list=stage_list,
        begin_stages=begin_stages,
        qid_of_name_id=np.asarray(qid_of_name, np.int32),
        query_names=names,
    )
