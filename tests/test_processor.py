"""Processor-level conformance (reference: CEPProcessorTest.java:101-135):
null key/value tolerance and high-water-mark replay dedup across topics."""
from kafkastreams_cep_tpu import CEPProcessor, QueryBuilder, value
from kafkastreams_cep_tpu.models.letters import letters_pattern


def make_processor():
    return CEPProcessor("test-query", letters_pattern())


def test_null_key_or_value_skipped():
    p = make_processor()
    assert p.process(None, "A") == []
    assert p.process("k", None) == []
    assert len(p.nfa_store) == 0


def test_high_water_mark_dedup():
    p = make_processor()
    p.process("k", "A", topic="t1", offset=0)
    p.process("k", "B", topic="t1", offset=1)
    # Replay below the HWM: ignored, state unchanged.
    assert p.process("k", "Z", topic="t1", offset=0) == []
    matches = p.process("k", "C", topic="t1", offset=2)
    assert len(matches) == 1


def test_high_water_mark_is_per_topic():
    p = make_processor()
    p.process("k", "A", topic="t1", offset=5)
    # A different topic has its own high-water mark; offset 0 is fine there.
    p.process("k", "B", topic="t2", offset=0)
    matches = p.process("k", "C", topic="t1", offset=6)
    assert len(matches) == 1


def test_match_across_restore():
    """Snapshot/restore: a fresh processor over the same stores resumes runs."""
    p1 = make_processor()
    p1.process("k", "A", topic="t1", offset=0)
    p1.process("k", "B", topic="t1", offset=1)

    p2 = CEPProcessor(
        "test-query",
        letters_pattern(),
        nfa_store=p1.nfa_store,
        buffer=p1.buffer,
        aggregates=p1.aggregates,
    )
    matches = p2.process("k", "C", topic="t1", offset=2)
    assert len(matches) == 1
    staged = [(s.stage, [e.value for e in s.events]) for s in matches[0].matched]
    assert staged == [
        ("select-A", ["A"]),
        ("select-B", ["B"]),
        ("select-C", ["C"]),
    ]


def test_device_processor_warns_on_low_key_cardinality():
    """runtime-choice guidance made operational (README "Choosing a
    runtime"): a persistently ~single-key stream on the device processor
    warns once that runtime="host" is faster."""
    import warnings as _warnings

    import pytest

    from kafkastreams_cep_tpu.streams.device_processor import DeviceCEPProcessor

    pattern = (
        QueryBuilder()
        .select("a").where(value() == "A")
        .then().select("b").where(value() == "B")
        .build()
    )
    proc = DeviceCEPProcessor("q", pattern, batch_size=2)
    with pytest.warns(RuntimeWarning, match="distinct key"):
        for i in range(2 * DeviceCEPProcessor.LOW_KEY_WARN_FLUSHES + 2):
            proc.process("only-key", "A" if i % 2 else "B", timestamp=i, offset=i)
    # ...and only once.
    with _warnings.catch_warnings():
        _warnings.simplefilter("error")
        for i in range(100, 104):
            proc.process("only-key", "A", timestamp=i, offset=i)
        proc.flush()


def test_batched_warns_on_collisions_without_replay():
    """With exact_replay off, a fired fold-divergence detector must surface
    as a warning at drain, not stay a silent counter (VERDICT r4 weak #6)."""
    import random

    import pytest
    from test_differential import ALPHABET, _branchy_pattern

    from kafkastreams_cep_tpu import Event, compile_pattern
    from kafkastreams_cep_tpu.ops.engine import EngineConfig
    from kafkastreams_cep_tpu.ops.tables import compile_query
    from kafkastreams_cep_tpu.parallel import BatchedDeviceNFA

    # The hunted colliding shape (differential seed 72).
    rng = random.Random(50_072)
    pattern = _branchy_pattern(rng)
    events = []
    ts = 1000
    for i in range(20):
        ts += rng.choice([0, 1, 1, 2])
        events.append(Event("k", rng.choice(ALPHABET), ts, "t", 0, i))
    bat = BatchedDeviceNFA(
        compile_query(compile_pattern(pattern), None),
        keys=["k"],
        config=EngineConfig(lanes=256, nodes=4096, matches=2048,
                            matches_per_step=256),
        exact_replay=False,
    )
    with pytest.warns(RuntimeWarning, match="seq_collisions"):
        for b in range(0, 20, 5):
            bat.advance({"k": events[b : b + 5]})
    assert bat.stats["seq_collisions"] > 0
