"""Key-axis parallelism: vmapped multi-key engine + mesh sharding."""

from .batched import BatchedDeviceNFA
from .drain_sched import AdmissionPacer, CapacityAutosizer, DrainController
from .stacked import StackedQueryEngine
from .key_shard import (
    KEY_AXIS,
    build_batched_advance,
    build_batched_append,
    build_batched_flush,
    build_batched_post,
    global_stats,
    init_batched_pool,
    init_batched_state,
    key_mesh,
    key_sharding,
    shard_state,
    shard_xs,
)

__all__ = [
    "AdmissionPacer",
    "BatchedDeviceNFA",
    "CapacityAutosizer",
    "DrainController",
    "StackedQueryEngine",
    "KEY_AXIS",
    "build_batched_advance",
    "build_batched_append",
    "build_batched_flush",
    "build_batched_post",
    "global_stats",
    "init_batched_pool",
    "init_batched_state",
    "key_mesh",
    "key_sharding",
    "shard_state",
    "shard_xs",
]
