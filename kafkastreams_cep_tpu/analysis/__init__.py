"""ceplint: invariant-enforcing static analysis for the CEP engine.

The engine's hardest-won properties are behavioral contracts no type
checker sees: the zero-sync advance path (NFA^b runs must branch without
host round-trips), single-writer-or-locked shared state under the obs /
driver / decode threads, stable jit caches across traffic churn, and
serde frames that round-trip every field of every checkpointed
structure. Each has already produced a real production bug (SOAK_r01's
churn-recompile RSS leak; PR 9's gate-state atomicity bug) that hand
review missed -- this package turns those invariant classes into
machine-checked lints over the stdlib `ast`.

Checkers (each with a seeded mutation fixture under tests/fixtures/lint/
proving it can fail):

- ``zerosync``  host-sync constructs inside hot-path functions
- ``threads``   attributes written from >= 2 thread roots outside a lock
- ``recompile`` jit-cache hazards (jit-in-loop, mutable static args,
                closures over mutable state)
- ``serde``     checkpoint field round-trip completeness
- ``metrics``   cep_* metric names vs the PERF.md dictionary

Audited sites are annotated in source with the pragma grammar
``# cep: <kind>(<reason>)`` (see analysis/core.py); residual accepted
findings live in the committed ``ceplint.baseline.json``. The CLI is
``scripts/ceplint.py``; ``tests/test_lint.py`` runs the whole gate in
tier-1. Runtime companions: ``analysis/lockmon.py`` (instrumented-lock
lock-order cycle detection, armed in the chaos and quick-soak tests) and
``analysis/jit_audit.py`` (replays a churn epoch and asserts
``cep_compiles_total{fn}`` stays flat for unchanged shapes -- SOAK_r01's
leak class as a red test).
"""
from .core import (  # noqa: F401
    Finding,
    Pragma,
    SourceFile,
    iter_source_files,
    run_checkers,
    CHECKERS,
)
