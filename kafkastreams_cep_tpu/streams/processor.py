"""Per-record CEP processor: the host-path stream driver.

Re-design of the reference processor
(reference: core/.../cep/processor/CEPProcessor.java:45-171). Per record it
loads (or creates) the key's NFA from the states store, applies the
high-water-mark idempotence check (skip records whose offset is below the
persisted offset for their topic), runs the match loop, persists the updated
snapshot, and forwards each completed Sequence downstream.

The TPU path replaces the inner `nfa.match_pattern` call with the
micro-batched device engine while keeping this store/HWM contract
(ops/engine.py, streams/device_processor.py).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Generic, List, Optional, Tuple, TypeVar

from ..core.event import Event
from ..core.sequence import Sequence
from ..nfa.nfa import NFA, initial_computation_stage
from ..pattern.compiler import ensure_stages
from ..pattern.stages import Stages
from ..state.aggregates import AggregatesStore
from ..state.buffer import BufferStore
from ..state.naming import normalize_query_name
from ..state.nfa_store import NFAStates, NFAStore

K = TypeVar("K")
V = TypeVar("V")


class CEPProcessor(Generic[K, V]):
    """Host per-record driver bound to the three query stores."""

    def __init__(
        self,
        query_name: str,
        pattern_or_stages: Any,
        nfa_store: Optional[NFAStore] = None,
        buffer: Optional[BufferStore] = None,
        aggregates: Optional[AggregatesStore] = None,
        strict_windows: bool = False,
        registry: Optional[Any] = None,
        reorder_capacity: int = 0,
        lateness_ms: int = 0,
        late_policy: str = "drop",
        reorder_overflow: str = "drop",
        watermark_gen: Optional[Any] = None,
    ) -> None:
        from ..obs.registry import default_registry

        self.stages: Stages = ensure_stages(pattern_or_stages)
        self.query_name = normalize_query_name(query_name)
        self.nfa_store = nfa_store if nfa_store is not None else NFAStore()
        self.buffer = buffer if buffer is not None else BufferStore()
        self.aggregates = aggregates if aggregates is not None else AggregatesStore()
        # See NFA(strict_windows=...): False = reference window parity,
        # True = epsilon stages inherit windows (bounded-memory mode).
        self.strict_windows = strict_windows
        # Per-query stream counters (labels bounded by the query count):
        # the always-on host-path telemetry, in the process default
        # registry unless one is passed.
        self.metrics = registry if registry is not None else default_registry()
        # Children bound once: labels() takes a lock per resolution, and
        # this is the per-record hot path (also the vs_baseline denominator).
        self._m_records = self.metrics.counter(
            "cep_processor_records_total",
            "Records processed by the host per-record driver",
            labels=("query",),
        ).labels(query=self.query_name)
        self._m_matches = self.metrics.counter(
            "cep_processor_matches_total",
            "Completed sequences emitted by the host per-record driver",
            labels=("query",),
        ).labels(query=self.query_name)
        self._m_skipped = self.metrics.counter(
            "cep_processor_skipped_total",
            "Records skipped below the high-water mark (at-least-once dedup)",
            labels=("query",),
        ).labels(query=self.query_name)
        self._m_errors = self.metrics.counter(
            "cep_processor_errors_total",
            "Records whose match loop raised (user predicate/fold errors; "
            "the driver quarantines them to the DLQ)",
            labels=("query",),
        ).labels(query=self.query_name)
        # Event-time gate (ISSUE 10): with reorder_capacity > 0 arriving
        # records route through a bounded per-key reorder buffer and the
        # match loop runs on the watermark's event-time-ordered releases.
        # The host NFA's expiry clock is each record's own timestamp, so
        # the released (sorted) stream gives reference-exact event-time
        # semantics; `recompute-none` late admissions process at their raw
        # (older) timestamp -- the documented best-effort mode.
        self.gate = None
        #: Arrival-side HWM for the gated mode: IN-MEMORY on purpose. A
        #: record below the mark was already offered to the gate, so the
        #: mark must live and die with the gate contents it guards --
        #: both checkpoint atomically (event_time_state / the event-time
        #: changelog store), never through the per-record nfa_store
        #: offsets, whose changelog would make the mark durable while the
        #: buffered record it covers evaporates on crash.
        self._arrival_hwm: Dict[Tuple[Any, str], int] = {}
        self._et_opts = dict(
            reorder_capacity=reorder_capacity, lateness_ms=lateness_ms,
            late_policy=late_policy, reorder_overflow=reorder_overflow,
        )
        if reorder_capacity > 0:
            from ..time import EventTimeGate

            self.gate = EventTimeGate(
                capacity=reorder_capacity,
                lateness_ms=lateness_ms,
                late_policy=late_policy,
                on_overflow=reorder_overflow,
                generator=watermark_gen,
                registry=self.metrics,
                query_name=self.query_name,
            )

    def _load_nfa(self, key: K) -> Tuple[NFA, NFAStates]:
        snapshot = self.nfa_store.find(key)
        key_buffer = self.buffer.for_key(key)
        if snapshot is not None:
            nfa = NFA(
                self.aggregates,
                key_buffer,
                self.stages.defined_states(),
                snapshot.computation_stages,
                snapshot.runs,
                strict_windows=self.strict_windows,
            )
            return nfa, snapshot
        nfa = NFA.build(
            self.stages, self.aggregates, key_buffer,
            strict_windows=self.strict_windows,
        )
        return nfa, NFAStates(list(nfa.computation_stages), nfa.runs)

    def process(
        self,
        key: K,
        value: V,
        timestamp: int = 0,
        topic: str = "",
        partition: int = 0,
        offset: int = 0,
    ) -> List[Sequence[K, V]]:
        """Process one record; returns completed matches for this key.

        With an event-time gate armed, the arriving record is deduped (and
        its high-water mark advanced) at ARRIVAL, then buffered; the match
        loop runs on whatever the watermark released -- possibly other
        keys' earlier records, possibly nothing yet."""
        if key is None or value is None:
            return []
        event = Event(key, value, timestamp, topic, partition, offset)
        if self.gate is None:
            return self._process_event(event)
        return [seq for _k, seq in self._process_gated(event)]

    def process_keyed(
        self,
        key: K,
        value: V,
        timestamp: int = 0,
        topic: str = "",
        partition: int = 0,
        offset: int = 0,
    ) -> List[Tuple[K, Sequence[K, V]]]:
        """Like process(), but every match carries ITS OWN key. With an
        event-time gate armed, one arriving record can release OTHER
        keys' buffered records -- the topology must attribute those
        matches (sink keys, emission-dedup digests) to the key that
        matched, never to the arrival that triggered the release."""
        if key is None or value is None:
            return []
        event = Event(key, value, timestamp, topic, partition, offset)
        if self.gate is None:
            return [(key, s) for s in self._process_event(event)]
        return self._process_gated(event)

    def _process_gated(self, event: Event) -> List[Tuple[K, Sequence[K, V]]]:
        if self._arrival_below_hwm(event):
            self._m_skipped.inc()
            return []
        # Admission first (may raise CEPOverflowError under
        # on_overflow="raise" -- the HWM must stay untouched so a retry
        # of the rejected record is not deduped as a replay), THEN the
        # durable arrival mark, then the released records' match loops.
        released = self.gate.offer(event)
        self._advance_arrival_hwm(event)
        out: List[Tuple[K, Sequence[K, V]]] = []
        for ev, _clk in released:
            out.extend(
                (ev.key, s) for s in self._process_event(ev, check_hwm=False)
            )
        return out

    def _arrival_below_hwm(self, event: Event) -> bool:
        """Arrival-side HWM dedup (gate armed): released records were
        already deduped here, so the match loop skips the re-check -- the
        release-side mark would otherwise reject every buffered record
        behind its own arrival."""
        latest = self._arrival_hwm.get(
            (event.key, f"{event.topic}#{event.partition}")
        )
        return latest is not None and event.offset < latest

    def _advance_arrival_hwm(self, event: Event) -> None:
        """Advance the arrival mark AFTER gate admission succeeded (a
        CEPOverflowError rejection must leave it untouched, or the retry
        would be deduped as a replay)."""
        self._arrival_hwm[
            (event.key, f"{event.topic}#{event.partition}")
        ] = event.offset + 1

    def event_time_state(self) -> Dict[str, Any]:
        """Gate contents + arrival marks as ONE state dict: the two are
        meaningless apart (a durable mark over lost buffer contents is a
        silent record loss), so every durability surface -- snapshot()
        and the event-time changelog store -- carries them together."""
        state = self.gate.snapshot_state()
        state["hwm"] = dict(self._arrival_hwm)
        return state

    def restore_event_time(self, state: Dict[str, Any]) -> None:
        self.gate.restore_state(state)
        self._arrival_hwm = dict(state.get("hwm", {}))

    def _process_event(
        self, event: Event, check_hwm: bool = True
    ) -> List[Sequence[K, V]]:
        nfa, snapshot = self._load_nfa(event.key)

        # The reference keys the HWM by topic only because each of its
        # processor tasks owns exactly one partition; here one processor may
        # see every partition, so the mark is per (topic, partition).
        hwm_key = f"{event.topic}#{event.partition}"
        if check_hwm:
            latest = snapshot.latest_offset_for_topic(hwm_key)
            if latest is not None and event.offset < latest:
                # Replayed record below the high-water mark: at-least-once
                # dedup.
                self._m_skipped.inc()
                return []

        try:
            sequences = nfa.match_pattern(event)
        except Exception:
            # A raising user predicate/fold is poison, not a pipeline bug:
            # count it here (per query) and let the driver quarantine the
            # record to the DLQ with the pump still advancing. The key's
            # stored snapshot is untouched (it persists below only on
            # success), so the next record resumes from pre-poison state.
            self._m_errors.inc()
            raise
        self._m_records.inc()
        if sequences:
            self._m_matches.inc(len(sequences))

        offsets = dict(snapshot.latest_offsets)
        if check_hwm:
            offsets[hwm_key] = event.offset + 1
        self.nfa_store.put(
            event.key,
            NFAStates(list(nfa.computation_stages), nfa.runs, offsets),
        )
        # Re-put the key's buffer so a change-logging backing captures this
        # record's in-place chain mutations (CEPProcessor.java:144-147
        # persists all three stores every record).
        self.buffer.persist(event.key)
        return sequences

    # ---------------------------------------------------------- event time
    def tick_event_time(self, now_ms: int) -> List[Tuple[K, Sequence[K, V]]]:
        """Wall-clock tick (idle-source watermarks); returns [(key, seq)]
        for matches the released records completed."""
        if self.gate is None:
            return []
        out: List[Tuple[K, Sequence[K, V]]] = []
        for ev, _clk in self.gate.advance_wall(now_ms):
            out.extend(
                (ev.key, s) for s in self._process_event(ev, check_hwm=False)
            )
        return out

    def flush_event_time(self) -> List[Tuple[K, Sequence[K, V]]]:
        """End-of-stream: run the match loop over every buffered record in
        event-time order."""
        if self.gate is None:
            return []
        out: List[Tuple[K, Sequence[K, V]]] = []
        for ev, _clk in self.gate.flush():
            out.extend(
                (ev.key, s) for s in self._process_event(ev, check_hwm=False)
            )
        return out

    def take_late(self) -> List[Event]:
        """Drain the gate's late side output (late_policy=sideoutput)."""
        return self.gate.take_late() if self.gate is not None else []

    # --------------------------------------------------------- checkpointing
    def snapshot(self) -> bytes:
        """Bytes-level checkpoint of the query's three stores (the changelog
        write, reference: CEPProcessor.java:144-147 + store serdes). With
        an event-time gate armed, the gate's reorder buffers + watermark
        state ride a wrapper frame (state/serde.wrap_event_time)."""
        from ..state.serde import (
            CheckpointCodec,
            encode_event_time_state,
            wrap_event_time,
        )

        codec = CheckpointCodec(self.stages, strict_windows=self.strict_windows)
        data = codec.encode_query_stores(
            self.nfa_store, self.buffer, self.aggregates
        )
        if self.gate is not None:
            data = wrap_event_time(
                data, encode_event_time_state(self.event_time_state())
            )
        return data

    @classmethod
    def restore(
        cls,
        query_name: str,
        pattern_or_stages: Any,
        data: bytes,
        strict_windows: bool = False,
        **et_opts: Any,
    ) -> "CEPProcessor":
        """Rebuild a processor from `snapshot()` bytes in a fresh object
        graph: the pattern is recompiled and run-queue stages re-linked by
        id (ComputationStageSerde.java:56-101). Event-time knobs
        (reorder_capacity, lateness_ms, late_policy, reorder_overflow,
        watermark_gen) must match the snapshotting processor's for the
        gate state to restore."""
        from ..state.serde import (
            CheckpointCodec,
            decode_event_time_state,
            split_event_time,
        )

        data, gate_bytes = split_event_time(data)
        proc = cls(
            query_name, pattern_or_stages, strict_windows=strict_windows,
            **et_opts,
        )
        if gate_bytes is not None and proc.gate is None:
            raise ValueError(
                "checkpoint carries event-time gate state but the restored "
                "processor has no gate; pass the original reorder_capacity "
                "(and friends) to restore()"
            )
        codec = CheckpointCodec(proc.stages, strict_windows=strict_windows)
        nfa_store, buffers, aggregates = codec.decode_query_stores(data)
        proc.nfa_store = nfa_store
        proc.buffer = buffers
        proc.aggregates = aggregates
        if gate_bytes is not None:
            proc.restore_event_time(decode_event_time_state(gate_bytes))
        return proc
