"""Shared versioned buffer: the SASE partial-match store, exact-lineage form.

Re-design of the reference buffer
(reference: core/.../cep/state/SharedVersionedBufferStore.java:32-77,
state/internal/SharedVersionedBufferStoreImpl.java:45-212,
state/internal/MatchedEvent.java, state/internal/Matched.java). The
reference stores partial matches of all simultaneous runs in one pointer
graph whose nodes are keyed by (stage, event) and whose predecessor pointers
are tagged with Dewey versions; extraction walks backwards choosing the
pointer whose version is Dewey-compatible with the requested one
(SharedVersionedBufferStoreImpl.java:176-201, MatchedEvent.java:90-98).

That routing is ambiguous: two runs can legitimately carry EQUAL version
digits after independent addRun() bumps (e.g. a branch clone parked on an
epsilon stage and an ordinary run, both at version "2.0"), and when both
consume the same event at the same stage the shared node holds two pointers
tagged "2.0" -- extraction then splices one run's prefix onto the other
run's match and silently drops events the run actually consumed. This is
observable in the reference itself; it is a correctness bug, not a
behavior to reproduce.

This store therefore keeps the reference's *sharing* (branch clones share
their prefix chain -- the SASE space optimization) but drops the ambiguous
cross-run node merging: every put appends a fresh node holding an exact
parent index, each run tracks its chain head by node id
(ComputationStage.last_node), and extraction is a plain parent walk --
unambiguous by construction. This is the same scheme as the device engine's
HBM node pool (ops/engine.py: node_pred per slot, per-lane `node` index),
which makes host and device agree on match lineage by design. Refcounts are
replaced by mark-sweep reclamation from the live runs' chain heads (`gc`),
the host analog of the device's batch-boundary compaction
(ops/runtime.py:_compact).
"""
from __future__ import annotations

from typing import Any, Dict, Generic, Iterable, Optional, TypeVar

from ..core.event import Event
from ..core.sequence import Sequence, SequenceBuilder

K = TypeVar("K")
V = TypeVar("V")


class BufferNode(Generic[K, V]):
    """One appended event in a run's lineage chain (MatchedEvent analog)."""

    __slots__ = ("stage_name", "event", "parent")

    def __init__(self, stage_name: str, event: Event[K, V], parent: Optional[int]) -> None:
        self.stage_name = stage_name
        self.event = event
        self.parent = parent

    def __repr__(self) -> str:
        return f"BufferNode(stage={self.stage_name!r}, event={self.event!r}, parent={self.parent})"


class SharedVersionedBuffer(Generic[K, V]):
    """Append-only lineage store with shared prefixes (the host oracle store).

    API shape follows the reference contract
    (SharedVersionedBufferStore.java:32-77) translated to index-linked
    chains: `put` appends and returns the new chain head, `get` materializes
    a chain into a `Sequence`, and reclamation is `gc` over live heads
    instead of per-extraction refcount decrements.
    """

    def __init__(self) -> None:
        self._nodes: Dict[int, BufferNode[K, V]] = {}
        self._next_id = 0

    def __len__(self) -> int:
        return len(self._nodes)

    # -- writes --------------------------------------------------------------
    def put(self, stage_name: str, event: Event[K, V], parent: Optional[int] = None) -> int:
        """Append one consumed event chained to `parent`; returns its node id.

        The root put (parent None) starts a new lineage
        (SharedVersionedBufferStoreImpl.java:149-157); a chained put is the
        reference's predecessor-linked put (:101-126) without the version
        tag -- the parent index IS the (unambiguous) pointer.
        """
        if parent is not None and parent not in self._nodes:
            raise ValueError(f"Cannot find predecessor node {parent}")
        node_id = self._next_id
        self._next_id += 1
        self._nodes[node_id] = BufferNode(stage_name, event, parent)
        return node_id

    # -- reads ---------------------------------------------------------------
    def get(self, head: Optional[int]) -> Sequence[K, V]:
        """Materialize the chain ending at `head`, oldest stage first.

        The analog of peek(remove=false): sequence assembly in reverse while
        walking predecessors (SharedVersionedBufferStoreImpl.java:176-201,
        Sequence.java:211-222).
        """
        builder: SequenceBuilder[K, V] = SequenceBuilder()
        node_id = head
        while node_id is not None:
            node = self._nodes[node_id]
            builder.add(node.stage_name, node.event)
            node_id = node.parent
        return builder.build(reversed_=True)

    # -- reclamation ---------------------------------------------------------
    def gc(self, live_heads: Iterable[Optional[int]]) -> int:
        """Mark-sweep: keep only chains reachable from live runs' heads.

        Replaces the reference's refcount decrements during extraction
        (which, combined with branch() pinning, leak shared chains -- see
        round-2 analysis). Returns the number of reclaimed nodes.
        """
        marked: set = set()
        for head in live_heads:
            node_id = head
            while node_id is not None and node_id not in marked:
                marked.add(node_id)
                node_id = self._nodes[node_id].parent
        dead_ids = [i for i in self._nodes if i not in marked]
        for i in dead_ids:
            del self._nodes[i]
        return len(dead_ids)


class ReadOnlySharedVersionBuffer(Generic[K, V]):
    """Read-only facade handed to sequence predicates (ReadOnlySharedVersionBuffer.java)."""

    def __init__(self, buffer: SharedVersionedBuffer[K, V]) -> None:
        self._buffer = buffer

    def get(self, head: Optional[int]) -> Sequence[K, V]:
        return self._buffer.get(head)


class BufferStore(Generic[K, V]):
    """The query-level buffer state store: one lineage buffer per record key.

    The reference keeps all keys' partial matches in a single KV store
    (SharedVersionedBufferStoreImpl.java:49) -- safe there because node keys
    embed event identity and reclamation is per-chain refcounts. With
    mark-sweep reclamation, sharing one arena across keys would let one
    key's GC see only its own live heads, so the store is partitioned per
    record key (chains never cross keys: each key owns its NFA,
    CEPProcessor.java:111-124). The device engine partitions identically
    (one node pool per key lane, parallel/key_shard.py).
    """

    def __init__(self, backing: Optional[Any] = None) -> None:
        if backing is None:
            from .store import InMemoryKeyValueStore

            backing = InMemoryKeyValueStore("event-buffer")
        self._kv = backing

    def for_key(self, key: Any) -> SharedVersionedBuffer[K, V]:
        buffer = self._kv.get(key)
        if buffer is None:
            buffer = SharedVersionedBuffer()
            self._kv.put(key, buffer)
        return buffer

    def persist(self, key: Any) -> None:
        """Re-put the key's buffer so a change-logging backing captures the
        in-place mutations the NFA made this record (the reference's store
        writes each node mutation individually,
        SharedVersionedBufferStoreImpl.java:117-126; here the changelog
        granularity is the per-key chain store)."""
        buffer = self._kv.get(key)
        if buffer is not None:
            self._kv.put(key, buffer)

    def items(self):
        return self._kv.items()

    def set_for_key(self, key: Any, buffer: SharedVersionedBuffer[K, V]) -> None:
        self._kv.put(key, buffer)

    def flush(self) -> None:
        self._kv.flush()

    def __len__(self) -> int:
        return sum(len(b) for _k, b in self._kv.items())
