"""ceplint core: findings, the pragma grammar, and the checker driver.

Pragma grammar (one or more per line, in any comment):

    # cep: <kind>(<reason>)

kinds:
    hot-path            marks the following/containing ``def`` as a
                        zero-sync hot-path function (no reason needed)
    sync-ok(<reason>)   audited host sync on this line (zerosync)
    thread-ok(<reason>) audited unlocked shared write (threads)
    static-ok(<reason>) audited jit-cache hazard (recompile)
    serde-ok(<reason>)  audited serde field exclusion (serde)
    metric-ok(<reason>) audited metric-dictionary exception (metrics)
    trace-ok(<reason>)  audited trace-free control-plane append (tracectx)

A suppression pragma without a reason is itself a finding (CEP-P01): an
audit that does not say *why* the invariant may bend is not an audit.
Findings are fingerprinted line-number-free (checker | code | path |
normalized source line | occurrence index) so unrelated edits do not
churn the committed baseline.
"""
from __future__ import annotations

import ast
import hashlib
import os
import re
import tokenize
from dataclasses import dataclass, field
from io import StringIO
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "dotted_name",
    "Finding",
    "Pragma",
    "SourceFile",
    "iter_source_files",
    "run_checkers",
    "CHECKERS",
    "repo_root",
]

def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for an Attribute/Name chain, None for anything else --
    the shared AST helper every checker resolves call targets with."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


#: kinds that suppress a checker's findings on their line, mapped to the
#: checker family they may suppress.
SUPPRESSION_KINDS = {
    "sync-ok": "zerosync",
    "thread-ok": "threads",
    "static-ok": "recompile",
    "serde-ok": "serde",
    "metric-ok": "metrics",
    "trace-ok": "tracectx",
}
#: kinds that annotate rather than suppress.
MARKER_KINDS = ("hot-path",)

_PRAGMA_RE = re.compile(
    r"#\s*cep:\s*(?P<kind>[a-z][a-z0-9-]*)\s*(?:\((?P<reason>[^)]*)\))?"
)


@dataclass(frozen=True)
class Pragma:
    kind: str
    reason: Optional[str]
    line: int  # 1-based

    @property
    def has_reason(self) -> bool:
        return bool(self.reason and self.reason.strip())


@dataclass
class Finding:
    checker: str
    code: str  # CEP-XNN
    path: str  # repo-relative, "/"-separated
    line: int  # 1-based; 0 for file-level findings
    message: str
    #: normalized source context (fingerprint input, line-number free)
    context: str = ""
    #: disambiguates identical (code, path, context) findings
    occurrence: int = 0
    suppressed_by: Optional[Pragma] = None
    baselined: bool = False

    def fingerprint(self) -> str:
        raw = "|".join(
            (
                self.checker,
                self.code,
                self.path,
                self.context.strip(),
                str(self.occurrence),
            )
        )
        return hashlib.sha1(raw.encode("utf-8")).hexdigest()[:16]

    def render(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"{loc}: {self.code} [{self.checker}] {self.message}"


class SourceFile:
    """One analyzed file: source text, AST, and per-line pragmas."""

    def __init__(self, path: str, relpath: str, text: str) -> None:
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        #: {line -> [Pragma]} from real comment tokens (never string
        #: literals -- a docstring describing the grammar must not arm it).
        self.pragmas: Dict[int, List[Pragma]] = {}
        for tok in _iter_comments(text):
            for m in _PRAGMA_RE.finditer(tok.string):
                self.pragmas.setdefault(tok.start[0], []).append(
                    Pragma(m.group("kind"), m.group("reason"), tok.start[0])
                )

    # ---------------------------------------------------------------- pragmas
    def pragmas_on(self, line: int, kind: str) -> List[Pragma]:
        return [p for p in self.pragmas.get(line, []) if p.kind == kind]

    def suppression(self, line: int, checker: str) -> Optional[Pragma]:
        """The first well-formed suppression pragma for `checker` on
        `line` (a reasonless pragma does not suppress -- CEP-P01)."""
        for p in self.pragmas.get(line, []):
            if SUPPRESSION_KINDS.get(p.kind) == checker and p.has_reason:
                return p
        return None

    def has_marker(self, line: int, kind: str) -> bool:
        return any(p.kind == kind for p in self.pragmas.get(line, []))

    def context_line(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""


def _iter_comments(text: str):
    try:
        for tok in tokenize.generate_tokens(StringIO(text).readline):
            if tok.type == tokenize.COMMENT:
                yield tok
    except tokenize.TokenError:  # pragma: no cover - ast.parse catches first
        return


def pragma_findings(src: SourceFile) -> List[Finding]:
    """Pragma-grammar findings: reasonless suppressions and unknown kinds."""
    out: List[Finding] = []
    known = set(SUPPRESSION_KINDS) | set(MARKER_KINDS)
    for line, pragmas in sorted(src.pragmas.items()):
        for p in pragmas:
            if p.kind not in known:
                out.append(
                    Finding(
                        "pragma", "CEP-P02", src.relpath, line,
                        f"unknown pragma kind {p.kind!r} "
                        f"(known: {', '.join(sorted(known))})",
                        context=src.context_line(line),
                    )
                )
            elif p.kind in SUPPRESSION_KINDS and not p.has_reason:
                out.append(
                    Finding(
                        "pragma", "CEP-P01", src.relpath, line,
                        f"pragma {p.kind} has no reason -- an audit must "
                        "say why the invariant may bend here",
                        context=src.context_line(line),
                    )
                )
    return out


# ---------------------------------------------------------------------------
# file discovery + driver
# ---------------------------------------------------------------------------
def repo_root() -> str:
    """The repository root (two levels above this package)."""
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


#: roots scanned by ``ceplint --all``, relative to the repo root.
DEFAULT_ROOTS = ("kafkastreams_cep_tpu", "scripts", "bench.py")
#: never analyzed: generated, vendored, or non-source trees.
EXCLUDE_PARTS = ("__pycache__", ".jax_cache", "_build", "fixtures")


def iter_source_files(
    roots: Iterable[str] = DEFAULT_ROOTS, root_dir: Optional[str] = None
) -> List[SourceFile]:
    root_dir = root_dir or repo_root()
    paths: List[str] = []
    for root in roots:
        full = root if os.path.isabs(root) else os.path.join(root_dir, root)
        if os.path.isfile(full):
            paths.append(full)
            continue
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames[:] = [d for d in dirnames if d not in EXCLUDE_PARTS]
            for name in sorted(filenames):
                if name.endswith(".py"):
                    paths.append(os.path.join(dirpath, name))
    out: List[SourceFile] = []
    for path in sorted(set(paths)):
        rel = os.path.relpath(path, root_dir)
        with open(path, "r", encoding="utf-8") as f:
            text = f.read()
        out.append(SourceFile(path, rel, text))
    return out


def _number_occurrences(findings: List[Finding]) -> None:
    """Stable occurrence indices for otherwise-identical fingerprints."""
    seen: Dict[Tuple[str, str, str, str], int] = {}
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.code)):
        key = (f.checker, f.code, f.path, f.context.strip())
        f.occurrence = seen.get(key, 0)
        seen[key] = f.occurrence + 1


def run_checkers(
    files: List[SourceFile],
    checkers: Optional[Iterable[str]] = None,
    root_dir: Optional[str] = None,
) -> List[Finding]:
    """Run the named checkers (all when None) over `files`.

    Returns every finding, with `suppressed_by` set where a well-formed
    pragma covered the line; pragma-grammar findings always run.
    """
    root_dir = root_dir or repo_root()
    names = list(checkers) if checkers is not None else list(CHECKERS)
    findings: List[Finding] = []
    for src in files:
        findings.extend(pragma_findings(src))
    for name in names:
        if name not in CHECKERS:
            raise KeyError(
                f"unknown checker {name!r} (have: {', '.join(CHECKERS)})"
            )
        findings.extend(CHECKERS[name](files, root_dir))
    by_path = {src.relpath: src for src in files}
    for f in findings:
        src = by_path.get(f.path)
        if src is not None and f.line and f.checker in set(
            SUPPRESSION_KINDS.values()
        ):
            f.suppressed_by = src.suppression(f.line, f.checker)
    _number_occurrences(findings)
    findings.sort(key=lambda f: (f.path, f.line, f.code, f.occurrence))
    return findings


def _load_checkers() -> Dict[str, Callable]:
    from . import (
        metrics_check, recompile, serde_check, threads, trace_check, zerosync,
    )

    return {
        "zerosync": zerosync.check,
        "threads": threads.check,
        "recompile": recompile.check,
        "serde": serde_check.check,
        "metrics": metrics_check.check,
        "tracectx": trace_check.check,
    }


class _LazyCheckers(dict):
    """Checker registry resolved on first use (keeps import cycles out
    of the submodules, which all import core)."""

    def _ensure(self) -> None:
        if not super().__len__():
            super().update(_load_checkers())

    def __getitem__(self, key: str) -> Callable:
        self._ensure()
        return super().__getitem__(key)

    def __iter__(self):
        self._ensure()
        return super().__iter__()

    def __contains__(self, key: object) -> bool:
        self._ensure()
        return super().__contains__(key)

    def __len__(self) -> int:
        self._ensure()
        return super().__len__()


CHECKERS: Dict[str, Callable] = _LazyCheckers()
