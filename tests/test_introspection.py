"""Live introspection plane (ISSUE 7): registry merge, HTTP exposition,
time-driven reporting, end-to-end match latency, match provenance.

Pins the tentpole contracts:
- obs/merge.py semantics: counters sum, gauges pick up a `device` label,
  histograms merge bucket-wise, and the MERGED registry round-trips
  through both expositions (prom text <-> snapshot), including the
  bounded-cardinality edge;
- the HTTP plane serves /metrics, /snapshot, /healthz and /tracez from a
  live LogDriver, and its clock thread drives the periodic reporter on
  wall time (the poll-gated reporter never fired on an idle topic --
  the ISSUE 7 regression test);
- `cep_match_latency_seconds{query}`: ingest stamp at driver poll ->
  sink emission, for both runtimes, with zero device involvement;
- provenance exemplars: the sampled lineage agrees with the host-oracle
  NFA run for the same stream on both step engines and both drain modes
  (differential pin), and stride sampling is deterministic.
"""
from __future__ import annotations

import json
import random
import time
import urllib.error
import urllib.request

import pytest

from kafkastreams_cep_tpu import (
    ComplexStreamsBuilder,
    LogDriver,
    QueryBuilder,
    RecordLog,
    compile_pattern,
    produce,
)
from kafkastreams_cep_tpu.core.event import Event
from kafkastreams_cep_tpu.nfa.nfa import NFA
from kafkastreams_cep_tpu.obs import (
    IntrospectionServer,
    MetricsRegistry,
    SpanTracer,
    merge_registries,
    merge_snapshots,
    parse_prom_text,
    registry_from_snapshot,
)
from kafkastreams_cep_tpu.ops.engine import EngineConfig
from kafkastreams_cep_tpu.ops.runtime import sequence_provenance
from kafkastreams_cep_tpu.ops.tables import compile_query
from kafkastreams_cep_tpu.parallel import BatchedDeviceNFA
from kafkastreams_cep_tpu.pattern.expressions import value
from kafkastreams_cep_tpu.state.aggregates import AggregatesStore
from kafkastreams_cep_tpu.state.buffer import SharedVersionedBuffer

pytestmark = pytest.mark.obs

TS = 1_000_000


def letters_pattern():
    return (
        QueryBuilder()
        .select("a").where(value() == "A")
        .then().select("b").where(value() == "B")
        .then().select("c").where(value() == "C")
        .build()
    )


def letter_stream(seed, n, key="K"):
    rng = random.Random(seed)
    return [
        Event(key, rng.choice("ABCD"), TS + i, "t", 0, i) for i in range(n)
    ]


def _get(url: str):
    return urllib.request.urlopen(url, timeout=10).read()


def _get_json(url: str):
    return json.loads(_get(url))


# ------------------------------------------------------------------- merge
def _device_regs(n=3):
    regs = {}
    for d in range(n):
        r = MetricsRegistry()
        r.counter("dev_events_total", "events", labels=("counter",)).labels(
            counter="n_events"
        ).inc(10 * (d + 1))
        r.gauge("dev_fill", "region fill").set(d)
        h = r.histogram("dev_wall_seconds", "wall", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(0.5 * (d + 1))
        regs[str(d)] = r
    return regs


def test_merge_counters_sum_gauges_device_label_histograms_bucketwise():
    merged = merge_registries(_device_regs())
    snap = merged.snapshot()
    # Counters with identical label sets summed across devices.
    assert snap["dev_events_total"]["values"][0]["value"] == 60
    assert snap["dev_events_total"]["label_names"] == ["counter"]
    # Gauges became per-device series under the appended `device` label.
    assert snap["dev_fill"]["label_names"] == ["device"]
    fills = {
        v["labels"]["device"]: v["value"] for v in snap["dev_fill"]["values"]
    }
    assert fills == {"0": 0.0, "1": 1.0, "2": 2.0}
    # Histograms merged bucket-wise: counts and sums add, layout kept.
    hv = snap["dev_wall_seconds"]["values"][0]
    assert hv["count"] == 6
    assert abs(hv["sum"] - (3 * 0.05 + 0.5 + 1.0 + 1.5)) < 1e-9
    assert hv["buckets"]["0.1"] == 3  # the three 0.05 observations
    assert hv["buckets"]["+Inf"] == 6


def test_merged_registry_round_trips_both_expositions():
    """Satellite: parse_prom_text / registry_from_snapshot round-trip over
    a MERGED multi-device registry (device= labels, summed counters,
    bucket-merged histograms)."""
    merged = merge_registries(_device_regs())
    snap = merged.snapshot()
    rebuilt = registry_from_snapshot(snap)
    assert rebuilt.to_prom_text() == merged.to_prom_text()
    parsed = parse_prom_text(merged.to_prom_text())
    assert parsed["dev_events_total"][(("counter", "n_events"),)] == 60
    assert parsed["dev_fill"][(("device", "2"),)] == 2
    assert parsed["dev_wall_seconds_count"][()] == 6
    assert parsed["dev_wall_seconds_bucket"][(("le", "0.1"),)] == 3
    # Snapshot-level merge agrees with the live-registry merge.
    snap2 = merge_snapshots(
        {d: r.snapshot() for d, r in _device_regs().items()}
    )
    assert registry_from_snapshot(snap2).to_prom_text() == merged.to_prom_text()


def test_merge_bounded_cardinality_and_mismatches():
    # The merged registry still enforces the cardinality bound: K devices
    # x 1 gauge series exceeds a bound of 2.
    with pytest.raises(ValueError, match="cardinality"):
        merge_registries(_device_regs(4), max_label_sets=2)
    # Kind mismatch across devices is a bug, not a merge.
    a, b = MetricsRegistry(), MetricsRegistry()
    a.counter("m", "x").inc()
    b.gauge("m", "x").set(1)
    with pytest.raises(ValueError, match="disagrees"):
        merge_registries({"0": a, "1": b})
    # Histogram bucket-layout mismatch refuses too.
    c, d = MetricsRegistry(), MetricsRegistry()
    c.histogram("h", buckets=(0.1, 1.0)).observe(0.2)
    d.histogram("h", buckets=(0.5, 5.0)).observe(0.2)
    with pytest.raises(ValueError, match="bucket"):
        merge_registries({"0": c, "1": d})
    # ...including across DISJOINT label sets (family-level check: a
    # collision-gated check would let two layouts smuggle into one
    # family and corrupt the rebuilt exposition).
    c2, d2 = MetricsRegistry(), MetricsRegistry()
    c2.histogram("h2", labels=("shard",), buckets=(0.1, 1.0)).labels(
        shard="0"
    ).observe(0.2)
    d2.histogram("h2", labels=("shard",), buckets=(0.5, 5.0)).labels(
        shard="1"
    ).observe(0.2)
    with pytest.raises(ValueError, match="bucket"):
        merge_registries({"0": c2, "1": d2})
    # Two devices claiming one gauge device-label value collide loudly.
    e, f = MetricsRegistry(), MetricsRegistry()
    e.gauge("g", labels=("device",)).labels(device="x").set(1)
    f.gauge("g", labels=("device",)).labels(device="x").set(2)
    with pytest.raises(ValueError, match="device"):
        merge_registries({"0": e, "1": f})


def test_engine_device_registries_merge_to_global_totals():
    """key_shard.shard_stats -> per-device registries -> one merged
    exposition whose counters reproduce the global reduction."""
    query = compile_query(compile_pattern(letters_pattern()), None)
    bat = BatchedDeviceNFA(
        query, keys=["x", "y"],
        config=EngineConfig(lanes=8, nodes=128, matches=16),
    )
    bat.advance({"x": letter_stream(3, 6, key="x"),
                 "y": letter_stream(4, 6, key="y")})
    merged = merge_registries(bat.device_registries())
    snap = merged.snapshot()
    totals = {
        v["labels"]["counter"]: v["value"]
        for v in snap["cep_device_state_total"]["values"]
    }
    assert totals["n_events"] == bat.stats["n_events"] == 12
    assert snap["cep_device_runs"]["label_names"] == ["device"]


# ------------------------------------------------------------- HTTP plane
def test_http_endpoints_serve_registry_tracer_health():
    reg = MetricsRegistry()
    reg.counter("c_total", "c").inc(5)
    tracer = SpanTracer(reg)
    with tracer.span("restore"):
        pass
    exemplars = [{"query": "q", "stage_path": ["a"], "key": "K"}]
    with IntrospectionServer(
        registry=reg, tracer=tracer,
        health_fn=lambda: {"group": "g"},
        match_exemplars=lambda n: exemplars[:n],
    ) as srv:
        text = _get(srv.url + "/metrics").decode()
        assert parse_prom_text(text)["c_total"][()] == 5
        snap = _get_json(srv.url + "/snapshot")
        assert snap["c_total"]["values"][0]["value"] == 5
        # /metrics and /snapshot carry the same values (the acceptance's
        # wire-vs-artifact agreement) -- rebuilt snapshot renders the
        # identical exposition.
        assert registry_from_snapshot(snap).to_prom_text() == text
        hz = _get_json(srv.url + "/healthz")
        assert hz["status"] == "ok"
        assert hz["group"] == "g"
        assert hz["faults_armed"] is False
        tz = _get_json(srv.url + "/tracez")
        assert tz["kind"] == "span"
        assert tz["spans"][0]["span"] == "restore"
        assert tz["spans"][0]["duration_s"] >= 0
        mz = _get_json(srv.url + "/tracez?kind=match&limit=8")
        assert mz == {"kind": "match", "matches": exemplars}
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(srv.url + "/nope")
        assert ei.value.code == 404


def test_http_server_restart_keeps_ticking():
    """stop() then start() must revive the clock thread (a set _stop
    event would kill it on its first wait -- silently, since HTTP keeps
    answering)."""
    ticks = []
    srv = IntrospectionServer(
        registry=MetricsRegistry(),
        tick_fns=(lambda: ticks.append(1),), tick_every_s=0.01,
    )
    srv.start()
    deadline = time.time() + 5.0
    while not ticks and time.time() < deadline:
        time.sleep(0.005)
    srv.stop()
    n = len(ticks)
    assert n >= 1
    srv.start()
    try:
        deadline = time.time() + 5.0
        while len(ticks) <= n and time.time() < deadline:
            time.sleep(0.005)
        assert len(ticks) > n, "restarted server's clock thread never ticked"
    finally:
        srv.stop()


def _letters_pipeline(runtime, registry, log, **opts):
    builder = ComplexStreamsBuilder(log=log, app_id="intro")
    builder.stream("letters").query(
        "q", letters_pattern(), runtime=runtime, registry=registry, **opts
    ).to("matches")
    return builder.build()


def test_idle_driver_reports_on_time_via_clock_thread():
    """Regression (ISSUE 7 satellite): report_every_s on an idle topic
    used to never fire -- the check lived on the poll path only. The HTTP
    plane's clock thread now drives it on wall time."""
    log = RecordLog()
    reg = MetricsRegistry()
    topo = _letters_pipeline("host", reg, log)
    reports = []
    driver = LogDriver(
        topo, group="idle", registry=reg,
        report_every_s=0.03, reporter=reports.append,
    )
    # No records, no polls: the poll path alone would never report.
    srv = driver.serve_http()
    try:
        deadline = time.time() + 5.0
        while not reports and time.time() < deadline:
            time.sleep(0.01)
        assert reports, "idle topic never reported (poll-gated cadence)"
        assert "cep_driver_polls_total" in reports[0]
        # The reports counter moved without a single poll.
        snap = reg.snapshot()
        vals = {
            v["labels"]["group"]: v["value"]
            for v in snap["cep_driver_reports_total"]["values"]
        }
        assert vals["idle"] >= 1
        polls = {
            v["labels"]["group"]: v["value"]
            for v in snap["cep_driver_polls_total"]["values"]
        }
        assert polls["idle"] == 0
    finally:
        srv.stop()


def test_driver_healthz_liveness_fields():
    log = RecordLog()
    for i, ch in enumerate("XABC"):
        produce(log, "letters", "K", ch, timestamp=i)
    reg = MetricsRegistry()
    topo = _letters_pipeline("host", reg, log)
    driver = LogDriver(topo, group="hz", registry=reg)
    srv = driver.serve_http()
    try:
        hz = _get_json(srv.url + "/healthz")
        assert hz["last_poll_age_s"] is None  # no poll yet
        driver.poll()
        hz = _get_json(srv.url + "/healthz")
        assert hz["polls"] == 1 and hz["records"] == 4
        assert hz["last_poll_age_s"] is not None
        assert hz["last_commit_age_s"] is not None
        assert hz["last_commit_age_s"] < 60
        assert hz["faults_armed"] is False
        assert hz["restore_failures"] == 0
        # The driver's restore/commit spans surface on /tracez.
        tz = _get_json(srv.url + "/tracez")
        spans = {s["span"] for s in tz["spans"]}
        assert {"restore", "commit"} <= spans
    finally:
        srv.stop()


# ------------------------------------------------------------ match latency
@pytest.mark.parametrize("runtime,opts", [
    ("host", {}),
    ("tpu", dict(
        config=EngineConfig(lanes=8, nodes=128, matches=16),
        batch_size=4, initial_keys=1,
    )),
])
def test_match_latency_histogram_ingest_to_emission(runtime, opts):
    """cep_match_latency_seconds{query}: one sample per sink-emitted
    match, anchored at the driver's poll-time ingest stamp -- on both
    runtimes (the device path rides the flat-drain decode; stamping is
    pure host state)."""
    log = RecordLog()
    for i, ch in enumerate("XABCABC"):
        produce(log, "letters", "K", ch, timestamp=i)
    reg = MetricsRegistry()
    topo = _letters_pipeline(runtime, reg, log, **opts)
    driver = LogDriver(topo, group="lat", registry=reg)
    driver.poll()
    snap = reg.snapshot()
    fam = snap["cep_match_latency_seconds"]
    vals = {v["labels"]["query"]: v for v in fam["values"]}
    assert vals["q"]["count"] == 2  # ABC completes twice
    assert vals["q"]["sum"] >= 0
    # Replayed records below the HWM never re-observe: polling the same
    # stream again emits nothing new.
    driver.poll()
    snap = reg.snapshot()
    vals = {
        v["labels"]["query"]: v
        for v in snap["cep_match_latency_seconds"]["values"]
    }
    assert vals["q"]["count"] == 2


def test_ingest_stamps_full_identity_and_bounded_eviction():
    """Stamps key on the full event identity -- (key, offset) alone
    collides across topics/partitions -- and evict oldest-first in O(1)."""
    log = RecordLog()
    topo = _letters_pipeline("host", MetricsRegistry(), log)
    topo.stamp_ingest("a", 0, "K", 5, 100.0)
    topo.stamp_ingest("b", 0, "K", 5, 200.0)  # same (key, offset), other topic
    assert topo._ingest_stamps[("a", 0, "K", 5)] == (100.0, None, None)
    assert topo._ingest_stamps[("b", 0, "K", 5)] == (200.0, None, None)
    topo.INGEST_STAMPS_MAX = 3  # instance override for the bound
    for i in range(6):
        topo.stamp_ingest("a", 0, "K", 100 + i, float(i))
    assert len(topo._ingest_stamps) == 3
    assert ("a", 0, "K", 105) in topo._ingest_stamps
    assert ("a", 0, "K", 5) not in topo._ingest_stamps  # oldest evicted


def test_direct_process_without_stamp_skips_latency():
    """Topology.process outside a driver (no ingest stamp) emits matches
    but records no latency sample -- no stamp, no fabricated number."""
    log = RecordLog()
    reg = MetricsRegistry()
    topo = _letters_pipeline("host", reg, log)
    for i, ch in enumerate("ABC"):
        topo.process("letters", "K", ch, timestamp=i, offset=i)
    snap = reg.snapshot()
    assert snap["cep_processor_matches_total"]["values"][0]["value"] == 1
    assert snap["cep_match_latency_seconds"]["values"][0]["count"] == 0


# -------------------------------------------------------------- provenance
def _oracle_sequences(stream):
    stages = compile_pattern(letters_pattern())
    nfa = NFA.build(stages, AggregatesStore(), SharedVersionedBuffer())
    out = []
    for e in stream:
        out.extend(nfa.match_pattern(e))
    return out


def _lineage(seq):
    p = sequence_provenance(seq)
    return (p.stage_path, p.chain_depth, p.branch_depth,
            p.first_offset, p.last_offset,
            p.first_timestamp, p.last_timestamp)


@pytest.mark.parametrize("engine,drain_mode", [
    ("xla", "flat"),
    ("xla", "pool"),
    ("pallas_interpret", "flat"),
    ("pallas_interpret", "pool"),
])
def test_provenance_differential_vs_host_oracle(engine, drain_mode):
    """Satellite: the sampled lineage (stage path, window offsets, chain
    depth) agrees with the host-oracle NFA run for the same stream, on
    both step engines and both drain modes."""
    n = 24 if engine == "xla" else 15
    # ABC runs embedded in noise: strict contiguity completes one match
    # per 5-event block, and the tail blocks straddle the advance splits.
    stream = [
        Event("K", "ABC"[i % 5] if i % 5 < 3 else "XY"[i % 2], TS + i,
              "t", 0, i)
        for i in range(n)
    ]
    want = sorted(_lineage(s) for s in _oracle_sequences(stream))
    assert want, "oracle produced no matches -- test stream broken"
    query = compile_query(compile_pattern(letters_pattern()), None)
    bat = BatchedDeviceNFA(
        query, keys=["K"],
        config=EngineConfig(lanes=8, nodes=256, matches=256,
                            matches_per_step=4, nodes_per_step=8),
        engine=engine, drain_mode=drain_mode,
        provenance_sample=1.0, query_name="q",
    )
    got = []
    for lo, hi in ((0, 6), (6, 11), (11, 100)):
        chunk = stream[lo:hi]
        if chunk:
            for seqs in bat.advance({"K": chunk}).values():
                got.extend(seqs)
    # Every decoded match carries provenance at sample=1.0, with the
    # right query/trigger attribution...
    assert got and all(s.provenance is not None for s in got)
    assert all(s.provenance.query == "q" for s in got)
    assert all(s.provenance.trigger == "drain" for s in got)
    # ...whose lineage is the oracle's, field for field.
    device = sorted(
        (s.provenance.stage_path, s.provenance.chain_depth,
         s.provenance.branch_depth,
         s.provenance.first_offset, s.provenance.last_offset,
         s.provenance.first_timestamp, s.provenance.last_timestamp)
        for s in got
    )
    assert device == want
    # The exemplar ring serves the same lineage as JSON-ready dicts.
    ex = bat.provenance_exemplars(256)
    assert len(ex) == len(got)
    assert all(e["key"] == "K" for e in ex)


def test_provenance_stride_sampling_deterministic():
    """rate r samples exactly every 1/r-th decoded match (stride
    accumulator, not RNG): rate 0.5 over 2k matches -> exactly k."""
    stream = []
    for b in range(8):
        for i, ch in enumerate("ABC"):
            stream.append(Event("K", ch, TS + 10 * b + i, "t", 0, 10 * b + i))
    query = compile_query(compile_pattern(letters_pattern()), None)
    bat = BatchedDeviceNFA(
        query, keys=["K"],
        config=EngineConfig(lanes=8, nodes=128, matches=64),
        provenance_sample=0.5,
    )
    got = []
    for seqs in bat.advance({"K": stream}).values():
        got.extend(seqs)
    assert len(got) == 8
    sampled = [s for s in got if s.provenance is not None]
    assert len(sampled) == 4
    assert len(bat.provenance_exemplars()) == 4
    # sample=0 never attaches and the ring stays empty.
    bat0 = BatchedDeviceNFA(
        query, keys=["K"],
        config=EngineConfig(lanes=8, nodes=128, matches=64),
    )
    out0 = [s for seqs in bat0.advance({"K": stream}).values() for s in seqs]
    assert all(s.provenance is None for s in out0)
    assert bat0.provenance_exemplars() == []


def test_device_pipeline_exemplars_surface_user_keys():
    """Through the streams stack the exemplar keys are the record keys
    (lane handles unwrapped), and /tracez?kind=match serves them."""
    log = RecordLog()
    for i, ch in enumerate("XABC"):
        produce(log, "letters", "KEY-7", ch, timestamp=i)
    reg = MetricsRegistry()
    topo = _letters_pipeline(
        "tpu", reg, log,
        config=EngineConfig(lanes=8, nodes=128, matches=16),
        batch_size=4, initial_keys=1, provenance_sample=1.0,
    )
    driver = LogDriver(topo, group="prov", registry=reg)
    srv = driver.serve_http()
    try:
        driver.poll()
        mz = _get_json(srv.url + "/tracez?kind=match")
        assert mz["matches"], "no exemplars surfaced"
        ex = mz["matches"][0]
        assert ex["key"] == "KEY-7"
        assert ex["query"] == "q"
        assert ex["stage_path"] == ["a", "b", "c"]
        assert ex["first_offset"] == 1 and ex["last_offset"] == 3
    finally:
        srv.stop()


# ----------------------------------------------------- merge edge cases
def test_merge_zero_and_single_registry():
    """Satellite (ISSUE 9): the degenerate fleet sizes must merge, not
    crash -- zero registries yield an empty exposition, one registry
    round-trips its values (gauges still gain the device label: a
    one-device fleet is a fleet)."""
    empty = merge_registries({})
    assert empty.snapshot() == {}
    assert empty.to_prom_text() == ""
    assert merge_snapshots({}) == {}
    one = _device_regs(1)
    merged = merge_registries(one)
    snap = merged.snapshot()
    assert snap["dev_events_total"]["values"][0]["value"] == 10
    assert snap["dev_fill"]["label_names"] == ["device"]
    assert snap["dev_fill"]["values"][0]["labels"] == {"device": "0"}
    hv = snap["dev_wall_seconds"]["values"][0]
    assert hv["count"] == 2 and hv["buckets"]["+Inf"] == 2
    # A single EMPTY registry merges to an empty exposition too.
    assert merge_registries({"0": MetricsRegistry()}).snapshot() == {}


def test_merge_disjoint_histogram_layouts_typed_error():
    """Disjoint layouts raise the typed error (ValueError), whichever
    device arrives first -- never a corrupt merged family."""
    a, b = MetricsRegistry(), MetricsRegistry()
    a.histogram("h", buckets=(0.1, 1.0)).observe(0.2)
    b.histogram("h", buckets=(0.1, 1.0, 10.0)).observe(0.2)
    with pytest.raises(ValueError, match="bucket"):
        merge_registries({"0": a, "1": b})
    with pytest.raises(ValueError, match="bucket"):
        merge_registries({"0": b, "1": a})


# ------------------------------------------------ provenance ring bound
def test_provenance_exemplar_ring_bounded_at_full_sample_rate():
    """Satellite (ISSUE 9): provenance_sample=1.0 over many more matches
    than the ring holds keeps the ring -- and /tracez?kind=match -- at
    the configured bound, newest-first."""
    query = compile_query(compile_pattern(letters_pattern()), None)
    bat = BatchedDeviceNFA(
        query, keys=["K"],
        config=EngineConfig(lanes=8, nodes=512, matches=256),
        provenance_sample=1.0, provenance_ring=8, query_name="q",
    )
    stream = []
    for b in range(24):  # 24 matches >> ring of 8
        for i, ch in enumerate("ABC"):
            stream.append(Event("K", ch, TS + 10 * b + i, "t", 0, 10 * b + i))
    got = [s for seqs in bat.advance({"K": stream}).values() for s in seqs]
    assert len(got) == 24
    # Every match was sampled (the counter saw all of them)...
    snap = bat.metrics.snapshot()
    sampled = {
        v["labels"]["query"]: v["value"]
        for v in snap["cep_provenance_sampled_total"]["values"]
    }
    assert sampled["q"] == 24
    # ...but the ring holds only the newest 8, whatever limit is asked.
    assert len(bat._prov_ring) == 8
    ex = bat.provenance_exemplars(10_000)
    assert len(ex) == 8
    assert ex[0]["last_offset"] == 232  # newest first (block 23, i=2)
    ring_served = bat.provenance_exemplars(3)
    assert len(ring_served) == 3


# ------------------------------------------------- chrome trace export
def test_chrome_trace_export_shapes():
    from kafkastreams_cep_tpu.obs.trace_export import (
        MATCH_PID,
        SPAN_PID,
        chrome_trace,
        match_events,
        span_events,
    )

    reg = MetricsRegistry()
    tracer = SpanTracer(reg)
    with tracer.span("restore"):
        time.sleep(0.002)
    with tracer.span("commit"):
        pass
    evs = span_events(tracer.recent(16))
    assert {e["name"] for e in evs} == {"restore", "commit"}
    for e in evs:
        assert e["ph"] == "X" and e["pid"] == SPAN_PID
        assert e["dur"] >= 0 and e["ts"] > 0
    restore = next(e for e in evs if e["name"] == "restore")
    assert restore["dur"] >= 2_000  # 2 ms in us
    # One tid row per span name.
    assert len({e["tid"] for e in evs}) == 2
    mevs = match_events([
        {"query": "q", "first_timestamp": 100, "last_timestamp": 130,
         "stage_path": ["a"], "key": "K"},
        {"query": "q2", "first_timestamp": 50, "last_timestamp": 50},
    ])
    assert mevs[0]["ts"] == 100_000 and mevs[0]["dur"] == 30_000
    assert mevs[1]["dur"] == 0  # zero-width window still renders
    assert mevs[0]["pid"] == MATCH_PID
    assert mevs[0]["args"]["key"] == "K"
    doc = chrome_trace(tracer=tracer, match_exemplars=[
        {"query": "q", "first_timestamp": 1, "last_timestamp": 2},
    ])
    names = [e["name"] for e in doc["traceEvents"]]
    assert "process_name" in names and "restore" in names and "q" in names
    # The document is JSON-serializable as served.
    json.dumps(doc)


def test_tracez_chrome_format_served_and_loadable():
    """The acceptance contract: /tracez?format=chrome returns a document
    whose traceEvents loads as a valid Chrome-trace event array."""
    reg = MetricsRegistry()
    tracer = SpanTracer(reg)
    with tracer.span("poll"):
        pass
    exemplars = [
        {"query": "q", "first_timestamp": 10, "last_timestamp": 20,
         "stage_path": ["a", "b"], "key": "K"},
    ]
    with IntrospectionServer(
        registry=reg, tracer=tracer, match_exemplars=lambda n: exemplars[:n],
    ) as srv:
        doc = _get_json(srv.url + "/tracez?format=chrome")
        events = doc["traceEvents"]
        assert isinstance(events, list) and events
        for e in events:
            assert "name" in e and "ph" in e and "pid" in e
            if e["ph"] != "M":
                assert isinstance(e["ts"], (int, float))
        assert any(e["name"] == "poll" for e in events)
        match = next(e for e in events if e["name"] == "q")
        assert match["args"]["stage_path"] == ["a", "b"]
        # ?kind/?limit behavior is untouched by the format switch.
        tz = _get_json(srv.url + "/tracez")
        assert tz["kind"] == "span"


def test_profilez_arms_capture_and_reports_busy(tmp_path):
    reg = MetricsRegistry()
    tracer = SpanTracer(reg)
    with IntrospectionServer(
        registry=reg, tracer=tracer, profile_dir=str(tmp_path),
    ) as srv:
        pz = _get_json(srv.url + "/profilez?secs=0")
        assert pz["armed"] is True
        assert pz["log_dir"] == str(tmp_path)
        # The capture wall lands as a device_trace span once done.
        deadline = time.time() + 10.0
        while time.time() < deadline:
            if any(
                s["span"] == "device_trace" for s in tracer.recent(16)
            ):
                break
            time.sleep(0.01)
        assert any(s["span"] == "device_trace" for s in tracer.recent(16))
    # Busy arbitration: a long capture refuses a second concurrent arm.
    with IntrospectionServer(
        registry=reg, tracer=tracer, profile_dir=str(tmp_path),
    ) as srv:
        first = _get_json(srv.url + "/profilez?secs=30")
        assert first["armed"] is True
        second = _get_json(srv.url + "/profilez?secs=1")
        assert second == {"armed": False, "busy": True}
    # Context exit stopped the 30s capture early (stop() sets the event
    # and joins) -- reaching here quickly IS the assertion.


def test_profilez_degraded_profiler_still_answers(monkeypatch, tmp_path):
    import jax

    monkeypatch.setattr(
        jax.profiler, "trace",
        lambda d: (_ for _ in ()).throw(RuntimeError("no profiler")),
    )
    reg = MetricsRegistry()
    with IntrospectionServer(
        registry=reg, tracer=SpanTracer(reg), profile_dir=str(tmp_path),
    ) as srv:
        pz = _get_json(srv.url + "/profilez?secs=0")
        assert pz["armed"] is True  # armed; the capture no-ops inside
        deadline = time.time() + 10.0
        while time.time() < deadline:
            if "cep_profiler_unavailable" in reg.snapshot():
                break
            time.sleep(0.01)
        assert "cep_profiler_unavailable" in reg.snapshot()


# --------------------------------------------------------- driver close
def test_driver_close_joins_clock_thread_before_teardown():
    """Satellite (ISSUE 9): close() must stop the introspection plane --
    joining its clock thread -- BEFORE tearing down driver state, so no
    tick can drive maybe_report()/health reads mid-teardown
    (disarm_reporter only covered the report_every_s=None race)."""
    log = RecordLog()
    for i, ch in enumerate("XABC"):
        produce(log, "letters", "K", ch, timestamp=i)
    reg = MetricsRegistry()
    topo = _letters_pipeline("host", reg, log)
    reports = []
    driver = LogDriver(
        topo, group="close", registry=reg,
        report_every_s=0.01, reporter=reports.append,
    )
    srv = driver.serve_http(tick_every_s=0.01)
    driver.poll()
    deadline = time.time() + 5.0
    while not reports and time.time() < deadline:
        time.sleep(0.005)
    assert reports  # the clock thread is live and reporting
    driver.close()
    # The plane is fully down: both threads joined, handle cleared.
    assert driver.http is None
    assert srv._clock_thread is None and srv._serve_thread is None
    assert srv._httpd is None
    # No tick can fire a report after close returned.
    n = len(reports)
    time.sleep(0.08)
    assert len(reports) == n
    assert driver.maybe_report() is False  # reporter disarmed
    # The pump refuses further work; close is idempotent.
    with pytest.raises(RuntimeError, match="closed"):
        driver.poll()
    with pytest.raises(RuntimeError, match="closed"):
        driver.serve_http()
    driver.close()


def test_driver_context_manager_closes():
    log = RecordLog()
    for i, ch in enumerate("ABC"):
        produce(log, "letters", "K", ch, timestamp=i)
    reg = MetricsRegistry()
    topo = _letters_pipeline("host", reg, log)
    with LogDriver(topo, group="cm", registry=reg) as driver:
        srv = driver.serve_http()
        assert driver.poll() == 3
    # __exit__ closed: plane down, final positions committed.
    assert driver.http is None and srv._httpd is None
    assert driver._committed[("letters", 0)] == 3
