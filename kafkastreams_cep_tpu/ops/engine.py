"""Device NFA engine: the jit-compiled, lane-vectorized SASE transition kernel.

This is the TPU-native replacement for the reference's per-record, run-at-a-
time evaluator (reference: core/.../cep/nfa/NFA.java:134-397). The host
oracle (nfa/nfa.py) defines the conformance contract; this module implements
the *same transition relation* as a data-parallel program:

  * live runs live in a fixed-capacity structure-of-arrays lane table
    (stage id, synthesized-epsilon target, Dewey digits as fixed-width i32
    lanes, run id, last-buffer-node index, start timestamp, branching/ignored
    flags) -- the device form of ComputationStage.java:30-91;
  * the recursive epsilon descent (NFA.java:222-237) is unrolled to the
    statically-known chain depth (CompiledQuery.max_depth): each level
    evaluates one stage's edges for every lane at once;
  * predicates are evaluated as vectorized masks: stateless predicates for
    the whole micro-batch up front ([T, P] in one fused pass -- the
    replacement for the per-edge virtual call, NFA.java:371-384), stateful
    ones per (lane, event) against the fold-register file;
  * one event-step emits up to 4*max_depth output slots per lane in exactly
    the oracle's DFS order (consume/ignore emissions level-down, then
    branch-clone and begin-re-add level-up, NFA.java:238-338) and compacts
    them into the new lane table with a prefix-sum scatter, so queue order,
    run counts and match order match the oracle;
  * the shared versioned buffer (SharedVersionedBufferStoreImpl.java) becomes
    an append-only node pool (event idx, stage name id, predecessor index).
    Because every run tracks its last node *by index*, the Dewey-compatible
    pointer routing of the reference's merged store is unnecessary: each
    lineage owns its chain, branches share prefixes by construction, and
    match extraction is a host-side (or batched-gather) predecessor walk.
    Refcount GC is replaced by mark-sweep compaction at batch boundaries.

Known, documented divergences from the oracle (both unobservable in the
conformance suite; counted by the `seq_collisions` stat so a workload that
hits them is detectable):

  * fold registers are stored per lane with copy-on-emit; two live lanes
    sharing one run id (possible after PROCEED+TAKE branching) receive their
    own lane's updates rather than a shared per-run cell, and predicates read
    the event-start snapshot rather than seeing earlier queue items' folds
    within the same event;
  * buffer-node refcounts are not maintained on device (GC is mark-sweep),
    so the reference's refcount quirks (MatchedEvent.java:66-68) have no
    analog here.

The scan is vmap-able over a leading key axis (parallel/key_shard.py) and
shards over a device mesh along that axis with `jax.sharding`.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .tables import (
    OP_BEGIN,
    OP_NONE,
    OP_TAKE,
    PR_NONE,
    PR_PROCEED,
    PR_SKIP,
    CompiledQuery,
    DeviceEnv,
)

_I32_MAX = np.int64(2**31 - 1)


@dataclass(frozen=True)
class EngineConfig:
    """Capacity knobs (SURVEY.md section 5.6: typed config, not a flag framework)."""

    lanes: int = 64          # max simultaneous runs per key (run-lane pool)
    nodes: int = 8192        # buffer node pool per key per batch window
    matches: int = 1024      # match-descriptor ring per batch
    digits: int = 0          # Dewey digit width; 0 = auto (n_stages + 2)
    #: Reference parity (False): synthesized epsilon stages carry no window
    #: (Stage.java:247-251,42), so consumed runs are never expired and
    #: skip-till-any run populations grow without bound. True = epsilon runs
    #: inherit their descent target's window and any run with a consumed
    #: event (ts >= 0) expires -- the bounded-memory mode (matches the host
    #: oracle's NFA(strict_windows=True)).
    strict_windows: bool = False

    def dewey_width(self, query: CompiledQuery) -> int:
        return self.digits if self.digits > 0 else query.n_stages + 2


def init_state(query: CompiledQuery, config: EngineConfig) -> Dict[str, jnp.ndarray]:
    """Initial device state: one begin run, version `1`, run id 1.

    Mirrors Stages.initialComputationStage (Stages.java:53-60).
    """
    R = config.lanes
    D = config.dewey_width(query)
    A = query.n_aggs
    B = config.nodes
    M = config.matches

    ver = np.zeros((R, D), np.int32)
    ver[0, 0] = 1
    state = {
        # -- run lane table (SoA ComputationStage) ---------------------------
        "active": np.zeros(R, bool),
        "src": np.zeros(R, np.int32),          # stage id (identity of the run's stage)
        "eps": np.full(R, -1, np.int32),       # synthesized-epsilon PROCEED target
        "ver": ver,                            # Dewey digits (zero-padded)
        "vlen": np.zeros(R, np.int32),         # digit count
        "seq": np.zeros(R, np.int32),          # run id (NFA.java runs counter)
        "node": np.full(R, -1, np.int32),      # last matched event's buffer node
        "ts": np.full(R, -1, np.int32),        # start timestamp (rebased ms)
        "branching": np.zeros(R, bool),
        "ignored": np.zeros(R, bool),
        "regs": np.zeros((R, A), np.float32),  # fold registers (per lane)
        "regs_set": np.zeros((R, A), bool),
        "runs": np.asarray(1, np.int32),       # global run counter
        # -- buffer node pool (slot B = overflow trash) ----------------------
        "node_event": np.full(B + 1, -1, np.int32),   # global event index
        "node_name": np.full(B + 1, -1, np.int32),    # stage (name, type) id
        "node_pred": np.full(B + 1, -1, np.int32),    # predecessor node (-1 root)
        "node_count": np.asarray(0, np.int32),
        # -- match ring (slot M = overflow trash) ----------------------------
        "match_node": np.full(M + 1, -1, np.int32),
        "match_count": np.asarray(0, np.int32),
        # -- observability counters (SURVEY.md section 5.1/5.5) --------------
        "n_events": np.asarray(0, np.int32),
        "n_branches": np.asarray(0, np.int32),
        "n_expired": np.asarray(0, np.int32),
        "lane_drops": np.asarray(0, np.int32),
        "node_drops": np.asarray(0, np.int32),
        "match_drops": np.asarray(0, np.int32),
        "seq_collisions": np.asarray(0, np.int32),
    }
    state["active"][0] = True
    state["src"][0] = query.begin_stage
    state["vlen"][0] = 1
    state["seq"][0] = 1
    return {k: jnp.asarray(v) for k, v in state.items()}


def _excl_cumsum(mask: jnp.ndarray) -> jnp.ndarray:
    c = jnp.cumsum(mask.astype(jnp.int32))
    return c - mask.astype(jnp.int32)


def build_step(
    query: CompiledQuery, config: EngineConfig, debug: bool = False
) -> Callable[[Dict[str, jnp.ndarray], Dict[str, jnp.ndarray]], Tuple[Dict[str, jnp.ndarray], Any]]:
    """Build the one-event transition function (a `lax.scan` body).

    The returned `step(state, x)` consumes one packed event
    (x = column scalars + precomputed stateless predicate row + global event
    index + validity flag) and returns the next state. All shapes static.
    """
    R = config.lanes
    D = config.dewey_width(query)
    A = query.n_aggs
    B = config.nodes
    M = config.matches
    L = query.max_depth
    P = query.n_preds
    SLOTS = 4 * L

    # Device-constant stage tables.
    t_consume_op = jnp.asarray(query.consume_op)
    t_consume_pred = jnp.asarray(query.consume_pred)
    t_consume_target = jnp.asarray(query.consume_target)
    t_ignore_pred = jnp.asarray(query.ignore_pred)
    t_proceed_kind = jnp.asarray(query.proceed_kind)
    t_proceed_pred = jnp.asarray(query.proceed_pred)
    t_proceed_target = jnp.asarray(query.proceed_target)
    # i64 window clamped into i32: rebased timestamps are i32, so a clamped
    # huge window compares identically to "no expiry".
    t_window = jnp.asarray(
        np.where(query.window_ms < 0, -1, np.minimum(query.window_ms, _I32_MAX - 1)).astype(
            np.int32
        )
    )
    t_name_id = jnp.asarray(query.name_id)
    t_pure_name = jnp.asarray(query.pure_name_id)
    t_is_begin = jnp.asarray(query.is_begin)
    t_is_final = jnp.asarray(query.is_final)
    t_is_fwd = jnp.asarray(query.is_fwd)
    t_fwd_final = jnp.asarray(query.fwd_final)

    stateful = [bool(f) for f in query.pred_stateful]

    # Flattened fold list [(stage, slot, fn)] preserving per-stage order
    # (evaluateAggregates iterates a stage's folds sequentially,
    # NFA.java:362-369).
    flat_folds: List[Tuple[int, int, Callable]] = []
    for stage_i, stage_folds in enumerate(query.folds):
        for slot, fn in stage_folds:
            flat_folds.append((stage_i, slot, fn))

    def add_run(ver: jnp.ndarray, vlen: jnp.ndarray, off: jnp.ndarray) -> jnp.ndarray:
        """DeweyVersion.addRun: +1 at digit (len - off) (DeweyVersion.java:58-67)."""
        idx = vlen - off
        onehot = (jnp.arange(D)[None, :] == idx[:, None]).astype(jnp.int32)
        return ver + onehot

    def step(state: Dict[str, jnp.ndarray], x: Dict[str, jnp.ndarray]):
        ev_ts = x["ts"]
        gidx = x["gidx"]

        active = state["active"]
        src = state["src"]
        eps = state["eps"]
        lane_node = state["node"]
        lane_ts = state["ts"]
        lane_seq = state["seq"]
        regs_in = state["regs"]
        regs_set_in = state["regs_set"]

        # -- predicate mask matrix [R, P] ------------------------------------
        # Stateless rows were evaluated for the whole batch up front; stateful
        # predicates read the event-start register snapshot (all of a lane's
        # predicate evaluations precede all of its folds in the oracle's DFS).
        env = DeviceEnv(x, regs_in, regs_set_in, query.agg_slots, query.agg_defaults)
        cols = []
        for p in range(max(P, 1)):
            if p < P and stateful[p]:
                v = query.predicates[p](env)
            elif p < P:
                v = x["spred"][p]
            else:
                v = jnp.asarray(False)
            cols.append(jnp.broadcast_to(jnp.asarray(v, bool), (R,)))
        pred_vals = jnp.stack(cols, axis=1)

        def pval(pid: jnp.ndarray) -> jnp.ndarray:
            got = jnp.take_along_axis(pred_vals, pid.clip(0)[:, None], axis=1)[:, 0]
            return got & (pid >= 0)

        # -- window expiry (NFA.java:183-184; begin states never expire, and
        # synthesized epsilon stages carry no window, Stage.java:247-251;
        # strict_windows inherits the target's window instead -- see
        # EngineConfig.strict_windows) -----------------------------------
        root_begin = t_is_begin[src]
        if config.strict_windows:
            w_eps = t_window[eps.clip(0)]
            w_eps = jnp.where(w_eps >= 0, w_eps, t_window[src])
            eff_window = jnp.where(eps >= 0, w_eps, t_window[src])
            expired = (
                active & (lane_ts >= 0) & (eff_window >= 0)
                & ((ev_ts - lane_ts) > eff_window)
            )
        else:
            eff_window = jnp.where(eps >= 0, -1, t_window[src])
            expired = (
                active & ~root_begin & (eff_window >= 0)
                & ((ev_ts - lane_ts) > eff_window)
            )
        active = active & ~expired

        root_fwd = (eps >= 0) | t_is_fwd[src]
        start_ts = jnp.where(root_begin, ev_ts, lane_ts)

        # ==== downward pass: unrolled epsilon descent =======================
        alive = active
        cs = src
        is_eps = eps >= 0
        ceps = eps
        ver = state["ver"]
        vlen = state["vlen"]
        br = state["branching"]
        ig = state["ignored"]
        ps = jnp.full(R, -1, jnp.int32)

        levels: List[Dict[str, jnp.ndarray]] = []
        for _l in range(L):
            c_op = jnp.where(is_eps, OP_NONE, t_consume_op[cs])
            c_m = alive & (c_op != OP_NONE) & pval(
                jnp.where(is_eps, -1, t_consume_pred[cs])
            )
            take_m = c_m & (c_op == OP_TAKE)
            begin_m = c_m & (c_op == OP_BEGIN)
            ig_m = alive & ~is_eps & pval(t_ignore_pred[cs])
            pk = jnp.where(is_eps, PR_PROCEED, t_proceed_kind[cs])
            ptgt = jnp.where(is_eps, ceps, t_proceed_target[cs])
            p_m = alive & (pk != PR_NONE) & (is_eps | pval(t_proceed_pred[cs]))
            # Branching combos (NFA.java:392-397): PROCEED+TAKE, IGNORE+TAKE,
            # IGNORE+BEGIN, IGNORE+PROCEED (SKIP_PROCEED does not count).
            p_strict = p_m & (pk == PR_PROCEED)
            branch_m = (p_strict & take_m) | (ig_m & (c_m | p_strict))

            ptgt_c = ptgt.clip(0)
            fwd_next = (
                p_m
                & (t_pure_name[ptgt_c] != t_pure_name[cs])
                & ~br
                & ~ig
            )

            levels.append(
                dict(
                    alive=alive, cs=cs, is_eps=is_eps, ver=ver, vlen=vlen,
                    br=br, ig=ig, ps=ps, c_m=c_m, take_m=take_m,
                    begin_m=begin_m, ig_m=ig_m, p_m=p_m, pk=pk, ptgt=ptgt_c,
                    branch_m=branch_m,
                )
            )

            # Descend (PROCEED/SKIP_PROCEED, NFA.java:222-237): extend the
            # version when genuinely crossing stage names with clean flags;
            # SKIP_PROCEED keeps the previous stage (NFA.java:232-236).
            vlen = jnp.where(fwd_next, vlen + 1, vlen)
            br = jnp.where(fwd_next, False, br)
            ig = jnp.where(fwd_next, False, ig)
            ps = jnp.where(pk == PR_SKIP, ps, cs).astype(jnp.int32)
            alive = p_m
            cs = ptgt_c
            is_eps = jnp.zeros(R, bool)
            ceps = jnp.full(R, -1, jnp.int32)

        # ==== fold-register chain (deepest level first, NFA.java:319-321) ===
        def apply_folds(v: Dict[str, jnp.ndarray], regs, regs_set):
            for stage_i, slot, fn in flat_folds:
                mask = v["c_m"] & (v["cs"] == stage_i)
                fenv = DeviceEnv(x, regs, regs_set, query.agg_slots, query.agg_defaults)
                val = jnp.broadcast_to(
                    jnp.asarray(fn(fenv), jnp.float32), (R,)
                )
                regs = regs.at[:, slot].set(jnp.where(mask, val, regs[:, slot]))
                regs_set = regs_set.at[:, slot].set(regs_set[:, slot] | mask)
            return regs, regs_set

        cur_regs, cur_set = regs_in, regs_set_in
        clone_regs: List[Tuple[jnp.ndarray, jnp.ndarray]] = [None] * L  # type: ignore
        for l in reversed(range(L)):
            clone_regs[l] = (cur_regs, cur_set)  # pre-this-level snapshot for clones
            if flat_folds:
                cur_regs, cur_set = apply_folds(levels[l], cur_regs, cur_set)
        final_regs, final_set = cur_regs, cur_set

        # Same-run-id collision detector: >1 lane consuming with one run id
        # in a single event (the documented per-lane-register divergence).
        consuming = jnp.zeros(R, bool)
        for l in range(L):
            consuming = consuming | levels[l]["c_m"]
        seq_sorted = jnp.sort(jnp.where(consuming, lane_seq, -jnp.arange(R) - 1))
        collide = jnp.any(seq_sorted[1:] == seq_sorted[:-1])

        # ==== buffer puts (one per consumed level, NFA.java:238-271) ========
        put_flat = jnp.stack([v["c_m"] for v in levels], axis=1).reshape(-1)  # [R*L]
        put_pos = state["node_count"] + _excl_cumsum(put_flat)
        node_drop = put_flat & (put_pos >= B)
        put_idx_flat = jnp.where(put_flat & ~node_drop, put_pos, B)
        put_idx = put_idx_flat.reshape(R, L)
        cs_mat = jnp.stack([v["cs"] for v in levels], axis=1)  # [R, L]
        node_event = state["node_event"].at[put_idx_flat].set(
            jnp.where(put_flat, gidx, -1), mode="drop"
        )
        node_name = state["node_name"].at[put_idx_flat].set(
            jnp.where(put_flat, t_name_id[cs_mat.reshape(-1)], -1), mode="drop"
        )
        node_pred = state["node_pred"].at[put_idx_flat].set(
            jnp.where(put_flat, jnp.repeat(lane_node, L), -1), mode="drop"
        )
        # Trash slot stays clean.
        node_event = node_event.at[B].set(-1)
        node_name = node_name.at[B].set(-1)
        node_pred = node_pred.at[B].set(-1)
        new_node_count = state["node_count"] + jnp.sum(put_flat & ~node_drop).astype(jnp.int32)

        # ==== upward pass: clones / begin-re-adds (NFA.java:289-338) ========
        desc_any = jnp.zeros(R, bool)
        up: List[Optional[Dict[str, jnp.ndarray]]] = [None] * L
        for l in reversed(range(L)):
            v = levels[l]
            ignore_emit = v["ig_m"] & ~v["branch_m"]
            clone_m = v["branch_m"] & v["c_m"]
            rootcopy_m = v["branch_m"] & ~v["c_m"] & ~desc_any
            readd_cond = root_begin & ~root_fwd & v["alive"]
            readd_fresh = readd_cond & v["c_m"]
            readd_root = readd_cond & ~v["c_m"]
            ns_before = v["c_m"] | ignore_emit | desc_any | clone_m | rootcopy_m
            # Begin re-add version: bare when nothing else was emitted at this
            # level, else addRun (NFA.java:323-331).
            readd_ver = jnp.where(
                (readd_fresh & ns_before)[:, None],
                add_run(v["ver"], v["vlen"], jnp.ones(R, jnp.int32)),
                v["ver"],
            )
            up[l] = dict(
                ignore_emit=ignore_emit, clone_m=clone_m, rootcopy_m=rootcopy_m,
                readd_fresh=readd_fresh, readd_root=readd_root, readd_ver=readd_ver,
            )
            desc_any = ns_before | readd_fresh | readd_root

        # ==== output slot table in oracle DFS order =========================
        # Downward: consume emit, ignore emit per level; upward: clone (or
        # branch-root-re-add) then begin-re-add per level, deepest first.
        zero_i = jnp.zeros(R, jnp.int32)
        false_b = jnp.zeros(R, bool)

        slot_occ, slot_src, slot_eps = [], [], []
        slot_ver, slot_vlen, slot_seq = [], [], []
        slot_node, slot_ts, slot_br, slot_ig = [], [], [], []
        slot_newseq = []       # allocates a fresh run id
        slot_regs, slot_regs_set = [], []

        for l in range(L):
            v = levels[l]
            # consume emission: TAKE -> epsilon(self, self); BEGIN ->
            # epsilon(self, target) (NFA.java:238-271).
            c_eps = jnp.where(v["take_m"], v["cs"], t_consume_target[v["cs"]])
            slot_occ.append(v["c_m"])
            slot_src.append(v["cs"])
            slot_eps.append(c_eps)
            slot_ver.append(v["ver"])
            slot_vlen.append(v["vlen"])
            slot_seq.append(lane_seq)
            slot_node.append(put_idx[:, l].astype(jnp.int32))
            slot_ts.append(start_ts)
            slot_br.append(false_b)
            slot_ig.append(false_b)
            slot_newseq.append(false_b)
            slot_regs.append(final_regs)
            slot_regs_set.append(final_set)

            # ignore emission keeps the computation as-is with ignored=True:
            # ROOT stage identity at any descent depth
            # (NFA.java:272-285 re-adds ctx.getComputationStage().getStage(),
            # i.e. the queue item's own -- possibly synthesized-epsilon --
            # stage, never the descended stage; rewriting identity here both
            # skips the epsilon hop and re-attaches the descended stage's
            # window to a run the oracle never expires).
            slot_occ.append(up[l]["ignore_emit"])
            slot_src.append(src)
            slot_eps.append(eps)
            slot_ver.append(v["ver"])
            slot_vlen.append(v["vlen"])
            slot_seq.append(lane_seq)
            slot_node.append(lane_node)
            slot_ts.append(lane_ts)
            slot_br.append(false_b)
            slot_ig.append(jnp.ones(R, bool))
            slot_newseq.append(false_b)
            slot_regs.append(final_regs)
            slot_regs_set.append(final_set)

        for l in reversed(range(L)):
            v = levels[l]
            u = up[l]
            # branch clone: epsilon(prev, current), version addRun(2) off a
            # begin previous stage else addRun(), last event = previous when
            # ignored else current (NFA.java:289-307). A null previous stage
            # parks the clone at the current stage (oracle divergence note,
            # nfa/nfa.py:286-291).
            has_ps = v["ps"] >= 0
            cl_src = jnp.where(has_ps, v["ps"], v["cs"])
            ps_begin = jnp.where(has_ps, t_is_begin[v["ps"].clip(0)], True)
            off = jnp.where(ps_begin & (v["vlen"] >= 2), 2, 1).astype(jnp.int32)
            cl_ver = add_run(v["ver"], v["vlen"], off)
            cl_node = jnp.where(v["ig_m"], lane_node, put_idx[:, l].astype(jnp.int32))

            m_clone = u["clone_m"]
            m_copy = u["rootcopy_m"]
            occ = m_clone | m_copy
            slot_occ.append(occ)
            slot_src.append(jnp.where(m_clone, cl_src, src))
            slot_eps.append(jnp.where(m_clone, v["cs"], eps))
            slot_ver.append(jnp.where(m_clone[:, None], cl_ver, state["ver"]))
            slot_vlen.append(jnp.where(m_clone, v["vlen"], state["vlen"]))
            slot_seq.append(jnp.where(m_clone, zero_i, lane_seq))  # fresh id patched below
            slot_node.append(jnp.where(m_clone, cl_node, lane_node))
            slot_ts.append(jnp.where(m_clone, start_ts, lane_ts))
            slot_br.append(jnp.where(m_clone, True, state["branching"]))
            slot_ig.append(jnp.where(m_clone, False, state["ignored"]))
            slot_newseq.append(m_clone)
            cr, cr_set = clone_regs[l]
            slot_regs.append(jnp.where(m_clone[:, None], cr, final_regs))
            slot_regs_set.append(jnp.where(m_clone[:, None], cr_set, final_set))

            # begin re-add: fresh run on consume else the root itself
            # (NFA.java:323-338).
            m_fresh = u["readd_fresh"]
            m_root = u["readd_root"]
            occ = m_fresh | m_root
            slot_occ.append(occ)
            slot_src.append(src)
            slot_eps.append(eps)
            slot_ver.append(jnp.where(m_fresh[:, None], u["readd_ver"], state["ver"]))
            slot_vlen.append(jnp.where(m_fresh, v["vlen"], state["vlen"]))
            slot_seq.append(jnp.where(m_fresh, zero_i, lane_seq))
            slot_node.append(jnp.where(m_fresh, -1, lane_node))
            slot_ts.append(jnp.where(m_fresh, -1, lane_ts))
            slot_br.append(jnp.where(m_fresh, False, state["branching"]))
            slot_ig.append(jnp.where(m_fresh, False, state["ignored"]))
            slot_newseq.append(m_fresh)
            slot_regs.append(jnp.where(m_fresh[:, None], jnp.zeros_like(final_regs), final_regs))
            slot_regs_set.append(
                jnp.where(m_fresh[:, None], jnp.zeros_like(final_set), final_set)
            )

        occ = jnp.stack(slot_occ, axis=1)              # [R, SLOTS]
        o_src = jnp.stack(slot_src, axis=1)
        o_eps = jnp.stack(slot_eps, axis=1)
        o_ver = jnp.stack(slot_ver, axis=1)            # [R, SLOTS, D]
        o_vlen = jnp.stack(slot_vlen, axis=1)
        o_seq = jnp.stack(slot_seq, axis=1)
        o_node = jnp.stack(slot_node, axis=1)
        o_ts = jnp.stack(slot_ts, axis=1)
        o_br = jnp.stack(slot_br, axis=1)
        o_ig = jnp.stack(slot_ig, axis=1)
        o_newseq = jnp.stack(slot_newseq, axis=1)
        o_regs = jnp.stack(slot_regs, axis=1)          # [R, SLOTS, A]
        o_regs_set = jnp.stack(slot_regs_set, axis=1)

        # Fresh run ids in (lane, slot) order = the oracle's queue-item-major
        # DFS allocation order for the runs counter.
        newseq_flat = (occ & o_newseq).reshape(-1)
        seq_alloc = state["runs"] + 1 + _excl_cumsum(newseq_flat)
        o_seq = jnp.where(
            (occ & o_newseq).reshape(-1), seq_alloc, o_seq.reshape(-1)
        ).reshape(R, SLOTS).astype(jnp.int32)
        new_runs = state["runs"] + jnp.sum(newseq_flat).astype(jnp.int32)

        # ==== match extraction (forwarding-to-final, NFA.java:148-158) ======
        is_match = occ & (
            ((o_eps >= 0) & t_is_final[o_eps.clip(0)])
            | ((o_eps < 0) & t_fwd_final[o_src.clip(0)])
        )
        match_flat = is_match.reshape(-1)
        mpos = state["match_count"] + _excl_cumsum(match_flat)
        match_drop = match_flat & (mpos >= M)
        midx = jnp.where(match_flat & ~match_drop, mpos, M)
        match_node = state["match_node"].at[midx].set(
            jnp.where(match_flat, o_node.reshape(-1), -1), mode="drop"
        )
        match_node = match_node.at[M].set(-1)
        new_match_count = state["match_count"] + jnp.sum(match_flat & ~match_drop).astype(
            jnp.int32
        )

        # ==== lane compaction (new queue in emission order) =================
        keep = (occ & ~is_match).reshape(-1)
        lpos = _excl_cumsum(keep)
        lane_drop = keep & (lpos >= R)
        lidx = jnp.where(keep & ~lane_drop, lpos, R)

        def scat(flat_vals, fill, extra_dims=()):
            out = jnp.full((R + 1,) + extra_dims, fill, flat_vals.dtype)
            out = out.at[lidx].set(
                jnp.where(
                    keep.reshape((-1,) + (1,) * len(extra_dims)), flat_vals, fill
                ),
                mode="drop",
            )
            return out[:R]

        n_active = scat(keep, False)
        n_src = scat(o_src.reshape(-1), 0)
        n_eps = scat(o_eps.reshape(-1), -1)
        n_ver = scat(o_ver.reshape(-1, D), 0, (D,))
        n_vlen = scat(o_vlen.reshape(-1), 0)
        n_seq = scat(o_seq.reshape(-1), 0)
        n_node = scat(o_node.reshape(-1), -1)
        n_ts = scat(o_ts.reshape(-1), -1)
        n_br = scat(o_br.reshape(-1), False)
        n_ig = scat(o_ig.reshape(-1), False)
        n_regs = scat(o_regs.reshape(-1, A), jnp.float32(0), (A,))
        n_regs_set = scat(o_regs_set.reshape(-1, A), False, (A,))

        new_state = {
            "active": n_active, "src": n_src, "eps": n_eps, "ver": n_ver,
            "vlen": n_vlen, "seq": n_seq, "node": n_node, "ts": n_ts,
            "branching": n_br, "ignored": n_ig,
            "regs": n_regs, "regs_set": n_regs_set,
            "runs": new_runs,
            "node_event": node_event, "node_name": node_name,
            "node_pred": node_pred, "node_count": new_node_count,
            "match_node": match_node, "match_count": new_match_count,
            "n_events": state["n_events"] + 1,
            "n_branches": state["n_branches"]
            + jnp.sum(jnp.stack([u["clone_m"] for u in up if u is not None])).astype(jnp.int32),
            "n_expired": state["n_expired"] + jnp.sum(expired).astype(jnp.int32),
            "lane_drops": state["lane_drops"] + jnp.sum(lane_drop).astype(jnp.int32),
            "node_drops": state["node_drops"] + jnp.sum(node_drop).astype(jnp.int32),
            "match_drops": state["match_drops"] + jnp.sum(match_drop).astype(jnp.int32),
            "seq_collisions": state["seq_collisions"] + collide.astype(jnp.int32),
        }

        # Padding lanes in a batched multi-key step carry valid=False.
        valid = x["valid"]
        merged = jax.tree.map(
            lambda new, old: jnp.where(valid, new, old), new_state, state
        )
        if debug:
            dbg = dict(
                occ=occ, o_src=o_src, o_eps=o_eps, o_seq=o_seq, o_node=o_node,
                is_match=is_match, expired=expired,
                levels=[
                    {k: v for k, v in lv.items()} for lv in levels
                ],
                up=[{k: v for k, v in u.items()} for u in up],
            )
            return merged, dbg
        return merged, None

    return step


def build_gc(config: EngineConfig):
    """Device mark-sweep compaction of the buffer node pool (single key).

    The host-native analog of the reference's refcount GC
    (SharedVersionedBufferStoreImpl.java:176-201) re-designed write-free for
    the hot path: nodes reachable from any live lane's `node` chain are kept
    and compacted to the front of the pool; everything else is freed. The
    whole pass runs on device (a `lax.while_loop` predecessor walk over all
    lanes at once + prefix-sum scatter), so no pool bytes cross the host
    boundary. vmap-able over a leading key axis.
    """
    B = config.nodes

    def gc(state: Dict[str, jnp.ndarray]) -> Dict[str, jnp.ndarray]:
        node_pred = state["node_pred"]
        lane_node = jnp.where(state["active"], state["node"], -1)

        def cond(carry):
            _, cur = carry
            return jnp.any(cur >= 0)

        def body(carry):
            marked, cur = carry
            live = cur >= 0
            # Dead cursors route to the trash slot B so their writes cannot
            # clobber slot 0 (duplicate-index .set is last-write-wins).
            cidx = jnp.where(live, cur, B)
            seen = marked[cidx] & live
            marked = marked.at[cidx].set(True)
            cur = jnp.where(live & ~seen, node_pred[cidx], -1)
            return marked, cur

        marked, _ = jax.lax.while_loop(
            cond, body, (jnp.zeros(B + 1, bool), lane_node)
        )
        keep = marked[:B]
        pos = _excl_cumsum(keep)
        remap = jnp.where(keep, pos, -1).astype(jnp.int32)  # old idx -> new
        idx_new = jnp.where(keep, pos, B)

        def scatter(vals: jnp.ndarray, fill) -> jnp.ndarray:
            out = jnp.full(B + 1, fill, vals.dtype)
            out = out.at[idx_new].set(jnp.where(keep, vals, fill), mode="drop")
            return out.at[B].set(fill)

        # Index domain of stored node pointers is [-1, B] (B = trash slot).
        remap_full = jnp.concatenate([remap, jnp.full(1, -1, jnp.int32)])
        pred_b = node_pred[:B]
        pred_remapped = jnp.where(pred_b >= 0, remap_full[pred_b.clip(0)], -1)
        new_lane = jnp.where(
            state["node"] >= 0, remap_full[state["node"].clip(0)], -1
        )
        return {
            **state,
            "node_event": scatter(state["node_event"][:B], -1),
            "node_name": scatter(state["node_name"][:B], -1),
            "node_pred": scatter(pred_remapped, -1),
            "node_count": jnp.sum(keep).astype(jnp.int32),
            "node": new_lane.astype(jnp.int32),
        }

    return gc


def build_batch_fn(query: CompiledQuery, config: EngineConfig):
    """jit-compiled batch advance: scan the one-event step over [T] columns.

    `xs` is the packed batch: event columns ("f:*", "ts", "topic") of shape
    [T], plus "spred" [T, P] (precomputed stateless predicate rows),
    "gidx" [T] global event indices and "valid" [T].
    """
    step = build_step(query, config)

    @jax.jit
    def advance(state, xs):
        state, _ = jax.lax.scan(step, state, xs)
        return state

    return advance


def eval_stateless_preds(query: CompiledQuery, cols: Dict[str, np.ndarray]) -> jnp.ndarray:
    """Evaluate all stateless predicates over the whole batch: one fused
    vectorized pass per predicate (the [T, P] mask precompute).

    Column leaves may be [T] (single key) or [T, K] (batched multi-key); the
    predicate axis is appended last, so the result is [T, P] or [T, K, P].
    """
    shape = np.shape(cols["ts"])
    env = DeviceEnv(
        {k: jnp.asarray(v) for k, v in cols.items()},
        jnp.zeros((1, query.n_aggs), jnp.float32),
        jnp.zeros((1, query.n_aggs), bool),
        query.agg_slots,
        query.agg_defaults,
    )
    out = []
    for p in range(max(query.n_preds, 1)):
        if p < query.n_preds and not query.pred_stateful[p]:
            v = jnp.broadcast_to(jnp.asarray(query.predicates[p](env), bool), shape)
        else:
            v = jnp.zeros(shape, bool)  # stateful: evaluated in-step per lane
        out.append(v)
    return jnp.stack(out, axis=-1)
