"""Log pump driver: consume source topics, drive the topology, commit.

The Kafka-Streams-runtime role the reference delegates to its platform
(reference: the poll/process/commit loop of Kafka Streams' StreamThread
driving CEPProcessor.java:111-160, with changelog restore on start and
consumer-group offset commits). Here the transport is the embedded
`RecordLog` (streams/log.py): the driver restores every query store from
its changelog topic, resumes from the committed consumer offsets (stored in
the log's `__consumer_offsets` topic), and pumps records through
`Topology.process`, committing after each poll.

Records in source topics carry pickled keys/values by default; pass
`key_deserializer`/`value_deserializer` for custom wire formats.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

from ..state.store import default_deserializer, default_serializer
from .builder import Topology
from .log import RecordLog

OFFSETS_TOPIC = "__consumer_offsets"


def produce(
    log: RecordLog,
    topic: str,
    key: Any,
    value: Any,
    timestamp: int = 0,
    partition: int = 0,
) -> int:
    """Producer-side helper: append one (key, value) record, default serde."""
    return log.append(
        topic,
        default_serializer(key),
        default_serializer(value),
        timestamp=timestamp,
        partition=partition,
    )


class LogDriver:
    """Drives one topology from a RecordLog: restore, poll, commit."""

    def __init__(
        self,
        topology: Topology,
        log: Optional[RecordLog] = None,
        group: str = "default",
        key_deserializer: Callable[[bytes], Any] = default_deserializer,
        value_deserializer: Callable[[bytes], Any] = default_deserializer,
        restore: bool = True,
    ) -> None:
        self.topology = topology
        self.log = log if log is not None else topology.log
        if self.log is None:
            raise ValueError("LogDriver needs a RecordLog (topology built without one)")
        self.group = group
        self.key_de = key_deserializer
        self.value_de = value_deserializer
        self._positions: Dict[Tuple[str, int], int] = {}
        #: positions as last durably committed -- commit() appends only the
        #: deltas, so the offsets topic grows with progress, not with the
        #: commit count (the last-write-wins read tolerates either).
        self._committed: Dict[Tuple[str, int], int] = {}
        self.restored_records = 0
        if restore:
            self.restored_records = self.topology.restore_stores()
        self._load_committed()

    # ------------------------------------------------------------- offsets
    def _load_committed(self) -> None:
        """Latest committed position per (group, topic, partition)."""
        for rec in self.log.read(OFFSETS_TOPIC):
            if rec.key is None or rec.value is None:
                continue
            group, topic, partition = default_deserializer(rec.key)
            if group != self.group:
                continue
            pos = default_deserializer(rec.value)
            self._positions[(topic, partition)] = pos
            self._committed[(topic, partition)] = pos

    def commit(self) -> None:
        """Durably record consumer positions after making the state they
        cover durable (the reference commits offsets and flushes stores
        together at the commit interval).

        Order matters for at-least-once: the changelog/sink appends are
        fsynced BEFORE the offset record is appended and fsynced, so a crash
        between the two replays the interval (deduped by the HWM) instead of
        silently skipping records whose effects were lost."""
        self.topology.flush_stores()
        self.log.flush()  # changelog + sink records durable first
        dirty = {
            tp: pos
            for tp, pos in self._positions.items()
            if self._committed.get(tp) != pos
        }
        if not dirty:
            return
        for (topic, partition), pos in dirty.items():
            self.log.append(
                OFFSETS_TOPIC,
                default_serializer((self.group, topic, partition)),
                default_serializer(pos),
            )
        self.log.flush()
        self._committed.update(dirty)

    def position(self, topic: str, partition: int = 0) -> int:
        return self._positions.get((topic, partition), 0)

    # ---------------------------------------------------------------- poll
    def poll(self, max_records: Optional[int] = None, commit: bool = True) -> int:
        """Consume available records from every source topic, in offset
        order per partition; returns how many were processed."""
        processed = 0
        budget = max_records
        for topic in self.topology.source_topics:
            partitions = self.log.partitions(topic) or [0]
            for partition in partitions:
                start = self._positions.get((topic, partition), 0)
                records = self.log.read(topic, partition, start, budget)
                for rec in records:
                    self.topology.process(
                        topic,
                        self.key_de(rec.key) if rec.key is not None else None,
                        self.value_de(rec.value) if rec.value is not None else None,
                        timestamp=rec.timestamp,
                        partition=partition,
                        offset=rec.offset,
                    )
                    processed += 1
                if records:
                    self._positions[(topic, partition)] = records[-1].offset + 1
                if budget is not None:
                    budget -= len(records)
                    if budget <= 0:
                        break
            if budget is not None and budget <= 0:
                break
        self.topology.flush()  # flush device micro-batches
        if commit and processed:
            self.commit()
        return processed
