"""Fleet controller: SLO burn rates close the rebalance loop (ISSUE 20).

ROADMAP item 4 left "driving `plan()` from a periodic controller loop
instead of call sites" open: PR 13 built the mechanism (fence ->
checkpoint -> resume via `rebalance.migrate`, salvage recovery via
`recover_broker`) but every invocation was a call site deciding for
itself. `FleetController` is the daemon that decides from OBSERVATIONS
only:

1. **Scrape.** Each tick pulls every configured source's registry
   snapshot -- an `IntrospectionServer` URL (``GET /snapshot``), a live
   `MetricsRegistry`, or any callable returning a snapshot dict. A
   source that fails to scrape is counted
   (`cep_controller_scrape_errors_total`) and skipped; the loop never
   wedges on one dead broker.
2. **Merge.** Snapshots merge through `obs.merge.merge_snapshots` --
   counters sum, gauges gain the `device` label, histograms add
   bucket-wise -- so SLO evaluation sees the fleet as one system.
3. **Evaluate burn.** Three SLOs, the same families the PR 10 soak
   gates: match-latency p99 (merged `cep_match_latency_seconds`
   buckets), emission integrity (the soak's DROP_SERIES counters --
   any fleet-wide drop is burn), and pend-occupancy drift (least-squares
   slope of the merged `cep_pend_occupancy` over the controller's own
   sample history). Burn = observed / budget; >= the policy threshold
   is a breach (`cep_slo_burn_rate{slo}` /
   `cep_slo_burn_breaches_total{slo}`).
4. **Act.** Per-shard load (delta of each device's
   `cep_driver_records_total` per tick) feeds `rebalance.plan()`;
   returned actions -- skew migrations, dead-broker recovery -- are
   handed to the configured `execute` callback (the harness wires it to
   `RebalanceController.migrate` / `recover_broker`), rate-limited by a
   cooldown so one hot window cannot thrash shards back and forth.
   Every decision (burn, loads, actions, execution results) lands in a
   bounded ring served by `state()` -- the block the soak artifact
   records.

Pure host-side: scraping, merging and planning never touch a device or
the data path; acting is whatever the callback does.
"""
from __future__ import annotations

import json
import threading
import time
from typing import Any, Callable, Dict, List, Mapping, Optional, Tuple
from urllib.request import urlopen

from ..obs.merge import merge_snapshots
from ..obs.registry import MetricsRegistry, default_registry

__all__ = ["ControllerPolicy", "FleetController", "histogram_quantile"]

#: Counter families whose fleet-wide increase burns the emission SLO --
#: mirrors faults.soak.DROP_SERIES (imported lazily there to avoid a
#: faults -> ops cycle; the soak asserts the two stay equal).
DROP_SERIES: Tuple[str, ...] = (
    "cep_overflow_dropped_total",
    "cep_reorder_overflow_dropped_total",
    "cep_late_dropped_total",
    "cep_driver_dead_letters_total",
)


class ControllerPolicy:
    """Thresholds the controller steers by. Budgets are per-SLO
    denominators (burn = observed / budget); `burn_threshold` is where a
    burn becomes a breach; skew/dead knobs pass through to
    `rebalance.plan`; `cooldown_s` bounds how often actions execute."""

    __slots__ = (
        "latency_p99_budget_s",
        "drops_budget_per_s",
        "pend_slope_budget_per_s",
        "burn_threshold",
        "skew_ratio",
        "min_load",
        "dead_after_s",
        "cooldown_s",
    )

    def __init__(
        self,
        latency_p99_budget_s: float = 0.5,
        drops_budget_per_s: float = 0.0,
        pend_slope_budget_per_s: float = 50.0,
        burn_threshold: float = 1.0,
        skew_ratio: float = 4.0,
        min_load: float = 1.0,
        dead_after_s: float = 10.0,
        cooldown_s: float = 2.0,
    ) -> None:
        self.latency_p99_budget_s = float(latency_p99_budget_s)
        self.drops_budget_per_s = float(drops_budget_per_s)
        self.pend_slope_budget_per_s = float(pend_slope_budget_per_s)
        self.burn_threshold = float(burn_threshold)
        self.skew_ratio = float(skew_ratio)
        self.min_load = float(min_load)
        self.dead_after_s = float(dead_after_s)
        self.cooldown_s = float(cooldown_s)

    def as_dict(self) -> Dict[str, float]:
        return {name: getattr(self, name) for name in self.__slots__}


def histogram_quantile(fam: Mapping[str, Any], q: float) -> Optional[float]:
    """Quantile estimate from a snapshot histogram family: sum the
    cumulative buckets across every label set (layouts agree within a
    family -- the registry and merge both enforce it), then return the
    smallest finite upper bound covering q of the count. None on an
    empty family; the top bucket answers with its lower neighbor's bound
    (the honest "at least this much" -- there is no upper edge)."""
    cum: Dict[float, float] = {}
    total = 0.0
    for entry in fam.get("values", ()):
        total += float(entry.get("count", 0))
        for le_s, c in entry.get("buckets", {}).items():
            le = float("inf") if le_s in ("+Inf", "inf") else float(le_s)
            cum[le] = cum.get(le, 0.0) + float(c)
    if total <= 0:
        return None
    want = q * total
    bounds = sorted(cum)
    prev_finite = 0.0
    for le in bounds:
        if cum[le] >= want:
            return prev_finite if le == float("inf") else le
        if le != float("inf"):
            prev_finite = le
    return prev_finite


def _fold_counter(fam: Optional[Mapping[str, Any]]) -> float:
    if fam is None:
        return 0.0
    return sum(float(e.get("value", 0.0)) for e in fam.get("values", ()))


def _fold_gauge_sum(fam: Optional[Mapping[str, Any]]) -> float:
    if fam is None:
        return 0.0
    return sum(float(e.get("value", 0.0)) for e in fam.get("values", ()))


class FleetController:
    """The burn-rate-driven rebalance daemon (module docstring).

    `sources` maps a device/shard id to where its metrics live: an
    IntrospectionServer base URL (``http://...``), a live
    `MetricsRegistry`, or a zero-arg callable returning a snapshot dict.
    `execute` receives each `rebalance.plan` action dict and does the
    actual migration/recovery; its return value (or exception string)
    is recorded in the decision. `broker_ages_fn` supplies
    {device: last_ok_age_s} for dead-broker planning (all-zero default:
    scrape failure is the liveness signal instead)."""

    def __init__(
        self,
        sources: Mapping[str, Any],
        registry: Optional[MetricsRegistry] = None,
        policy: Optional[ControllerPolicy] = None,
        execute: Optional[Callable[[Dict[str, Any]], Any]] = None,
        broker_ages_fn: Optional[Callable[[], Mapping[str, float]]] = None,
        every_s: float = 1.0,
        timeout_s: float = 2.0,
        decisions: int = 128,
    ) -> None:
        if not sources:
            raise ValueError("FleetController needs at least one source")
        self.sources = dict(sources)
        self.metrics = registry if registry is not None else default_registry()
        self.policy = policy if policy is not None else ControllerPolicy()
        self.execute = execute
        self.broker_ages_fn = broker_ages_fn
        self.every_s = max(0.01, float(every_s))
        self.timeout_s = float(timeout_s)
        from collections import deque

        self._decisions: Any = deque(maxlen=max(1, int(decisions)))
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        #: Per-device previous (wall, records_total) for load deltas.
        self._prev_records: Dict[str, Tuple[float, float]] = {}
        #: Previous (wall, fleet drop total) for the emission burn rate.
        self._prev_drops: Optional[Tuple[float, float]] = None
        #: (wall, merged pend occupancy) history for the drift slope.
        self._pend_hist: Any = deque(maxlen=256)
        self._last_action_t: Optional[float] = None
        self.ticks = 0
        m = self.metrics
        self._m_burn = m.gauge(
            "cep_slo_burn_rate",
            "Fleet SLO burn (observed/budget; >= policy threshold is a "
            "breach) from merged scrapes, per SLO",
            labels=("slo",),
        )
        self._m_breaches = m.counter(
            "cep_slo_burn_breaches_total",
            "Controller ticks on which an SLO's burn crossed the policy "
            "threshold",
            labels=("slo",),
        )
        self._m_ticks = m.counter(
            "cep_controller_ticks_total",
            "Fleet-controller evaluation ticks",
        )
        self._m_scrape_errors = m.counter(
            "cep_controller_scrape_errors_total",
            "Source scrapes that failed (skipped, never wedging the loop)",
            labels=("device",),
        )
        self._m_actions = m.counter(
            "cep_controller_actions_total",
            "Rebalance actions the controller invoked, by plan kind",
            labels=("kind",),
        )
        self._m_load = m.gauge(
            "cep_controller_shard_load",
            "Per-shard load (records/s delta of cep_driver_records_total) "
            "the controller last fed to rebalance.plan",
            labels=("shard",),
        )

    # ------------------------------------------------------------- scraping
    def _snapshot_of(self, source: Any) -> Dict[str, Any]:
        if isinstance(source, str):
            with urlopen(
                source.rstrip("/") + "/snapshot", timeout=self.timeout_s
            ) as resp:
                return json.loads(resp.read().decode("utf-8"))
        if callable(source):
            return source()
        return source.snapshot()

    def _scrape(self) -> Dict[str, Dict[str, Any]]:
        snaps: Dict[str, Dict[str, Any]] = {}
        for device, source in self.sources.items():
            try:
                snaps[device] = self._snapshot_of(source)
            except Exception:
                self._m_scrape_errors.labels(device=str(device)).inc()
        return snaps

    # ------------------------------------------------------------ one tick
    def tick(self) -> Dict[str, Any]:
        """One scrape -> merge -> evaluate -> (maybe) act pass. Returns
        the decision record (also kept in the bounded ring)."""
        from ..streams.rebalance import plan

        now = time.time()
        snaps = self._scrape()
        merged = merge_snapshots(snaps) if snaps else {}

        # Per-shard load: records/s since each device's previous tick.
        # tick() is reachable from both the daemon loop and direct
        # callers (tests, one-shot harnesses), so delta state lives
        # under the lock; scraping and acting stay outside it.
        shard_loads: Dict[str, float] = {}
        with self._lock:
            for device, snap in snaps.items():
                total = _fold_counter(snap.get("cep_driver_records_total"))
                prev = self._prev_records.get(device)
                self._prev_records[device] = (now, total)
                if prev is None or now <= prev[0]:
                    continue
                shard_loads[device] = (
                    max(0.0, total - prev[1]) / (now - prev[0])
                )
        for shard, load in shard_loads.items():
            self._m_load.labels(shard=str(shard)).set(load)

        # SLO burn rates off the merged fleet view.
        pol = self.policy
        p99 = histogram_quantile(
            merged.get("cep_match_latency_seconds", {}), 0.99
        )
        burn: Dict[str, float] = {}
        burn["match_latency_p99"] = (
            0.0 if p99 is None else p99 / max(pol.latency_p99_budget_s, 1e-9)
        )
        drops = sum(_fold_counter(merged.get(s)) for s in DROP_SERIES)
        with self._lock:
            prev_drops = self._prev_drops
            self._prev_drops = (now, drops)
        if prev_drops is None or now <= prev_drops[0]:
            drop_rate = 0.0
        else:
            drop_rate = max(0.0, drops - prev_drops[1]) / (now - prev_drops[0])
        if pol.drops_budget_per_s > 0:
            burn["emission_integrity"] = drop_rate / pol.drops_budget_per_s
        else:
            # Zero budget: any fleet-wide drop is a full breach.
            burn["emission_integrity"] = (
                0.0 if drop_rate <= 0 else max(1.0, drop_rate)
            )
        pend = _fold_gauge_sum(merged.get("cep_pend_occupancy"))
        self._pend_hist.append((now, pend))
        burn["pend_drift"] = (
            max(0.0, self._pend_slope())
            / max(pol.pend_slope_budget_per_s, 1e-9)
        )
        breached = []
        for slo, b in burn.items():
            self._m_burn.labels(slo=slo).set(b)
            if b >= pol.burn_threshold:
                self._m_breaches.labels(slo=slo).inc()
                breached.append(slo)

        # Plan + act. plan() detects skew and dead brokers on its own;
        # the controller supplies what it observed and rate-limits the
        # execution.
        ages = (
            dict(self.broker_ages_fn())
            if self.broker_ages_fn is not None
            else {d: 0.0 for d in self.sources}
        )
        actions = plan(
            shard_loads,
            ages,
            skew_ratio=pol.skew_ratio,
            dead_after_s=pol.dead_after_s,
            min_load=pol.min_load,
        )
        executed: List[Dict[str, Any]] = []
        with self._lock:
            cooled = (
                self._last_action_t is not None
                and now - self._last_action_t < pol.cooldown_s
            )
            acting = bool(actions) and self.execute is not None and not cooled
            if acting:
                self._last_action_t = now
        if acting:
            for action in actions:
                self._m_actions.labels(kind=str(action.get("kind"))).inc()
                outcome: Dict[str, Any] = dict(action)
                try:
                    result = self.execute(action)
                    outcome["ok"] = True
                    if result is not None:
                        outcome["result"] = str(result)
                except Exception as exc:
                    outcome["ok"] = False
                    outcome["error"] = f"{type(exc).__name__}: {exc}"
                executed.append(outcome)
        with self._lock:
            self.ticks += 1
        self._m_ticks.inc()
        decision = {
            "t_unix": now,
            "scraped": sorted(snaps),
            "shard_loads": shard_loads,
            "burn": burn,
            "breached": breached,
            "planned": actions,
            "cooldown": bool(actions) and cooled,
            "executed": executed,
        }
        with self._lock:
            self._decisions.append(decision)
        return decision

    def _pend_slope(self) -> float:
        """Least-squares slope (units/s) of the merged pend occupancy
        history -- the same drift statistic the soak's leak SLO uses."""
        pts = list(self._pend_hist)
        if len(pts) < 3:
            return 0.0
        n = float(len(pts))
        t0 = pts[0][0]
        xs = [t - t0 for t, _v in pts]
        ys = [v for _t, v in pts]
        sx = sum(xs)
        sy = sum(ys)
        sxx = sum(x * x for x in xs)
        sxy = sum(x * y for x, y in zip(xs, ys))
        denom = n * sxx - sx * sx
        if denom <= 0:
            return 0.0
        return (n * sxy - sx * sy) / denom

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "FleetController":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="kct-fleet-controller", daemon=True
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.every_s):
            try:
                self.tick()
            except Exception:
                import logging

                logging.getLogger("kafkastreams_cep_tpu.obs").warning(
                    "fleet controller tick failed", exc_info=True
                )

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def __enter__(self) -> "FleetController":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()

    # -------------------------------------------------------------- surface
    def decisions(self, limit: int = 64) -> List[Dict[str, Any]]:
        """Recent decision records, newest first."""
        with self._lock:
            snap = list(self._decisions)
        return snap[::-1][: max(0, limit)]

    def state(self) -> Dict[str, Any]:
        """The controller block a soak artifact records: tick/action
        totals, last burn, policy, and the bounded decision ring
        (oldest first, JSON-ready)."""
        with self._lock:
            decs = list(self._decisions)
        last_burn = decs[-1]["burn"] if decs else {}
        actions = sum(len(d["executed"]) for d in decs)
        return {
            "enabled": True,
            "ticks": self.ticks,
            "actions": actions,
            "burn": last_burn,
            "policy": self.policy.as_dict(),
            "decisions": decs,
        }
