"""Sink-to-bytes golden parity (ISSUE 17 tentpole pin).

`sink_format="json"|"arrow"` decodes the flat chain table straight to
sink payload bytes (native/decoder.cc decode_matches_json/arrow). The
correctness contract this suite pins:

  * payloads are BYTE-EQUAL to host-Python serialization of the object
    path's decoded Sequences -- across engines (xla, pallas_interpret),
    mid-stream drain boundaries, capacity pressure (GC-dropped chains),
    and an out-of-order event-time-gated stream;
  * EmissionGate digests are IDENTICAL between the object path
    (`admit(key, seq)`) and the bytes path (`admit_ident(key, frames)`),
    including occurrence qualification and crash-recovery dedup -- the
    sink topic's record keys are the observable;
  * the exactly-once recovery path is format-agnostic (digests ride the
    sink records either way).
"""
import random

import pytest

from kafkastreams_cep_tpu import Event, QueryBuilder, Selected, compile_pattern
from kafkastreams_cep_tpu.ops.engine import EngineConfig
from kafkastreams_cep_tpu.parallel import BatchedDeviceNFA
from kafkastreams_cep_tpu.pattern.expressions import agg, value
from kafkastreams_cep_tpu.streams.serde import (
    SinkMatch,
    sequence_to_arrow_ipc,
    sequence_to_json_bytes,
)

TS = 1_000_000

REF = {"json": sequence_to_json_bytes, "arrow": sequence_to_arrow_ipc}


def abc_pattern():
    return (
        QueryBuilder()
        .select("a").where(value() == "A")
        .then().select("b").where(value() == "B")
        .then().select("c").where(value() == "C")
        .build()
    )


def branching_pattern():
    return (
        QueryBuilder()
        .select("first").where(value() == "A")
        .fold("cnt", agg("cnt", default=0) + 1)
        .then().select("second", Selected.with_skip_til_any_match())
        .one_or_more().where(value() == "C")
        .then().select("latest").where(value() == "D")
        .build()
    )


def letter_stream(seed, n, key=None, letters="ABCD"):
    rng = random.Random(seed)
    return [
        Event(key or f"k{seed}", rng.choice(letters), TS + i, "t", 0, i)
        for i in range(n)
    ]


def drive(pattern, streams, splits, config, *, sink_format="objects",
          engine="xla", native=True, **kw):
    keys = list(streams)
    bat = BatchedDeviceNFA(
        compile_pattern(pattern), keys=keys, config=config,
        drain_mode="flat", sink_format=sink_format, engine=engine,
        query_name="q1", **kw,
    )
    if not native:
        bat._native_dec = None
    got = {k: [] for k in keys}
    for lo, hi in splits:
        chunk = {k: evs[lo:hi] for k, evs in streams.items() if evs[lo:hi]}
        if not chunk:
            continue
        for k, seqs in bat.advance(chunk).items():
            got[k].extend(seqs)
    return got, bat


def assert_parity(obj, sink, fmt):
    """Bytes run == serialize(object run), match for match, in order."""
    assert set(k for k, v in obj.items() if v) == set(
        k for k, v in sink.items() if v
    )
    total = 0
    for k, seqs in obj.items():
        sms = sink[k]
        assert len(sms) == len(seqs), k
        for sm, seq in zip(sms, seqs):
            assert isinstance(sm, SinkMatch)
            assert sm.format == fmt
            assert sm.payload == REF[fmt](seq)
            assert sm.last_event == seq.matched[-1].events[-1]
            total += 1
    return total


@pytest.mark.parametrize("engine", ["xla", "pallas_interpret"])
@pytest.mark.parametrize("fmt", ["json", "arrow"])
def test_sink_parity_engines(engine, fmt):
    """Native sink bytes == host serialization of the object path, across
    both compute engines and mid-stream drain boundaries."""
    config = EngineConfig(lanes=32, nodes=256, matches=64,
                          matches_per_step=16, nodes_per_step=16)
    streams = {f"k{i}": letter_stream(300 + i, 18) for i in range(3)}
    splits = [(0, 7), (7, 12), (12, 100)]
    obj, _ = drive(branching_pattern(), streams, splits, config,
                   engine=engine)
    sink, bat = drive(branching_pattern(), streams, splits, config,
                      sink_format=fmt, engine=engine)
    assert assert_parity(obj, sink, fmt) > 0
    assert bat._native_decoder() is not None


@pytest.mark.parametrize("fmt", ["json", "arrow"])
def test_sink_parity_python_fallback(fmt):
    """The host-Python fallback (no native module) produces the same
    SinkMatch bytes -- plus the object-path Sequence it serialized."""
    config = EngineConfig(lanes=32, nodes=256, matches=64,
                          matches_per_step=16)
    streams = {f"k{i}": letter_stream(41 + i, 16) for i in range(2)}
    splits = [(0, 9), (9, 100)]
    obj, _ = drive(branching_pattern(), streams, splits, config)
    sink, _ = drive(branching_pattern(), streams, splits, config,
                    sink_format=fmt, native=False)
    assert assert_parity(obj, sink, fmt) > 0
    for sms in sink.values():
        for sm in sms:
            assert sm.sequence is not None  # fallback decodes objects


@pytest.mark.parametrize("fmt", ["json", "arrow"])
def test_sink_parity_capacity_pressure(fmt):
    """Under node-region overflow (node_drops > 0, GC-dropped chains) the
    bytes path must degrade IDENTICALLY to the object path: dead chains
    decode to nothing, survivors byte-match."""
    config = EngineConfig(lanes=64, nodes=48, matches=128,
                          matches_per_step=16)
    streams = {f"k{i}": letter_stream(500 + i, 40) for i in range(2)}
    splits = [(0, 14), (14, 27), (27, 100)]
    obj, bo = drive(branching_pattern(), streams, splits, config)
    sink, bs = drive(branching_pattern(), streams, splits, config,
                     sink_format=fmt)
    assert bs.stats == bo.stats
    assert assert_parity(obj, sink, fmt) > 0


def test_sink_json_out_of_order_event_time_gated():
    """An out-of-order stream behind the event-time gate (reorder buffer
    + watermark release) must emit identical sink bytes and identical
    emission digests in objects and json modes -- the gate feeds the
    engine in event-time order either way, so the parity pin extends
    through the reorder plane."""
    from kafkastreams_cep_tpu.streams.builder import ComplexStreamsBuilder
    from kafkastreams_cep_tpu.streams.log import RecordLog

    # Bounded shuffle of an ABC stream: at most 3 positions displaced.
    letters = list("ABCXABCABCXABC")
    evs = [(v, TS + i) for i, v in enumerate(letters)]
    rng = random.Random(13)
    arrival = list(evs)
    for i in range(0, len(arrival) - 3, 3):
        j = i + rng.randint(0, 2)
        arrival[i], arrival[j] = arrival[j], arrival[i]

    def run(sink_format):
        log = RecordLog()
        b = ComplexStreamsBuilder(log=log, app_id="oo")
        opts = {} if sink_format == "objects" else {
            "sink_format": sink_format, "drain_mode": "flat",
        }
        (b.stream("letters")
          .query("q1", abc_pattern(), runtime="tpu", batch_size=4,
                 config=EngineConfig(lanes=16, nodes=256, matches=64,
                                     reorder_capacity=32, lateness_ms=4),
                 **opts)
          .to("matches"))
        topo = b.build()
        for off, (v, ts) in enumerate(arrival):
            topo.process("letters", "K", v, timestamp=ts, offset=off)
        topo.flush_event_time()
        topo.flush()
        return [(r.key, r.value) for r in log.read("matches")]

    obj = run("objects")
    js = run("json")
    assert len(obj) == len(js) > 0
    # Sink keys carry the emission digests: byte-equal keys == digest
    # parity; byte-equal values == payload parity.
    assert obj == js


@pytest.mark.parametrize("fmt", ["json", "arrow"])
def test_sink_topology_digest_and_dedup_parity(fmt):
    """Topology-level: same sink record keys (digests) in objects and
    bytes modes, including the duplicate-match occurrence qualification,
    and the recovery dedup window accepts bytes-mode digests."""
    from kafkastreams_cep_tpu.streams.builder import ComplexStreamsBuilder
    from kafkastreams_cep_tpu.streams.log import RecordLog

    stream = list("ABCABCXABC")

    def run(sink_format):
        log = RecordLog()
        b = ComplexStreamsBuilder(log=log, app_id="dd")
        opts = {} if sink_format == "objects" else {
            "sink_format": sink_format, "drain_mode": "flat",
        }
        (b.stream("letters")
          .query("q1", abc_pattern(), runtime="tpu", batch_size=3,
                 config=EngineConfig(lanes=16, nodes=256, matches=64),
                 **opts)
          .to("matches"))
        topo = b.build()
        for off, v in enumerate(stream):
            topo.process("letters", "K", v, timestamp=TS + off, offset=off)
        topo.flush()
        recs = log.read("matches")
        return topo, [(r.key, r.value) for r in recs]

    _, obj = run("objects")
    topo, got = run(fmt)
    assert [k for k, _ in obj] == [k for k, _ in got]
    if fmt == "json":
        assert [v for _, v in obj] == [v for _, v in got]
    # Recovery over bytes-mode sink records: recover() re-reads the tail
    # and seeds the dedup window with the same digests.
    node = topo.queries[0][1]
    node.gate._emitted.clear()
    n = node.gate.recover(topo.log, ["matches"])
    assert n == len(got)


def test_sink_format_validation():
    cfg = EngineConfig(lanes=8, nodes=64, matches=16)
    q = compile_pattern(abc_pattern())
    with pytest.raises(ValueError, match="sink_format"):
        BatchedDeviceNFA(q, keys=["k"], config=cfg, sink_format="csv")
    with pytest.raises(ValueError, match="flat"):
        BatchedDeviceNFA(q, keys=["k"], config=cfg, drain_mode="pool",
                         sink_format="json")
    from kafkastreams_cep_tpu.ops.tables import compile_multi_query

    mq = compile_multi_query(
        [("qa", abc_pattern()), ("qb", abc_pattern())], None
    )
    with pytest.raises(ValueError, match="stacked"):
        BatchedDeviceNFA(mq, keys=["k"], config=cfg, sink_format="json")


def test_sink_bytes_replay_boundary_parity():
    """Exact-replay boundaries (fold-divergence recovery) in bytes mode:
    oracle-replayed matches re-serialize through the host reference and
    must byte-match the object-mode run of the same stream."""
    rng = random.Random(50_072)
    pattern = (
        QueryBuilder()
        .select("s0").where(value() == "A")
        .then().select("s1", Selected.with_skip_til_any_match())
        .one_or_more().where(value() == "B")
        .fold("cnt", agg("cnt", default=0) + 1)
        .then().select("s2").where(
            (value() == "C") & (agg("cnt", default=0) <= 2)
        )
        .build()
    )
    keys = ["kA", "kB"]
    streams = {}
    for key in keys:
        ts = 1000
        events = []
        for i in range(20):
            ts += rng.choice([0, 1, 1, 2])
            events.append(Event(key, rng.choice("ABCD"), ts, "t", 0, i))
        streams[key] = events
    config = EngineConfig(lanes=256, nodes=2048, matches=1024,
                          matches_per_step=128)
    splits = [(0, 5), (5, 10), (10, 15), (15, 100)]
    obj, bo = drive(pattern, streams, splits, config, exact_replay=True)
    sink, bs = drive(pattern, streams, splits, config, sink_format="json",
                     exact_replay=True)
    assert bs.replays == bo.replays
    assert assert_parity(obj, sink, "json") > 0


def test_sink_metrics_registered():
    """cep_sink_matches_total / cep_sink_bytes_total count the bytes-mode
    decode (labels query, format)."""
    from kafkastreams_cep_tpu.obs.registry import MetricsRegistry

    reg = MetricsRegistry()
    config = EngineConfig(lanes=8, nodes=64, matches=32)
    streams = {"k0": [Event("k0", "ABC"[i % 3], TS + i, "t", 0, i)
                      for i in range(12)]}
    got, _ = drive(abc_pattern(), streams, [(0, 100)], config,
                   sink_format="json", registry=reg)
    n = sum(len(v) for v in got.values())
    assert n > 0
    fam = reg.get("cep_sink_matches_total")
    assert fam.labels(query="q1", format="json").value == n
    total = sum(len(sm.payload) for v in got.values() for sm in v)
    assert reg.get("cep_sink_bytes_total").labels(
        query="q1", format="json"
    ).value == total


def test_sink_bytes_provenance_sampling():
    """Sampled matches re-decode through the object path: the SinkMatch
    carries the materialized Sequence with provenance attached, the ring
    records the exemplar, and the payload still byte-matches."""
    config = EngineConfig(lanes=32, nodes=256, matches=64,
                          matches_per_step=16)
    streams = {f"k{i}": letter_stream(70 + i, 16) for i in range(2)}
    sink, bat = drive(branching_pattern(), streams, [(0, 100)], config,
                      sink_format="json", provenance_sample=0.5)
    n = sum(len(v) for v in sink.values())
    sampled = [sm for v in sink.values() for sm in v if sm.sequence is not None]
    assert n > 1
    assert 0 < len(sampled) <= n
    for sm in sampled:
        assert sm.sequence.provenance is not None
        assert sequence_to_json_bytes(sm.sequence) == sm.payload
    assert len(bat.provenance_exemplars()) == len(sampled)
