"""Cross-device registry merge: one exposition for a sharded fleet.

The deferred PR 5 follow-up (ROADMAP item 2): each device/shard owns a
host-side MetricsRegistry, and a scraper wants ONE exposition for the
fleet. The merge rules mirror what the series semantics demand:

- **Counters sum.** Monotonic totals with identical label sets add across
  devices (the fleet's total is the sum of the parts; per-device
  attribution, when wanted, belongs in an explicit label the source
  registry already carries).
- **Gauges carry a `device` label.** A point-in-time value from two
  devices is two series, never a sum -- each child gains
  `device="<id>"` so the series can never interleave. Gauges whose
  family already declares a `device` label are passed through verbatim,
  and a collision (two source registries claiming the same device value)
  is an error, not a silent overwrite.
- **Histograms merge bucket-wise.** Families must agree on bucket
  layout (a mismatch is two subsystems fighting over one name -- the
  same rule MetricsRegistry enforces at registration); cumulative bucket
  counts, `sum` and `count` add per label set.

The merge operates on snapshots (`MetricsRegistry.snapshot()` dicts), so
it works identically for live registries, bench artifacts and anything a
remote shard shipped over the wire; `merge_registries` is the live-object
convenience. Bounded cardinality survives the merge: the rebuilt registry
enforces `max_label_sets` like any other, so a fleet-wide label explosion
(K devices x L series) fails loudly instead of flooding the exposition.
"""
from __future__ import annotations

from typing import Any, Dict, List, Mapping, Optional, Tuple

from .registry import MetricsRegistry, registry_from_snapshot

__all__ = ["merge_registries", "merge_snapshots"]


def _label_key(labels: Mapping[str, Any], names: List[str]) -> Tuple[str, ...]:
    return tuple(str(labels[n]) for n in names)


def merge_snapshots(
    snaps: Mapping[str, Mapping[str, Any]],
) -> Dict[str, Any]:
    """Merge per-device registry snapshots into one snapshot dict.

    `snaps` maps a device id (mesh shard index, hostname, ...) to that
    device's `MetricsRegistry.snapshot()`. Returns a snapshot in the same
    format, mergeable further or rebuildable via `registry_from_snapshot`.
    """
    merged: Dict[str, Any] = {}
    for device, snap in snaps.items():
        for name, fam in snap.items():
            kind = fam["type"]
            label_names = list(fam.get("label_names", ()))
            out = merged.get(name)
            if out is None:
                out_label_names = list(label_names)
                if kind == "gauge" and "device" not in out_label_names:
                    out_label_names.append("device")
                out = merged[name] = {
                    "type": kind,
                    "help": fam.get("help", ""),
                    "label_names": out_label_names,
                    "_src_label_names": label_names,
                    "_bucket_layout": None,
                    "values": [],
                    "_index": {},
                }
            else:
                if out["type"] != kind or out["_src_label_names"] != label_names:
                    raise ValueError(
                        f"metric {name!r}: device {device!r} disagrees on "
                        f"type/labels ({kind} {label_names} vs "
                        f"{out['type']} {out['_src_label_names']})"
                    )
            for entry in fam["values"]:
                labels = dict(entry["labels"])
                if kind == "histogram":
                    # FAMILY-level layout check (prom registries hold one
                    # bucket layout per family): comparing only on a label
                    # collision would let disjoint label sets smuggle two
                    # layouts into one family, which the rebuilt registry
                    # then renders corruptly.
                    layout = frozenset(entry["buckets"])
                    if out["_bucket_layout"] is None:
                        out["_bucket_layout"] = layout
                    elif out["_bucket_layout"] != layout:
                        raise ValueError(
                            f"histogram {name!r}: device {device!r} bucket "
                            f"layout {sorted(entry['buckets'])} differs "
                            f"from the family's "
                            f"{sorted(out['_bucket_layout'])}"
                        )
                if kind == "gauge":
                    if "device" not in label_names:
                        labels["device"] = str(device)
                    key = _label_key(labels, out["label_names"])
                    if key in out["_index"]:
                        raise ValueError(
                            f"gauge {name!r}: device series {labels} "
                            "already present (two devices claim one "
                            "device label value)"
                        )
                    out["_index"][key] = len(out["values"])
                    out["values"].append({"labels": labels, "value": entry["value"]})
                    continue
                key = _label_key(labels, out["label_names"])
                at = out["_index"].get(key)
                if at is None:
                    out["_index"][key] = len(out["values"])
                    if kind == "histogram":
                        out["values"].append(
                            {
                                "labels": labels,
                                "count": int(entry["count"]),
                                "sum": float(entry["sum"]),
                                "buckets": {
                                    k: int(v) for k, v in entry["buckets"].items()
                                },
                            }
                        )
                    else:
                        out["values"].append(
                            {"labels": labels, "value": float(entry["value"])}
                        )
                    continue
                acc = out["values"][at]
                if kind == "histogram":
                    # Layout agreement was enforced family-level above.
                    # Cumulative-per-bucket counts add bucket-wise: the
                    # merged cumulative distribution is the sum of the
                    # parts' (both are cumulative over the same bounds).
                    for k, v in entry["buckets"].items():
                        acc["buckets"][k] += int(v)
                    acc["sum"] += float(entry["sum"])
                    acc["count"] += int(entry["count"])
                else:
                    acc["value"] += float(entry["value"])
    for fam in merged.values():
        fam.pop("_index")
        fam.pop("_src_label_names")
        fam.pop("_bucket_layout")
    return merged


def merge_registries(
    registries: Mapping[str, MetricsRegistry],
    max_label_sets: Optional[int] = None,
) -> MetricsRegistry:
    """Merge live per-device registries into one rebuilt MetricsRegistry.

    `registries` maps device id -> registry; the result holds the merged
    values (counters summed, gauges device-labeled, histograms merged
    bucket-wise) and exposes them through the normal `to_prom_text` /
    `snapshot` paths. Histogram sample reservoirs are not merged -- the
    rebuilt copy is exposition-only, like `registry_from_snapshot`.
    `max_label_sets` bounds the merged cardinality (fleet-wide series
    explosions fail loudly at the merge, not at the scraper)."""
    snap = merge_snapshots(
        {dev: reg.snapshot() for dev, reg in registries.items()}
    )
    return registry_from_snapshot(snap, max_label_sets=max_label_sets)
