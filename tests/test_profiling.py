"""Observability: per-batch timings, match-emit latency histogram,
sampled phase profiling (profile_every), compile telemetry, and the
device_trace fallback (ISSUE 9)."""
from __future__ import annotations

import numpy as np
import pytest

from kafkastreams_cep_tpu import QueryBuilder, compile_pattern
from kafkastreams_cep_tpu.core.event import Event
from kafkastreams_cep_tpu.obs import CompileWatch, MetricsRegistry, SpanTracer
from kafkastreams_cep_tpu.ops.engine import EngineConfig
from kafkastreams_cep_tpu.ops.profiling import BatchTimings, device_trace
from kafkastreams_cep_tpu.ops.tables import compile_query
from kafkastreams_cep_tpu.parallel import BatchedDeviceNFA
from kafkastreams_cep_tpu.pattern.expressions import value

# `pytest -m profiling` selects the performance-observability suite.
pytestmark = pytest.mark.profiling


def test_batch_timings_summary_and_histogram():
    t = BatchTimings(capacity=4)
    t.record_advance(0.010, 64)
    t.record_drain(0.002, 3)
    t.record_advance(0.020, 64)
    t.record_drain(0.001, 0)
    s = t.summary()
    assert s["batches"] == 2 and s["drains"] == 2
    assert s["slots"] == 128 and s["matches"] == 3
    assert s["emit_latency_ms_p99"] >= s["emit_latency_ms_p50"] > 0
    h = t.histogram()
    assert sum(h["counts"]) == h["n"] == 2
    # Ring bound: capacity 4 keeps only the latest records.
    for _ in range(10):
        t.record_advance(0.001, 1)
    assert t.summary()["batches"] <= 4


def test_batch_timings_components_and_tunnel_rate():
    """The per-component breakdown: {advance, post, drain_pull, decode} ms
    means plus tunnel_mbps = pulled bytes / D2H wall."""
    t = BatchTimings()
    t.record_advance(0.010, 64, post_s=0.004)
    t.record_drain(0.020, 5, pull_s=0.010, decode_s=0.006,
                   bytes_pulled=1_000_000)
    c = t.components()
    assert c["advance_ms"] == 10.0
    assert c["post_ms"] == 4.0
    assert c["drain_pull_ms"] == 10.0
    assert c["decode_ms"] == 6.0
    assert c["drain_bytes"] == 1_000_000
    assert abs(c["tunnel_mbps"] - 100.0) < 1e-6  # 1 MB / 10 ms
    # No pull observed -> no rate claimed (None, not 0 or inf).
    assert BatchTimings().components()["tunnel_mbps"] is None


def test_engine_records_timings():
    pattern = (
        QueryBuilder()
        .select("a").where(value() == "A")
        .then().select("b").where(value() == "B")
        .then().select("c").where(value() == "C")
        .build()
    )
    query = compile_query(compile_pattern(pattern), None)
    bat = BatchedDeviceNFA(
        query, keys=["x"], config=EngineConfig(lanes=8, nodes=128, matches=16)
    )
    events = [Event("x", v, 1000 + i, "t", 0, i) for i, v in enumerate("XABC")]
    out = bat.advance({"x": events})
    assert len(out.get("x", [])) == 1
    s = bat.timings.summary()
    assert s["batches"] == 1 and s["drains"] == 1 and s["matches"] == 1
    assert bat.timings.histogram()["n"] == 1
    assert s["emit_latency_ms_p50"] > 0
    # A match-bearing drain populates the component breakdown and the
    # D2H accounting (the flat path's table + probe bytes).
    c = bat.timings.components()
    assert c["advance_ms"] > 0
    assert c["drain_pull_ms"] > 0 and c["drain_bytes"] > 0
    assert c["tunnel_mbps"] is None or c["tunnel_mbps"] > 0
    assert bat.drain_pull_bytes > 0


def _letters_query():
    pattern = (
        QueryBuilder()
        .select("a").where(value() == "A")
        .then().select("b").where(value() == "B")
        .then().select("c").where(value() == "C")
        .build()
    )
    return compile_query(compile_pattern(pattern), None)


def _noise_batch(bat, b, n=4):
    return bat.pack({"x": [
        Event("x", "Z", 1_000_000 + 10 * b + i, "t", 0, 100 + 10 * b + i)
        for i in range(n)
    ]})


# -------------------------------------------------------- profile_every
def test_profile_every_syncs_only_every_nth_advance(monkeypatch):
    """The sampled phase-timing dial (ISSUE 9): profile_every=2 blocks on
    advances 0, 2, 4 only (two blocks each: post-advance and post-post),
    and every CLEAN sampled advance feeds one observation per phase into
    cep_advance_compute_seconds -- batch 0 traced+compiled, so its wall
    belongs to cep_compile_seconds and is excluded from the compute
    histogram -- while the other advances keep the zero-sync pipeline
    (same detector as the zero-sync pin)."""
    bat = BatchedDeviceNFA(
        _letters_query(), keys=["x"],
        config=EngineConfig(lanes=8, nodes=128, matches=1024),
        profile_every=2,
    )
    import jax as jax_mod

    calls = {"block": 0}
    real_block = jax_mod.block_until_ready
    monkeypatch.setattr(
        jax_mod, "block_until_ready",
        lambda *a, **k: calls.__setitem__("block", calls["block"] + 1)
        or real_block(*a, **k),
    )
    for b in range(5):  # batches 0..4: sampled at 0, 2, 4
        bat.advance_packed(_noise_batch(bat, b), decode=False)
    assert calls["block"] == 6  # 3 sampled advances x 2 phase blocks
    snap = bat.metrics.snapshot()
    per_phase = {
        v["labels"]["phase"]: v["count"]
        for v in snap["cep_advance_compute_seconds"]["values"]
    }
    # Batch 0 compiled (cep_compiles_total moved) -> its compile wall is
    # excluded; batches 2 and 4 are warm compute observations.
    assert per_phase == {"advance": 2, "post": 2}
    compiles = {
        v["labels"]["fn"]: v["value"]
        for v in snap["cep_compiles_total"]["values"]
    }
    assert compiles["advance"] == 1


def test_profile_sync_feeds_compute_histogram_and_validation():
    bat = BatchedDeviceNFA(
        _letters_query(), keys=["x"],
        config=EngineConfig(lanes=8, nodes=128, matches=1024),
        profile_sync=True,
    )
    for b in range(2):
        bat.advance_packed(_noise_batch(bat, b), decode=False)
    snap = bat.metrics.snapshot()
    per_phase = {
        v["labels"]["phase"]: v["count"]
        for v in snap["cep_advance_compute_seconds"]["values"]
    }
    # Batch 0 compiled -> compile-wall guard excludes it; batch 1 is the
    # clean compute observation.
    assert per_phase == {"advance": 1, "post": 1}
    with pytest.raises(ValueError, match="profile_every"):
        BatchedDeviceNFA(
            _letters_query(), keys=["x"],
            config=EngineConfig(lanes=8, nodes=128, matches=16),
            profile_every=0,
        )


# ----------------------------------------------------- compile telemetry
def test_compile_watch_counts_signatures_and_estimates_cost():
    import jax
    import jax.numpy as jnp

    reg = MetricsRegistry()
    watch = CompileWatch(reg)
    fn = watch.wrap(jax.jit(lambda x: x @ x), "mm")
    fn(jnp.ones((8, 8)))
    fn(jnp.ones((8, 8)))          # warm: same signature, no new compile
    assert watch.compiles("mm") == 1
    fn(jnp.ones((16, 16)))        # new shape -> new compile
    assert watch.compiles("mm") == 2
    snap = reg.snapshot()
    secs = {
        v["labels"]["fn"]: v["count"]
        for v in snap["cep_compile_seconds"]["values"]
    }
    assert secs["mm"] == 2
    # cost_analysis estimates landed for the matmul lowering (CPU XLA
    # provides flops/bytes for it).
    flops = {
        v["labels"]["fn"]: v["value"]
        for v in snap.get("cep_compile_flops", {}).get("values", ())
    }
    assert flops.get("mm", 0) > 0


def test_compile_watch_distinguishes_programs_sharing_label():
    """Two DISTINCT programs under one label with identical arg shapes
    (the per-(Mb, Cb) flatten buckets fed by the shape-padded window
    view) are two compiles -- the per-wrap token keeps bucket churn
    visible instead of collapsing it into the first bucket's entry."""
    import jax
    import jax.numpy as jnp

    reg = MetricsRegistry()
    watch = CompileWatch(reg, estimate_cost=False)
    f1 = watch.wrap(jax.jit(lambda x: x + 1), "flatten")
    f2 = watch.wrap(jax.jit(lambda x: x * 2), "flatten")
    f1(jnp.ones(8))
    f2(jnp.ones(8))  # same shapes, different program
    assert watch.compiles("flatten") == 2
    f1(jnp.ones(8))
    f2(jnp.ones(8))  # both warm now
    assert watch.compiles("flatten") == 2
    assert watch.seen_count == 2


def test_engine_compile_telemetry_tracks_retraces():
    """A [T, K] shape change (a retrace/recompile) moves the engine's
    compile counters; a same-shape advance does not."""
    bat = BatchedDeviceNFA(
        _letters_query(), keys=["x"],
        config=EngineConfig(lanes=8, nodes=128, matches=1024),
    )
    bat.advance_packed(_noise_batch(bat, 0, n=4), decode=False)
    snap = bat.metrics.snapshot()
    compiles = {
        v["labels"]["fn"]: v["value"]
        for v in snap["cep_compiles_total"]["values"]
    }
    assert compiles["advance"] == 1 and compiles["append"] == 1
    base = compiles["advance"]
    bat.advance_packed(_noise_batch(bat, 1, n=4), decode=False)  # warm
    snap = bat.metrics.snapshot()
    compiles = {
        v["labels"]["fn"]: v["value"]
        for v in snap["cep_compiles_total"]["values"]
    }
    assert compiles["advance"] == base
    bat.advance_packed(_noise_batch(bat, 2, n=7), decode=False)  # T changed
    snap = bat.metrics.snapshot()
    compiles = {
        v["labels"]["fn"]: v["value"]
        for v in snap["cep_compiles_total"]["values"]
    }
    assert compiles["advance"] == base + 1
    # The compile walls are on the same registry (the artifact's
    # `compile` block reads them).
    secs = {
        v["labels"]["fn"]: v["sum"]
        for v in snap["cep_compile_seconds"]["values"]
    }
    assert secs["advance"] > 0
    # Opt-out: compile_telemetry=False registers nothing.
    bat2 = BatchedDeviceNFA(
        _letters_query(), keys=["x"],
        config=EngineConfig(lanes=8, nodes=128, matches=16),
        compile_telemetry=False,
    )
    bat2.advance({"x": [Event("x", "Z", 1_000_000, "t", 0, 0)]})
    assert "cep_compiles_total" not in bat2.metrics.snapshot()


def test_drain_flatten_bucket_growth_counts_as_compiles():
    """Flatten-bucket churn is the recompile-storm signal: a drain that
    needs a new (Mb, Cb) bucket compiles one more `flatten` program."""
    bat = BatchedDeviceNFA(
        _letters_query(), keys=["x"],
        config=EngineConfig(lanes=8, nodes=128, matches=16),
    )
    out = bat.advance({"x": [
        Event("x", v, 1_000_000 + i, "t", 0, i)
        for i, v in enumerate("XABC")
    ]})
    assert sum(len(v) for v in out.values()) == 1
    snap = bat.metrics.snapshot()
    compiles = {
        v["labels"]["fn"]: v["value"]
        for v in snap["cep_compiles_total"]["values"]
    }
    assert compiles.get("drain_probe", 0) >= 1
    assert compiles.get("flatten", 0) >= 1


# ------------------------------------------------- device_trace fallback
def test_device_trace_degrades_to_noop_with_warning_gauge(
    monkeypatch, tmp_path
):
    """Satellite (ISSUE 9): an unavailable profiler (no TPU / missing
    plugin) must degrade the capture to a no-op with a persistent
    warning gauge -- never raise into the pipeline."""
    import jax

    def _broken(log_dir):
        raise RuntimeError("profiler plugin missing")

    monkeypatch.setattr(jax.profiler, "trace", _broken)
    reg = MetricsRegistry()
    ran = []
    with device_trace(str(tmp_path), registry=reg):
        ran.append(1)  # the enclosed block still runs
    assert ran == [1]
    snap = reg.snapshot()
    vals = snap["cep_profiler_unavailable"]["values"]
    assert vals[0]["value"] == 1
    assert "profiler plugin missing" in vals[0]["labels"]["reason"]


def test_device_trace_finalize_failure_degrades_too(monkeypatch, tmp_path):
    import jax

    class _BrokenExit:
        def __enter__(self):
            return self

        def __exit__(self, *exc):
            raise RuntimeError("xplane serialization failed")

    monkeypatch.setattr(jax.profiler, "trace", lambda d: _BrokenExit())
    reg = MetricsRegistry()
    with device_trace(str(tmp_path), registry=reg):
        pass  # must not raise
    assert reg.snapshot()["cep_profiler_unavailable"]["values"][0]["value"] == 1
    # ...and it never masks the block's own exception.
    with pytest.raises(KeyError, match="real"):
        with device_trace(str(tmp_path), registry=reg):
            raise KeyError("real")


def test_span_tracer_device_records_span_despite_broken_profiler(
    monkeypatch, tmp_path
):
    import jax

    monkeypatch.setattr(
        jax.profiler, "trace",
        lambda d: (_ for _ in ()).throw(RuntimeError("no profiler")),
    )
    reg = MetricsRegistry()
    tracer = SpanTracer(reg)
    with tracer.device(str(tmp_path)):
        pass
    assert tracer.recent(8)[0]["span"] == "device_trace"
    assert "cep_profiler_unavailable" in reg.snapshot()
