#!/usr/bin/env python
"""Runnable end-to-end stock demo: the framework's `CEPStockDemo.main`.

Mirrors the reference demo app (reference:
example/src/main/java/.../CEPStockDemo.java:52-112): produce the 8 golden
stock events into a file-backed RecordLog topic, build a topology with the
SASE SIGMOD'08 rising-stock query, pump it with the LogDriver (restore ->
poll -> commit), and read the 4 golden JSON matches back off the sink
topic -- once with the per-record host runtime and once with the
micro-batching TPU runtime (which falls back to the XLA-on-CPU engine when
no TPU is present, so the demo runs anywhere).

    python examples/stocks_demo.py [--runtime host|tpu|both] [--dir DIR]
"""
from __future__ import annotations

import argparse
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import numpy as np

from kafkastreams_cep_tpu import ComplexStreamsBuilder
from kafkastreams_cep_tpu.models.stocks import (
    GOLDEN_EVENTS,
    GOLDEN_MATCHES,
    stocks_pattern,
)
from kafkastreams_cep_tpu.ops.schema import EventSchema
from kafkastreams_cep_tpu.streams.driver import LogDriver, produce
from kafkastreams_cep_tpu.streams.log import RecordLog
from kafkastreams_cep_tpu.streams.serde import Queried, sequence_to_json


def run(runtime: str, base_dir: str) -> None:
    log = RecordLog(path=str(Path(base_dir) / f"cep-demo-{runtime}"))
    for i, event in enumerate(GOLDEN_EVENTS):
        produce(log, "StockEvents", "K1", event, timestamp=i)

    builder = ComplexStreamsBuilder(log=log, app_id="stock-demo")
    kwargs = {}
    if runtime == "tpu":
        kwargs = dict(
            queried=Queried(
                schema=EventSchema(
                    {"name": np.int32, "price": np.int32, "volume": np.int32}
                )
            ),
            batch_size=4,
        )
    out = (
        builder.stream("StockEvents")
        .query("Stocks", stocks_pattern(), runtime=runtime, **kwargs)
        .to("Matches")
    )
    topology = builder.build()

    driver = LogDriver(topology, group="stock-demo")
    processed = driver.poll()
    topology.flush()
    driver.commit()

    got = [sequence_to_json(r.value) for r in out.records]
    sink = [r for r in log.read("Matches")]
    print(f"[{runtime}] processed {processed} events, "
          f"{len(got)} matches, {len(sink)} sink records:")
    for line in got:
        print(f"  {line}")
    assert got == GOLDEN_MATCHES, "output diverged from the golden matches!"
    assert len(sink) == len(GOLDEN_MATCHES)
    print(f"[{runtime}] OK -- exact golden output "
          f"(CEPStockDemoTest.java:101-109)")


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--runtime", default="both",
                    choices=["host", "tpu", "both"])
    ap.add_argument("--dir", default=None,
                    help="RecordLog directory (default: a temp dir)")
    args = ap.parse_args()
    runtimes = ["host", "tpu"] if args.runtime == "both" else [args.runtime]
    if args.dir is not None:
        for rt in runtimes:
            run(rt, args.dir)
        return
    with tempfile.TemporaryDirectory() as tmp:
        for rt in runtimes:
            run(rt, tmp)


if __name__ == "__main__":
    main()
