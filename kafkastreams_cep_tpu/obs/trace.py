"""Span tracer: host-side wall spans + the device xplane trace, one API.

`SpanTracer.span("restore")` times a host block and records it into the
registry (`cep_span_seconds{span=...}` histogram + `cep_span_total`
counter), so the streams layer's poll/commit/restore sections land in the
same spine as the engine's section walls. `SpanTracer.device(log_dir)`
wraps ops.profiling.device_trace (jax.profiler xplane capture) and records
the capture wall as a span of the same name -- one call site for "time
this, and profile the device while at it".

Since ISSUE 7 the tracer also keeps a bounded ring of recent completed
spans (`recent()`), which the introspection plane serves as `/tracez` --
a curl-able "what did this process just spend time on" without a
profiler attach.
"""
from __future__ import annotations

import contextlib
import itertools
import threading
import time
from collections import deque
from typing import Any, Dict, Iterator, List, Optional

from .registry import MetricsRegistry, default_registry

__all__ = ["SpanTracer"]


class SpanTracer:
    """Named wall-clock spans recorded into a MetricsRegistry.

    `ring` bounds the recent-span buffer behind `recent()` (the /tracez
    surface); completed spans beyond it age out oldest-first.
    """

    def __init__(
        self, registry: Optional[MetricsRegistry] = None, ring: int = 256
    ) -> None:
        self.registry = registry if registry is not None else default_registry()
        self._hist = self.registry.histogram(
            "cep_span_seconds", "Host wall per named span", labels=("span",)
        )
        self._count = self.registry.counter(
            "cep_span_total", "Completed spans", labels=("span",)
        )
        # deque appends are atomic, but recent()'s snapshot iteration must
        # not race a rotating append from another thread.
        self._ring: deque = deque(maxlen=max(1, ring))
        self._ring_lock = threading.Lock()

    @contextlib.contextmanager
    def span(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self._hist.labels(span=name).observe(dt)
            self._count.labels(span=name).inc()
            with self._ring_lock:
                self._ring.append(
                    {
                        "span": name,
                        "end_unix": time.time(),
                        "duration_s": dt,
                    }
                )

    def recent(
        self, limit: int = 64, name: Optional[str] = None
    ) -> List[Dict[str, Any]]:
        """Most-recent completed spans, newest first (the /tracez view)."""
        with self._ring_lock:
            spans = list(self._ring)
        it = reversed(spans)
        if name is not None:
            it = (s for s in it if s["span"] == name)
        return list(itertools.islice(it, max(0, limit)))

    @contextlib.contextmanager
    def device(self, log_dir: str, name: str = "device_trace") -> Iterator[Any]:
        """Capture a device xplane profile of the block AND record its wall
        as a span (the existing ops.profiling.device_trace, wrapped).
        An unavailable profiler degrades to the bare span, with the
        condition persisted on this tracer's registry
        (`cep_profiler_unavailable{reason}`)."""
        from ..ops.profiling import device_trace

        with self.span(name):
            with device_trace(log_dir, registry=self.registry):
                yield
