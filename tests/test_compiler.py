"""Pattern->NFA compiler conformance (reference: StagesFactoryTest.java:36-157)."""
import pytest

from kafkastreams_cep_tpu import (
    EdgeOperation,
    InvalidPatternException,
    QueryBuilder,
    StateType,
    compile_pattern,
    value,
)

STAGE_1 = "stage-1"
STAGE_2 = "stage-2"
STAGE_3 = "stage-3"


def test_invalid_final_one_or_more_stage():
    pattern = QueryBuilder().select().one_or_more().where(value() == "N/A").build()
    with pytest.raises(InvalidPatternException):
        compile_pattern(pattern)


def test_invalid_final_optional_stage():
    pattern = QueryBuilder().select().optional().where(value() == "N/A").build()
    with pytest.raises(InvalidPatternException):
        compile_pattern(pattern)


def test_single_stage():
    pattern = QueryBuilder().select(STAGE_1).where(value() == 0).build()
    stages = compile_pattern(pattern).stages

    assert len(stages) == 2
    final, begin = stages
    assert final.type == StateType.FINAL
    assert len(final.edges) == 0
    assert begin.type == StateType.BEGIN
    assert len(begin.edges) == 1
    assert begin.edges[0].is_op(EdgeOperation.BEGIN)
    assert begin.edges[0].target is final
    assert begin.name == STAGE_1


def test_multiple_stages():
    pattern = (
        QueryBuilder()
        .select(STAGE_1).where(value() == 0)
        .then()
        .select(STAGE_2).where(value() % 2 == 0)
        .then()
        .select(STAGE_3).where(value() > 100)
        .build()
    )
    stages = compile_pattern(pattern).stages

    assert len(stages) == 4
    assert stages[0].type == StateType.FINAL
    assert stages[1].type == StateType.NORMAL and stages[1].name == STAGE_3
    assert stages[2].type == StateType.NORMAL and stages[2].name == STAGE_2
    assert stages[3].type == StateType.BEGIN and stages[3].name == STAGE_1


def test_one_or_more_expansion():
    pattern = (
        QueryBuilder()
        .select(STAGE_1).where(value() == 0)
        .then()
        .select(STAGE_2).one_or_more().where(value() % 2 == 0)
        .then()
        .select(STAGE_3).where(value() > 100)
        .build()
    )
    stages = compile_pattern(pattern).stages

    assert len(stages) == 5

    final = stages[0]
    assert final.type == StateType.FINAL

    stage3 = stages[1]
    assert stage3.type == StateType.NORMAL and stage3.name == STAGE_3
    assert stage3.edges[0].operation == EdgeOperation.BEGIN
    assert stage3.edges[0].target.name == final.name

    stage2 = stages[2]
    assert stage2.type == StateType.NORMAL and stage2.name == STAGE_2
    assert stage2.edges[0].operation == EdgeOperation.TAKE
    assert stage2.edges[0].target.name == stage3.name
    assert stage2.edges[1].operation == EdgeOperation.PROCEED
    assert stage2.edges[1].target.name == stage3.name

    internal2 = stages[3]
    assert internal2.type == StateType.NORMAL and internal2.name == STAGE_2
    assert internal2.edges[0].operation == EdgeOperation.BEGIN

    begin = stages[4]
    assert begin.type == StateType.BEGIN and begin.name == STAGE_1


def test_times_expansion():
    # times(n) expands into n-1 chained internal BEGIN stages
    # (StagesFactory.java:141-157).
    pattern = (
        QueryBuilder()
        .select(STAGE_1).where(value() == "A")
        .then()
        .select(STAGE_2).times(3).where(value() == "C")
        .then()
        .select(STAGE_3).where(value() == "E")
        .build()
    )
    stages = compile_pattern(pattern).stages
    # final, stage-3, stage-2 (x3: main + 2 internal), stage-1
    assert len(stages) == 6
    names = [s.name for s in stages]
    assert names == ["$final", STAGE_3, STAGE_2, STAGE_2, STAGE_2, STAGE_1]


def test_window_pushed_to_all_stages():
    pattern = (
        QueryBuilder()
        .select(STAGE_1).where(value() == "A")
        .then()
        .select(STAGE_2).where(value() == "B").within(minutes=5)
        .build()
    )
    stages = compile_pattern(pattern).stages
    assert stages[1].window_ms == 300_000  # stage-2 carries its own window
    assert stages[2].window_ms == 300_000  # stage-1 inherits successor's window
