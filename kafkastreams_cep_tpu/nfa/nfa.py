"""Host NFA runtime: the per-record match loop.

This is the behavioral oracle for the TPU engine: a faithful re-implementation
of the reference SASE NFA^b evaluator
(reference: core/.../cep/nfa/NFA.java:134-397, ComputationStage.java:30-185).
Per event it drains the run queue once, evaluates each live run against the
compiled stage graph (recursively descending epsilon PROCEED chains), applies
the edge operations:

  * PROCEED/SKIP_PROCEED: epsilon descent, extending the Dewey version with a
    new stage digit when genuinely crossing to the next stage;
  * TAKE: consume on a self loop, re-adding the run, buffer put chained to
    the run's lineage (NFA.java:238-255);
  * BEGIN: consume and forward via a synthesized epsilon state
    (NFA.java:256-271);
  * IGNORE: re-add the run unchanged (NFA.java:272-285);

branches a run when one event matches >=2 edge combinations
(PROCEED+TAKE / IGNORE+TAKE / IGNORE+BEGIN / IGNORE+PROCEED,
NFA.java:392-397) -- cloning the run with a bumped Dewey number (addRun(2)
from a begin state), duplicating fold registers and sharing the lineage
prefix -- and always re-adds the begin state so new matches can start
(NFA.java:323-338). Matches are extracted from the shared buffer when a run
forwards to the final state.

Partial matches live in the exact-lineage shared buffer (state/buffer.py):
each run tracks the node id of its last consumed event (`last_node`, the
host analog of the device engine's per-lane node index) and extraction is an
unambiguous parent walk. The reference instead routes a merged
(stage, event)-keyed store by Dewey-version compatibility
(SharedVersionedBufferStoreImpl.java:176-201), which splices runs' prefixes
whenever independent addRun() bumps produce colliding version tags -- a
reference bug this redesign does not reproduce (see state/buffer.py).
Dewey versions are still maintained run-for-run (they are part of the
observable run-queue shape and drive branch numbering) -- they just no
longer route storage.

The TPU engine (ops/engine.py) implements the same transition relation as a
vmapped kernel over fixed-capacity run lanes with the epsilon descent
unrolled at query-compile time; this interpreter defines its conformance
contract.
"""
from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Generic, List, Optional, Set, TypeVar

from ..core.dewey import DeweyVersion
from ..core.event import Event
from ..core.sequence import Sequence
from ..pattern.stages import Edge, EdgeOperation, Stage, Stages
from ..state.aggregates import AggregatesStore, States
from ..state.buffer import ReadOnlySharedVersionBuffer, SharedVersionedBuffer
from .context import FoldEnv, MatcherContext

K = TypeVar("K")
V = TypeVar("V")


@dataclass(frozen=True)
class ComputationStage(Generic[K, V]):
    """One live NFA run (ComputationStage.java:30-185)."""

    stage: Stage
    version: DeweyVersion
    sequence: int
    last_event: Optional[Event[K, V]] = None
    timestamp: int = -1
    is_branching: bool = False
    is_ignored: bool = False
    #: buffer node id of the run's last consumed event (chain head). The
    #: reference reconstructs a store key from (previousStage, previousEvent)
    #: at put time (NFA.java:351-360), which breaks when the storing stage
    #: and the descent's previous stage carry different StateTypes; tracking
    #: the chain head explicitly is the host analog of the device engine's
    #: per-lane last-node *index* and sidesteps both that bug and the
    #: version-routing ambiguity (see state/buffer.py).
    last_node: Optional[int] = None

    def with_version(self, version: DeweyVersion) -> "ComputationStage[K, V]":
        # Mirrors ComputationStage.setVersion: branching/ignored flags reset.
        return ComputationStage(
            self.stage, version, self.sequence, self.last_event, self.timestamp,
            last_node=self.last_node,
        )

    @property
    def is_begin_state(self) -> bool:
        return self.stage.is_begin

    def is_out_of_window(self, time: int) -> bool:
        return self.stage.window_ms != -1 and (time - self.timestamp) > self.stage.window_ms

    @property
    def is_forwarding(self) -> bool:
        edges = self.stage.edges
        return len(edges) == 1 and edges[0].operation == EdgeOperation.PROCEED

    @property
    def is_forwarding_to_final(self) -> bool:
        return self.is_forwarding and self.stage.edges[0].target.is_final


def initial_computation_stage(stages: Stages) -> ComputationStage:
    return ComputationStage(stage=stages.begin_stage(), version=DeweyVersion(1), sequence=1)


class NFA(Generic[K, V]):
    """Non-deterministic finite automaton over the exact-lineage shared buffer."""

    def __init__(
        self,
        aggregates_store: AggregatesStore,
        buffer: SharedVersionedBuffer[K, V],
        aggregates_names: Set[str],
        computation_stages: List[ComputationStage[K, V]],
        runs: int = 1,
        strict_windows: bool = False,
    ) -> None:
        self.aggregates_store = aggregates_store
        self.buffer = buffer
        self.aggregates_names = set(aggregates_names)
        self.computation_stages: List[ComputationStage[K, V]] = list(computation_stages)
        self.runs = runs
        # Reference parity (False): synthesized epsilon stages carry no window
        # (Stage.java:247-251 never copies windowMs, DEFAULT_WINDOW_MS=-1 at
        # Stage.java:42), so any run that has consumed an event -- which always
        # sits at an epsilon stage -- is never expired, run populations grow
        # without bound under skip-till-any, and matches can span longer than
        # within(). strict_windows=True fixes that documented reference leak:
        # epsilon stages inherit the descent target's window and expiry keys
        # off "has consumed an event" instead of "is not the begin stage".
        self.strict_windows = strict_windows

    @staticmethod
    def build(
        stages: Stages,
        aggregates_store: AggregatesStore,
        buffer: SharedVersionedBuffer,
        strict_windows: bool = False,
    ) -> "NFA":
        return NFA(
            aggregates_store,
            buffer,
            stages.defined_states(),
            [initial_computation_stage(stages)],
            strict_windows=strict_windows,
        )

    # ------------------------------------------------------------------ API
    def match_pattern(self, event: Event[K, V]) -> List[Sequence[K, V]]:
        """Process one event; returns completed matches in emission order."""
        to_process = len(self.computation_stages)
        final_states: List[ComputationStage[K, V]] = []
        any_died = False

        while to_process > 0:
            to_process -= 1
            computation = self.computation_stages.pop(0)
            states = self._match_computation(computation, event)
            if not states:
                any_died = True
            final_states.extend(s for s in states if s.is_forwarding_to_final)
            self.computation_stages.extend(s for s in states if not s.is_forwarding_to_final)

        matches = self._match_construction(final_states)
        # Reclaim chains no longer reachable from any live run: the mark-sweep
        # that replaces the reference's per-extraction refcount GC
        # (SharedVersionedBufferStoreImpl.java:176-201). Nodes can only become
        # unreachable when a run dies or leaves the queue through the final
        # state (every other transition retains its chain prefix), so the
        # sweep is skipped otherwise.
        if final_states or any_died:
            self.buffer.gc(c.last_node for c in self.computation_stages)
        return matches

    # ------------------------------------------------------------ internals
    def _match_construction(
        self, states: List[ComputationStage[K, V]]
    ) -> List[Sequence[K, V]]:
        return [self.buffer.get(c.last_node) for c in states]

    def _match_computation(
        self, computation: ComputationStage[K, V], event: Event[K, V]
    ) -> List[ComputationStage[K, V]]:
        if self.strict_windows:
            # Expire any run that has consumed an event (timestamp set); the
            # begin run itself (timestamp -1) has nothing to expire.
            expired = computation.timestamp >= 0 and computation.is_out_of_window(
                event.timestamp
            )
        else:
            # Reference parity (NFA.java:183-184): begin-typed queue items --
            # including the epsilon state a consumed begin run sits at -- are
            # exempt, and epsilon stages carry no window at all.
            expired = not computation.is_begin_state and computation.is_out_of_window(
                event.timestamp
            )
        if expired:
            return []
        return self._evaluate(computation, event, computation.stage, None)

    def _new_epsilon(self, current: Stage, target: Stage) -> Stage:
        eps = Stage.new_epsilon(current, target)
        if self.strict_windows:
            eps.window_ms = (
                target.window_ms if target.window_ms != -1 else current.window_ms
            )
        return eps

    def _matched_edges(
        self,
        previous_event: Optional[Event[K, V]],
        current_event: Event[K, V],
        version: DeweyVersion,
        sequence: int,
        previous_stage: Optional[Stage],
        current_stage: Stage,
        previous_node: Optional[int] = None,
    ) -> List[Edge]:
        states = States(self.aggregates_store, current_event.key, sequence)
        read_only = ReadOnlySharedVersionBuffer(self.buffer)
        ctx_args = dict(
            buffer=read_only,
            version=version,
            previous_stage=previous_stage,
            current_stage=current_stage,
            previous_event=previous_event,
            current_event=current_event,
            states=states,
            previous_node=previous_node,
        )
        return [e for e in current_stage.edges if e.predicate.accept(MatcherContext(**ctx_args))]

    @staticmethod
    def _is_branching(operations: List[EdgeOperation]) -> bool:
        ops = set(operations)
        return (
            {EdgeOperation.PROCEED, EdgeOperation.TAKE} <= ops
            or {EdgeOperation.IGNORE, EdgeOperation.TAKE} <= ops
            or {EdgeOperation.IGNORE, EdgeOperation.BEGIN} <= ops
            or {EdgeOperation.IGNORE, EdgeOperation.PROCEED} <= ops
        )

    def _evaluate(
        self,
        root: ComputationStage[K, V],
        event: Event[K, V],
        current_stage: Stage,
        previous_stage: Optional[Stage],
        computation: Optional[ComputationStage[K, V]] = None,
    ) -> List[ComputationStage[K, V]]:
        """Evaluate `current_stage`'s edges for one run; recursive over epsilon chains.

        `root` is the queue item being processed (its begin-state re-add rule
        applies once, at any depth); `computation` is the effective run state
        at this recursion level (version possibly extended by addStage).
        """
        if computation is None:
            computation = root

        sequence_id = computation.sequence
        previous_event = computation.last_event
        previous_node = computation.last_node
        version = computation.version

        matched_edges = self._matched_edges(
            previous_event, event, version, sequence_id, previous_stage, current_stage,
            previous_node,
        )
        operations = [e.operation for e in matched_edges]
        is_branching = self._is_branching(operations)
        ignored = EdgeOperation.IGNORE in operations

        start_time = event.timestamp if root.is_begin_state else computation.timestamp

        next_stages: List[ComputationStage[K, V]] = []
        consumed = False
        proceed = False
        consumed_node: Optional[int] = None

        for edge in matched_edges:
            op = edge.operation

            if op in (EdgeOperation.PROCEED, EdgeOperation.SKIP_PROCEED):
                next_computation = computation
                if self._is_forwarding_to_next_stage(current_stage, computation, edge):
                    next_computation = computation.with_version(version.add_stage())
                prev_for_descent = (
                    previous_stage if op == EdgeOperation.SKIP_PROCEED else current_stage
                )
                descended = self._evaluate(
                    root, event, edge.target, prev_for_descent, next_computation
                )
                next_stages.extend(descended)
                if descended:
                    proceed = True

            elif op == EdgeOperation.TAKE:
                # Consume on the self loop: the run stays at this stage
                # (NFA.java:238-255; the reference's branch-aware put version
                # only routed the merged store -- lineage needs no tag).
                consumed_node = self.buffer.put(current_stage.name, event, previous_node)
                next_stages.append(
                    ComputationStage(
                        stage=self._new_epsilon(current_stage, current_stage),
                        version=version,
                        sequence=sequence_id,
                        last_event=event,
                        timestamp=start_time,
                        last_node=consumed_node,
                    )
                )
                consumed = True

            elif op == EdgeOperation.BEGIN:
                consumed_node = self.buffer.put(current_stage.name, event, previous_node)
                next_stages.append(
                    ComputationStage(
                        stage=self._new_epsilon(current_stage, edge.target),
                        version=version,
                        sequence=sequence_id,
                        last_event=event,
                        timestamp=start_time,
                        last_node=consumed_node,
                    )
                )
                consumed = True

            elif op == EdgeOperation.IGNORE:
                if not is_branching:
                    next_stages.append(replace(computation, is_ignored=True, is_branching=False))

        if is_branching:
            if consumed:
                self.runs += 1
                new_sequence = self.runs
                last_event = previous_event if ignored else event
                prev_is_begin = previous_stage is not None and previous_stage.is_begin
                if previous_stage is not None:
                    branch_stage = self._new_epsilon(previous_stage, current_stage)
                else:
                    # Begin-stage branching (untestable in the reference:
                    # NFA.java:293 would NPE); park the clone at the current
                    # stage itself.
                    branch_stage = self._new_epsilon(current_stage, current_stage)
                    prev_is_begin = True
                run_offset = 2 if (prev_is_begin and len(version.digits) >= 2) else 1
                next_version = version.add_run(run_offset)
                # The clone shares the lineage prefix by pointing at the same
                # node: the reference's branch() refcount walk
                # (NFA.java:289-317) is structural sharing here.
                clone_node = previous_node if ignored else consumed_node
                next_stages.append(
                    ComputationStage(
                        stage=branch_stage,
                        version=next_version,
                        sequence=new_sequence,
                        last_event=last_event,
                        timestamp=start_time,
                        is_branching=True,
                        last_node=clone_node,
                    )
                )
                for agg_name in self.aggregates_names:
                    self.aggregates_store.branch(event.key, agg_name, sequence_id, new_sequence)
            elif not proceed:
                next_stages.append(root)

        if consumed:
            self._evaluate_aggregates(current_stage, sequence_id, event)

        # The begin state is always re-added so new matches can start.
        if root.is_begin_state and not root.is_forwarding:
            if consumed:
                self.runs += 1
                new_version = version if not next_stages else version.add_run()
                next_stages.append(
                    ComputationStage(
                        stage=root.stage,
                        version=new_version,
                        sequence=self.runs,
                    )
                )
            else:
                next_stages.append(root)

        return next_stages

    @staticmethod
    def _is_forwarding_to_next_stage(
        current_stage: Stage, computation: ComputationStage, edge: Edge
    ) -> bool:
        return (
            edge.target.name != current_stage.name
            and not computation.is_branching
            and not computation.is_ignored
        )

    def _evaluate_aggregates(self, stage: Stage, sequence: int, event: Event[K, V]) -> None:
        for aggregator in stage.aggregates:
            current = self.aggregates_store.find(event.key, aggregator.name, sequence)
            if current is None:
                current = aggregator.initial
            states = States(self.aggregates_store, event.key, sequence)

            def env_factory(cur, _agg=aggregator, _states=states):
                return FoldEnv(event, _states, _agg.name, cur)

            new_value = aggregator.apply(event.key, event.value, current, env_factory)
            self.aggregates_store.put(event.key, aggregator.name, sequence, new_value)
