"""Seeded zero-sync violations: every construct the checker must flag.

Mutation fixture for tests/test_lint.py -- ceplint must exit 1 on this
file (the gate is proven able to fail). NOT runnable production code.
"""
import numpy as np

import jax
import jax.numpy as jnp


# cep: hot-path
def hot_advance(state, xs):
    occupancy = jnp.max(state["pend_pos"])          # traced
    n = int(occupancy)                              # CEP-S02 scalarization
    host = np.asarray(xs["gidx"])                   # CEP-S01 materialize
    jax.block_until_ready(occupancy)                # CEP-S01 hard sync
    state["runs"].item()                            # CEP-S01 .item()
    if occupancy > 0:                               # CEP-S03 truthiness
        n += 1
    flag = bool(xs["valid"])                        # CEP-S02 bool()
    return n, host, flag


def cold_helper(state):
    """Not hot-path marked: the same constructs are fine here."""
    return int(jnp.max(state["pend_pos"]))
