"""Unified observability layer: metrics registry, exposition, span tracer.

One telemetry spine instead of four private ones (ISSUE 5): every layer
registers its counters/gauges/histograms here, and the registry exposes
them as Prometheus 0.0.4 text (`to_prom_text`) or a JSON snapshot
(`snapshot`) -- the same values bench.py emits and
scripts/check_bench_schema.py validates. See PERF.md "v10" for the full
metrics dictionary.
"""
from .compile import CompileWatch
from .http import IntrospectionServer
from .merge import merge_registries, merge_snapshots
from .registry import (
    FAULT_SERIES,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
    fault_series_totals,
    parse_prom_text,
    registry_from_snapshot,
)
from .scrape import MetricsScraper, TimeSeries
from .trace import SpanTracer
from .trace_export import chrome_trace, write_chrome_trace

__all__ = [
    "CompileWatch",
    "Counter",
    "chrome_trace",
    "write_chrome_trace",
    "FAULT_SERIES",
    "Gauge",
    "Histogram",
    "IntrospectionServer",
    "MetricsRegistry",
    "MetricsScraper",
    "SpanTracer",
    "TimeSeries",
    "default_registry",
    "fault_series_totals",
    "merge_registries",
    "merge_snapshots",
    "parse_prom_text",
    "registry_from_snapshot",
]
