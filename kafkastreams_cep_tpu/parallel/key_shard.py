"""Key-axis data parallelism: vmap over key lanes, pjit over the device mesh.

The reference's only parallelism mechanism is Kafka partitioning: one stream
task per partition, one NFA per record key inside a task
(reference: core/.../cep/processor/CEPProcessor.java:111-124,139; SURVEY.md
section 2.8). The TPU-native equivalent is a *batched* engine: the one-event
transition kernel (ops/engine.py) is vmapped over a trailing key axis, so one
chip advances thousands of independent per-key NFAs in lockstep, and the key
axis is sharded across a `jax.sharding.Mesh` for multi-chip scale-out.

Collectives stay off the per-event hot path (per-key state never crosses
chips for a single query); only the observability reduction
(`global_stats`) and any key re-sharding ride ICI -- the design stance of
SURVEY.md section 2.8/5.8.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.engine import (
    STATE_COUNTER_KEYS,
    EngineConfig,
    build_step,
    init_pool,
    init_state,
)
from ..ops.tables import CompiledQuery

#: Mesh axis name for the key shard (data-parallel axis).
KEY_AXIS = "keys"


def _broadcast_tree(tree: Dict[str, jnp.ndarray], n_keys: int) -> Dict[str, jnp.ndarray]:
    # Key axis LAST: TPU tiles pad the two minor dims to (8, 128); the
    # engine's per-key tensors have small trailing dims (Dewey digits, slot
    # counts, lane counts), so a leading key axis wastes up to ~16x memory
    # bandwidth in padding. K-last makes the minor dim the (large) key axis.
    return jax.tree.map(
        lambda leaf: jnp.broadcast_to(leaf[..., None], leaf.shape + (n_keys,)).copy(),
        tree,
    )


def init_batched_state(
    query: CompiledQuery, config: EngineConfig, n_keys: int
) -> Dict[str, jnp.ndarray]:
    """Per-key engine state stacked along a trailing [..., K] axis."""
    return _broadcast_tree(init_state(query, config), n_keys)


def init_batched_pool(
    query: CompiledQuery, config: EngineConfig, n_keys: int
) -> Dict[str, jnp.ndarray]:
    """Per-key node pool / pending-match buffer stacked along [..., K]."""
    return _broadcast_tree(init_pool(query, config), n_keys)


def build_batched_advance(query: CompiledQuery, config: EngineConfig):
    """jit-compiled multi-key batch advance.

    xs leaves are time-major [T, K, ...]: the scan walks events in lockstep
    across keys (each key sees its own column slice; padding steps carry
    valid=False). The step index is scanned *unbatched* (in_axes=None) so
    the time-indexed node-window layout stays shared across keys. State and
    ys leaves carry the key axis LAST (TPU tiling: the minor dim should be
    the large axis); returns (new [..., K] state, ys leaves [T, ..., K]).
    """
    step = build_step(query, config)
    vstep = jax.vmap(step, in_axes=(-1, 0, None), out_axes=(-1, -1))

    @jax.jit
    def advance(state, xs):
        T = xs["valid"].shape[0]

        def body(carry, xt):
            x, t = xt
            return vstep(carry, x, t)

        # The group-phase step offset stays UNBATCHED (first key's scalar:
        # the drivers advance/flush all keys in lockstep, so every key
        # carries the same phase) -- a per-key t would break the shared
        # time-indexed window layout.
        state, ys = jax.lax.scan(
            body, state,
            (xs, state["gc_phase"][0] + jnp.arange(T, dtype=jnp.int32)),
        )
        return state, ys

    return advance


def build_batched_append(config: EngineConfig):
    """jit-compiled multi-key per-advance light post: the unvmapped dense
    scatter-append (every key's real match ids land at its own count
    cursor in one op) + the group-phase bump. The mark/sweep GC is
    deferred to the group flush (build_batched_flush); capacity guards
    keep observing true pending counts because the append stays
    per-advance."""
    from ..ops.engine import build_append_post

    return jax.jit(build_append_post(config))


def build_batched_flush(query: CompiledQuery, config: EngineConfig):
    """jit-compiled multi-key group flush: the per-key GC vmapped over the
    trailing key axis, run on the group's ACCUMULATED window (ys node
    planes + page roots concatenated along the step axis), + the ring
    remap as a dynamic block loop over the occupied prefix
    (engine.remap_pend_blocks -- the remap cost tracks true occupancy,
    which only the device knows). Resets the group-phase scalar."""
    from ..ops.engine import build_gc, remap_pend_blocks

    gc = jax.vmap(
        build_gc(query, config, defer_pend_remap=True),
        in_axes=(-1, -1, -1, -1), out_axes=(-1, -1, -1),
    )

    @jax.jit
    def flush(state, pool, ys, page_roots):
        state, pool, remap_full = gc(state, pool, ys, page_roots)
        pool = {
            **pool,
            "pend": remap_pend_blocks(
                pool["pend"], remap_full, pool["pend_pos"]
            ),
        }
        state = {**state, "gc_phase": jnp.zeros_like(state["gc_phase"])}
        return state, pool

    return flush


def build_batched_post(query: CompiledQuery, config: EngineConfig):
    """jit-compiled multi-key every-advance post pass (append + GC in one
    jit): the G=1 composition kept for tests and one-shot callers; the
    batched driver runs build_batched_append/build_batched_flush at the
    group cadence (EngineConfig.gc_group)."""
    from ..ops.engine import build_append_post, build_gc, remap_pend_blocks

    append = build_append_post(config)
    gc = jax.vmap(
        build_gc(query, config, defer_pend_remap=True),
        in_axes=(-1, -1, -1, -1), out_axes=(-1, -1, -1),
    )

    @jax.jit
    def post(state, pool, ys):
        state, pool, page_roots = append(state, pool, ys)
        state, pool, remap_full = gc(state, pool, ys, page_roots)
        pool = {
            **pool,
            "pend": remap_pend_blocks(
                pool["pend"], remap_full, pool["pend_pos"]
            ),
        }
        state = {**state, "gc_phase": jnp.zeros_like(state["gc_phase"])}
        return state, pool

    return post


def key_mesh(n_devices: Optional[int] = None) -> Mesh:
    """A 1-D device mesh over the key axis."""
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (KEY_AXIS,))


def key_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for a 1-D per-key leaf ([K]) over the key axis."""
    return NamedSharding(mesh, P(KEY_AXIS))


def shard_state(state: Dict[str, jnp.ndarray], mesh: Mesh) -> Dict[str, jnp.ndarray]:
    """Shard the trailing key axis of every leaf over the mesh."""

    def put(leaf):
        spec = P(*([None] * (leaf.ndim - 1) + [KEY_AXIS]))
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree.map(put, state)


def shard_xs(xs: Dict[str, jnp.ndarray], mesh: Mesh) -> Dict[str, jnp.ndarray]:
    """Time-major xs: shard axis 1 (keys), replicate time."""
    sharding = NamedSharding(mesh, P(None, KEY_AXIS))
    return jax.tree.map(lambda leaf: jax.device_put(leaf, sharding), xs)


def global_stats(state: Dict[str, jnp.ndarray]) -> Dict[str, jnp.ndarray]:
    """Cross-key counter reduction -- the one collective in the system.

    Under a sharded key axis XLA lowers these sums to an all-reduce over ICI
    (SURVEY.md section 5.5 observability counters).
    """
    keys = STATE_COUNTER_KEYS + ("runs",)
    return {k: jnp.sum(state[k]) for k in keys}


def shard_stats(
    state: Dict[str, jnp.ndarray], n_shards: int = 1
) -> Dict[str, jnp.ndarray]:
    """Per-shard counter reduction: [K] counters summed within each of the
    `n_shards` contiguous key blocks (the mesh's block partitioning of the
    trailing key axis), giving [n_shards] totals per counter.

    This is the observability aggregation point for the obs registry's
    per-shard gauges (BatchedDeviceNFA.shard_stats): under a sharded key
    axis each block sum stays device-local and only the tiny [n_shards]
    result crosses ICI at the pull -- the per-event hot path still carries
    no collectives (SURVEY.md section 2.8/5.5). The same pull feeds
    BatchedDeviceNFA.device_registries(), whose per-shard registries
    obs/merge.py combines into one cross-device exposition (ISSUE 7)."""
    keys = STATE_COUNTER_KEYS + ("runs",)

    def per_shard(leaf: jnp.ndarray) -> jnp.ndarray:
        k = leaf.shape[-1]
        if k % n_shards:
            raise ValueError(
                f"key extent {k} not divisible by {n_shards} shards"
            )
        return jnp.sum(leaf.reshape(n_shards, k // n_shards), axis=-1)

    return {k: per_shard(state[k]) for k in keys}


def _shard_block(k: int, n_shards: int, shard: int) -> Tuple[int, int]:
    """[lo, hi) key-column range of one shard under the contiguous block
    partitioning every consumer of the trailing key axis shares
    (shard_stats, the mesh layout, and migration must agree on it)."""
    if k % n_shards:
        raise ValueError(f"key extent {k} not divisible by {n_shards} shards")
    if not 0 <= shard < n_shards:
        raise ValueError(f"shard {shard} out of range ({n_shards} shards)")
    span = k // n_shards
    return shard * span, (shard + 1) * span


def slice_shard_tree(
    tree: Dict[str, jnp.ndarray], n_shards: int, shard: int
) -> Dict[str, jnp.ndarray]:
    """One shard's engine columns: every leaf's trailing key axis cut to
    the shard's contiguous block (same blocks as shard_stats), keeping the
    [..., K/n_shards] layout. The engine-state half of a shard checkpoint:
    the slice is self-contained because per-key state never crosses key
    lanes (SURVEY.md section 2.8 -- no cross-key coupling to sever)."""
    lo = hi = None

    def cut(leaf):
        nonlocal lo, hi
        lo, hi = _shard_block(leaf.shape[-1], n_shards, shard)
        return leaf[..., lo:hi]

    return jax.tree.map(cut, tree)


def merge_shard_tree(
    base: Dict[str, jnp.ndarray],
    shard_tree: Dict[str, jnp.ndarray],
    n_shards: int,
    shard: int,
) -> Dict[str, jnp.ndarray]:
    """Graft a migrated shard's columns into a host tree: the inverse of
    slice_shard_tree, writing the shard's block back over `base`'s columns
    (bitwise -- migration must not perturb a single lane)."""

    def paste(leaf, cols):
        lo, hi = _shard_block(leaf.shape[-1], n_shards, shard)
        if cols.shape != leaf[..., lo:hi].shape:
            raise ValueError(
                f"shard column shape {cols.shape} does not fit block "
                f"[{lo}:{hi}] of leaf shape {leaf.shape}"
            )
        return jnp.concatenate(
            [leaf[..., :lo], cols, leaf[..., hi:]], axis=-1
        )

    return jax.tree.map(paste, base, shard_tree)
