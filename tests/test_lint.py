"""The ceplint invariant gate (ISSUE 13): full-package run rides tier-1.

Covers: the green full-package gate within its runtime budget, one
seeded mutation fixture per checker (each proving its gate can fail),
pragma grammar semantics, baseline add/expire semantics, CLI exit
codes, the jit-cache churn audit (flat and seeded-violation), and the
runtime lock-order monitor.
"""
from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import threading
import time

import pytest

from kafkastreams_cep_tpu.analysis import baseline as baseline_mod
from kafkastreams_cep_tpu.analysis import core, serde_check
from kafkastreams_cep_tpu.analysis.cli import main as ceplint_main
from kafkastreams_cep_tpu.analysis.lockmon import (
    LockMonitor,
    lock_monitor,
)

pytestmark = pytest.mark.lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join("tests", "fixtures", "lint")


def findings_for(paths, checkers=None, root_dir=REPO):
    files = core.iter_source_files(
        paths if isinstance(paths, (list, tuple)) else [paths],
        root_dir=root_dir,
    )
    return core.run_checkers(files, checkers, root_dir=root_dir)


def active(findings):
    return [
        f for f in findings if f.suppressed_by is None and not f.baselined
    ]


def codes(findings):
    return {f.code for f in active(findings)}


# --------------------------------------------------------- the tier-1 gate
def test_full_package_green_within_budget():
    """`ceplint --all` over the real repo: zero active findings, and the
    full static pass fits the <= 10 s budget (in-process, no jit)."""
    t0 = time.perf_counter()
    rc = ceplint_main(["--all"])
    wall = time.perf_counter() - t0
    assert rc == 0
    assert wall < 10.0, f"static lint took {wall:.1f}s (budget 10s)"


def test_repo_has_audited_sites_not_silence():
    """The green gate must be green because sites were audited, not
    because the checkers match nothing: the real tree carries pragma'd
    sync/thread/serde sites (the first run's 27 findings)."""
    findings = findings_for(core.DEFAULT_ROOTS)
    suppressed = [f for f in findings if f.suppressed_by is not None]
    assert len(suppressed) >= 8
    assert {f.checker for f in suppressed} >= {"zerosync", "threads"}
    for f in suppressed:
        assert f.suppressed_by.has_reason


# ------------------------------------------------------- mutation fixtures
def test_zerosync_fixture_flagged():
    fx = os.path.join(FIXTURES, "zerosync_violation.py")
    fs = active(findings_for(fx, ["zerosync"]))
    got = codes(findings_for(fx, ["zerosync"]))
    assert {"CEP-S01", "CEP-S02", "CEP-S03"} <= got
    # .item(), block_until_ready, np.asarray all land; int()+bool() land.
    assert sum(1 for f in fs if f.code == "CEP-S01") >= 3
    assert sum(1 for f in fs if f.code == "CEP-S02") >= 2
    # The unmarked function is never hot: every finding names hot_advance.
    assert all("hot_advance" in f.message for f in fs)


def test_threads_fixture_flagged():
    fx = os.path.join(FIXTURES, "threads_violation.py")
    fs = active(findings_for(fx, ["threads"]))
    t01 = [f for f in fs if f.code == "CEP-T01"]
    t03 = [f for f in fs if f.code == "CEP-T03"]
    assert len(t01) == 2  # the two unguarded counter writes
    assert all("counter" in f.message for f in t01)
    assert len(t03) == 1  # the anonymous Thread
    # The lock-guarded attribute is never flagged.
    assert not any("self.ok" in f.message for f in fs)


def test_recompile_fixture_flagged():
    fx = os.path.join(FIXTURES, "recompile_violation.py")
    fs = active(findings_for(fx, ["recompile"]))
    got = {f.code for f in fs}
    assert got == {"CEP-R01", "CEP-R02", "CEP-R03", "CEP-R04", "CEP-R05"}
    r04 = [f for f in fs if f.code == "CEP-R04"]
    assert any("self" in f.message for f in r04)
    assert any("TABLES" in f.message for f in r04)


def test_serde_fixture_flagged(monkeypatch):
    structs = os.path.join(FIXTURES, "serde_structs.py").replace(os.sep, "/")
    frames = os.path.join(FIXTURES, "serde_violation.py").replace(
        os.sep, "/"
    )
    monkeypatch.setattr(serde_check, "SERDE_PATH", frames)
    monkeypatch.setattr(
        serde_check, "STRUCT_BINDINGS",
        ((structs, "Record", "encode_record", "decode_record"),),
    )
    monkeypatch.setattr(
        serde_check, "DICT_BINDINGS",
        ((
            structs, "Gate.snapshot_state", "Gate.restore_state",
            "encode_gate_state", "decode_gate_state",
        ),),
    )
    fs = active(findings_for([structs, frames], ["serde"]))
    msgs = "\n".join(f.message for f in fs)
    assert any(
        f.code == "CEP-D01" and "Record.c" in f.message for f in fs
    )
    assert any(f.code == "CEP-D01" and "'z'" in f.message for f in fs)
    assert any(
        f.code == "CEP-D03" and "'q'" in f.message for f in fs
    )
    assert any(
        f.code == "CEP-D03" and "'y'" in f.message
        and "never consumes" in f.message
        for f in fs
    )
    # The pragma'd field is audited, not flagged.
    assert "skipme" not in msgs


def test_metrics_fixture_flagged(tmp_path):
    pkg = tmp_path / "kafkastreams_cep_tpu" / "obs"
    pkg.mkdir(parents=True)
    (pkg / "registry.py").write_text(
        "class R:\n"
        "    def setup(self, reg):\n"
        '        reg.counter("cep_undocumented_total", "seeded")\n'
        '        reg.gauge("cep_documented_gauge", "fine")\n'
    )
    (tmp_path / "PERF.md").write_text(
        "# perf\n"
        "<!-- ceplint:metrics-dictionary:begin -->\n"
        "- `cep_documented_gauge` -- fine\n"
        "- `cep_ghost_total` -- registered by no code\n"
        "<!-- ceplint:metrics-dictionary:end -->\n"
    )
    fs = active(
        findings_for(
            ["kafkastreams_cep_tpu"], ["metrics"], root_dir=str(tmp_path)
        )
    )
    assert any(
        f.code == "CEP-M01" and "cep_undocumented_total" in f.message
        for f in fs
    )
    assert any(
        f.code == "CEP-M02" and "cep_ghost_total" in f.message for f in fs
    )
    assert not any("cep_documented_gauge" in f.message for f in fs)
    # Missing markers are their own loud finding.
    (tmp_path / "PERF.md").write_text("# perf, no markers\n")
    fs2 = active(
        findings_for(
            ["kafkastreams_cep_tpu"], ["metrics"], root_dir=str(tmp_path)
        )
    )
    assert [f.code for f in fs2] == ["CEP-M03"]


# ---------------------------------------------------------- pragma grammar
def test_pragma_suppression_requires_reason(tmp_path):
    mod = tmp_path / "mod.py"
    mod.write_text(
        "# cep: hot-path\n"
        "def hot(state):\n"
        "    a = state['x'].item()  # cep: sync-ok(audited: drain point)\n"
        "    b = state['y'].item()  # cep: sync-ok\n"
        "    c = state['z'].item()  # cep: bogus-kind(what)\n"
        "    return a, b, c\n"
    )
    fs = findings_for(["mod.py"], root_dir=str(tmp_path))
    by_line = {}
    for f in fs:
        by_line.setdefault(f.line, []).append(f)
    # line 3: suppressed by a well-formed pragma.
    line3 = [f for f in by_line.get(3, []) if f.checker == "zerosync"]
    assert line3 and all(f.suppressed_by is not None for f in line3)
    assert line3[0].suppressed_by.reason == "audited: drain point"
    # line 4: reasonless pragma does NOT suppress, and is itself flagged.
    line4 = {f.code for f in by_line.get(4, [])}
    assert "CEP-S01" in line4 and "CEP-P01" in line4
    # line 5: unknown kind flagged, sync finding stays active.
    line5 = {f.code for f in by_line.get(5, [])}
    assert "CEP-S01" in line5 and "CEP-P02" in line5


def test_pragma_in_string_literal_is_inert(tmp_path):
    mod = tmp_path / "mod.py"
    mod.write_text(
        'DOC = "use # cep: sync-ok(reason) to audit a site"\n'
        "# cep: hot-path\n"
        "def hot(state):\n"
        '    s = "# cep: sync-ok(not a comment)"\n'
        "    return state['x'].item(), s\n"
    )
    fs = findings_for(["mod.py"], root_dir=str(tmp_path))
    s01 = [f for f in fs if f.code == "CEP-S01"]
    assert len(s01) == 1 and s01[0].suppressed_by is None
    assert not any(f.checker == "pragma" for f in fs)


def test_hot_path_marker_on_def_line(tmp_path):
    mod = tmp_path / "mod.py"
    mod.write_text(
        "def cold(state):\n"
        "    return state['x'].item()\n"
        "def hot(state):  # cep: hot-path\n"
        "    return state['x'].item()\n"
    )
    fs = active(
        findings_for(["mod.py"], ["zerosync"], root_dir=str(tmp_path))
    )
    assert len(fs) == 1 and fs[0].line == 4


# ------------------------------------------------------ baseline semantics
def test_baseline_add_annotate_expire(tmp_path, capsys):
    mod = tmp_path / "mod.py"
    shutil.copy(
        os.path.join(REPO, FIXTURES, "zerosync_violation.py"), mod
    )
    bl = tmp_path / "ceplint.baseline.json"
    # zerosync only: the repo-level serde/metrics checkers would report
    # the tmp tree's missing PERF.md and muddy the add/expire flow.
    args = [
        "mod.py", "--root", str(tmp_path), "--baseline", str(bl),
        "--checker", "zerosync",
    ]
    # 1) raw findings: exit 1, no baseline file consulted.
    assert ceplint_main(args) == 1
    # 2) record them: entries land with TODO notes, which still fail.
    assert ceplint_main(args + ["--update-baseline"]) == 1
    entries = baseline_mod.load(str(bl))
    assert entries and all(
        e["note"] == "TODO: annotate" for e in entries
    )
    # 3) annotate: a justified baseline is green and reported as such.
    for e in entries:
        e["note"] = "accepted: fixture exercising the gate"
    baseline_mod.save(str(bl), entries)
    assert ceplint_main(args) == 0
    out = capsys.readouterr().out
    assert "[baselined]" in out
    # 4) fix the findings: every entry is now stale -> exit 1 (expire).
    mod.write_text("def clean():\n    return 1\n")
    assert ceplint_main(args) == 1
    out = capsys.readouterr().out
    assert "CEP-B01" in out and "stale" in out
    # 5) --update-baseline expires them; the gate is green again.
    assert ceplint_main(args + ["--update-baseline"]) == 0
    assert baseline_mod.load(str(bl)) == []


def test_committed_baseline_is_empty_or_annotated():
    entries = baseline_mod.load(
        os.path.join(REPO, baseline_mod.BASELINE_NAME)
    )
    for e in entries:
        note = str(e.get("note", "")).strip()
        assert note and note != "TODO: annotate", e


# -------------------------------------------------------- CLI + exit codes
def test_cli_unknown_checker_exits_2(capsys):
    assert ceplint_main(["--all", "--checker", "bogus"]) == 2
    assert "unknown checker" in capsys.readouterr().err


def test_cli_fixture_exits_1(capsys):
    rc = ceplint_main(
        [
            os.path.join(FIXTURES, "zerosync_violation.py"),
            "--checker", "zerosync", "--no-baseline",
        ]
    )
    assert rc == 1
    out = capsys.readouterr().out
    assert "CEP-S01" in out and "finding(s)" in out


def test_cli_json_and_script_shim():
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO, "scripts", "ceplint.py"),
            "--all", "--json",
        ],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    doc = json.loads(proc.stdout)
    assert doc["tool"] == "ceplint" and doc["active"] == 0
    assert any(f["suppressed"] for f in doc["findings"])
    for f in doc["findings"]:
        if f["suppressed"]:
            assert f["suppression_reason"]


# ----------------------------------------------------------- jit-cache audit
def test_jit_cache_audit_flat_on_same_shapes():
    """The acceptance pin: a same-shape churn replay (advances, drains,
    checkpoint flushes across epochs) compiles NOTHING after warmup."""
    from kafkastreams_cep_tpu.analysis.jit_audit import run_jit_cache_audit

    assert run_jit_cache_audit() == []


def test_jit_cache_audit_catches_shape_churn():
    """Seeded violation: growing [T, K] signatures must recompile, and
    the audit must say so (the gate is proven able to fail)."""
    from kafkastreams_cep_tpu.analysis.jit_audit import run_jit_cache_audit

    fs = run_jit_cache_audit(vary_shapes=True)
    assert fs and all(f.code == "CEP-J01" for f in fs)
    assert any("cep_compiles_total" in f.message for f in fs)


# -------------------------------------------------------- lock-order monitor
def test_lockmon_detects_inverted_order():
    with lock_monitor() as mon:
        a = threading.Lock()
        b = threading.Lock()
        with a:
            with b:
                pass
        with b:
            with a:  # inverted: the classic deadlock shape
                pass
    cycles = mon.cycles()
    assert cycles, mon.report()
    assert any(len(set(c)) == 2 for c in cycles)
    assert "CYCLE" in mon.report()


def test_lockmon_consistent_order_is_clean():
    with lock_monitor() as mon:
        a = threading.Lock()
        b = threading.Lock()
        for _ in range(3):
            with a:
                with b:
                    pass
    assert mon.cycles() == []
    assert mon.acquires >= 6


def test_lockmon_wrappers_delegate_and_survive_disarm():
    with lock_monitor():
        lock = threading.Lock()
        cond = threading.Condition()  # allocates an instrumented RLock
        with cond:
            cond.notify_all()
        assert lock.acquire(False) is True
        assert lock.locked()
        lock.release()
    # After uninstall the wrapper still guards correctly (daemon threads
    # may hold references past the monitored region) and threading.Lock
    # is back to the stdlib factory.
    assert lock.acquire(False) is True
    lock.release()
    assert not hasattr(threading.Lock(), "_mon")


def test_lockmon_cross_thread_edges_record_thread_names():
    with lock_monitor() as mon:
        a = threading.Lock()
        b = threading.Lock()

        def worker():
            with a:
                with b:
                    pass

        t = threading.Thread(target=worker, name="kct-lint-worker")
        t.start()
        t.join()
    assert any(
        "kct-lint-worker" in threads for threads in mon.edges.values()
    )


def test_cli_zero_files_scanned_is_an_error(capsys):
    """A typo'd path must not read as a green gate (exit 2, not 0)."""
    assert ceplint_main(
        ["kafkastreams_cep_tpu/obs/typo.py", "--checker", "zerosync"]
    ) == 2
    assert "no Python files found" in capsys.readouterr().err


def test_cli_corrupt_baseline_is_an_error(tmp_path, capsys):
    bad = tmp_path / "bl.json"
    bad.write_text("not json")
    assert ceplint_main(["--all", "--baseline", str(bad)]) == 2
    assert "baseline" in capsys.readouterr().err


def test_partial_update_preserves_out_of_scope_entries(tmp_path, capsys):
    """--update-baseline on a partial run (path/checker subset) must not
    erase entries it could not have re-observed -- and a partial run
    must not stale-flag them either."""
    mod = tmp_path / "mod.py"
    shutil.copy(
        os.path.join(REPO, FIXTURES, "zerosync_violation.py"), mod
    )
    bl = tmp_path / "ceplint.baseline.json"
    foreign = {
        "fingerprint": "feedfacefeedface",
        "checker": "metrics",
        "code": "CEP-M02",
        "path": "PERF.md",
        "message": "stale doc entry accepted during migration",
        "note": "accepted: dashboard still reads it; remove in PR 12",
    }
    baseline_mod.save(str(bl), [foreign])
    args = [
        "mod.py", "--root", str(tmp_path), "--baseline", str(bl),
        "--checker", "zerosync",
    ]
    # Partial run: the metrics entry is out of scope -> not stale.
    assert ceplint_main(args) == 1  # the fixture's own findings
    assert "CEP-B01" not in capsys.readouterr().out
    # Partial update: records zerosync findings, PRESERVES the foreign
    # entry and its note.
    assert ceplint_main(args + ["--update-baseline"]) == 1  # TODO notes
    entries = baseline_mod.load(str(bl))
    kept = [e for e in entries if e["checker"] == "metrics"]
    assert kept == [foreign]
    assert any(e["checker"] == "zerosync" for e in entries)


def test_cli_no_baseline_update_baseline_conflict(capsys):
    assert ceplint_main(
        ["--all", "--no-baseline", "--update-baseline"]
    ) == 2
    assert "mutually exclusive" in capsys.readouterr().err


def test_worker_only_helper_does_not_inherit_parent_roots(tmp_path):
    """Calls made only inside a promoted worker def belong to the
    worker's unit: a helper reached solely from the worker thread must
    not be reported as shared with the spawning method's roots."""
    mod = tmp_path / "mod.py"
    mod.write_text(
        "import threading\n"
        "class Pump:\n"
        "    def __init__(self):\n"
        "        self.n = 0\n"
        "    def start(self):\n"
        "        def _run():\n"
        "            self._bump()\n"
        "        threading.Thread(target=_run, name='w').start()\n"
        "    def _bump(self):\n"
        "        self.n += 1\n"
    )
    fs = active(
        findings_for(["mod.py"], ["threads"], root_dir=str(tmp_path))
    )
    # _bump is worker-only: a single root, so self.n needs no lock.
    assert not any(f.code == "CEP-T01" for f in fs), [
        f.message for f in fs
    ]


def test_jit_audit_module_pins_cpu_backend():
    """The documented `--jit-audit` command must not hang on a downed
    TPU tunnel: importing the audit module pins JAX_PLATFORMS like
    faults/soak.py does (a no-op under the already-pinned test env)."""
    import importlib

    import kafkastreams_cep_tpu.analysis.jit_audit as ja

    importlib.reload(ja)
    assert os.environ.get("JAX_PLATFORMS") == "cpu"
