"""Conformance: the fused Pallas kernel vs the XLA scan step.

The kernel (ops/pallas_step.py) must implement the *identical* transition
relation -- same slot table, DFS emission order, counters and drop policy --
so these tests compare full engine state bitwise after every batch, plus the
decoded match sequences, across the three pattern families (strict
contiguity, folds + skip-till-next, skip-till-any + windows). Runs the
kernel in the Pallas interpreter so the suite stays CPU-only; the same
kernel compiles for TPU via Mosaic (BatchedDeviceNFA(engine="pallas")).
"""
import random

import numpy as np
import pytest

from kafkastreams_cep_tpu import Event, QueryBuilder, Selected, compile_pattern
from kafkastreams_cep_tpu.ops.engine import EngineConfig
from kafkastreams_cep_tpu.ops.schema import EventSchema
from kafkastreams_cep_tpu.ops.tables import compile_query
from kafkastreams_cep_tpu.parallel import BatchedDeviceNFA
from kafkastreams_cep_tpu.pattern.expressions import agg, field, value
from kafkastreams_cep_tpu.streams.serde import sequence_to_json

TS0 = 1_000_000


def letters_pattern():
    return (
        QueryBuilder()
        .select("select-A").where(value() == "A")
        .then().select("select-B").where(value() == "B")
        .then().select("select-C").where(value() == "C")
        .build()
    )


def stock_pattern():
    return (
        QueryBuilder()
        .select("stage-1").where(field("volume") > 1000)
        .fold("avg", field("price"))
        .then().select("stage-2", Selected.with_skip_til_next_match())
        .zero_or_more().where(field("price") > agg("avg", default=0))
        .fold("avg", (agg("avg", default=0) + field("price")) // 2)
        .fold("volume", field("volume"))
        .then().select("stage-3", Selected.with_skip_til_next_match())
        .where(field("volume") < 0.8 * agg("volume", default=0))
        .within(ms=64)
        .build()
    )


def skip2_pattern():
    qb = QueryBuilder()
    b = qb.select("s0").where(value() == "A").within(ms=16)
    for i, ch in enumerate("BC", start=1):
        b = (
            b.then().select(f"s{i}", Selected.with_skip_til_any_match())
            .where(value() == ch).within(ms=16)
        )
    return b.build()


def letters_stream(rng, n):
    return [Event("K", rng.choice("ABCD"), TS0 + i, "t", 0, i) for i in range(n)]


def stock_stream(rng, n):
    return [
        Event(
            "K",
            {"name": "s", "price": rng.randint(80, 140),
             "volume": rng.randint(500, 1500)},
            TS0 + i, "t", 0, i,
        )
        for i in range(n)
    ]


CASES = {
    "letters": (
        letters_pattern, None, letters_stream,
        EngineConfig(lanes=8, nodes=128, matches=32, matches_per_step=8,
                     nodes_per_step=4),
    ),
    "stock": (
        stock_pattern,
        EventSchema({"name": np.int32, "price": np.int32, "volume": np.int32}),
        stock_stream,
        EngineConfig(lanes=32, nodes=512, matches=64, matches_per_step=16,
                     nodes_per_step=16),
    ),
    "skip2": (
        skip2_pattern, None, letters_stream,
        EngineConfig(lanes=32, nodes=256, matches=64, matches_per_step=16,
                     nodes_per_step=16, strict_windows=True),
    ),
}


@pytest.mark.parametrize("case", sorted(CASES))
def test_pallas_matches_xla_bitwise(case):
    pattern_fn, schema, stream_fn, config = CASES[case]
    query = compile_query(compile_pattern(pattern_fn()), schema)
    K, T, n_batches = 8, 10, 3
    keys = [f"k{i}" for i in range(K)]
    bx = BatchedDeviceNFA(query, keys=keys, config=config, engine="xla")
    bp = BatchedDeviceNFA(
        query, keys=keys, config=config, engine="pallas_interpret"
    )
    rng = random.Random(5)
    streams = {k: stream_fn(rng, T * n_batches) for k in keys}
    for b in range(n_batches):
        chunk = {k: s[b * T : (b + 1) * T] for k, s in streams.items()}
        ox = bx.advance(chunk)
        op = bp.advance(chunk)
        for name in bx.state:
            assert np.array_equal(
                np.asarray(bx.state[name]), np.asarray(bp.state[name])
            ), f"{case} batch {b}: state[{name}] diverged"
        for name in bx.pool:
            assert np.array_equal(
                np.asarray(bx.pool[name]), np.asarray(bp.pool[name])
            ), f"{case} batch {b}: pool[{name}] diverged"
        assert set(ox) == set(op), f"{case} batch {b}: matched key sets differ"
        for k in ox:
            jx = [sequence_to_json(s) for s in ox[k]]
            jp = [sequence_to_json(s) for s in op[k]]
            assert jx == jp, f"{case} batch {b}: matches differ for {k}"


def test_engine_auto_falls_back_off_tpu():
    query = compile_query(compile_pattern(letters_pattern()), None)
    bat = BatchedDeviceNFA(
        query, keys=["a", "b"],
        config=EngineConfig(lanes=8, nodes=128, matches=16), engine="auto",
    )
    # The suite runs on the forced CPU mesh: auto must pick the XLA path
    # and say why.
    assert bat.engine == "xla"
    assert "cpu" in (bat.engine_fallback_reason or "")


def test_pallas_pads_key_axis_to_blocks():
    query = compile_query(compile_pattern(letters_pattern()), None)
    config = EngineConfig(lanes=8, nodes=128, matches=16, nodes_per_step=4)
    bat = BatchedDeviceNFA(
        query, keys=[f"k{i}" for i in range(5)], config=config,
        engine="pallas_interpret",
    )
    assert bat.K_padded == 8
    out = bat.advance(
        {"k0": [Event("k0", v, TS0 + i, "t", 0, i)
                for i, v in enumerate("ABC")]}
    )
    assert len(out.get("k0", [])) == 1


def test_pallas_checkpoint_roundtrip_across_engines():
    query = compile_query(compile_pattern(letters_pattern()), None)
    config = EngineConfig(lanes=8, nodes=128, matches=16, nodes_per_step=4)
    keys = [f"k{i}" for i in range(4)]
    bx = BatchedDeviceNFA(query, keys=keys, config=config, engine="xla")
    rng = random.Random(3)
    streams = {k: letters_stream(rng, 12) for k in keys}
    bx.advance({k: s[:6] for k, s in streams.items()})
    snap = bx.snapshot()
    # Restore into the pallas engine: K_padded grows 4 -> 8 with padding.
    bp = BatchedDeviceNFA.restore(
        query, snap, config=config, engine="pallas_interpret"
    )
    assert bp.K_padded == 8
    ox = bx.advance({k: s[6:] for k, s in streams.items()})
    op = bp.advance({k: s[6:] for k, s in streams.items()})
    assert set(ox) == set(op)
    for k in ox:
        assert [sequence_to_json(s) for s in ox[k]] == [
            sequence_to_json(s) for s in op[k]
        ]
