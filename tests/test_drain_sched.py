"""Adaptive drain scheduler (parallel/drain_sched.py, ISSUE 17).

Pins the control law (AIMD on target_emit_ms, budgeted pow2 gc_group
steps with an explicit group flush), the compile-flatness of steady
state (the jit_audit contract: an armed controller whose knobs have
settled adds ZERO retraces), and the `cep_drain_controller_*` gauges.
"""
import random

import pytest

from kafkastreams_cep_tpu import Event, QueryBuilder, compile_pattern
from kafkastreams_cep_tpu.obs.registry import MetricsRegistry
from kafkastreams_cep_tpu.ops.engine import EngineConfig
from kafkastreams_cep_tpu.parallel import BatchedDeviceNFA, DrainController
from kafkastreams_cep_tpu.pattern.expressions import value

TS = 1_000_000


def abc_pattern():
    return (
        QueryBuilder()
        .select("a").where(value() == "A")
        .then().select("b").where(value() == "B")
        .then().select("c").where(value() == "C")
        .build()
    )


def mk_engine(reg, *, gc_group=1, compile_telemetry=False, **cfg_kw):
    cfg = EngineConfig(lanes=8, nodes=64, matches=32, gc_group=gc_group,
                       **cfg_kw)
    return BatchedDeviceNFA(
        compile_pattern(abc_pattern()), keys=["k0", "k1"], config=cfg,
        drain_mode="flat", query_name="q1", registry=reg,
        compile_telemetry=compile_telemetry,
    )


def feed(bat, n, start=0):
    evs = {
        k: [Event(k, "ABC"[i % 3], TS + start + i, "t", 0, start + i)
            for i in range(n)]
        for k in ("k0", "k1")
    }
    bat.advance(evs)


def test_controller_arms_emit_dial():
    reg = MetricsRegistry()
    bat = mk_engine(reg)
    assert bat.target_emit_ms is None
    ctl = DrainController(bat, max_emit_ms=800.0, registry=reg)
    assert bat.target_emit_ms == 800.0
    st = ctl.state()
    assert st["target_emit_ms"] == 800.0
    assert st["gc_group"] == 1


def test_emit_decreases_on_hot_p99_and_relaxes_when_cool():
    reg = MetricsRegistry()
    bat = mk_engine(reg)
    ctl = DrainController(bat, target_p99_ms=500.0, min_emit_ms=2.0,
                          max_emit_ms=1000.0, registry=reg)
    h = reg.histogram(
        "cep_match_latency_seconds", "", labels=("query",)
    ).labels(query="q1")
    for _ in range(40):
        h.observe(2.0)  # p99 == 2000 ms, 4x over target
    before = bat.target_emit_ms
    for _ in range(6):
        ctl.observe()
    assert bat.target_emit_ms < before / 8  # multiplicative decrease
    floor = bat.target_emit_ms
    # Cool the histogram (reservoir refills with fast samples) and the
    # ring is empty: multiplicative-increase back toward the ceiling.
    for _ in range(2000):
        h.observe(0.001)
    for _ in range(40):
        ctl.observe()
    assert bat.target_emit_ms > floor
    assert bat.target_emit_ms <= 1000.0


def test_emit_decreases_on_hot_ring_without_latency_signal():
    """No latency histogram at all (bench drives the engine directly):
    ring occupancy alone must tighten the cadence."""
    reg = MetricsRegistry()
    bat = mk_engine(reg, matches_per_step=4)
    ctl = DrainController(bat, registry=reg)
    before = bat.target_emit_ms
    # Fake a hot probe observation: ring 60% full.
    bat._pos_obs = (bat._pend_accum, int(bat.config.matches * 0.6),
                    bat.config.nodes // 2)
    ctl.observe()
    assert bat.target_emit_ms < before


def test_gc_group_steps_are_budgeted_and_flush_first():
    reg = MetricsRegistry()
    bat = mk_engine(reg, gc_group=8)
    ctl = DrainController(bat, compile_budget=2, cooldown=1, registry=reg)
    feed(bat, 6)
    assert bat._group_ys  # pending window under the old cadence
    flushes_before = bat.flushes
    # Hot region: fill fraction > 0.75 -> halve, flushing the group first.
    bat._pos_obs = (bat._pend_accum, 0, int(bat.config.nodes * 0.9))
    ctl.observe()
    assert bat.gc_group == 4
    assert bat.flushes == flushes_before + 1
    assert not bat._group_ys
    st = ctl.state()
    assert st["gc_changes"] == 1
    # Second step spends the budget...
    bat._pos_obs = (bat._pend_accum, 0, int(bat.config.nodes * 0.9))
    ctl.observe()
    assert bat.gc_group == 2
    # ...after which the knob is FROZEN no matter the signal.
    for _ in range(10):
        bat._pos_obs = (bat._pend_accum, 0, int(bat.config.nodes * 0.9))
        ctl.observe()
    assert bat.gc_group == 2
    assert ctl.state()["gc_changes"] == 2


def test_gc_group_grows_only_when_post_wall_dominates():
    reg = MetricsRegistry()
    bat = mk_engine(reg, gc_group=2)
    ctl = DrainController(bat, cooldown=1, registry=reg)
    # Cool region, but no profiling samples: no growth signal.
    bat._pos_obs = (bat._pend_accum, 0, 0)
    ctl.observe()
    assert bat.gc_group == 2
    # Feed the sampled walls: post dominates advance -> double.
    h = reg.get("cep_advance_compute_seconds")
    h.labels(instance=bat.instance_id, phase="advance").observe(0.001)
    h.labels(instance=bat.instance_id, phase="post").observe(0.010)
    bat._pos_obs = (bat._pend_accum, 0, 0)
    ctl.observe()
    assert bat.gc_group == 4


def test_cooldown_spaces_gc_steps():
    reg = MetricsRegistry()
    bat = mk_engine(reg, gc_group=16)
    ctl = DrainController(bat, cooldown=5, compile_budget=8, registry=reg)
    for i in range(10):
        bat._pos_obs = (bat._pend_accum, 0, int(bat.config.nodes * 0.9))
        ctl.observe()
    # 10 ticks / cooldown 5 -> exactly 2 steps: 16 -> 8 -> 4.
    assert bat.gc_group == 4


def test_steady_state_is_compile_flat():
    """The jit_audit pin: with the controller armed and knobs settled,
    continued advances + controller ticks add zero new compiles."""
    reg = MetricsRegistry()
    bat = mk_engine(reg, compile_telemetry=True, matches_per_step=4)
    ctl = DrainController(bat, registry=reg)
    for i in range(4):
        feed(bat, 6, start=i * 6)
        ctl.observe(events=12)
    bat.drain()
    settled = bat.compile_watch.seen_count
    for i in range(4, 10):
        feed(bat, 6, start=i * 6)
        ctl.observe(events=12)
        bat.drain()
    assert bat.compile_watch.seen_count == settled, (
        "drain controller caused retraces in steady state"
    )
    assert ctl.state()["compiles_seen"] == settled


def test_suggest_t_tracks_rate_and_budget():
    reg = MetricsRegistry()
    bat = mk_engine(reg)
    ctl = DrainController(bat, t_min=8, t_max=512, registry=reg)
    assert ctl.suggest_t() == 8  # no rate observed yet
    ctl._rate_ev_s = 20_000.0  # 10k ev/s per key
    bat.target_emit_ms = 100.0
    # per-key 10k ev/s * 50 ms of budget = 500 events
    assert ctl.suggest_t() == 500
    bat.target_emit_ms = 1000.0
    assert ctl.suggest_t() == 512  # clamped to t_max


def test_controller_gauges_and_state_are_jsonable():
    import json

    reg = MetricsRegistry()
    bat = mk_engine(reg)
    ctl = DrainController(bat, registry=reg)
    feed(bat, 6)
    st = ctl.observe(events=12)
    json.dumps(st)  # the soak/bench artifacts embed state() directly
    snap = reg.snapshot()
    for name in (
        "cep_drain_controller_target_emit_ms",
        "cep_drain_controller_gc_group",
        "cep_drain_controller_occupancy_ratio",
        "cep_drain_controller_adjustments_total",
        "cep_drain_controller_p99_ms",
    ):
        assert name in snap, name


def test_controller_validation():
    reg = MetricsRegistry()
    bat = mk_engine(reg)
    with pytest.raises(ValueError):
        DrainController(bat, target_p99_ms=0, registry=reg)
    with pytest.raises(ValueError):
        DrainController(bat, min_emit_ms=10, max_emit_ms=5, registry=reg)
