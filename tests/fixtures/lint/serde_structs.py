"""Structures for the seeded serde-completeness violations.

Paired with serde_violation.py via monkeypatched bindings in
tests/test_lint.py. NOT runnable production code.
"""
from dataclasses import dataclass
from typing import Any, Dict


@dataclass
class Record:
    a: int
    b: int
    c: int  # encode/decode in serde_violation.py both drop this field
    skipme: int = 0  # cep: serde-ok(derived at load time; fixture pragma)


class Gate:
    def snapshot_state(self) -> Dict[str, Any]:
        return {"x": 1, "y": 2, "z": 3}  # 'z' is never encoded

    def restore_state(self, state: Dict[str, Any]) -> None:
        self.x = state["x"]  # 'y' decoded but never consumed
