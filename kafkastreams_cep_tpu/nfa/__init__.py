from .context import FoldEnv, HostEventEnv, MatcherContext
from .nfa import NFA, ComputationStage, initial_computation_stage
