"""threads: attributes written from >= 2 thread roots outside a lock.

The engine's concurrency model is deliberately narrow: a main/driver
thread, the introspection plane's serve/clock threads, the soak
scraper, and the single decode worker. Shared mutable state between any
two of them must be written under a lock (or be single-writer by
construction). This checker derives the thread roots statically --
``threading.Thread(target=...)`` constructions, ``.submit(...)`` onto a
``ThreadPoolExecutor``, plus the ``EXTRA_ROOTS`` table for roots that
enter through foreign frameworks (http.server handler threads, the
introspection clock calling registered tick callables) -- then flags
every ``self.attr`` write that (a) is reachable from two distinct roots
or from a multi-instance root, and (b) is not inside a
``with self.<...lock...>`` region.

Findings:
    CEP-T01  unguarded write to an attribute shared across thread roots
    CEP-T03  anonymous thread root (Thread without name=, executor
             without thread_name_prefix) -- lock-order reports and
             tracebacks must be attributable

Audited sites carry ``# cep: thread-ok(reason)`` (e.g. a write that is
ordered after ``join()`` by construction). ``__init__`` writes are
initialization-before-spawn and never flagged. The static pass is
paired with the runtime lock-order monitor (analysis/lockmon.py) armed
in the chaos and quick-soak suites.
"""
from __future__ import annotations

import ast
from fnmatch import fnmatch
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, SourceFile, dotted_name as _dotted

#: repo-relative file -> {method fnmatch pattern: (root name, multi)}.
#: Roots that no static Thread() scan can see: framework-driven entry
#: points. `multi` marks roots whose instances run concurrently with
#: themselves (every ThreadingHTTPServer request gets its own thread).
EXTRA_ROOTS: Dict[str, Dict[str, Tuple[str, bool]]] = {
    "kafkastreams_cep_tpu/obs/http.py": {
        # _Handler.do_GET dispatches plane._routes on per-request threads.
        "IntrospectionServer._route_*": ("http-handler", True),
    },
    "kafkastreams_cep_tpu/streams/driver.py": {
        # serve_http registers maybe_report as an IntrospectionServer
        # tick_fn: it runs on the kct-introspect-clock thread AND on the
        # poll path.
        "LogDriver.maybe_report": ("kct-introspect-clock", False),
    },
}

_MAIN = "main"


def _is_lockish(expr: ast.AST) -> bool:
    dotted = _dotted(expr)
    return dotted is not None and "lock" in dotted.lower()


class _Unit:
    """One analyzable body: a method, or a nested def that is a thread
    target (its writes belong to its own root, not its parent's)."""

    def __init__(self, name: str, node: ast.AST, method: str) -> None:
        self.name = name  # display name (method or method.<nested>)
        self.node = node
        self.method = method  # enclosing method name
        self.roots: Set[str] = set()
        #: methods this unit calls via self.m(...)
        self.calls: Set[str] = set()
        #: attr -> [(lineno, guarded, context)]
        self.writes: Dict[str, List[Tuple[int, bool]]] = {}


def _thread_calls(node: ast.AST):
    """Yield (call, kind) for Thread/ThreadPoolExecutor constructions and
    executor .submit() calls anywhere under `node`."""
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        dotted = _dotted(sub.func) or ""
        base = dotted.rsplit(".", 1)[-1]
        if base == "Thread":
            yield sub, "thread"
        elif base == "ThreadPoolExecutor":
            yield sub, "executor"
        elif (
            isinstance(sub.func, ast.Attribute) and sub.func.attr == "submit"
        ):
            yield sub, "submit"


def _kwarg(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _collect_writes(unit: _Unit, skip: Set[ast.AST]) -> None:
    """self.attr write sites with their with-lock guard state."""

    def walk(node: ast.AST, guarded: bool) -> None:
        if node in skip:
            return
        if isinstance(node, ast.With):
            locked = guarded or any(
                _is_lockish(item.context_expr) for item in node.items
            )
            for child in node.body:
                walk(child, locked)
            return
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        def written_attr(t: ast.AST) -> Optional[str]:
            # self.x = ... / self.x += ...       -> x
            # self.x[k] = ... (container entry)  -> x
            # out[self.x[k]] = ...               -> None (self.x only read)
            if (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
            ):
                return t.attr
            if isinstance(t, ast.Subscript):
                return written_attr(t.value)
            return None

        for t in targets:
            elts = (
                t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
            )
            for elt in elts:
                attr = written_attr(elt)
                if attr is not None:
                    unit.writes.setdefault(attr, []).append(
                        (node.lineno, guarded)
                    )
        for child in ast.iter_child_nodes(node):
            walk(child, guarded)

    for child in ast.iter_child_nodes(unit.node):
        walk(child, False)


def _analyze_class(
    src: SourceFile, cls: ast.ClassDef
) -> List[Finding]:
    findings: List[Finding] = []
    methods: Dict[str, ast.AST] = {
        n.name: n
        for n in cls.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    units: Dict[str, _Unit] = {
        name: _Unit(name, node, name) for name, node in methods.items()
    }
    #: methods referenced ONLY as thread targets get no implicit main root
    target_only: Set[str] = set()
    #: nested defs promoted to their own unit (skipped in parent walks)
    promoted: Dict[str, Set[ast.AST]] = {m: set() for m in methods}

    def resolve_target(
        expr: ast.AST, method: str
    ) -> Tuple[Optional[str], Optional[ast.AST]]:
        """(unit key, nested node) for a thread-target expression."""
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and expr.attr in methods
        ):
            return expr.attr, None
        if isinstance(expr, ast.Name):
            for sub in ast.walk(methods[method]):
                if (
                    isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and sub.name == expr.id
                ):
                    return f"{method}.{expr.id}", sub
        return None, None  # external callable (httpd.serve_forever, ...)

    # ------------------------------------------------- roots from Thread()
    for mname, mnode in methods.items():
        for call, kind in _thread_calls(mnode):
            if kind == "executor":
                if _kwarg(call, "thread_name_prefix") is None:
                    findings.append(
                        Finding(
                            "threads", "CEP-T03", src.relpath, call.lineno,
                            "ThreadPoolExecutor without thread_name_prefix: "
                            "anonymous worker threads are unattributable in "
                            "lock-order reports and tracebacks",
                            context=src.context_line(call.lineno),
                        )
                    )
                continue
            if kind == "thread":
                name_kw = _kwarg(call, "name")
                target_kw = _kwarg(call, "target")
                if name_kw is None:
                    findings.append(
                        Finding(
                            "threads", "CEP-T03", src.relpath, call.lineno,
                            "anonymous thread root: Thread(...) without "
                            "name= -- lock-order reports and tracebacks "
                            "must be attributable",
                            context=src.context_line(call.lineno),
                        )
                    )
                if target_kw is None:
                    continue
                root = (
                    name_kw.value
                    if isinstance(name_kw, ast.Constant)
                    and isinstance(name_kw.value, str)
                    else f"thread@{call.lineno}"
                )
                key, nested = resolve_target(target_kw, mname)
                if key is None:
                    continue
                if nested is not None and key not in units:
                    units[key] = _Unit(key, nested, mname)
                    promoted[mname].add(nested)
                units[key].roots.add(root)
                if nested is None:
                    target_only.add(key)
            elif kind == "submit":
                if not call.args:
                    continue
                fn = call.args[0]
                pool = (
                    _dotted(call.func.value)
                    if isinstance(call.func, ast.Attribute)
                    else None
                )
                pool_attr = (
                    pool.split(".", 1)[1] if pool and "." in pool else None
                )
                key, nested = resolve_target(fn, mname)
                if key is None:
                    continue
                if nested is not None and key not in units:
                    units[key] = _Unit(key, nested, mname)
                    promoted[mname].add(nested)
                units[key].roots.add(
                    f"executor:{pool_attr or 'anonymous'}"
                )
                if nested is None:
                    target_only.add(key)

    # --------------------------------------------------------- extra roots
    multi_roots: Set[str] = set()
    for pattern, (root, multi) in EXTRA_ROOTS.get(src.relpath, {}).items():
        for mname in methods:
            if fnmatch(f"{cls.name}.{mname}", pattern):
                units[mname].roots.add(root)
                if multi:
                    multi_roots.add(root)

    # --------------------------------------------- implicit main + callgraph
    # Main enters through the public surface (and dunders); private
    # helpers inherit whatever roots actually call them via the
    # propagation below -- a worker-only private helper must not be
    # painted with main just for existing.
    for mname in methods:
        if mname in target_only:
            continue
        is_public = not mname.startswith("_") or (
            mname.startswith("__") and mname.endswith("__")
        )
        if is_public:
            units[mname].roots.add(_MAIN)
    for key, unit in units.items():
        # Recursive walk with promoted subtrees pruned (ast.walk cannot
        # prune): a call made only inside a promoted worker def belongs
        # to the worker's unit, not the spawning method's -- otherwise
        # the parent's roots leak into worker-only helpers.
        skip = (
            promoted.get(unit.method, set())
            if key == unit.method
            else set()
        )

        def collect(node: ast.AST, unit: _Unit = unit, skip=skip) -> None:
            if node in skip and node is not unit.node:
                return
            if isinstance(node, ast.Call):
                dotted = _dotted(node.func)
                if (
                    dotted
                    and dotted.startswith("self.")
                    and dotted.count(".") == 1
                ):
                    callee = dotted.split(".", 1)[1]
                    if callee in methods:
                        unit.calls.add(callee)
            for child in ast.iter_child_nodes(node):
                collect(child, unit, skip)

        collect(unit.node)
    changed = True
    while changed:
        changed = False
        for unit in units.values():
            for callee in unit.calls:
                target = units[callee]
                before = len(target.roots)
                target.roots |= unit.roots
                if len(target.roots) != before:
                    changed = True

    # -------------------------------------------------------------- writes
    for key, unit in units.items():
        _collect_writes(unit, promoted.get(unit.method, set())
                        if key == unit.method else set())

    by_attr: Dict[str, List[Tuple[_Unit, int, bool]]] = {}
    for unit in units.values():
        if unit.method == "__init__" and unit.name == "__init__":
            continue  # initialization happens-before every spawn
        for attr, sites in unit.writes.items():
            for line, guarded in sites:
                by_attr.setdefault(attr, []).append((unit, line, guarded))

    for attr, sites in sorted(by_attr.items()):
        roots: Set[str] = set()
        for unit, _line, _guarded in sites:
            roots |= unit.roots
        shared = len(roots) > 1 or bool(roots & multi_roots)
        if not shared:
            continue
        for unit, line, guarded in sites:
            if guarded:
                continue
            findings.append(
                Finding(
                    "threads", "CEP-T01", src.relpath, line,
                    f"unguarded write to self.{attr} shared across thread "
                    f"roots {{{', '.join(sorted(roots))}}} "
                    f"(in {cls.name}.{unit.name})",
                    context=src.context_line(line),
                )
            )
    return findings


def check(files: Sequence[SourceFile], root_dir: str) -> List[Finding]:
    findings: List[Finding] = []
    for src in files:
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(_analyze_class(src, node))
    return findings
