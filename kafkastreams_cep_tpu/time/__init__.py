"""Event-time subsystem: bounded reorder buffer, watermarks, late policy.

The engine's `Event` contract assumes per-partition in-order offsets and
window expiry advances on arrival order -- the SASE in-order stream model
(Agrawal et al., SIGMOD'08). Real multi-source traffic interleaves late
and out-of-order records; this package adds the low-watermark / allowed-
lateness model of the Dataflow paper (Akidau et al., VLDB'15) as a layer
between ingestion and the pack step:

  * `ReorderBuffer` -- per-key bounded binary heap on event time, released
    in event-time order as the watermark advances;
  * watermark generators -- `ArrivalOrderWatermark` (arrival parity, the
    bitwise-pinned default), `BoundedOutOfOrderness`, `MinMergeWatermark`
    (per-source min-merge for fan-in), `IdleTimeout` (stalled sources stop
    holding the merged watermark back);
  * `EventTimeGate` -- the composition the stream processors drive: late
    policy (drop | sideoutput | recompute-none), overflow honoring
    `EngineConfig.on_overflow` (with the `time.reorder_overflow` fault
    point), watermark metrics through the obs registry, and checkpointing
    via state/serde.py.

Host-only by design: nothing here imports jax, so the gate can front the
host runtime, the device runtime and tests alike.
"""
from .gate import EventTimeGate
from .reorder import ReorderBuffer
from .watermarks import (
    ArrivalOrderWatermark,
    BoundedOutOfOrderness,
    IdleTimeout,
    MinMergeWatermark,
    WatermarkGenerator,
    WM_MIN_MS,
)

__all__ = [
    "ArrivalOrderWatermark",
    "BoundedOutOfOrderness",
    "EventTimeGate",
    "IdleTimeout",
    "MinMergeWatermark",
    "ReorderBuffer",
    "WatermarkGenerator",
    "WM_MIN_MS",
]
