"""Automatic runtime routing (ISSUE 18): host first, device on growth.

The reference engine is per-key sequential and fast at K=1
(CEPProcessor.java:111-124): a stream with a handful of keys pays device
batch overhead for nothing, while a high-cardinality stream starves on
the host loop. `runtime="auto"` removes that decision from the caller:

- the query STARTS on the host `CEPProcessor` (the reference-parity
  runtime, including its event-time gate when armed);
- every raw arrival is also appended to a bounded promotion ledger;
- when the observed distinct-key count reaches `promote_after`
  (default 64 -- the same scale DeviceCEPProcessor's low-key warning
  flags from the other side), the router builds a `DeviceCEPProcessor`
  and REPLAYS the ledger through it, then routes everything after to
  the device.

Replay is the promotion-correctness trick: the device rebuilds its
state from the full event history, so it emits every match the history
completes -- including those the host already emitted. The router
absorbs that overlap itself: every host-phase output is recorded as an
occurrence-qualified sequence identity (the same
`streams/emission.py` framing the EmissionGate hashes), and the replay
renumbers deterministically against a fresh counter -- exactly the
renumbering argument crash recovery relies on -- so regenerated
matches drop and only genuinely new ones surface. The sink therefore
sees each match exactly once with the same digests an all-device run
assigns (the acceptance pin), and in-memory consumers never see the
replay at all.

If the ledger would exceed `buffer_max` before the key threshold is
reached, promotion is disabled and the query stays on the host runtime
for its lifetime (high per-key volume means the host loop is handling
it; an unbounded ledger would be a leak). Durability: the host trio's
changelogs cover the host phase; after promotion the engine state is
rebuilt by re-reading the source topics on restore (the ledger is not
checkpointed), so long-lived durable deployments that want device-side
snapshots should pin `runtime="tpu"` explicitly.
"""
from __future__ import annotations

import hashlib
from typing import Any, Dict, List, Optional, Set, Tuple

__all__ = ["AutoRoutingProcessor"]


class AutoRoutingProcessor:
    """Routes one query between the host and device runtimes.

    Presents the keyed-processor surface `Topology` drives
    (`process_keyed`, `flush`, `tick_event_time`, `flush_event_time`)
    and delegates everything else to whichever runtime is live.
    """

    #: Distinct-key threshold at which the device runtime wins.
    PROMOTE_AFTER = 64
    #: Promotion-ledger bound: past this, stay on the host for good.
    BUFFER_MAX = 65536

    def __init__(
        self,
        query_name: str,
        pattern: Any,
        host: Any,
        *,
        schema: Optional[Any] = None,
        registry: Optional[Any] = None,
        promote_after: Optional[int] = None,
        buffer_max: Optional[int] = None,
        device_opts: Optional[Dict[str, Any]] = None,
        autosize: bool = True,
    ) -> None:
        self.query_name = query_name
        self.pattern = pattern
        self.host = host
        self.schema = schema
        self.registry = registry
        self.promote_after = int(
            promote_after if promote_after is not None else self.PROMOTE_AFTER
        )
        self.buffer_max = int(
            buffer_max if buffer_max is not None else self.BUFFER_MAX
        )
        self.device_opts = dict(device_opts or {})
        self.autosize = bool(autosize)
        self.device: Optional[Any] = None
        self.autosizer: Optional[Any] = None
        self._ledger: List[Tuple[Any, Any, int, str, int, int]] = []
        self._keys_seen: set = set()
        self._pinned_host = False
        self._since_tick = 0
        #: occurrence-qualified identities of every host-phase output;
        #: the promotion replay renumbers against a fresh counter and
        #: drops collisions (module docstring). Dropped after promotion.
        self._host_emitted: Set[bytes] = set()
        self._host_occ: Dict[bytes, int] = {}
        from ..obs.registry import default_registry

        metrics = registry if registry is not None else default_registry()
        self._m_promotions = metrics.counter(
            "cep_auto_promotions_total",
            "runtime='auto' host->device promotions (distinct-key "
            "threshold crossed; the promotion ledger replays through "
            "the fresh device engine)",
            labels=("query",),
        ).labels(query=query_name)
        self._m_runtime = metrics.gauge(
            "cep_auto_runtime",
            "Live runtime for a runtime='auto' query (value 1 on the "
            "current one)",
            labels=("query", "runtime"),
        )
        self._m_runtime.labels(query=query_name, runtime="host").set(1)
        self._m_runtime.labels(query=query_name, runtime="tpu").set(0)

    # ------------------------------------------------------------- routing
    @property
    def runtime(self) -> str:
        return "tpu" if self.device is not None else "host"

    @property
    def gate(self) -> Optional[Any]:
        active = self.device if self.device is not None else self.host
        return getattr(active, "gate", None)

    @property
    def engine(self) -> Optional[Any]:
        return None if self.device is None else self.device.engine

    def process_keyed(
        self,
        key: Any,
        value: Any,
        timestamp: int = 0,
        topic: str = "",
        partition: int = 0,
        offset: int = 0,
    ) -> List[Tuple[Any, Any]]:
        if self.device is not None:
            out = self.device.process(
                key, value, timestamp=timestamp, topic=topic,
                partition=partition, offset=offset,
            )
            self._tick(1)
            return out
        if not self._pinned_host and key is not None and value is not None:
            self._ledger.append(
                (key, value, timestamp, topic, partition, offset)
            )
            self._keys_seen.add(key)
            if len(self._ledger) > self.buffer_max:
                # High volume, low cardinality: the host loop is the
                # right runtime; an unbounded ledger would be a leak.
                self._pinned_host = True
                self._ledger = []
        out = self._record_host(
            self.host.process_keyed(
                key, value, timestamp=timestamp, topic=topic,
                partition=partition, offset=offset,
            )
        )
        if (
            not self._pinned_host
            and len(self._keys_seen) >= self.promote_after
        ):
            out = out + self._promote()
        return out

    def _ident(self, key: Any, seq: Any) -> bytes:
        """Base sequence identity of one output, bitwise-equal for the
        host Sequence and the device's replayed copy of the same match
        (both hash the `streams/emission.py` identity frames)."""
        from .emission import identity_prefix, sequence_ident_frames
        from .serde import SinkMatch

        h = hashlib.blake2b(digest_size=16)
        h.update(identity_prefix(self.query_name, key))
        if isinstance(seq, SinkMatch):
            h.update(seq.ident)
        else:
            h.update(sequence_ident_frames(seq))
        return h.digest()

    def _record_host(
        self, out: List[Tuple[Any, Any]]
    ) -> List[Tuple[Any, Any]]:
        if self.device is None and not self._pinned_host:
            for key, seq in out:
                base = self._ident(key, seq)
                n = self._host_occ.get(base, 0)
                self._host_occ[base] = n + 1
                self._host_emitted.add(base + n.to_bytes(8, "little"))
        return list(out)

    def _promote(self) -> List[Tuple[Any, Any]]:
        """Build the device processor and replay the ledger through it.

        The replay regenerates the host phase's matches along with any
        the fuller device batch completes; regenerated ones renumber
        deterministically into the recorded host identities and drop, so
        downstream admission sees each match exactly once with the same
        occurrence numbering an all-device run assigns."""
        from .device_processor import DeviceCEPProcessor

        dev = DeviceCEPProcessor(
            self.query_name,
            self.pattern,
            schema=self.schema,
            registry=self.registry,
            **self.device_opts,
        )
        if self.autosize:
            from ..parallel.drain_sched import CapacityAutosizer

            self.autosizer = CapacityAutosizer(
                dev.engine, registry=self.registry
            )
        replayed: List[Tuple[Any, Any]] = []
        for key, value, timestamp, topic, partition, offset in self._ledger:
            replayed.extend(
                dev.process(
                    key, value, timestamp=timestamp, topic=topic,
                    partition=partition, offset=offset,
                )
            )
        replayed.extend(dev.flush())
        # Renumber the replay from zero (deterministic engine order) and
        # drop everything the host phase already delivered.
        out: List[Tuple[Any, Any]] = []
        replay_occ: Dict[bytes, int] = {}
        for key, seq in replayed:
            base = self._ident(key, seq)
            n = replay_occ.get(base, 0)
            replay_occ[base] = n + 1
            if base + n.to_bytes(8, "little") in self._host_emitted:
                continue
            out.append((key, seq))
        self.device = dev
        self._ledger = []
        self._keys_seen = set()
        self._host_emitted = set()
        self._host_occ = {}
        self._m_promotions.inc()
        self._m_runtime.labels(query=self.query_name, runtime="host").set(0)
        self._m_runtime.labels(query=self.query_name, runtime="tpu").set(1)
        return out

    def _tick(self, n: int) -> None:
        """Batch the autosizer's control ticks to the device flush scale
        (host arithmetic only; never per-record device work)."""
        if self.autosizer is None:
            return
        self._since_tick += n
        batch = max(1, int(getattr(self.device, "batch_size", 64)))
        if self._since_tick >= batch:
            self.autosizer.observe(events=self._since_tick)
            self._since_tick = 0

    # ------------------------------------------------------- passthroughs
    def flush(self) -> List[Tuple[Any, Any]]:
        if self.device is None:
            return []
        out = self.device.flush()
        self._tick(0)
        return out

    def tick_event_time(self, now_ms: int) -> List[Tuple[Any, Any]]:
        active = self.device if self.device is not None else self.host
        fn = getattr(active, "tick_event_time", None)
        return [] if fn is None else self._record_host(fn(now_ms))

    def flush_event_time(self) -> List[Tuple[Any, Any]]:
        active = self.device if self.device is not None else self.host
        fn = getattr(active, "flush_event_time", None)
        return [] if fn is None else self._record_host(fn())

    def take_poisoned(self) -> List[Any]:
        if self.device is None:
            return []
        fn = getattr(self.device, "take_poisoned", None)
        return [] if fn is None else fn()

    def event_time_state(self) -> Dict[str, Any]:
        # Host-phase durability surface (EventTimeStateStore); after
        # promotion the device carries its own gate, and the restore
        # path rebuilds from the source topics (module docstring).
        return self.host.event_time_state()

    def restore_event_time(self, state: Dict[str, Any]) -> None:
        self.host.restore_event_time(state)

    def state(self) -> Dict[str, Any]:
        """JSON-ready routing snapshot (artifacts / health endpoints)."""
        return {
            "runtime": self.runtime,
            "keys_seen": len(self._keys_seen),
            "promote_after": self.promote_after,
            "ledger": len(self._ledger),
            "pinned_host": self._pinned_host,
            "autosizer": (
                None if self.autosizer is None else self.autosizer.state()
            ),
        }
