"""Stacked multi-query engine: one device program serving Q queries.

The equivalence contract: per-query matches from the stacked engine are
identical (content and per-key order) to running each query on its own
BatchedDeviceNFA over the same streams -- the device analog of the
reference's N processor nodes on one topic (CEPStreamImpl.java:80-93).
"""
import random

import pytest

from kafkastreams_cep_tpu import Event, QueryBuilder, compile_pattern
from kafkastreams_cep_tpu.ops.engine import EngineConfig
from kafkastreams_cep_tpu.ops.schema import EventSchema
from kafkastreams_cep_tpu.ops.tables import compile_multi_query, compile_query
from kafkastreams_cep_tpu.parallel import BatchedDeviceNFA, StackedQueryEngine
from kafkastreams_cep_tpu.pattern.expressions import agg, value

LETTER_QUERIES = ["ABC", "BCD", "ACD", "ABD"]


def _letters_pattern(tag: str, seq: str):
    qb = QueryBuilder()
    b = qb.select(f"{tag}-0").where(value() == seq[0])
    for j, ch in enumerate(seq[1:], start=1):
        b = b.then().select(f"{tag}-{j}").where(value() == ch)
    return b.build()


def _streams(rng, keys, n):
    return {
        k: [Event(k, rng.choice("ABCD"), 1000 + i, "t", 0, i) for i in range(n)]
        for k in keys
    }


def test_stacked_equals_independent_engines():
    keys = [f"k{i}" for i in range(6)]
    rng = random.Random(13)
    streams = _streams(rng, keys, 48)
    config = EngineConfig(lanes=32, nodes=1024, matches=512, matches_per_step=16)

    named = [
        (f"q{i}", _letters_pattern(f"q{i}", seq))
        for i, seq in enumerate(LETTER_QUERIES)
    ]
    stacked = StackedQueryEngine(named, keys=keys, config=config)
    got = {k: {} for k in keys}
    for b in range(0, 48, 12):
        chunk = {k: s[b : b + 12] for k, s in streams.items()}
        for k, per_q in stacked.advance(chunk).items():
            for qname, seqs in per_q.items():
                got[k].setdefault(qname, []).extend(seqs)

    for i, seq_letters in enumerate(LETTER_QUERIES):
        solo = BatchedDeviceNFA(
            compile_query(
                compile_pattern(_letters_pattern(f"q{i}", seq_letters)), None
            ),
            keys=keys,
            config=EngineConfig(lanes=16, nodes=1024, matches=512,
                                matches_per_step=16),
        )
        want = {k: [] for k in keys}
        for b in range(0, 48, 12):
            chunk = {k: s[b : b + 12] for k, s in streams.items()}
            for k, seqs in solo.advance(chunk).items():
                want[k].extend(seqs)
        for k in keys:
            assert got[k].get(f"q{i}", []) == want[k], (
                f"query q{i} key {k} diverges from the independent engine"
            )
    assert stacked.stats["lane_drops"] == 0
    assert stacked.stats["match_drops"] == 0


def test_stacked_with_folds_and_windows():
    """Stacked queries with (distinctly named) folds and windows keep
    per-query fold registers isolated in the shared register file."""
    keys = ["ka", "kb"]
    rng = random.Random(3)
    streams = _streams(rng, keys, 40)

    def q_counted(tag):
        return (
            QueryBuilder()
            .select(f"{tag}-first").where(value() == "A")
            .fold(f"{tag}-n", agg(f"{tag}-n", default=0) + 1)
            .then()
            .select(f"{tag}-second").where(
                (value() == "B") & (agg(f"{tag}-n", default=0) <= 2)
            )
            .within(ms=8)
            .build()
        )

    named = [("qx", q_counted("qx")), ("qy", _letters_pattern("qy", "BCD"))]
    stacked = StackedQueryEngine(
        named, keys=keys,
        config=EngineConfig(lanes=32, nodes=512, matches=256,
                            matches_per_step=16),
    )
    got = {k: {} for k in keys}
    for b in range(0, 40, 10):
        chunk = {k: s[b : b + 10] for k, s in streams.items()}
        for k, per_q in stacked.advance(chunk).items():
            for qname, seqs in per_q.items():
                got[k].setdefault(qname, []).extend(seqs)

    for qname, pattern in named:
        solo = BatchedDeviceNFA(
            compile_query(compile_pattern(pattern), None),
            keys=keys,
            config=EngineConfig(lanes=16, nodes=512, matches=256,
                                matches_per_step=16),
        )
        want = {k: [] for k in keys}
        for b in range(0, 40, 10):
            chunk = {k: s[b : b + 10] for k, s in streams.items()}
            for k, seqs in solo.advance(chunk).items():
                want[k].extend(seqs)
        for k in keys:
            assert got[k].get(qname, []) == want[k], f"{qname}/{k} diverges"


def test_stacked_agg_name_collision_raises():
    def q_with_fold(tag):
        return (
            QueryBuilder()
            .select(f"{tag}-a").where(value() == "A")
            .fold("shared", agg("shared", default=0) + 1)
            .then()
            .select(f"{tag}-b").where(value() == "B")
            .build()
        )

    with pytest.raises(ValueError, match="shared"):
        compile_multi_query(
            [("q0", q_with_fold("q0")), ("q1", q_with_fold("q1"))]
        )


def test_stacked_schema_must_be_shared():
    q = _letters_pattern("q0", "ABC")
    cq = compile_query(compile_pattern(q), EventSchema())
    with pytest.raises(ValueError, match="shared schema"):
        compile_multi_query([("q0", cq)], schema=EventSchema())


def test_stacked_pallas_interpret_parity():
    """The stacked table set runs through the fused kernel (interpret mode
    on CPU) with the same outputs as the XLA step."""
    keys = ["k0", "k1"]
    rng = random.Random(7)
    streams = _streams(rng, keys, 24)
    named = [
        ("qa", _letters_pattern("qa", "ABC")),
        ("qb", _letters_pattern("qb", "BCD")),
    ]
    config = EngineConfig(lanes=16, nodes=256, matches=128, matches_per_step=8)
    outs = []
    for engine in ("xla", "pallas_interpret"):
        eng = StackedQueryEngine(named, keys=keys, config=config, engine=engine)
        got = {}
        for b in range(0, 24, 8):
            chunk = {k: s[b : b + 8] for k, s in streams.items()}
            for k, per_q in eng.advance(chunk).items():
                for qname, seqs in per_q.items():
                    got.setdefault((k, qname), []).extend(seqs)
        outs.append(got)
    assert outs[0] == outs[1]
