"""Device NFA engine: the jit-compiled, lane-vectorized SASE transition kernel.

This is the TPU-native replacement for the reference's per-record, run-at-a-
time evaluator (reference: core/.../cep/nfa/NFA.java:134-397). The host
oracle (nfa/nfa.py) defines the conformance contract; this module implements
the *same transition relation* as a data-parallel program:

  * live runs live in a fixed-capacity structure-of-arrays lane table
    (stage id, synthesized-epsilon target, Dewey digits as fixed-width i32
    lanes, run id, last-buffer-node index, start timestamp, branching/ignored
    flags) -- the device form of ComputationStage.java:30-91;
  * the recursive epsilon descent (NFA.java:222-237) is unrolled to the
    statically-known chain depth (CompiledQuery.max_depth): each level
    evaluates one stage's edges for every lane at once;
  * predicates are evaluated as vectorized masks: stateless predicates for
    the whole micro-batch up front ([T, P] in one fused pass -- the
    replacement for the per-edge virtual call, NFA.java:371-384), stateful
    ones per (lane, event) against the fold-register file;
  * one event-step emits up to 4*max_depth output slots per lane in exactly
    the oracle's DFS order (consume/ignore emissions level-down, then
    branch-clone and begin-re-add level-up, NFA.java:238-338) and compacts
    them into the new lane table with a prefix-sum scatter, so queue order,
    run counts and match order match the oracle;
  * the shared versioned buffer (SharedVersionedBufferStoreImpl.java) becomes
    an append-only node pool (event idx, stage name id, predecessor index).
    Because every run tracks its last node *by index*, the Dewey-compatible
    pointer routing of the reference's merged store is unnecessary: each
    lineage owns its chain, branches share prefixes by construction, and
    match extraction is a host-side (or batched-gather) predecessor walk.
    Refcount GC is replaced by mark-sweep compaction at batch boundaries.

Known, documented divergences from the oracle:

  * fold registers are stored per lane with copy-on-emit; two live lanes
    sharing one run id (possible after PROCEED+TAKE branching) receive their
    own lane's updates rather than a shared per-run cell, and predicates read
    the event-start snapshot rather than seeing earlier queue items' folds
    within the same event. This divergence is ENGINE-INTERNAL and corrected
    by replay: the `seq_collisions` counter soundly detects every event that
    could diverge (a consuming lane sharing its run id with any other live
    lane; seq_collisions == 0 guarantees oracle-exact engine output), and
    the drivers' default exact-replay path (ops/replay.py) re-runs the
    affected key's interval through the host oracle and resyncs, so the
    *processor-visible* output is oracle-exact even when the counter fires
    (tests/test_differential.py::test_seq_collision_detector_soundness,
    ::test_seq_collision_divergence_recovered_by_replay; the raw engine
    gap is pinned by ::test_seq_collision_divergence_is_real_without_replay);
  * buffer-node refcounts are not maintained on device (GC is mark-sweep),
    so the reference's refcount quirks (MatchedEvent.java:66-68) have no
    analog here.

The scan is vmap-able over a leading key axis (parallel/key_shard.py) and
shards over a device mesh along that axis with `jax.sharding`.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .tables import (
    OP_BEGIN,
    OP_NONE,
    OP_TAKE,
    PR_NONE,
    PR_PROCEED,
    PR_SKIP,
    CompiledQuery,
    DeviceEnv,
)

_I32_MAX = np.int64(2**31 - 1)
#: `pend_min` sentinel: no pending match (any real node id is smaller).
_PEND_MIN_NONE = np.int32(2**31 - 1)
#: Watermark-column fill when no watermark is threaded (ISSUE 10): the
#: expiry clock is max(event ts, watermark), so this floor makes the clock
#: bitwise-equal to the event timestamp -- today's arrival-order expiry.
WM_NONE = np.int32(-(2**31))

#: The observable per-key state counters every stats pull reduces (the
#: `stats` / `shard_stats` / replay-handoff surfaces and the registry's
#: cep_engine_state_counter gauges all iterate this one tuple -- add new
#: engine counters here, not at the call sites). "runs" is state too but
#: reported per key, not as a counter total.
STATE_COUNTER_KEYS = (
    "n_events", "n_branches", "n_expired",
    "lane_drops", "node_drops", "match_drops", "seq_collisions",
)

#: The silent-loss counters the overflow policy (EngineConfig.on_overflow)
#: watches at drain boundaries.
DROP_COUNTER_KEYS = ("lane_drops", "node_drops", "match_drops")

# Typed escalation for on_overflow="raise"/"block"; defined in the
# host-only faults package so streams-layer callers need not import jax.
from ..faults.injection import CEPOverflowError  # noqa: E402,F401


@dataclass(frozen=True)
class EngineConfig:
    """Capacity knobs (SURVEY.md section 5.6: typed config, not a flag framework)."""

    lanes: int = 64          # max simultaneous runs per key (run-lane pool)
    nodes: int = 8192        # compacted node-pool region per key (post-GC)
    matches: int = 1024      # pending-match id buffer per key (between drains)
    #: per-(key, event-step) cap on emitted matches; one event can complete
    #: several runs at once (branching multi-match), but rarely more than a
    #: handful -- overflow is counted in match_drops.
    matches_per_step: int = 16
    #: per-(key, event-step) cap on buffer-node appends (consumed-event
    #: writes). 0 = uncapped (lanes * max_depth slots per step). One event
    #: consumes at most once per consuming lane; capping shrinks the
    #: time-indexed window the post-GC sweeps. Overflow -> node_drops.
    nodes_per_step: int = 0
    digits: int = 0          # Dewey digit width; 0 = auto (n_stages + 2)
    #: Reference parity (False): synthesized epsilon stages carry no window
    #: (Stage.java:247-251,42), so consumed runs are never expired and
    #: skip-till-any run populations grow without bound. True = epsilon runs
    #: inherit their descent target's window and any run with a consumed
    #: event (ts >= 0) expires -- the bounded-memory mode (matches the host
    #: oracle's NFA(strict_windows=True)).
    strict_windows: bool = False
    #: Pin pending matches' chains by ID INTERVAL instead of per-chain
    #: frontier walks. The GC's stable sweep keeps node ids
    #: creation-ordered, and a chain's root is its oldest node, so
    #: everything a pending match can reference lies in
    #: [min pending chain-root id, end) -- one compare replaces the
    #: page-root walks (the dominant post-pass term at production shapes,
    #: PERF.md v7). The trade: ALL nodes younger than the oldest pending
    #: root stay resident until a drain, so this suits sparse-match
    #: workloads (puts-per-drain-interval << nodes); put-heavy queries
    #: (e.g. one_or_more matching most events) should keep the default
    #: precise walks or size `nodes` for the interval's put volume.
    #: node_drops stays the loud overflow signal either way.
    pin_interval: bool = False
    #: GC group size G: the full mark/sweep + compaction folds the
    #: accumulated time-indexed append window back into the region only on
    #: every G-th advance, dividing the post pass's page-root/lane walks
    #: and the sweep argsort by G (the pend append stays per-advance, so
    #: capacity guards keep observing true match counts). The engine
    #: state's `gc_phase` scalar tracks the group's step offset; drains,
    #: checkpoints and region-pressure triggers force an early group
    #: flush, so G only changes WHEN garbage is collected, never what.
    #: The trade: up to G advances' window nodes stay resident between
    #: flushes, so size `nodes` for the group's retention (PERF.md v9
    #: "GC groups"). G=1 is the classic every-advance GC.
    gc_group: int = 1
    #: Capacity-overflow policy (ISSUE 6). The reference never drops a
    #: match (SharedVersionedBufferStoreImpl.java:101-126); the device
    #: engine's fixed pools can, and this knob decides how loudly:
    #:   "drop"  -- today's semantics, but every drop delta observed at a
    #:              drain boundary lands in the per-instance
    #:              `cep_overflow_dropped_total{counter}` counters;
    #:   "raise" -- a drop delta (or a replay-ledger overflow /
    #:              fold-divergence degradation) raises CEPOverflowError;
    #:   "block" -- backpressure: before an advance whose worst case could
    #:              overflow the pend ring (or while region pressure
    #:              persists), force a synchronous early drain + group
    #:              flush and retry admission (bounded by `block_retries`,
    #:              linear backoff), surfaced via
    #:              `cep_overflow_backpressure_total`; residual drops
    #:              escalate like "raise".
    on_overflow: str = "drop"
    #: Bounded admission retries for on_overflow="block".
    block_retries: int = 4
    #: Linear backoff step between blocked-admission retries (seconds).
    block_backoff_s: float = 0.0
    #: Event-time subsystem (ISSUE 10, kafkastreams_cep_tpu/time/): per-key
    #: reorder-buffer capacity ahead of the pack step. 0 disables the
    #: event-time gate entirely (today's arrival-order semantics); > 0 arms
    #: a bounded binary-heap buffer that releases records in event-time
    #: order as the watermark advances. Overflow honors `on_overflow`
    #: (drop = lose the incoming record loudly, raise = CEPOverflowError,
    #: block = forced early release -- no loss, later stragglers go late).
    reorder_capacity: int = 0
    #: Bounded-out-of-orderness lateness (ms): the default watermark
    #: generator trails the max observed event time by this bound, so any
    #: record no more than `lateness_ms` behind the stream head reorders
    #: cleanly; older records are late (see `late_policy`).
    lateness_ms: int = 0
    #: What happens to records older than the watermark (the Dataflow
    #: late-data triad, Akidau et al. VLDB'15):
    #:   "drop"           -- discard, counted in cep_late_dropped_total;
    #:   "sideoutput"     -- divert to the gate's side output
    #:                       (EventTimeGate.take_late), never the engine;
    #:   "recompute-none" -- admit downstream as-is (best effort, no
    #:                       retraction/recompute of already-expired
    #:                       windows), counted in cep_late_admitted_total.
    late_policy: str = "drop"

    def __post_init__(self) -> None:
        if self.on_overflow not in ("drop", "raise", "block"):
            raise ValueError(
                f"on_overflow must be drop|raise|block, got {self.on_overflow!r}"
            )
        if self.late_policy not in ("drop", "sideoutput", "recompute-none"):
            raise ValueError(
                "late_policy must be drop|sideoutput|recompute-none, got "
                f"{self.late_policy!r}"
            )
        if self.reorder_capacity < 0:
            raise ValueError(
                f"reorder_capacity must be >= 0, got {self.reorder_capacity}"
            )

    def dewey_width(self, query: CompiledQuery) -> int:
        return self.digits if self.digits > 0 else query.n_stages + 2


def init_state(query: CompiledQuery, config: EngineConfig) -> Dict[str, jnp.ndarray]:
    """Initial device state: one begin run, version `1`, run id 1.

    Mirrors Stages.initialComputationStage (Stages.java:53-60). The node
    pool and pending-match buffer live outside the scan carry (init_pool):
    the per-step transition writes nodes as time-indexed scan *outputs*, so
    the multi-megabyte pools are never copied per event step and -- crucial
    for the vmapped multi-key path -- never updated through a per-key
    dynamic offset, which XLA lowers to a serialized scatter inside scans.
    """
    R = config.lanes
    D = config.dewey_width(query)
    A = query.n_aggs
    begins = query.begin_stages if query.begin_stages else [query.begin_stage]
    if len(begins) > R:
        raise ValueError(
            f"{len(begins)} stacked queries exceed the {R}-lane pool"
        )

    ver = np.zeros((R, D), np.int32)
    for qi in range(len(begins)):
        ver[qi, 0] = 1
    state = {
        # -- run lane table (SoA ComputationStage) ---------------------------
        "active": np.zeros(R, bool),
        "src": np.zeros(R, np.int32),          # stage id (identity of the run's stage)
        "eps": np.full(R, -1, np.int32),       # synthesized-epsilon PROCEED target
        "ver": ver,                            # Dewey digits (zero-padded)
        "vlen": np.zeros(R, np.int32),         # digit count
        "seq": np.zeros(R, np.int32),          # run id (NFA.java runs counter)
        "node": np.full(R, -1, np.int32),      # last matched event's buffer node
        "root": np.full(R, -1, np.int32),      # FIRST node of the run's chain
        #                                        (invariant: root >= 0 iff
        #                                        node >= 0; chains share roots
        #                                        across branch clones; feeds
        #                                        interval pinning's pend_min)
        "ts": np.full(R, -1, np.int32),        # start timestamp (rebased ms)
        "branching": np.zeros(R, bool),
        "ignored": np.zeros(R, bool),
        "regs": np.zeros((R, A), np.float32),  # fold registers (per lane)
        "regs_set": np.zeros((R, A), bool),
        "runs": np.asarray(len(begins), np.int32),  # global run counter
        #: group-phase scalar (EngineConfig.gc_group): the number of event
        #: steps already written into the current group's time-indexed
        #: append window. The advance offsets fresh node ids by
        #: `gc_phase * nodes_per_step`-per-step past `nodes`; the flush
        #: (full mark/sweep) resets it to 0. Always 0 at drain/checkpoint
        #: boundaries (early group flush).
        "gc_phase": np.asarray(0, np.int32),
        # -- observability counters (SURVEY.md section 5.1/5.5) --------------
        "n_events": np.asarray(0, np.int32),
        "n_branches": np.asarray(0, np.int32),
        "n_expired": np.asarray(0, np.int32),
        "lane_drops": np.asarray(0, np.int32),
        "node_drops": np.asarray(0, np.int32),
        "match_drops": np.asarray(0, np.int32),
        "seq_collisions": np.asarray(0, np.int32),
    }
    # One begin lane per (stacked) query; run ids 1..Q so the fold-
    # divergence detector never sees a cross-query collision.
    for qi, b in enumerate(begins):
        state["active"][qi] = True
        state["src"][qi] = b
        state["vlen"][qi] = 1
        state["seq"][qi] = qi + 1
    return {k: jnp.asarray(v) for k, v in state.items()}


def init_pool(query: CompiledQuery, config: EngineConfig) -> Dict[str, jnp.ndarray]:
    """The GC-owned node-pool region + pending-match buffer (per key).

    Node ids < config.nodes index this compacted region; ids >= config.nodes
    index the current advance's time-indexed window (the scan's stacked
    outputs) until the post-advance GC folds the window back into the
    region.

    `pend` is a *dense* pending-match buffer: each advance scatter-appends
    its real match ids at `pend_pos`, the per-key occupancy count (== the
    true pending-match count; no hole pages -- see build_pend_append).
    Entries may later be nulled to -1 by a GC under region overflow (dead
    chains, counted in node_drops), which is the only source of holes.
    `pinned` marks region nodes reachable from already-appended matches so
    the per-advance GC mark walk only has to traverse the *new* page's
    chains (frontier O(lanes + page), independent of the ring size).
    """
    B = config.nodes
    M = config.matches
    return {
        "node_event": jnp.full(B, -1, jnp.int32),
        "node_name": jnp.full(B, -1, jnp.int32),
        "node_pred": jnp.full(B, -1, jnp.int32),
        "node_count": jnp.asarray(0, jnp.int32),
        "pend": jnp.full(M, -1, jnp.int32),
        "pend_count": jnp.asarray(0, jnp.int32),
        "pend_pos": jnp.asarray(0, jnp.int32),
        "pinned": jnp.zeros(B, bool),
        #: min chain-root id over pending matches (interval pinning's
        #: lower bound; _PEND_MIN_NONE when nothing is pending).
        "pend_min": jnp.asarray(_PEND_MIN_NONE, jnp.int32),
    }


def _excl_cumsum(mask: jnp.ndarray) -> jnp.ndarray:
    c = jnp.cumsum(mask.astype(jnp.int32))
    return c - mask.astype(jnp.int32)




def _nth_set_select(mask: jnp.ndarray, n_out: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Indices of the first `n_out` set bits of a [R, S] mask in row-major
    order, without a sort.

    TPU sorts lower to custom-calls whose operands get staged through
    scratch space with multi-ms layout-conversion copies per scan step
    (profiled: the former stable-argsort compaction dominated step time).
    This two-level selection is pure fused arithmetic: per-row inclusive
    cumsums locate the j-th set bit by (a) a row-offset comparison matrix
    [n_out, R] and (b) an equality hit on the gathered row [n_out, S].

    Returns (flat row-major indices [n_out] int32, valid [n_out] bool).
    vmap-safe (no data-dependent shapes)."""
    Rr, S = mask.shape
    cum = jnp.cumsum(mask.astype(jnp.int32), axis=1)     # [R, S] inclusive
    c = cum[:, -1]                                       # per-row set count
    off = jnp.cumsum(c) - c                              # exclusive row offsets
    total = off[-1] + c[-1]
    j = jnp.arange(n_out, dtype=jnp.int32)
    # Last row whose offset <= j; empty rows share the offset of their
    # successor, so the count lands on the row actually holding bit j.
    rj = (jnp.sum(off[None, :] <= j[:, None], axis=1) - 1).astype(jnp.int32)
    p = j - off[rj]                                      # rank within row
    cr = cum[rj]                                         # [n_out, S]
    mr = mask[rj]
    hit = mr & (cr == (p[:, None] + 1))                  # exactly one per row
    s = jnp.argmax(hit, axis=1).astype(jnp.int32)
    ok = j < total
    return jnp.where(ok, rj * S + s, 0), ok


def build_step(
    query: CompiledQuery, config: EngineConfig, debug: bool = False
) -> Callable[..., Tuple[Dict[str, jnp.ndarray], Any]]:
    """Build the one-event transition function (a `lax.scan` body).

    The returned `step(state, x, t)` consumes one packed event
    (x = column scalars + precomputed stateless predicate row + global event
    index + validity flag; t = the event's step index within the advance)
    and returns (next state, ys) where ys carries the step's buffer-node
    writes in a fixed time-indexed layout -- node id = nodes + t*R*L + slot
    -- plus up to `matches_per_step` emitted match ids. Pools stay out of
    the carry so the scan never copies them and never needs a per-key
    dynamic-offset update (a serialized scatter on TPU). All shapes static.
    """
    R = config.lanes
    D = config.dewey_width(query)
    A = query.n_aggs
    B = config.nodes
    M_STEP = config.matches_per_step
    L = query.max_depth
    P = query.n_preds
    # 3 slots per level: consume and ignore emissions are mutually exclusive
    # per (lane, level) -- ignore_emit = ig_m & ~branch_m and branch_m is set
    # whenever both fire (NFA.java:392-397) -- so they share one slot; the
    # upward clone and begin-re-add slots can both fire for a begin lane and
    # stay separate.
    SLOTS = 3 * L
    P_CAP = config.nodes_per_step if config.nodes_per_step > 0 else R * L

    # Stage tables as HOST numpy constants: every per-lane lookup goes
    # through a one-hot contraction against the lane's stage id instead of a
    # dynamic gather. TPU lowers gather-by-computed-index into multi-pass
    # fusions over padded minor dims (profiled at ~0.8 ms per gather per
    # step); the one-hot forms fuse into neighboring elementwise work.
    n_consume_op = np.asarray(query.consume_op)
    n_consume_pred = np.asarray(query.consume_pred)
    n_consume_target = np.asarray(query.consume_target)
    n_ignore_pred = np.asarray(query.ignore_pred)
    n_proceed_kind = np.asarray(query.proceed_kind)
    n_proceed_pred = np.asarray(query.proceed_pred)
    n_proceed_target = np.asarray(query.proceed_target)
    # i64 window clamped into i32: rebased timestamps are i32, so a clamped
    # huge window compares identically to "no expiry".
    n_window = np.where(
        query.window_ms < 0, -1, np.minimum(query.window_ms, _I32_MAX - 1)
    ).astype(np.int32)
    n_name_id = np.asarray(query.name_id)
    n_pure_name = np.asarray(query.pure_name_id)
    n_is_begin = np.asarray(query.is_begin)
    n_is_final = np.asarray(query.is_final)
    n_is_fwd = np.asarray(query.is_fwd)
    n_fwd_final = np.asarray(query.fwd_final)
    N_ST = len(n_consume_op)
    # Static table compositions (evaluated once at trace time).
    n_pure_of_ptgt = n_pure_name[n_proceed_target.clip(0)]
    n_isfin_of_ctgt = n_is_final[n_consume_target.clip(0)] & (n_consume_target >= 0)

    ar_st = jnp.arange(N_ST, dtype=jnp.int32)

    def onehot(ids: jnp.ndarray) -> jnp.ndarray:
        """[R] stage ids -> [R, N_ST] one-hot (all-false for id -1)."""
        return ids[:, None] == ar_st[None, :]

    def lut_i(oh: jnp.ndarray, table: np.ndarray) -> jnp.ndarray:
        return jnp.sum(
            jnp.where(oh, jnp.asarray(table, jnp.int32)[None, :], 0), axis=1
        ).astype(jnp.int32)

    def lut_b(oh: jnp.ndarray, table: np.ndarray) -> jnp.ndarray:
        return jnp.any(oh & jnp.asarray(table, bool)[None, :], axis=1)

    stateful = [bool(f) for f in query.pred_stateful]

    # Flattened fold list [(stage, slot, fn)] preserving per-stage order
    # (evaluateAggregates iterates a stage's folds sequentially,
    # NFA.java:362-369).
    flat_folds: List[Tuple[int, int, Callable]] = []
    for stage_i, stage_folds in enumerate(query.folds):
        for slot, fn in stage_folds:
            flat_folds.append((stage_i, slot, fn))

    def add_run(ver: jnp.ndarray, vlen: jnp.ndarray, off: jnp.ndarray) -> jnp.ndarray:
        """DeweyVersion.addRun: +1 at digit (len - off) (DeweyVersion.java:58-67)."""
        idx = vlen - off
        onehot = (jnp.arange(D)[None, :] == idx[:, None]).astype(jnp.int32)
        return ver + onehot

    def step(state: Dict[str, jnp.ndarray], x: Dict[str, jnp.ndarray], t: jnp.ndarray):
        ev_ts = x["ts"]
        # Expiry clock (ISSUE 10): window expiry sweeps off event time as
        # known by the watermark, not arrival order. Callers that thread no
        # "wm" column (or fill it with WM_NONE) get max(ts, WM_NONE) == ts
        # -- bitwise-identical to the historical arrival-order expiry. The
        # event-time gate threads each release's monotone per-key clock:
        # on its sorted release stream that equals the record's own
        # timestamp (oracle-exact expiry), and it exceeds ts exactly where
        # it must -- late admissions (recompute-none) and idle-advanced
        # watermarks -- so the clock never rewinds and can expire runs
        # whose window provably closed while no record carried a fresher
        # timestamp.
        ev_clk = jnp.maximum(ev_ts, x["wm"]) if "wm" in x else ev_ts
        gidx = x["gidx"]

        active = state["active"]
        src = state["src"]
        eps = state["eps"]
        lane_node = state["node"]
        lane_root = state["root"]
        lane_ts = state["ts"]
        lane_seq = state["seq"]
        regs_in = state["regs"]
        regs_set_in = state["regs_set"]

        # -- predicate mask matrix [R, P] ------------------------------------
        # Stateless rows were evaluated for the whole batch up front; stateful
        # predicates read the event-start register snapshot (all of a lane's
        # predicate evaluations precede all of its folds in the oracle's DFS).
        env = DeviceEnv(x, regs_in, regs_set_in, query.agg_slots, query.agg_defaults)
        cols = []
        for p in range(max(P, 1)):
            if p < P and stateful[p]:
                v = query.predicates[p](env)
            elif p < P:
                v = x["spred"][p]
            else:
                v = jnp.asarray(False)
            cols.append(jnp.broadcast_to(jnp.asarray(v, bool), (R,)))
        pred_vals = jnp.stack(cols, axis=1)

        def lut_pred(oh: jnp.ndarray, pid_table: np.ndarray) -> jnp.ndarray:
            """Per-lane predicate mask for a stage->pid table: static column
            permutation of pred_vals + one-hot contraction (no gather)."""
            cols_by_stage = pred_vals[:, pid_table.clip(0)]  # [R, N_ST], static
            valid = jnp.asarray(pid_table >= 0)[None, :]
            return jnp.any(oh & cols_by_stage & valid, axis=1)

        # -- window expiry (NFA.java:183-184; begin states never expire, and
        # synthesized epsilon stages carry no window, Stage.java:247-251;
        # strict_windows inherits the target's window instead -- see
        # EngineConfig.strict_windows) -----------------------------------
        oh_src = onehot(src)
        oh_eps = onehot(eps)  # all-false rows where eps == -1
        root_begin = lut_b(oh_src, n_is_begin)
        w_src = lut_i(oh_src, n_window)
        if config.strict_windows:
            w_eps = lut_i(oh_eps, n_window)
            w_eps = jnp.where(w_eps >= 0, w_eps, w_src)
            eff_window = jnp.where(eps >= 0, w_eps, w_src)
            expired = (
                active & (lane_ts >= 0) & (eff_window >= 0)
                & ((ev_clk - lane_ts) > eff_window)
            )
        else:
            eff_window = jnp.where(eps >= 0, -1, w_src)
            expired = (
                active & ~root_begin & (eff_window >= 0)
                & ((ev_clk - lane_ts) > eff_window)
            )
        active = active & ~expired

        root_fwd = (eps >= 0) | lut_b(oh_src, n_is_fwd)
        start_ts = jnp.where(root_begin, ev_ts, lane_ts)
        # Queue-item match flag for slots that keep the state's (src, eps)
        # identity (ignore / branch-root-copy / begin-root re-add slots).
        state_match = ((eps >= 0) & lut_b(oh_eps, n_is_final)) | (
            (eps < 0) & lut_b(oh_src, n_fwd_final)
        )

        # ==== downward pass: unrolled epsilon descent =======================
        alive = active
        cs = src
        is_eps = eps >= 0
        ceps = eps
        ver = state["ver"]
        vlen = state["vlen"]
        br = state["branching"]
        ig = state["ignored"]
        ps = jnp.full(R, -1, jnp.int32)

        levels: List[Dict[str, jnp.ndarray]] = []
        for _l in range(L):
            oh = oh_src if _l == 0 else onehot(cs)
            c_op = jnp.where(is_eps, OP_NONE, lut_i(oh, n_consume_op))
            c_m = (
                alive & ~is_eps & (c_op != OP_NONE)
                & lut_pred(oh, n_consume_pred)
            )
            take_m = c_m & (c_op == OP_TAKE)
            begin_m = c_m & (c_op == OP_BEGIN)
            ig_m = alive & ~is_eps & lut_pred(oh, n_ignore_pred)
            pk = jnp.where(is_eps, PR_PROCEED, lut_i(oh, n_proceed_kind))
            ptgt = jnp.where(is_eps, ceps, lut_i(oh, n_proceed_target))
            p_m = alive & (pk != PR_NONE) & (is_eps | lut_pred(oh, n_proceed_pred))
            # Branching combos (NFA.java:392-397): PROCEED+TAKE, IGNORE+TAKE,
            # IGNORE+BEGIN, IGNORE+PROCEED (SKIP_PROCEED does not count).
            p_strict = p_m & (pk == PR_PROCEED)
            branch_m = (p_strict & take_m) | (ig_m & (c_m | p_strict))

            ptgt_c = ptgt.clip(0)
            # pure_name[ptgt]: statically composed for the table path; the
            # level-0 epsilon path reads through the eps one-hot instead.
            pure_tgt = lut_i(oh, n_pure_of_ptgt)
            if _l == 0:
                pure_tgt = jnp.where(is_eps, lut_i(oh_eps, n_pure_name), pure_tgt)
            fwd_next = (
                p_m
                & (pure_tgt != lut_i(oh, n_pure_name))
                & ~br
                & ~ig
            )

            levels.append(
                dict(
                    alive=alive, cs=cs, is_eps=is_eps, ver=ver, vlen=vlen,
                    br=br, ig=ig, ps=ps, c_m=c_m, take_m=take_m,
                    begin_m=begin_m, ig_m=ig_m, p_m=p_m, pk=pk, ptgt=ptgt_c,
                    branch_m=branch_m, oh=oh,
                )
            )

            # Descend (PROCEED/SKIP_PROCEED, NFA.java:222-237): extend the
            # version when genuinely crossing stage names with clean flags;
            # SKIP_PROCEED keeps the previous stage (NFA.java:232-236).
            vlen = jnp.where(fwd_next, vlen + 1, vlen)
            br = jnp.where(fwd_next, False, br)
            ig = jnp.where(fwd_next, False, ig)
            ps = jnp.where(pk == PR_SKIP, ps, cs).astype(jnp.int32)
            alive = p_m
            cs = ptgt_c
            is_eps = jnp.zeros(R, bool)
            ceps = jnp.full(R, -1, jnp.int32)

        # ==== fold-register chain (deepest level first, NFA.java:319-321) ===
        def apply_folds(v: Dict[str, jnp.ndarray], regs, regs_set):
            for stage_i, slot, fn in flat_folds:
                mask = v["c_m"] & (v["cs"] == stage_i)
                fenv = DeviceEnv(x, regs, regs_set, query.agg_slots, query.agg_defaults)
                val = jnp.broadcast_to(
                    jnp.asarray(fn(fenv), jnp.float32), (R,)
                )
                regs = regs.at[:, slot].set(jnp.where(mask, val, regs[:, slot]))
                regs_set = regs_set.at[:, slot].set(regs_set[:, slot] | mask)
            return regs, regs_set

        cur_regs, cur_set = regs_in, regs_set_in
        clone_regs: List[Tuple[jnp.ndarray, jnp.ndarray]] = [None] * L  # type: ignore
        for l in reversed(range(L)):
            clone_regs[l] = (cur_regs, cur_set)  # pre-this-level snapshot for clones
            if flat_folds:
                cur_regs, cur_set = apply_folds(levels[l], cur_regs, cur_set)
        final_regs, final_set = cur_regs, cur_set

        # Fold-divergence detector: a consuming lane whose run id is shared
        # with ANY other live lane diverges the per-lane register copies
        # from the reference's shared per-run cell (AggregatesStoreImpl
        # .java:55-75) -- whether or not the sibling consumes this event
        # too: a one-sided fold write leaves the sibling's copy stale.
        # (Same-run pairs CREATED this event are exact: all non-clone
        # emissions of one source lane carry the same post-fold registers,
        # which is the oracle's cell value.) The counter keys the
        # exact-replay path (ops/replay.py); without folds the registers
        # never change, divergence is impossible, and it stays 0.
        if flat_folds:
            consuming = jnp.zeros(R, bool)
            for l in range(L):
                consuming = consuming | levels[l]["c_m"]
            idx = jnp.arange(R)
            pair = (
                (lane_seq[:, None] == lane_seq[None, :])
                & consuming[:, None]
                & active[None, :]
                & (idx[:, None] != idx[None, :])
            )
            collide = jnp.any(pair)
        else:
            collide = jnp.zeros((), bool)

        # ==== buffer puts (one per consumed level, NFA.java:238-271) ========
        # Time-indexed window layout: step t's appends live in window slots
        # [t*P_CAP, (t+1)*P_CAP) -- node id = B + t*P_CAP + rank -- emitted
        # as this step's scan output. No allocation counter, no scatter, no
        # carry traffic; empty slots carry event -1 and are swept by the
        # post-advance GC. With P_CAP < R*L one stable argsort compacts the
        # consumed slots to the front; overflow is counted in node_drops.
        put_flat = jnp.stack([v["c_m"] for v in levels], axis=1).reshape(-1)  # [R*L]
        name_mat = jnp.stack(
            [lut_i(v["oh"], n_name_id) for v in levels], axis=1
        )  # [R, L]
        v_event = jnp.where(put_flat, gidx, -1).astype(jnp.int32)
        v_name = jnp.where(put_flat, name_mat.reshape(-1), -1)
        v_pred = jnp.where(put_flat, jnp.repeat(lane_node, L), -1)
        base = B + t * P_CAP
        if P_CAP >= R * L:
            put_idx = (base + jnp.arange(R * L, dtype=jnp.int32)).reshape(R, L)
            w_event, w_name, w_pred = v_event, v_name, v_pred
            step_node_drops = jnp.asarray(0, jnp.int32)
        else:
            rank = _excl_cumsum(put_flat)
            n_put = jnp.sum(put_flat).astype(jnp.int32)
            put_ok = put_flat & (rank < P_CAP)
            put_idx = jnp.where(put_ok, base + rank, -1).reshape(R, L)
            psel, pok = _nth_set_select(put_flat.reshape(R, L), P_CAP)
            w_event = jnp.where(pok, v_event[psel], -1)
            w_name = jnp.where(pok, v_name[psel], -1)
            w_pred = jnp.where(pok, v_pred[psel], -1)
            step_node_drops = jnp.maximum(n_put - P_CAP, 0).astype(jnp.int32)

        # ==== upward pass: clones / begin-re-adds (NFA.java:289-338) ========
        desc_any = jnp.zeros(R, bool)
        up: List[Optional[Dict[str, jnp.ndarray]]] = [None] * L
        for l in reversed(range(L)):
            v = levels[l]
            ignore_emit = v["ig_m"] & ~v["branch_m"]
            clone_m = v["branch_m"] & v["c_m"]
            rootcopy_m = v["branch_m"] & ~v["c_m"] & ~desc_any
            readd_cond = root_begin & ~root_fwd & v["alive"]
            readd_fresh = readd_cond & v["c_m"]
            readd_root = readd_cond & ~v["c_m"]
            ns_before = v["c_m"] | ignore_emit | desc_any | clone_m | rootcopy_m
            # Begin re-add version: bare when nothing else was emitted at this
            # level, else addRun (NFA.java:323-331).
            readd_ver = jnp.where(
                (readd_fresh & ns_before)[:, None],
                add_run(v["ver"], v["vlen"], jnp.ones(R, jnp.int32)),
                v["ver"],
            )
            up[l] = dict(
                ignore_emit=ignore_emit, clone_m=clone_m, rootcopy_m=rootcopy_m,
                readd_fresh=readd_fresh, readd_root=readd_root, readd_ver=readd_ver,
            )
            desc_any = ns_before | readd_fresh | readd_root

        # ==== output slot table in oracle DFS order =========================
        # Downward: consume emit, ignore emit per level; upward: clone (or
        # branch-root-re-add) then begin-re-add per level, deepest first.
        zero_i = jnp.zeros(R, jnp.int32)
        false_b = jnp.zeros(R, bool)

        slot_occ, slot_src, slot_eps = [], [], []
        slot_ver, slot_vlen, slot_seq = [], [], []
        slot_node, slot_ts, slot_br, slot_ig = [], [], [], []
        slot_newseq = []       # allocates a fresh run id
        slot_regs, slot_regs_set = [], []
        slot_match = []        # forwarding-to-final flag per slot

        for l in range(L):
            v = levels[l]
            # Merged downward slot: consume emission (TAKE -> epsilon(self,
            # self); BEGIN -> epsilon(self, target), NFA.java:238-271) or
            # ignore emission (keeps the computation as-is with ignored=True:
            # ROOT stage identity at any descent depth, NFA.java:272-285
            # re-adds the queue item's own -- possibly synthesized-epsilon --
            # stage, never the descended stage). At most one of the two
            # fires per (lane, level) -- when both predicates pass, branch_m
            # is set and the ignore routes through the clone slot instead
            # (NFA.java:392-397) -- and DFS order (consume before ignore)
            # is preserved trivially with a single occupant.
            c_eps = jnp.where(v["take_m"], v["cs"], lut_i(v["oh"], n_consume_target))
            ign = up[l]["ignore_emit"]
            c_m = v["c_m"]
            slot_occ.append(c_m | ign)
            slot_src.append(jnp.where(c_m, v["cs"], src))
            slot_eps.append(jnp.where(c_m, c_eps, eps))
            slot_ver.append(v["ver"])
            slot_vlen.append(v["vlen"])
            slot_seq.append(lane_seq)
            slot_node.append(
                jnp.where(c_m, put_idx[:, l].astype(jnp.int32), lane_node)
            )
            slot_ts.append(jnp.where(c_m, start_ts, lane_ts))
            slot_br.append(false_b)
            slot_ig.append(~c_m)
            slot_newseq.append(false_b)
            slot_regs.append(final_regs)
            slot_regs_set.append(final_set)
            # consume: new eps = c_eps (TAKE -> self, BEGIN -> target), both
            # >= 0, so the match test is is_final[c_eps] -- statically
            # composed per stage; ignore keeps the queue item's identity.
            match_consume = jnp.where(
                v["take_m"],
                lut_b(v["oh"], n_is_final),
                lut_b(v["oh"], n_isfin_of_ctgt),
            )
            slot_match.append(jnp.where(c_m, match_consume, state_match))

        for l in reversed(range(L)):
            v = levels[l]
            u = up[l]
            # branch clone: epsilon(prev, current), version addRun(2) off a
            # begin previous stage else addRun(), last event = previous when
            # ignored else current (NFA.java:289-307). A null previous stage
            # parks the clone at the current stage (oracle divergence note,
            # nfa/nfa.py:286-291).
            has_ps = v["ps"] >= 0
            cl_src = jnp.where(has_ps, v["ps"], v["cs"])
            ps_begin = jnp.where(has_ps, lut_b(onehot(v["ps"]), n_is_begin), True)
            off = jnp.where(ps_begin & (v["vlen"] >= 2), 2, 1).astype(jnp.int32)
            cl_ver = add_run(v["ver"], v["vlen"], off)
            cl_node = jnp.where(v["ig_m"], lane_node, put_idx[:, l].astype(jnp.int32))

            m_clone = u["clone_m"]
            m_copy = u["rootcopy_m"]
            occ = m_clone | m_copy
            slot_occ.append(occ)
            slot_src.append(jnp.where(m_clone, cl_src, src))
            slot_eps.append(jnp.where(m_clone, v["cs"], eps))
            slot_ver.append(jnp.where(m_clone[:, None], cl_ver, state["ver"]))
            slot_vlen.append(jnp.where(m_clone, v["vlen"], state["vlen"]))
            slot_seq.append(jnp.where(m_clone, zero_i, lane_seq))  # fresh id patched below
            slot_node.append(jnp.where(m_clone, cl_node, lane_node))
            slot_ts.append(jnp.where(m_clone, start_ts, lane_ts))
            slot_br.append(jnp.where(m_clone, True, state["branching"]))
            slot_ig.append(jnp.where(m_clone, False, state["ignored"]))
            slot_newseq.append(m_clone)
            cr, cr_set = clone_regs[l]
            slot_regs.append(jnp.where(m_clone[:, None], cr, final_regs))
            slot_regs_set.append(jnp.where(m_clone[:, None], cr_set, final_set))
            # clone: eps = current (descended) stage; root copy keeps state.
            slot_match.append(
                jnp.where(m_clone, lut_b(v["oh"], n_is_final), state_match)
            )

            # begin re-add: fresh run on consume else the root itself
            # (NFA.java:323-338).
            m_fresh = u["readd_fresh"]
            m_root = u["readd_root"]
            occ = m_fresh | m_root
            slot_occ.append(occ)
            slot_src.append(src)
            slot_eps.append(eps)
            slot_ver.append(jnp.where(m_fresh[:, None], u["readd_ver"], state["ver"]))
            slot_vlen.append(jnp.where(m_fresh, v["vlen"], state["vlen"]))
            slot_seq.append(jnp.where(m_fresh, zero_i, lane_seq))
            slot_node.append(jnp.where(m_fresh, -1, lane_node))
            slot_ts.append(jnp.where(m_fresh, -1, lane_ts))
            slot_br.append(jnp.where(m_fresh, False, state["branching"]))
            slot_ig.append(jnp.where(m_fresh, False, state["ignored"]))
            slot_newseq.append(m_fresh)
            slot_regs.append(jnp.where(m_fresh[:, None], jnp.zeros_like(final_regs), final_regs))
            slot_regs_set.append(
                jnp.where(m_fresh[:, None], jnp.zeros_like(final_set), final_set)
            )
            # re-add keeps the root's (src, eps) identity in both cases.
            slot_match.append(state_match)

        occ = jnp.stack(slot_occ, axis=1)              # [R, SLOTS]
        o_src = jnp.stack(slot_src, axis=1)
        o_eps = jnp.stack(slot_eps, axis=1)
        o_ver = jnp.stack(slot_ver, axis=1)            # [R, SLOTS, D]
        o_vlen = jnp.stack(slot_vlen, axis=1)
        o_seq = jnp.stack(slot_seq, axis=1)
        o_node = jnp.stack(slot_node, axis=1)
        # Chain root: a lane with a chain passes its root to every slot
        # (any fresh put extends that chain); a chainless lane's slot
        # chain starts at the slot's own node (-1 when none) -- the
        # root >= 0 iff node >= 0 invariant makes this a single select.
        o_root = jnp.where(lane_root[:, None] >= 0, lane_root[:, None], o_node)
        o_ts = jnp.stack(slot_ts, axis=1)
        o_br = jnp.stack(slot_br, axis=1)
        o_ig = jnp.stack(slot_ig, axis=1)
        o_newseq = jnp.stack(slot_newseq, axis=1)
        o_regs = jnp.stack(slot_regs, axis=1)          # [R, SLOTS, A]
        o_regs_set = jnp.stack(slot_regs_set, axis=1)

        # Fresh run ids in (lane, slot) order = the oracle's queue-item-major
        # DFS allocation order for the runs counter.
        newseq_flat = (occ & o_newseq).reshape(-1)
        seq_alloc = state["runs"] + 1 + _excl_cumsum(newseq_flat)
        o_seq = jnp.where(
            (occ & o_newseq).reshape(-1), seq_alloc, o_seq.reshape(-1)
        ).reshape(R, SLOTS).astype(jnp.int32)
        new_runs = state["runs"] + jnp.sum(newseq_flat).astype(jnp.int32)

        # ==== match extraction + lane compaction (sortless) =================
        # Matches (forwarding-to-final, NFA.java:148-158) and surviving
        # queue slots are each selected by the two-level set-bit selector
        # over [R, SLOTS] masks in emission (row-major DFS) order -- no
        # sort custom-calls and no stacked-table gathers on the per-event
        # path (per-slot match flags were computed level-locally above).
        is_match = occ & jnp.stack(slot_match, axis=1)
        keep_2d = occ & ~is_match
        n_match = jnp.sum(is_match).astype(jnp.int32)
        n_keep = jnp.sum(keep_2d).astype(jnp.int32)

        msel, mok = _nth_set_select(is_match, M_STEP)
        w_match = jnp.where(mok, o_node.reshape(-1)[msel], -1)
        w_mroot = jnp.where(mok, o_root.reshape(-1)[msel], -1)
        step_match_drops = jnp.maximum(n_match - M_STEP, 0)

        sel, lane_ok = _nth_set_select(keep_2d, R)
        lane_drop_count = jnp.maximum(n_keep - R, 0)

        def compact(flat_vals, fill, extra_dims=()):
            g = flat_vals.reshape((SLOTS * R,) + extra_dims)[sel]
            mask = lane_ok.reshape((R,) + (1,) * len(extra_dims))
            return jnp.where(mask, g, jnp.asarray(fill, g.dtype))

        n_active = lane_ok
        n_src = compact(o_src, 0)
        n_eps = compact(o_eps, -1)
        n_ver = compact(o_ver, 0, (D,))
        n_vlen = compact(o_vlen, 0)
        n_seq = compact(o_seq, 0)
        n_node = compact(o_node, -1)
        n_root = compact(o_root, -1)
        n_ts = compact(o_ts, -1)
        n_br = compact(o_br, False)
        n_ig = compact(o_ig, False)
        n_regs = compact(o_regs, jnp.float32(0), (A,))
        n_regs_set = compact(o_regs_set, False, (A,))

        new_state = {
            "active": n_active, "src": n_src, "eps": n_eps, "ver": n_ver,
            "vlen": n_vlen, "seq": n_seq, "node": n_node, "root": n_root,
            "ts": n_ts,
            "branching": n_br, "ignored": n_ig,
            "regs": n_regs, "regs_set": n_regs_set,
            "runs": new_runs,
            "gc_phase": state["gc_phase"],  # advanced by the post pass only
            "n_events": state["n_events"] + 1,
            "n_branches": state["n_branches"]
            + jnp.sum(jnp.stack([u["clone_m"] for u in up if u is not None])).astype(jnp.int32),
            "n_expired": state["n_expired"] + jnp.sum(expired).astype(jnp.int32),
            "lane_drops": state["lane_drops"] + lane_drop_count.astype(jnp.int32),
            "node_drops": state["node_drops"] + step_node_drops,
            "match_drops": state["match_drops"] + step_match_drops.astype(jnp.int32),
            "seq_collisions": state["seq_collisions"] + collide.astype(jnp.int32),
        }

        # Padding lanes in a batched multi-key step carry valid=False: the
        # state is held and the step's outputs are masked empty.
        valid = x["valid"]
        merged = jax.tree.map(
            lambda new, old: jnp.where(valid, new, old), new_state, state
        )
        ys = {
            "w_event": jnp.where(valid, w_event, -1),
            "w_name": jnp.where(valid, w_name, -1),
            "w_pred": jnp.where(valid, w_pred, -1),
            "w_match": jnp.where(valid, w_match, -1),
            "w_mroot": jnp.where(valid, w_mroot, -1),
        }
        if debug:
            dbg = dict(
                occ=occ, o_src=o_src, o_eps=o_eps, o_seq=o_seq, o_node=o_node,
                is_match=is_match, expired=expired,
                levels=[
                    {k: v for k, v in lv.items()} for lv in levels
                ],
                up=[{k: v for k, v in u.items()} for u in up],
            )
            return merged, (ys, dbg)
        return merged, ys

    return step


def build_pend_append(config: EngineConfig):
    """The unvmapped pend-page append: one uniform-offset dynamic slice.

    Works on single-key ([M]) and batched K-last ([M, K]) pools alike --
    the page offset is the *same* for every key (each advance appends a
    fixed-size [T * matches_per_step] page, holes as -1), so the append
    never needs a per-key dynamic offset (a serialized scatter on TPU) and
    costs O(page), independent of the ring size.

    Returns (state', pool', page_roots): page_roots is the appended page
    ([TM] or [TM, K]) with the whole page blanked to -1 when it did not
    fit -- the GC must only pin chains of ids that actually landed in the
    ring. A rejected page's valid ids are counted into match_drops (the
    loud failure mode; BatchedDeviceNFA.auto_drain prevents this by
    draining before `pend_pos + TM` can exceed the ring).
    """
    M = config.matches
    M_STEP = config.matches_per_step

    def _min_root(
        pool: Dict[str, jnp.ndarray],
        roots: jnp.ndarray,
        placed_m: jnp.ndarray,
    ) -> jnp.ndarray:
        """min(pend_min, min chain-root id over PLACED matches): interval
        pinning's lower bound (dropped matches are lost+counted, so they
        must not pin; chainless matches carry root -1 and pin nothing)."""
        cand = jnp.where(
            placed_m & (roots >= 0), roots, _PEND_MIN_NONE
        )
        return jnp.minimum(pool["pend_min"], jnp.min(cand, axis=0)).astype(
            jnp.int32
        )

    def append_compact(
        state: Dict[str, jnp.ndarray],
        pool: Dict[str, jnp.ndarray],
        ids: jnp.ndarray,  # [TM] or [TM, K]
        roots: jnp.ndarray,
    ):
        """Fallback when a page exceeds the ring (TM > M): sort the page's
        valid ids to the front and place them at each key's own `pend_pos`
        cursor (no new holes). O(ring) per advance plus a page sort --
        fine for the single-key runtime and odd batch shapes; the dense
        scatter path below is the fast one. Both modes treat `pend_pos`
        as the dense per-key occupancy count (== true pending-match
        count, no hole pages), so they compose on one pool (the device
        processor flushes variable-length partial batches)."""
        TM = ids.shape[0]
        m_valid = ids >= 0
        pos = pool["pend_pos"]
        order = jnp.argsort(~m_valid, axis=0, stable=True)
        m_sorted = jnp.take_along_axis(ids, order, axis=0)
        n_m = jnp.sum(m_valid.astype(jnp.int32), axis=0)
        rank = jnp.cumsum(m_valid.astype(jnp.int32), axis=0) - 1
        idx = jnp.arange(M).reshape((M,) + (1,) * (ids.ndim - 1))
        rel = idx - pos
        take = (rel >= 0) & (rel < TM) & (rel < n_m)
        gathered = jnp.take_along_axis(
            m_sorted, jnp.broadcast_to(rel.clip(0, TM - 1), (M,) + ids.shape[1:]),
            axis=0,
        )
        new_pend = jnp.where(take, gathered, pool["pend"])
        placed = jnp.minimum(jnp.maximum(M - pos, 0), n_m)
        drops = n_m - placed
        placed_m = m_valid & (pos + rank < M)
        new_pool = {
            **pool,
            "pend": new_pend,
            "pend_count": pool["pend_count"] + placed,
            "pend_pos": (pos + placed).astype(jnp.int32),
            "pend_min": _min_root(pool, roots, placed_m),
        }
        new_state = {
            **state,
            "match_drops": state["match_drops"] + drops,
        }
        page_roots = jnp.where(placed_m, ids, -1)
        return new_state, new_pool, page_roots

    def append(
        state: Dict[str, jnp.ndarray],
        pool: Dict[str, jnp.ndarray],
        w_match: jnp.ndarray,  # [T, M_STEP] or [T, M_STEP, K]
        w_mroot: jnp.ndarray,  # same shape: each match's chain-root id
    ):
        T = w_match.shape[0]
        TM = T * M_STEP
        rest = w_match.shape[2:]
        ids = w_match.reshape((TM,) + rest)
        roots = w_mroot.reshape((TM,) + rest)
        if TM > M or not rest:
            # Oversized pages can't ride the scatter (every slot may be
            # real); and the single-key pool ([M], no key axis) is trivial
            # at the compact path's O(M) arithmetic.
            return append_compact(state, pool, ids, roots)
        pend = pool["pend"]
        pos = pool["pend_pos"]  # [K] per-key TRUE counts (no holes)
        # Dense scatter-append: each key's valid ids land at its own
        # cursor, in emission order (the page is t-major and each step's
        # match slots are a rank-ordered prefix, so the running count IS
        # the emission rank). No hole pages: ring occupancy equals the
        # true match count, so the GC's prefix-bucketed remap and the
        # drain guard track real match volume, not page burn. (An earlier
        # design appended whole fixed pages with holes at a uniform
        # cursor; sparse streams then hit ring-capacity syncs every
        # M/page advances and the GC remapped hole rows every advance --
        # honest-timing notes in PERF.md "v7".)
        m_valid = ids >= 0
        csum = jnp.cumsum(m_valid.astype(jnp.int32), axis=0)
        n_valid = csum[-1]                                   # [K]
        rank = csum - m_valid.astype(jnp.int32)
        target = pos[None, :] + rank                         # [TM, K]
        placed_m = m_valid & (target < M)
        kk = jnp.arange(ids.shape[1])[None, :]
        # mode="drop" discards out-of-range rows (hole slots route to M).
        new_pend = pend.at[
            jnp.where(placed_m, target, M), kk
        ].set(jnp.where(placed_m, ids, -1), mode="drop")
        placed = jnp.minimum(jnp.maximum(M - pos, 0), n_valid)
        new_pool = {
            **pool,
            "pend": new_pend,
            "pend_count": pool["pend_count"] + placed,
            "pend_pos": (pos + placed).astype(jnp.int32),
            "pend_min": _min_root(pool, roots, placed_m),
        }
        new_state = {
            **state,
            "match_drops": state["match_drops"] + (n_valid - placed),
        }
        page_roots = jnp.where(placed_m, ids, -1)
        return new_state, new_pool, page_roots

    return append


def build_gc(
    query: CompiledQuery,
    config: EngineConfig,
    defer_pend_remap: bool = False,
):
    """The per-key post-advance GC: pin-seeded mark + sweep compaction.

    With `defer_pend_remap`, the pend ring is returned UNREMAPPED and the
    per-key remap table is emitted as a third output: the batched post
    wrapper then rewrites only the occupied ring prefix in a dynamic
    block loop (`remap_pend_blocks`) -- the full-width value-remap gather
    was the single most expensive op in the post pass (honest D2H-forced
    timing, PERF.md "v7"), and only the device knows the true occupancy.

    Runs once per advance (not per event step):

      1. mark every node reachable from live lanes or this advance's
         pend page. The mark is seeded with the region's `pinned` bitmap
         (nodes kept alive by *earlier* pages), so the frontier is only
         [lanes + page] wide -- independent of the pend ring size -- and
         chains already pinned terminate the walk after one hop;
      2. compact marked nodes from (region + this advance's time-indexed
         window) into a fresh region of `config.nodes` slots via one stable
         argsort + gathers, remapping lane pointers, node preds, the whole
         pend ring and the pinned bitmap. Region overflow drops newest
         chains (node_drops).

    The host analog of the reference's refcount GC
    (SharedVersionedBufferStoreImpl.java:176-201). vmap over the trailing
    key axis for the multi-key engine (key_shard.build_batched_post).
    Note: the mark runs in two phases so `pinned` is exactly the
    pend-reachable closure (old pins + this advance's page), never the
    lane-reachable set: pinning lane-only chains would leak them forever
    on match-free streams, where the empty pend ring makes every drain a
    no-op that never clears pins (the round-4 advisory leak).
    """
    B = config.nodes
    R = config.lanes

    def gc(
        state: Dict[str, jnp.ndarray],
        pool: Dict[str, jnp.ndarray],
        ys: Dict[str, jnp.ndarray],
        page_roots: jnp.ndarray,  # [TM]
    ) -> Tuple[Dict[str, jnp.ndarray], Dict[str, jnp.ndarray]]:
        T, p_cap = ys["w_event"].shape
        W = T * p_cap
        w_event = ys["w_event"].reshape(W)
        w_name = ys["w_name"].reshape(W)
        w_pred = ys["w_pred"].reshape(W)
        pend = pool["pend"]

        # -- 1. mark reachable nodes (chunked frontier walk) -----------------
        # Each walk advances its frontier one predecessor hop per iteration;
        # marking uses a small scatter over the chunk's indices (measured
        # cheaper on TPU than sort+searchsorted membership at these widths).
        # Dead cursors route to a trash slot so their writes can't clobber
        # id 0. The page roots are mostly holes (-1), so the page is
        # reordered slot-major -- each step's w_match block is a valid
        # prefix, so slot-major concentrates real ids in the first chunk --
        # and walked in fixed-width chunks: an all-dead chunk's while_loop
        # exits after a single cond reduce, keeping the per-hop scatter
        # width O(chunk), not O(T * matches_per_step).
        BW = B + W
        combined_pred = jnp.concatenate([pool["node_pred"], w_pred])
        lane_roots = jnp.where(state["active"], state["node"], -1)
        marked0 = jnp.concatenate(
            [pool["pinned"], jnp.zeros(W + 1, bool)]
        )

        def walk(marked, frontier):
            def cond(carry):
                _, fr = carry
                return jnp.any(fr >= 0)

            def body(carry):
                mk, fr = carry
                live = fr >= 0
                cidx = jnp.where(live, fr, BW)  # BW = trash slot
                already = mk[cidx] & live
                mk = mk.at[cidx].set(True)
                fr = jnp.where(
                    live & ~already, combined_pred[cidx.clip(0, BW - 1)], -1
                )
                return mk, fr

            marked, _ = jax.lax.while_loop(cond, body, (marked, frontier))
            return marked

        if config.pin_interval:
            # Interval pinning: the stable sweep below keeps ids
            # creation-ordered, a chain's root is its oldest (smallest)
            # node, and `pend_min` is the min root over pending matches --
            # so the whole pend-reachable set lies in [pend_min, BW) and
            # ONE compare replaces the chunked page-root walks (the
            # dominant post-pass term, PERF.md v7). Conservative: every
            # node younger than the oldest pending root stays resident
            # until a drain (see EngineConfig.pin_interval for the
            # trade). The previous interval is covered automatically:
            # pend_min only decreases between drains and both sides
            # remap consistently each sweep.
            node_valid = jnp.concatenate(
                [pool["node_event"] >= 0, w_event >= 0, jnp.zeros(1, bool)]
            )
            marked_pin = (
                jnp.arange(BW + 1) >= pool["pend_min"]
            ) & node_valid
        else:
            # Phase 1: the pend-reachable closure = old pins (already a
            # closed set: preds of pinned nodes are pinned) + this
            # advance's match page. This closure -- and ONLY this closure
            # -- becomes the new `pinned` bitmap, so match-free streams
            # keep pinned empty.
            TM_page = page_roots.shape[0]
            m_step = max(config.matches_per_step, 1)
            if TM_page % m_step == 0 and TM_page > m_step:
                # [T * M_STEP] t-major -> slot-major (valid-dense prefix).
                page_sm = page_roots.reshape(-1, m_step).T.reshape(TM_page)
            else:
                page_sm = page_roots
            CHUNK = 256  # all-hole chunks exit the while_loop in one reduce
            marked_pin = marked0
            for c0 in range(0, TM_page, CHUNK):
                marked_pin = walk(marked_pin, page_sm[c0 : c0 + CHUNK])
        # Phase 2: + live-lane chains (kept this GC, but NOT pinned -- if
        # the lane survives, the next GC re-marks them from the lane root).
        marked = walk(marked_pin, lane_roots)
        marked_pin = marked_pin[:BW]
        marked = marked[:BW]

        # -- 2. compact into a fresh region [B] ------------------------------
        n_keep = jnp.sum(marked).astype(jnp.int32)
        rank = _excl_cumsum(marked)
        remap = jnp.where(marked & (rank < B), rank, -1).astype(jnp.int32)
        remap_full = jnp.concatenate([remap, jnp.full(1, -1, jnp.int32)])
        # One stable argsort per *advance* (not per event step) is cheaper
        # here than the two-level selector: the [B, BW/128] hit matrices it
        # needs outweigh a single sort at this width.
        sel = jnp.argsort(~marked, stable=True)[:B]
        ok = jnp.arange(B) < jnp.minimum(n_keep, B)
        combined_event = jnp.concatenate([pool["node_event"], w_event])
        combined_name = jnp.concatenate([pool["node_name"], w_name])
        pred_remapped = jnp.where(
            combined_pred >= 0, remap_full[combined_pred.clip(0)], -1
        )
        if defer_pend_remap:
            new_pend = pend  # rewritten by remap_pend_blocks in the wrapper
        else:
            new_pend = jnp.where(pend >= 0, remap_full[pend.clip(0)], -1)
        # pend_min rides the same remap (its node is pend-reachable, hence
        # marked). A dropped root (rank >= B under region overflow, itself
        # counted in node_drops) degrades to 0 = pin-everything, never to
        # an unpinning sentinel.
        pm = pool["pend_min"]
        pm_remap = remap_full[jnp.clip(pm, 0, BW)]
        new_pend_min = jnp.where(
            pm == _PEND_MIN_NONE,
            _PEND_MIN_NONE,
            jnp.maximum(pm_remap, 0),
        ).astype(jnp.int32)
        new_pool = {
            "node_event": jnp.where(ok, combined_event[sel], -1),
            "node_name": jnp.where(ok, combined_name[sel], -1),
            "node_pred": jnp.where(ok, pred_remapped[sel], -1),
            "node_count": jnp.minimum(n_keep, B),
            "pend": new_pend,
            "pend_count": pool["pend_count"],
            "pend_pos": pool["pend_pos"],
            "pinned": marked_pin[sel] & ok,
            "pend_min": new_pend_min,
        }
        new_state = {
            **state,
            "node": jnp.where(
                state["node"] >= 0, remap_full[state["node"].clip(0)], -1
            ).astype(jnp.int32),
            "root": jnp.where(
                state["root"] >= 0, remap_full[state["root"].clip(0)], -1
            ).astype(jnp.int32),
            "node_drops": state["node_drops"]
            + jnp.maximum(n_keep - B, 0).astype(jnp.int32),
        }
        if defer_pend_remap:
            return new_state, new_pool, remap_full
        return new_state, new_pool

    return gc


def remap_pend_blocks(
    pend: jnp.ndarray,      # [M, K] UNREMAPPED ring (dense prefix per key)
    remap_full: jnp.ndarray,  # [BW + 1, K] per-key node-id remap tables
    pend_pos: jnp.ndarray,  # [K] per-key occupancy cursors
    block: int = 512,
) -> jnp.ndarray:
    """Value-remap the ring's occupied prefix in dynamic fixed-width
    blocks: a device-side while_loop runs ceil(max(pend_pos) / block)
    iterations, each remapping one [block, K] slice at a UNIFORM offset
    (plain dynamic_slice/update, no per-key scatter). The remap cost then
    tracks true ring occupancy -- which only the device knows once
    dispatches run ahead of completions -- instead of ring capacity or a
    host-side worst-case bound."""
    M, K = pend.shape
    w = min(block, M)
    maxpos = jnp.max(pend_pos)
    gather = jax.vmap(
        lambda r, h: jnp.where(h >= 0, r[h.clip(0)], -1),
        in_axes=-1, out_axes=-1,
    )

    def cond(carry):
        i, _ = carry
        return i * w < jnp.minimum(maxpos, M)

    def body(carry):
        i, p = carry
        off_raw = i * w
        # The final block clamps to the ring end; rows below off_raw were
        # remapped by the previous iteration and must pass through
        # untouched (a second remap would corrupt them).
        off = jnp.minimum(off_raw, M - w)
        head = jax.lax.dynamic_slice(p, (off, 0), (w, K))
        fresh = (off + jnp.arange(w) >= off_raw)[:, None]
        return i + 1, jax.lax.dynamic_update_slice(
            p, jnp.where(fresh, gather(remap_full, head), head), (off, 0)
        )

    _, out = jax.lax.while_loop(cond, body, (jnp.int32(0), pend))
    return out


#: The ys node planes a GC group's accumulated window carries between the
#: per-advance append and the group flush (the match planes are consumed
#: by the append itself every advance).
WINDOW_PLANES = ("w_event", "w_name", "w_pred")


def concat_group_window(
    group_ys: List[Dict[str, jnp.ndarray]],
    group_roots: List[jnp.ndarray],
) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    """Concatenate a GC group's accumulated per-advance window segments
    (ys node planes along the step axis; page roots likewise) into the
    single window the group flush folds back. Single-segment groups pass
    through concat-free. Shared by the single-key and batched drivers --
    the flush semantics must never diverge between them (the differential
    suite uses the single-key engine as reference)."""
    if len(group_ys) == 1:
        return group_ys[0], group_roots[0]
    ys_cat = {
        k: jnp.concatenate([ys[k] for ys in group_ys], axis=0)
        for k in WINDOW_PLANES
    }
    return ys_cat, jnp.concatenate(group_roots, axis=0)


def build_append_post(config: EngineConfig):
    """Single-key per-advance light post: pend-page append + group-phase
    bump. Runs EVERY advance (capacity guards keep observing true pending
    counts); the mark/sweep GC is deferred to the group flush
    (build_flush_post). Returns (state', pool', page_roots) -- the caller
    accumulates page_roots (and the ys node planes) until the flush."""
    append = build_pend_append(config)

    def post_append(
        state: Dict[str, jnp.ndarray],
        pool: Dict[str, jnp.ndarray],
        ys: Dict[str, jnp.ndarray],
    ):
        state, pool, page_roots = append(
            state, pool, ys["w_match"], ys["w_mroot"]
        )
        state = {
            **state,
            "gc_phase": (
                state["gc_phase"]
                + jnp.int32(ys["w_event"].shape[0])
            ).astype(jnp.int32),
        }
        return state, pool, page_roots

    return post_append


def build_flush_post(query: CompiledQuery, config: EngineConfig):
    """Single-key group flush: pin-seeded mark/sweep + compaction over the
    group's ACCUMULATED time-indexed window (ys node planes concatenated
    along the step axis; page_roots likewise), then reset `gc_phase`.
    With gc_group=1 this is exactly the classic per-advance GC."""
    gc = build_gc(query, config)

    def flush(
        state: Dict[str, jnp.ndarray],
        pool: Dict[str, jnp.ndarray],
        ys: Dict[str, jnp.ndarray],
        page_roots: jnp.ndarray,
    ) -> Tuple[Dict[str, jnp.ndarray], Dict[str, jnp.ndarray]]:
        state, pool = gc(state, pool, ys, page_roots)
        state = {**state, "gc_phase": jnp.zeros_like(state["gc_phase"])}
        return state, pool

    return flush


def build_post(query: CompiledQuery, config: EngineConfig):
    """Single-key every-advance post pass (append + GC fused in one jit):
    the G=1 composition kept for tests and one-shot callers; the drivers
    run build_append_post/build_flush_post at the group cadence."""
    append = build_append_post(config)
    flush = build_flush_post(query, config)

    def post(
        state: Dict[str, jnp.ndarray],
        pool: Dict[str, jnp.ndarray],
        ys: Dict[str, jnp.ndarray],
    ) -> Tuple[Dict[str, jnp.ndarray], Dict[str, jnp.ndarray]]:
        state, pool, page_roots = append(state, pool, ys)
        return flush(state, pool, ys, page_roots)

    return post


def compact_valid_front(ids: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Stably move the valid (>= 0) entries of each key's column to the
    front; returns (compacted, per-key counts).

    Rank-scatter, not sort: TPU lowers a stable argsort over a major axis
    to sort custom-calls measured ~3x the cost of the cumsum + one scatter
    used here (honest D2H-forced timing; the broken-`block_until_ready`
    micro-profiles that originally picked argsort are documented in
    PERF.md "Measurement trap"). Hole entries scatter to a trash row that
    is sliced off, so duplicate targets only ever carry -1.
    """
    m = ids >= 0
    M = ids.shape[0]
    c = jnp.cumsum(m.astype(jnp.int32), axis=0)
    counts = c[-1]
    rank = jnp.where(m, c - 1, M)  # holes -> trash row
    out = jnp.full(ids.shape[:0] + (M + 1,) + ids.shape[1:], -1, ids.dtype)
    if ids.ndim == 1:
        out = out.at[rank].set(jnp.where(m, ids, -1))
    else:
        kk = jnp.arange(int(np.prod(ids.shape[1:]))).reshape(ids.shape[1:])
        out = out.at[rank, kk].set(jnp.where(m, ids, -1))
    return out[:M], counts


def drain_probe(pool: Dict[str, jnp.ndarray]) -> jnp.ndarray:
    """Fused drain-time probe: ``[3, K]`` = (pend_count, pend_pos, chain bound).

    Row 2 is an upper bound on the longest pending match chain (in nodes),
    computed by pointer doubling over the predecessor graph: after
    ceil(log2(B)) rounds every node knows its full chain length (a corrupt
    cyclic pool saturates and is clamped to B). The bound is taken over all
    valid nodes -- a superset of the pend-reachable set, so it can only
    over-size the flatten table's depth bucket, never truncate a chain.
    The doubling only runs when something is pending (one `lax.cond`);
    match-free drains pay the same tiny probe as before.

    This is the ONE host pull the flattened drain needs before sizing the
    chain-flatten program (build_chain_flatten); everything else rides the
    single dense table transfer.
    """
    pred = pool["node_pred"]
    B = pred.shape[0]
    valid = pool["node_event"] >= 0

    def depth_bound(_):
        d = valid.astype(jnp.int32)
        j = jnp.where(valid, pred, -1)
        for _hop in range(max(int(np.ceil(np.log2(max(B, 2)))), 1)):
            live = j >= 0
            cj = jnp.clip(j, 0, B - 1)
            d = d + jnp.where(live, jnp.take_along_axis(d, cj, axis=0), 0)
            j = jnp.where(live, jnp.take_along_axis(j, cj, axis=0), -1)
        return jnp.minimum(jnp.max(d, axis=0), B).astype(jnp.int32)

    depth = jax.lax.cond(
        jnp.sum(pool["pend_count"]) > 0,
        depth_bound,
        lambda _: jnp.zeros(pred.shape[1:], jnp.int32),
        operand=None,
    )
    return jnp.stack(
        [pool["pend_count"], pool["pend_pos"], depth]
    ).astype(jnp.int32)


def build_chain_flatten(max_matches: int, max_chain: int):
    """Build the jitted drain-time chain flattener.

    At drain time every pending match's predecessor chain is walked ON
    DEVICE and gathered into one dense table bounded by true match volume:

        table[3, max_matches, max_chain(, K)] int32
          plane 0: event gidx per hop (-1 for a GC-dropped put's node)
          plane 1: stage name id per hop
          plane 2: hop validity (1 while the walk was on a node; the first
                   0 ends the chain -- distinguishing "chain ended" from
                   "node present but event dropped", which decode must skip
                   while continuing, exactly as the pool-walk paths do)

    Hops are stored newest-first (the walk order of
    ops/runtime.decode_chains and native/decoder.cc); decode reverses.
    This replaces the drain's node-pool plane pulls entirely: the D2H
    transfer is this table plus the [3, K] drain_probe, so drain cost
    tracks matches x chain depth, not pool capacity. `max_matches` /
    `max_chain` are host-chosen pow2 buckets from the probe, keeping the
    number of distinct compiled programs O(log M x log B).

    Works on single-key ([M]/[B]) and batched K-last ([M, K]/[B, K]) pools
    alike. The pend ring is compacted valid-front first so GC-nulled holes
    (dead chains; node_drops counts them) sit behind each key's count, as
    in the pool-pull path.
    """
    Mb, Cb = max_matches, max_chain

    @jax.jit
    def flatten(pool: Dict[str, jnp.ndarray]) -> jnp.ndarray:
        compacted, _ = compact_valid_front(pool["pend"])
        starts = compacted[:Mb]
        ev = pool["node_event"]
        nm = pool["node_name"]
        pr = pool["node_pred"]
        B = pr.shape[0]

        def hop(cur, _):
            live = cur >= 0
            cidx = jnp.clip(cur, 0, B - 1)
            g = jnp.where(live, jnp.take_along_axis(ev, cidx, axis=0), -1)
            n = jnp.where(live, jnp.take_along_axis(nm, cidx, axis=0), -1)
            nxt = jnp.where(live, jnp.take_along_axis(pr, cidx, axis=0), -1)
            return nxt, jnp.stack([g, n, live.astype(jnp.int32)])

        _, levels = jax.lax.scan(hop, starts, None, length=Cb)
        # levels [Cb, 3, Mb(, K)] -> [3, Mb, Cb(, K)]
        return jnp.moveaxis(levels, 0, 2)

    return flatten


def drain_pend(pool: Dict[str, jnp.ndarray]) -> Dict[str, jnp.ndarray]:
    """Clear the pending-match buffer (jit-able; keeps shardings).

    Also clears `pinned`: pins exist solely to keep pending matches' chains
    alive across GC passes, so the next post pass rebuilds reachability
    from live lanes alone and pin-retained garbage is collected then.
    """
    return {
        **pool,
        "pend": jnp.full_like(pool["pend"], -1),
        "pend_count": jnp.zeros_like(pool["pend_count"]),
        "pend_pos": jnp.zeros_like(pool["pend_pos"]),
        "pinned": jnp.zeros_like(pool["pinned"]),
        "pend_min": jnp.full_like(pool["pend_min"], _PEND_MIN_NONE),
    }


def build_batch_fn(query: CompiledQuery, config: EngineConfig):
    """jit-compiled batch advance: scan the one-event step over [T] columns.

    `xs` is the packed batch: event columns ("f:*", "ts", "topic") of shape
    [T], plus "spred" [T, P] (precomputed stateless predicate rows),
    "gidx" [T] global event indices and "valid" [T]. Returns the new state
    and ys, the stacked per-step node/match outputs consumed by the post
    pass (build_append_post per advance + build_flush_post at group
    boundaries). The step index is offset by the state's `gc_phase` group
    scalar so each advance of a GC group writes its node emissions into its
    own segment of the accumulated time-indexed window.
    """
    step = build_step(query, config)

    @jax.jit
    def advance(state, xs):
        T = xs["valid"].shape[0]

        def body(carry, xt):
            x, t = xt
            return step(carry, x, t)

        state, ys = jax.lax.scan(
            body, state,
            (xs, state["gc_phase"] + jnp.arange(T, dtype=jnp.int32)),
        )
        return state, ys

    return advance


def eval_stateless_preds(query: CompiledQuery, cols: Dict[str, np.ndarray]) -> jnp.ndarray:
    """Evaluate all stateless predicates over the whole batch: one fused
    vectorized pass per predicate (the [T, P] mask precompute).

    Column leaves may be [T] (single key) or [T, K] (batched multi-key); the
    predicate axis is appended last, so the result is [T, P] or [T, K, P].
    """
    shape = np.shape(cols["ts"])
    env = DeviceEnv(
        {k: jnp.asarray(v) for k, v in cols.items()},
        jnp.zeros((1, query.n_aggs), jnp.float32),
        jnp.zeros((1, query.n_aggs), bool),
        query.agg_slots,
        query.agg_defaults,
    )
    out = []
    for p in range(max(query.n_preds, 1)):
        if p < query.n_preds and not query.pred_stateful[p]:
            v = jnp.broadcast_to(jnp.asarray(query.predicates[p](env), bool), shape)
        else:
            v = jnp.zeros(shape, bool)  # stateful: evaluated in-step per lane
        out.append(v)
    return jnp.stack(out, axis=-1)
