"""Native runtime components, built on demand.

The reference ships no native code (SURVEY.md §2.8: its near-native layer is
RocksDB via Kafka Streams); this framework's native layer is the XLA/Pallas
kernel set plus this C++ ingest packer (packer.cc), which removes the
per-(event, field) interpreter walk from the micro-batch packing hot path.

`load_packer()` returns the extension module, compiling it with g++ on
first use (no pybind11 in the image; plain CPython C API against the
running interpreter's headers). Any failure -- no compiler, no headers,
sandboxed filesystem -- degrades silently to the pure-Python packer, which
remains the semantic reference (ops/schema.py, parallel/batched.py).
"""
from __future__ import annotations

import importlib.util
import os
import subprocess
import sysconfig
from typing import Any, Optional

_packer: Any = None
_tried = False


def _build_dir() -> str:
    return os.path.join(os.path.dirname(__file__), "_build")


def _so_path() -> str:
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    return os.path.join(_build_dir(), f"_packer{suffix}")


def build_packer(force: bool = False) -> Optional[str]:
    """Compile packer.cc into the package-local _build dir; returns the .so
    path or None on failure."""
    src = os.path.join(os.path.dirname(__file__), "packer.cc")
    out = _so_path()
    if not force and os.path.exists(out) and (
        os.path.getmtime(out) >= os.path.getmtime(src)
    ):
        return out
    include = sysconfig.get_paths()["include"]
    os.makedirs(_build_dir(), exist_ok=True)
    cmd = [
        "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
        f"-I{include}", src, "-o", out,
    ]
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=120
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    if proc.returncode != 0:
        return None
    return out


def load_packer() -> Any:
    """The compiled _packer module, or None when unavailable."""
    global _packer, _tried
    if _tried:
        return _packer
    _tried = True
    if os.environ.get("KCT_NO_NATIVE"):
        return None
    so = build_packer()
    if so is None:
        return None
    try:
        # The name must match the extension's PyInit__packer symbol.
        spec = importlib.util.spec_from_file_location("_packer", so)
        assert spec is not None and spec.loader is not None
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _packer = mod
    except Exception:
        _packer = None
    return _packer
