"""EventTimeGate: the reorder/watermark/late-policy stage the stream
processors drive.

Sits between ingestion and the pack step (host and device runtimes
alike). Per record key it owns a bounded `ReorderBuffer`; one watermark
generator covers the whole gate (per-source structure lives inside
`MinMergeWatermark`). `offer()` takes one arriving record and returns the
records the watermark just released, each paired with the gate's
event-time CLOCK at its release:

    clock_i = max(clock_{i-1}, released_ts_i)

The released stream is sorted by event time (stable on arrival order for
ties), so on the normal path the clock equals each record's own timestamp
-- feeding the engine `watermarks=[clock_i]` makes window expiry sweep
off event time and the output equals the host oracle fed the pre-sorted
stream. The clock diverges from the raw timestamp exactly where it must:
a `recompute-none` late admission carries the (higher) current clock so
the engine's expiry clock never rewinds, and a forced release under
`on_overflow="block"` advances the clock past the stragglers it outran.

Late records (ts below the watermark at arrival) follow
`EngineConfig.late_policy`:

    drop            discarded, counted in cep_late_dropped_total{query}
    sideoutput      diverted to `take_late()` (never the engine), counted
                    in cep_late_sideoutput_total{query}
    recompute-none  admitted downstream as-is -- no retraction or window
                    recompute -- counted in cep_late_admitted_total{query}

Buffer overflow honors `EngineConfig.on_overflow` exactly like the
engine's pools: "drop" loses the incoming record loudly
(cep_reorder_overflow_dropped_total), "raise" raises CEPOverflowError
(nothing lost; the caller backs off), "block" force-releases the key's
oldest buffered record (backpressure, nothing lost; records older than
the forced release become late). The `time.reorder_overflow` fault point
(faults/injection.py) forces this path deterministically for chaos tests.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ..core.event import Event
from ..faults import injection as _flt
from ..faults.injection import CEPOverflowError, TransientFault
from .reorder import ReorderBuffer
from .watermarks import BoundedOutOfOrderness, WatermarkGenerator, WM_MIN_MS

class EventTimeGate:
    """Per-key reorder buffers + one watermark generator + late policy."""

    def __init__(
        self,
        capacity: int,
        lateness_ms: int = 0,
        late_policy: str = "drop",
        on_overflow: str = "drop",
        generator: Optional[WatermarkGenerator] = None,
        registry: Optional[Any] = None,
        query_name: str = "q",
    ) -> None:
        from ..obs.registry import default_registry

        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if late_policy not in ("drop", "sideoutput", "recompute-none"):
            raise ValueError(f"unknown late_policy {late_policy!r}")
        if on_overflow not in ("drop", "raise", "block"):
            raise ValueError(f"unknown on_overflow {on_overflow!r}")
        self.capacity = int(capacity)
        self.late_policy = late_policy
        self.on_overflow = on_overflow
        self.query_name = query_name
        self.generator: WatermarkGenerator = (
            generator
            if generator is not None
            else BoundedOutOfOrderness(max(int(lateness_ms), 0))
        )
        self._buffers: Dict[Any, ReorderBuffer] = {}
        self._seq = 0          # global arrival sequence (release tiebreak)
        #: Per-KEY monotone release clocks: expiry is per-key NFA state,
        #: so the clock attached to a release must never be dragged
        #: forward by OTHER keys' (faster) streams -- only by this key's
        #: own releases and late-admission clamps.
        self._clocks: Dict[Any, int] = {}
        self._forced_wm = WM_MIN_MS  # overflow-"block" forced floor
        self._max_seen = WM_MIN_MS   # max observed event time (lag gauge)
        self._occupancy = 0
        self._late: List[Event] = []  # side output (late_policy=sideoutput)
        #: forced releases staged by the overflow "block" path, merged
        #: ahead of the next release batch (they are the oldest records).
        self._pending_forced: List[Tuple[Event, int]] = []
        #: Lower bound on the min buffered head timestamp (None = unknown
        #: or empty): lets the common no-release offer skip the O(keys)
        #: buffer scan entirely. Invariant: never ABOVE the true min (a
        #: stale-LOW value only costs one redundant scan; forced
        #: evictions that raise a head leave it stale-low on purpose).
        self._min_head: Optional[int] = None

        self.metrics = registry if registry is not None else default_registry()
        q = {"query": query_name}
        self._m_late_dropped = self.metrics.counter(
            "cep_late_dropped_total",
            "Late records discarded by the event-time gate "
            "(ts below the watermark at arrival, late_policy=drop)",
            labels=("query",),
        ).labels(**q)
        self._m_late_side = self.metrics.counter(
            "cep_late_sideoutput_total",
            "Late records diverted to the gate's side output "
            "(late_policy=sideoutput; drained by take_late())",
            labels=("query",),
        ).labels(**q)
        self._m_late_admitted = self.metrics.counter(
            "cep_late_admitted_total",
            "Late records admitted downstream as-is "
            "(late_policy=recompute-none; no retraction/recompute)",
            labels=("query",),
        ).labels(**q)
        self._m_released = self.metrics.counter(
            "cep_reorder_released_total",
            "Records released by the reorder stage in event-time order",
            labels=("query",),
        ).labels(**q)
        self._m_overflow_dropped = self.metrics.counter(
            "cep_reorder_overflow_dropped_total",
            "Records lost to reorder-buffer overflow "
            "(on_overflow=drop; loud by contract)",
            labels=("query",),
        ).labels(**q)
        self._m_backpressure = self.metrics.counter(
            "cep_reorder_backpressure_total",
            "Forced early releases under on_overflow=block "
            "(nothing lost; stragglers behind the forced watermark go late)",
            labels=("query",),
        ).labels(**q)
        self._m_occupancy = self.metrics.gauge(
            "cep_reorder_occupancy",
            "Records currently buffered across all keys' reorder buffers",
            labels=("query",),
        ).labels(**q)
        self._m_lag = self.metrics.gauge(
            "cep_watermark_lag_seconds",
            "Event-time lag of the watermark behind the max observed "
            "event time (how much reordering slack is currently open)",
            labels=("query",),
        ).labels(**q)

    # ------------------------------------------------------------------ API
    @property
    def watermark_ms(self) -> int:
        """The effective low watermark: generator merged with the gate's
        monotone floor (`_forced_wm` -- overflow-backpressure releases
        raise it, and every read LATCHES it). The latch matters when a
        generator's own mark can regress: an idle-jumped source resuming,
        or a new min-merge source appearing, must not pull the watermark
        back below records already released -- a regressed mark would
        admit truly-late records and break the sorted-release invariant
        the expiry clocks and the differential contract are built on."""
        wm = max(self.generator.current_ms(), self._forced_wm)
        if wm > self._forced_wm:
            self._forced_wm = wm
        return wm

    @property
    def clock_ms(self) -> int:
        """The max per-key release clock (informational)."""
        return max(self._clocks.values(), default=WM_MIN_MS)

    @property
    def occupancy(self) -> int:
        return self._occupancy

    @property
    def watermark_lag_ms(self) -> Optional[int]:
        """Event-time lag of the watermark behind the max observed event
        time (None before the first record)."""
        wm = self.watermark_ms
        if self._max_seen <= WM_MIN_MS or wm <= WM_MIN_MS:
            return None
        return max(0, self._max_seen - wm)

    def offer(
        self, event: Event, source: Any = None
    ) -> List[Tuple[Event, int]]:
        """Admit one arriving record; return [(event, clock_ms)] releases.

        `source` keys per-source watermark tracking (MinMergeWatermark);
        defaults to the record's (topic, partition)."""
        if source is None:
            source = (event.topic, event.partition)
        ts = int(event.timestamp)
        wm = self.watermark_ms
        if wm > WM_MIN_MS and ts < wm:
            return self._late_record(event)
        buf = self._buffers.get(event.key)
        if buf is None:
            buf = self._buffers[event.key] = ReorderBuffer(self.capacity)
        # `time.reorder_overflow` fault point: armed chaos schedules raise
        # TransientFault here, which this site interprets as "the buffer
        # is full NOW" -- the overflow path below runs under the real
        # policy, so tests prove its semantics without filling a buffer.
        forced_overflow = False
        if _flt.ACTIVE is not None:
            try:
                _flt.ACTIVE.fire("time.reorder_overflow")
            except TransientFault:
                forced_overflow = True
        # Overflow resolves BEFORE any watermark mutation (mirrors
        # offer_batch's chunk-atomic contract): a CEPOverflowError
        # rejection must leave the gate untouched -- a never-admitted
        # record advancing the watermark would misclassify the in-bound
        # records behind it as late.
        if buf.full or forced_overflow:
            if not self._overflow(buf, event):
                # drop: the record is intentionally consumed, so its
                # observation still advances event time -- release what
                # it passed rather than holding now-releasable records
                # for a later arrival.
                self._observe_event_time(ts, source)
                out = self._release_upto(self.watermark_ms)
                self._observe_gauges()
                return out
            wm = self.watermark_ms
            if wm > WM_MIN_MS and ts < wm:
                # block's forced release raised the floor past the
                # ARRIVING record (it was older than the key's whole
                # buffer): admitting it now would release behind the
                # forced-out record out of event-time order -- it is
                # late, by the documented "stragglers behind the forced
                # mark go late" contract.
                out = self._release_upto(wm)  # ship the forced release
                out.extend(self._late_record(event))
                self._observe_gauges()
                return out
        self._observe_event_time(ts, source)
        buf.push(event, self._seq)
        if self._min_head is None or ts < self._min_head:
            self._min_head = ts
        self._seq += 1
        self._occupancy += 1
        out = self._release_upto(self.watermark_ms)
        self._observe_gauges()
        return out

    def _observe_event_time(self, ts: int, source: Any) -> None:
        self.generator.observe(ts, source)
        if ts > self._max_seen:
            self._max_seen = ts

    def offer_batch(
        self, events: List[Event], source: Any = None
    ) -> List[Tuple[Event, int]]:
        """Amortized admission for one ingest chunk (the driver/bench fast
        path): one watermark read and one generator observation per
        (chunk, source) instead of per record.

        Semantics vs. per-record `offer()`: lateness is checked against
        the watermark at CHUNK START (a record made late only by a
        later record in the same chunk still admits -- strictly more
        permissive, never lossier), and the shipped generators all track
        a per-source max, so observing the chunk max is equivalent to
        observing every record. Overflow still runs the per-record policy
        path inline; with a fault injector armed the whole chunk falls
        back to per-record offer() (the `time.reorder_overflow` hit
        counts are per-admission by contract)."""
        if not events:
            return []
        if _flt.ACTIVE is not None:
            out: List[Tuple[Event, int]] = []
            for e in events:
                out.extend(self.offer(e, source=source))
            return out
        wm0 = self.watermark_ms
        admit: List[Event] = []
        max_ts = WM_MIN_MS
        late: List[Event] = []
        for e in events:
            ts = e.timestamp
            if wm0 > WM_MIN_MS and ts < wm0:
                late.append(e)
                continue
            admit.append(e)
            if ts > max_ts:
                max_ts = ts
        if self.on_overflow == "raise" and admit:
            # Chunk-ATOMIC admission under "raise": check capacity before
            # ANY mutation (late-record side effects included), so the
            # escalation leaves the gate untouched and the caller can
            # retry the whole chunk without duplicating releases or
            # losing already-staged late admissions. Conservative: a
            # release mid-chunk could have freed space; the retry after a
            # drain will see it.
            per_key: Dict[Any, int] = {}
            for e in admit:
                per_key[e.key] = per_key.get(e.key, 0) + 1
            for k, n in per_key.items():
                have = len(self._buffers[k]) if k in self._buffers else 0
                if have + n > self.capacity:
                    raise CEPOverflowError(
                        f"reorder buffer would overflow for key {k!r} "
                        f"({have} buffered + {n} arriving > capacity "
                        f"{self.capacity}; policy 'raise' -- raise "
                        "EngineConfig.reorder_capacity or drain faster)"
                    )
        out: List[Tuple[Event, int]] = []
        for e in late:
            out.extend(self._late_record(e))
        if admit:
            # One observation per (chunk, SOURCE) -- attributing a mixed-
            # source chunk's max to a single source would advance a
            # min-merge watermark past the slow sources and wrongly drop
            # their in-bound records as late.
            per_src: Dict[Any, int] = {}
            for e in admit:
                src = source if source is not None else (
                    e.topic, e.partition
                )
                prev = per_src.get(src)
                if prev is None or e.timestamp > prev:
                    per_src[src] = int(e.timestamp)
            for src, m in per_src.items():
                self.generator.observe(m, src)
            if max_ts > self._max_seen:
                self._max_seen = int(max_ts)
            for e in admit:
                buf = self._buffers.get(e.key)
                if buf is None:
                    buf = self._buffers[e.key] = ReorderBuffer(self.capacity)
                if buf.full:
                    if not self._overflow(buf, e):
                        continue
                    wm2 = self.watermark_ms
                    if wm2 > WM_MIN_MS and e.timestamp < wm2:
                        # block's forced release raised the floor past
                        # this record: it is late NOW (see offer()).
                        out.extend(self._late_record(e))
                        continue
                buf.push(e, self._seq)
                if self._min_head is None or e.timestamp < self._min_head:
                    self._min_head = int(e.timestamp)
                self._seq += 1
                self._occupancy += 1
        out.extend(self._release_upto(self.watermark_ms))
        self._observe_gauges()
        return out

    def advance_wall(self, now_ms: int) -> List[Tuple[Event, int]]:
        """Wall-clock tick (driver poll cadence): idle-timeout generators
        may advance the watermark with no record arriving; release what
        it passed."""
        self.generator.advance_wall(int(now_ms))
        out = self._release_upto(self.watermark_ms)
        self._observe_gauges()
        return out

    def flush(self) -> List[Tuple[Event, int]]:
        """End-of-stream: release every buffered record in event-time
        order (the watermark is moot -- nothing else is coming)."""
        out: List[Tuple[Event, int]] = []
        if self._pending_forced:
            out.extend(self._pending_forced)
            self._pending_forced.clear()
        entries = []
        for key, buf in self._buffers.items():
            entries.extend(buf.drain())
        entries.sort(key=lambda se: (se[1].timestamp, se[0]))
        self._occupancy = 0
        self._min_head = None
        out.extend(self._emit(ev) for _seq, ev in entries)
        self._observe_gauges()
        return out

    def take_late(self) -> List[Event]:
        """Drain the late side output (late_policy=sideoutput)."""
        out, self._late = self._late, []
        return out

    # --------------------------------------------------------- checkpointing
    def snapshot_state(self) -> Dict[str, Any]:
        """Plain-dict state for state/serde.encode_event_time_state."""
        return {
            "gen_kind": self.generator.kind,
            "gen_state": self.generator.state(),
            "clocks": dict(self._clocks),
            "forced_wm": self._forced_wm,
            "max_seen": self._max_seen,
            "seq": self._seq,
            "buffers": {
                key: buf.entries() for key, buf in self._buffers.items()
            },
            "late": list(self._late),
        }

    def restore_state(self, state: Dict[str, Any]) -> None:
        """Inverse of snapshot_state (generator kind must match the
        configured generator -- the pattern/config is never serialized,
        mirroring the engine checkpoint contract)."""
        kind = state["gen_kind"]
        if kind != self.generator.kind:
            raise ValueError(
                f"checkpoint watermark generator {kind!r} != configured "
                f"{self.generator.kind!r}; rebuild the gate with the "
                "matching generator before restoring"
            )
        self.generator.restore(state["gen_state"])
        self._clocks = dict(state["clocks"])
        self._forced_wm = int(state["forced_wm"])
        self._max_seen = int(state["max_seen"])
        self._seq = int(state["seq"])
        self._buffers = {}
        self._occupancy = 0
        self._min_head = None
        for key, entries in state["buffers"].items():
            buf = self._buffers[key] = ReorderBuffer(self.capacity)
            for ts, seq, ev in entries:
                buf.push(ev, seq)
                if self._min_head is None or ts < self._min_head:
                    self._min_head = int(ts)
                self._occupancy += 1
        self._late = list(state["late"])
        self._observe_gauges()

    # ------------------------------------------------------------ internals
    def _late_record(self, event: Event) -> List[Tuple[Event, int]]:
        if self.late_policy == "drop":
            self._m_late_dropped.inc()
            return []
        if self.late_policy == "sideoutput":
            self._m_late_side.inc()
            self._late.append(event)
            return []
        # recompute-none: admit as-is at the key's CURRENT clock (clamped
        # to the watermark) so the engine's expiry clock never rewinds --
        # no retraction of already-expired windows.
        self._m_late_admitted.inc()
        self._m_released.inc()
        clk = max(
            self._clocks.get(event.key, WM_MIN_MS), self.watermark_ms
        )
        self._clocks[event.key] = clk
        return [(event, clk)]

    def _overflow(self, buf: ReorderBuffer, event: Event) -> bool:
        """Apply the overflow policy; True = admit the incoming record."""
        if self.on_overflow == "raise":
            raise CEPOverflowError(
                f"reorder buffer full for key {event.key!r} "
                f"(capacity {self.capacity}; policy 'raise' -- raise "
                "EngineConfig.reorder_capacity or drain faster)"
            )
        if self.on_overflow == "block":
            # Backpressure: force the key's oldest record out NOW. The
            # forced watermark floor makes any later record older than it
            # late -- loud, ordered, nothing lost.
            if len(buf):
                ts, _seq, oldest = buf.pop_oldest()
                self._occupancy -= 1
                self._m_backpressure.inc()
                self._forced_wm = max(self._forced_wm, ts)
                self._pending_forced.append(self._emit(oldest))
            return True
        # drop: the incoming record is lost, loudly.
        self._m_overflow_dropped.inc()
        return False

    def _release_upto(self, watermark_ms: int) -> List[Tuple[Event, int]]:
        out: List[Tuple[Event, int]] = []
        if self._pending_forced:
            out.extend(self._pending_forced)
            self._pending_forced.clear()
        if watermark_ms == WM_MIN_MS or self._occupancy == 0:
            return out
        if self._min_head is not None and watermark_ms < self._min_head:
            # Nothing buffered is at or below the watermark: the shared-
            # gate hot path stays O(1) per record instead of scanning
            # every key's buffer per offer.
            return out
        entries: List[Tuple[int, Event]] = []
        for buf in self._buffers.values():
            got = buf.release(watermark_ms)
            entries.extend(got)
        heads = [
            h for h in (b.peek_ts() for b in self._buffers.values())
            if h is not None
        ]
        self._min_head = min(heads) if heads else None
        if entries:
            self._occupancy -= len(entries)
            entries.sort(key=lambda se: (se[1].timestamp, se[0]))
            out.extend(self._emit(ev) for _seq, ev in entries)
        return out

    def _emit(self, event: Event) -> Tuple[Event, int]:
        ts = int(event.timestamp)
        clk = max(self._clocks.get(event.key, WM_MIN_MS), ts)
        self._clocks[event.key] = clk
        self._m_released.inc()
        return (event, clk)

    def _observe_gauges(self) -> None:
        self._m_occupancy.set(self._occupancy)
        if self._max_seen > WM_MIN_MS:
            wm = self.watermark_ms
            lag = (self._max_seen - wm) / 1000.0 if wm > WM_MIN_MS else 0.0
            self._m_lag.set(max(lag, 0.0))
