"""recompile: jit-cache hazards in the builder layer.

SOAK_r01's churn-recompile RSS leak (358 MB -> 1.3 GB over 240 s) was a
whole class of bug: a jit cache that cannot stay warm across identical
shapes. The static half of the gate flags the constructs that produce
that class; the runtime half (analysis/jit_audit.py) replays a
same-shape churn epoch and asserts ``cep_compiles_total{fn}`` stays
flat.

Findings:
    CEP-R01  jax.jit inside a for/while loop body -- a fresh cache per
             iteration, nothing ever warm
    CEP-R02  jax.jit inside a hot-path function -- a fresh cache per
             call on the advance path
    CEP-R03  mutable/unhashable static arg: static_argnums/argnames
             naming a parameter with a mutable default, or a package
             call site passing a list/dict/set for a static parameter
    CEP-R04  jitted closure over mutable state: the traced inner
             function reads ``self.X`` or a module-level mutable --
             mutation after the first trace silently never retraces
    CEP-R05  closure capture rebound after the jit wrap in the same
             builder -- the trace keeps the old binding

Builders are ``build_*`` functions (the repo convention, also the hot
set in zerosync.HOT_PATHS); CEP-R04/R05 apply inside any function that
wraps an inner def with jit. Audited sites carry
``# cep: static-ok(reason)``.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, SourceFile, dotted_name as _dotted
from .zerosync import function_index, hot_functions


def _is_jit(node: ast.AST) -> bool:
    dotted = _dotted(node)
    return dotted in ("jax.jit", "jit") or (
        dotted is not None and dotted.endswith(".jit")
    )


def _jit_calls(tree: ast.AST):
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_jit(node.func):
            yield node


def _mutable_display(node: ast.AST) -> bool:
    return isinstance(
        node, (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
               ast.SetComp)
    )


# ---------------------------------------------------------------------------
# module-level mutable globals (for CEP-R04)
# ---------------------------------------------------------------------------
def _mutable_globals(tree: ast.Module) -> Set[str]:
    out: Set[str] = set()
    for node in tree.body:
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = node.targets
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
            value = node.value
        else:
            continue
        if _mutable_display(value):
            for t in targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


# ---------------------------------------------------------------------------
# per-function analysis
# ---------------------------------------------------------------------------
def _local_names(fn: ast.AST) -> Set[str]:
    """Names bound in `fn`'s own scope (params, assignments, defs)."""
    out: Set[str] = set()
    args = fn.args
    for a in (
        list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
    ):
        out.add(a.arg)
    if args.vararg:
        out.add(args.vararg.arg)
    if args.kwarg:
        out.add(args.kwarg.arg)
    for node in ast.walk(fn):
        if node is fn:
            continue
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.add(node.name)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                for leaf in ast.walk(t):
                    if isinstance(leaf, ast.Name):
                        out.add(leaf.id)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            for leaf in ast.walk(node.target):
                if isinstance(leaf, ast.Name):
                    out.add(leaf.id)
        elif isinstance(node, (ast.For, ast.comprehension)):
            tgt = node.target
            for leaf in ast.walk(tgt):
                if isinstance(leaf, ast.Name):
                    out.add(leaf.id)
    return out


def _inner_defs(fn: ast.AST) -> Dict[str, ast.AST]:
    return {
        n.name: n
        for n in ast.walk(fn)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        and n is not fn
    }


def _jitted_inner_fns(fn: ast.AST) -> List[Tuple[ast.AST, int]]:
    """Inner defs wrapped by jit within `fn`: @jax.jit decorated, or
    referenced by name in a jax.jit(...) call. Returns (def, jit line)."""
    inner = _inner_defs(fn)
    out: List[Tuple[ast.AST, int]] = []
    for name, node in inner.items():
        for deco in node.decorator_list:
            target = deco.func if isinstance(deco, ast.Call) else deco
            if _is_jit(target):
                out.append((node, deco.lineno))
    for call in _jit_calls(fn):
        for arg in call.args[:1]:
            if isinstance(arg, ast.Name) and arg.id in inner:
                out.append((inner[arg.id], call.lineno))
    return out


def _check_static_args(
    src: SourceFile, fn_index: Dict[str, ast.AST], files_calls
) -> List[Finding]:
    """CEP-R03: static_argnums/static_argnames hazards."""
    findings: List[Finding] = []
    inner_by_name = {}
    for qual, fn in fn_index.items():
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                inner_by_name[node.name] = node
    for call in _jit_calls(src.tree):
        static_names: List[str] = []
        static_nums: List[int] = []
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                for leaf in ast.walk(kw.value):
                    if isinstance(leaf, ast.Constant) and isinstance(
                        leaf.value, str
                    ):
                        static_names.append(leaf.value)
            elif kw.arg == "static_argnums":
                for leaf in ast.walk(kw.value):
                    if isinstance(leaf, ast.Constant) and isinstance(
                        leaf.value, int
                    ):
                        static_nums.append(leaf.value)
        if not static_names and not static_nums:
            continue
        target = call.args[0] if call.args else None
        target_def = None
        if isinstance(target, ast.Name):
            target_def = inner_by_name.get(target.id)
        elif isinstance(target, (ast.FunctionDef,)):  # pragma: no cover
            target_def = target
        if target_def is None:
            continue
        args = target_def.args
        params = list(args.posonlyargs) + list(args.args)
        defaults = list(args.defaults)
        # defaults align to the tail of params
        by_name = {p.arg: i for i, p in enumerate(params)}
        static_idx = set(static_nums)
        static_idx.update(
            by_name[n] for n in static_names if n in by_name
        )
        for i in sorted(static_idx):
            di = i - (len(params) - len(defaults))
            if 0 <= di < len(defaults) and _mutable_display(defaults[di]):
                findings.append(
                    Finding(
                        "recompile", "CEP-R03", src.relpath, call.lineno,
                        f"static arg {params[i].arg!r} of jitted "
                        f"{target_def.name!r} has a mutable default -- "
                        "unhashable statics retrace (or raise) per call",
                        context=src.context_line(call.lineno),
                    )
                )
        # package call sites passing mutable displays for static params
        for csrc, ccall in files_calls:
            fname = _dotted(ccall.func) or ""
            if fname.split(".")[-1] != target_def.name:
                continue
            for i in sorted(static_idx):
                if i < len(ccall.args) and _mutable_display(ccall.args[i]):
                    findings.append(
                        Finding(
                            "recompile", "CEP-R03", csrc.relpath,
                            ccall.lineno,
                            f"call passes a mutable display for static "
                            f"arg {params[i].arg!r} of jitted "
                            f"{target_def.name!r} -- unhashable statics "
                            "retrace (or raise) per call",
                            context=csrc.context_line(ccall.lineno),
                        )
                    )
            for kw in ccall.keywords:
                if kw.arg in static_names and _mutable_display(kw.value):
                    findings.append(
                        Finding(
                            "recompile", "CEP-R03", csrc.relpath,
                            ccall.lineno,
                            f"call passes a mutable display for static "
                            f"arg {kw.arg!r} of jitted "
                            f"{target_def.name!r}",
                            context=csrc.context_line(ccall.lineno),
                        )
                    )
    return findings


def check(files: Sequence[SourceFile], root_dir: str) -> List[Finding]:
    findings: List[Finding] = []
    all_calls = [
        (src, node)
        for src in files
        for node in ast.walk(src.tree)
        if isinstance(node, ast.Call)
    ]
    for src in files:
        # Most modules never touch jax.jit; one cheap walk skips them.
        if not any(
            isinstance(n, ast.Call) and _is_jit(n.func)
            for n in ast.walk(src.tree)
        ):
            continue
        fn_index = function_index(src)
        mutable_globals = _mutable_globals(src.tree)
        hot_roots, _stale = hot_functions(src)
        findings.extend(_check_static_args(src, fn_index, all_calls))

        # ------------------------------------------------- R01: jit in a loop
        class _LoopJit(ast.NodeVisitor):
            def __init__(self) -> None:
                self.depth = 0
                self.qual: List[str] = []

            def _fn(self, node):
                self.qual.append(node.name)
                depth, self.depth = self.depth, 0
                self.generic_visit(node)
                self.depth = depth
                self.qual.pop()

            visit_FunctionDef = _fn
            visit_AsyncFunctionDef = _fn

            def visit_ClassDef(self, node):
                self.qual.append(node.name)
                self.generic_visit(node)
                self.qual.pop()

            def _loop(self, node):
                self.depth += 1
                self.generic_visit(node)
                self.depth -= 1

            visit_For = _loop
            visit_While = _loop

            def visit_Call(self, node):
                if _is_jit(node.func) and self.depth > 0:
                    findings.append(
                        Finding(
                            "recompile", "CEP-R01", src.relpath,
                            node.lineno,
                            "jax.jit inside a loop in "
                            f"{'.'.join(self.qual) or '<module>'}: a fresh "
                            "jit cache per iteration never stays warm",
                            context=src.context_line(node.lineno),
                        )
                    )
                self.generic_visit(node)

        _LoopJit().visit(src.tree)

        # ------------------------------------------------ R02: jit in hot path
        # Builders (build_*) are the sanctioned construction points --
        # called once per engine, not per advance (the jit-cache audit
        # catches a builder that churns at runtime). A jit under an
        # ``if <attr> is None`` memo guard is one-time by construction.
        for qual, fn in hot_roots.items():
            if qual.rsplit(".", 1)[-1].startswith("build_"):
                continue
            memo_guarded: Set[int] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.If) and (
                    isinstance(node.test, ast.Compare)
                    and any(
                        isinstance(op, ast.Is) for op in node.test.ops
                    )
                    and any(
                        isinstance(c, ast.Constant) and c.value is None
                        for c in node.test.comparators
                    )
                ):
                    for sub in ast.walk(node):
                        memo_guarded.add(id(sub))
            for node in ast.walk(fn):
                if (
                    isinstance(node, ast.Call)
                    and _is_jit(node.func)
                    and id(node) not in memo_guarded
                ):
                    findings.append(
                        Finding(
                            "recompile", "CEP-R02", src.relpath, node.lineno,
                            f"jax.jit constructed inside hot-path {qual}: "
                            "a fresh cache per call on the advance path",
                            context=src.context_line(node.lineno),
                        )
                    )

        # -------------------------------------- R04/R05: closure captures
        # fn_index carries nested defs as their own entries; the seen set
        # keeps a deeply-nested jitted fn from double-reporting through
        # every enclosing level.
        seen_inner: Set[Tuple[int, int]] = set()
        for qual, fn in fn_index.items():
            jitted = [
                (inner, line)
                for inner, line in _jitted_inner_fns(fn)
                if (inner.lineno, line) not in seen_inner
            ]
            if not jitted:
                continue
            seen_inner.update((inner.lineno, line) for inner, line in jitted)
            builder_locals = _local_names(fn)
            for inner, jit_line in jitted:
                inner_locals = _local_names(inner)
                reads_self = any(
                    isinstance(n, ast.Name) and n.id == "self"
                    for n in ast.walk(inner)
                )
                if reads_self and "self" not in inner_locals:
                    findings.append(
                        Finding(
                            "recompile", "CEP-R04", src.relpath,
                            inner.lineno,
                            f"jitted {qual}.{inner.name} closes over self: "
                            "instance state is baked into the trace and "
                            "mutation never retraces",
                            context=src.context_line(inner.lineno),
                        )
                    )
                captured_globals = {
                    n.id
                    for n in ast.walk(inner)
                    if isinstance(n, ast.Name)
                    and isinstance(n.ctx, ast.Load)
                    and n.id in mutable_globals
                    and n.id not in inner_locals
                    and n.id not in builder_locals
                }
                for name in sorted(captured_globals):
                    findings.append(
                        Finding(
                            "recompile", "CEP-R04", src.relpath,
                            inner.lineno,
                            f"jitted {qual}.{inner.name} closes over "
                            f"module-level mutable {name!r}: mutation "
                            "after the first trace never retraces",
                            context=src.context_line(inner.lineno),
                        )
                    )
                # R05: capture rebound after the jit wrap
                captured = {
                    n.id
                    for n in ast.walk(inner)
                    if isinstance(n, ast.Name)
                    and isinstance(n.ctx, ast.Load)
                    and n.id in builder_locals
                    and n.id not in inner_locals
                }
                for node in ast.walk(fn):
                    if (
                        isinstance(node, (ast.Assign, ast.AugAssign))
                        and node.lineno > jit_line
                    ):
                        targets = (
                            node.targets
                            if isinstance(node, ast.Assign)
                            else [node.target]
                        )
                        for t in targets:
                            if (
                                isinstance(t, ast.Name)
                                and t.id in captured
                            ):
                                findings.append(
                                    Finding(
                                        "recompile", "CEP-R05",
                                        src.relpath, node.lineno,
                                        f"{t.id!r} is captured by jitted "
                                        f"{qual}.{inner.name} but rebound "
                                        "after the jit wrap -- the trace "
                                        "keeps the old binding",
                                        context=src.context_line(
                                            node.lineno
                                        ),
                                    )
                                )
    return findings
