"""Per-record CEP processor: the host-path stream driver.

Re-design of the reference processor
(reference: core/.../cep/processor/CEPProcessor.java:45-171). Per record it
loads (or creates) the key's NFA from the states store, applies the
high-water-mark idempotence check (skip records whose offset is below the
persisted offset for their topic), runs the match loop, persists the updated
snapshot, and forwards each completed Sequence downstream.

The TPU path replaces the inner `nfa.match_pattern` call with the
micro-batched device engine while keeping this store/HWM contract
(ops/engine.py, streams/device_processor.py).
"""
from __future__ import annotations

from typing import Any, Callable, Generic, List, Optional, Tuple, TypeVar

from ..core.event import Event
from ..core.sequence import Sequence
from ..nfa.nfa import NFA, initial_computation_stage
from ..pattern.compiler import ensure_stages
from ..pattern.stages import Stages
from ..state.aggregates import AggregatesStore
from ..state.buffer import BufferStore
from ..state.naming import normalize_query_name
from ..state.nfa_store import NFAStates, NFAStore

K = TypeVar("K")
V = TypeVar("V")


class CEPProcessor(Generic[K, V]):
    """Host per-record driver bound to the three query stores."""

    def __init__(
        self,
        query_name: str,
        pattern_or_stages: Any,
        nfa_store: Optional[NFAStore] = None,
        buffer: Optional[BufferStore] = None,
        aggregates: Optional[AggregatesStore] = None,
        strict_windows: bool = False,
        registry: Optional[Any] = None,
    ) -> None:
        from ..obs.registry import default_registry

        self.stages: Stages = ensure_stages(pattern_or_stages)
        self.query_name = normalize_query_name(query_name)
        self.nfa_store = nfa_store if nfa_store is not None else NFAStore()
        self.buffer = buffer if buffer is not None else BufferStore()
        self.aggregates = aggregates if aggregates is not None else AggregatesStore()
        # See NFA(strict_windows=...): False = reference window parity,
        # True = epsilon stages inherit windows (bounded-memory mode).
        self.strict_windows = strict_windows
        # Per-query stream counters (labels bounded by the query count):
        # the always-on host-path telemetry, in the process default
        # registry unless one is passed.
        self.metrics = registry if registry is not None else default_registry()
        # Children bound once: labels() takes a lock per resolution, and
        # this is the per-record hot path (also the vs_baseline denominator).
        self._m_records = self.metrics.counter(
            "cep_processor_records_total",
            "Records processed by the host per-record driver",
            labels=("query",),
        ).labels(query=self.query_name)
        self._m_matches = self.metrics.counter(
            "cep_processor_matches_total",
            "Completed sequences emitted by the host per-record driver",
            labels=("query",),
        ).labels(query=self.query_name)
        self._m_skipped = self.metrics.counter(
            "cep_processor_skipped_total",
            "Records skipped below the high-water mark (at-least-once dedup)",
            labels=("query",),
        ).labels(query=self.query_name)
        self._m_errors = self.metrics.counter(
            "cep_processor_errors_total",
            "Records whose match loop raised (user predicate/fold errors; "
            "the driver quarantines them to the DLQ)",
            labels=("query",),
        ).labels(query=self.query_name)

    def _load_nfa(self, key: K) -> Tuple[NFA, NFAStates]:
        snapshot = self.nfa_store.find(key)
        key_buffer = self.buffer.for_key(key)
        if snapshot is not None:
            nfa = NFA(
                self.aggregates,
                key_buffer,
                self.stages.defined_states(),
                snapshot.computation_stages,
                snapshot.runs,
                strict_windows=self.strict_windows,
            )
            return nfa, snapshot
        nfa = NFA.build(
            self.stages, self.aggregates, key_buffer,
            strict_windows=self.strict_windows,
        )
        return nfa, NFAStates(list(nfa.computation_stages), nfa.runs)

    def process(
        self,
        key: K,
        value: V,
        timestamp: int = 0,
        topic: str = "",
        partition: int = 0,
        offset: int = 0,
    ) -> List[Sequence[K, V]]:
        """Process one record; returns completed matches for this key."""
        if key is None or value is None:
            return []
        nfa, snapshot = self._load_nfa(key)

        # The reference keys the HWM by topic only because each of its
        # processor tasks owns exactly one partition; here one processor may
        # see every partition, so the mark is per (topic, partition).
        hwm_key = f"{topic}#{partition}"
        latest = snapshot.latest_offset_for_topic(hwm_key)
        if latest is not None and offset < latest:
            # Replayed record below the high-water mark: at-least-once dedup.
            self._m_skipped.inc()
            return []

        event = Event(key, value, timestamp, topic, partition, offset)
        try:
            sequences = nfa.match_pattern(event)
        except Exception:
            # A raising user predicate/fold is poison, not a pipeline bug:
            # count it here (per query) and let the driver quarantine the
            # record to the DLQ with the pump still advancing. The key's
            # stored snapshot is untouched (it persists below only on
            # success), so the next record resumes from pre-poison state.
            self._m_errors.inc()
            raise
        self._m_records.inc()
        if sequences:
            self._m_matches.inc(len(sequences))

        offsets = dict(snapshot.latest_offsets)
        offsets[hwm_key] = offset + 1
        self.nfa_store.put(
            key, NFAStates(list(nfa.computation_stages), nfa.runs, offsets)
        )
        # Re-put the key's buffer so a change-logging backing captures this
        # record's in-place chain mutations (CEPProcessor.java:144-147
        # persists all three stores every record).
        self.buffer.persist(key)
        return sequences

    # --------------------------------------------------------- checkpointing
    def snapshot(self) -> bytes:
        """Bytes-level checkpoint of the query's three stores (the changelog
        write, reference: CEPProcessor.java:144-147 + store serdes)."""
        from ..state.serde import CheckpointCodec

        codec = CheckpointCodec(self.stages, strict_windows=self.strict_windows)
        return codec.encode_query_stores(
            self.nfa_store, self.buffer, self.aggregates
        )

    @classmethod
    def restore(
        cls,
        query_name: str,
        pattern_or_stages: Any,
        data: bytes,
        strict_windows: bool = False,
    ) -> "CEPProcessor":
        """Rebuild a processor from `snapshot()` bytes in a fresh object
        graph: the pattern is recompiled and run-queue stages re-linked by
        id (ComputationStageSerde.java:56-101)."""
        from ..state.serde import CheckpointCodec

        proc = cls(query_name, pattern_or_stages, strict_windows=strict_windows)
        codec = CheckpointCodec(proc.stages, strict_windows=strict_windows)
        nfa_store, buffers, aggregates = codec.decode_query_stores(data)
        proc.nfa_store = nfa_store
        proc.buffer = buffers
        proc.aggregates = aggregates
        return proc
