"""Transport + durability stack: RecordLog, store wrappers/builders, LogDriver.

Covers the reference's L0 contract the framework owes
(reference: README.md:350-355 changelog naming,
AbstractStoreBuilder.java:52-71 durability toggles,
WrappedStateStore.java:25-75 delegation, and the Kafka Streams
poll/process/commit/restore loop around CEPProcessor.java:111-160):
append/read semantics, changelog capture + replay, caching flush batching,
file-backed recovery, and end-to-end crash/resume through the LogDriver with
matches identical to an unbroken run.
"""
from __future__ import annotations

import json

import pytest

from kafkastreams_cep_tpu import (
    ComplexStreamsBuilder,
    LogDriver,
    QueryBuilder,
    RecordLog,
    produce,
)
from kafkastreams_cep_tpu.state.builders import (
    QueryStoreBuilders,
    changelog_topic,
    restore_store,
)
from kafkastreams_cep_tpu.state.store import (
    CachingKeyValueStore,
    ChangeLoggingKeyValueStore,
    InMemoryKeyValueStore,
    WrappedStateStore,
)
from kafkastreams_cep_tpu.streams.driver import OFFSETS_TOPIC


def letters_pattern():
    return (
        QueryBuilder()
        .select("select-A").where(lambda e, s: e.value == "A")
        .then().select("select-B").where(lambda e, s: e.value == "B")
        .then().select("select-C").where(lambda e, s: e.value == "C")
        .build()
    )


# ---------------------------------------------------------------- RecordLog
def test_record_log_append_read_in_memory():
    log = RecordLog()
    assert log.append("t", b"k1", b"v1", timestamp=5) == 0
    assert log.append("t", b"k2", None) == 1  # tombstone
    assert log.append("t", b"k3", b"v3", partition=2) == 0
    recs = log.read("t")
    assert [(r.offset, r.key, r.value, r.timestamp) for r in recs] == [
        (0, b"k1", b"v1", 5),
        (1, b"k2", None, 0),
    ]
    assert log.read("t", partition=2)[0].value == b"v3"
    assert log.end_offset("t") == 2
    assert log.partitions("t") == [0, 2]
    assert log.read("t", start=1) == recs[1:]
    assert log.read("t", start=0, max_records=1) == recs[:1]


def test_record_log_file_backed_reload(tmp_path):
    path = str(tmp_path / "log")
    log = RecordLog(path)
    log.append("topic/a", b"k", b"v", timestamp=9)
    log.append("topic/a", None, None)
    log.append("other", b"x", b"y")
    log.close()

    reloaded = RecordLog(path)
    recs = reloaded.read("topic/a")
    assert [(r.key, r.value, r.timestamp) for r in recs] == [
        (b"k", b"v", 9),
        (None, None, 0),
    ]
    assert reloaded.read("other")[0].value == b"y"
    # Appends continue at the right offset after reload.
    assert reloaded.append("topic/a", b"k2", b"v2") == 2
    reloaded.close()


def test_record_log_torn_tail_recovers(tmp_path):
    """A crash mid-append leaves a torn frame; reopen must drop exactly the
    torn tail, keep every complete record, and accept new appends."""
    path = str(tmp_path / "log")
    log = RecordLog(path)
    log.append("t", b"k1", b"v1")
    log.append("t", b"k2", b"v2")
    log.close()
    fname = [f for f in __import__("os").listdir(path) if f.endswith(".log")][0]
    with open(f"{path}/{fname}", "ab") as f:
        f.write(b"\x00\x07\x00\x00")  # header fragment: torn mid-append

    reopened = RecordLog(path)
    recs = reopened.read("t")
    assert [(r.key, r.value) for r in recs] == [(b"k1", b"v1"), (b"k2", b"v2")]
    assert reopened.append("t", b"k3", b"v3") == 2
    reopened.close()
    # And the reopened-again log sees all three complete records.
    final = RecordLog(path)
    assert [r.key for r in final.read("t")] == [b"k1", b"k2", b"k3"]
    final.close()


# ------------------------------------------------------------ store wrappers
def test_wrapped_store_delegation_and_unwrap():
    inner = InMemoryKeyValueStore("s")
    wrapped = WrappedStateStore(inner)
    wrapped.put("a", 1)
    assert inner.get("a") == 1
    assert wrapped.get("a") == 1
    assert wrapped.approximate_num_entries() == 1
    assert wrapped.delete("a") == 1
    assert inner.get("a") is None
    outer = WrappedStateStore(wrapped)
    assert outer.unwrap() is inner


def test_change_logging_store_appends_and_restores():
    log = RecordLog()
    store = ChangeLoggingKeyValueStore(InMemoryKeyValueStore("s"), log, "s-changelog")
    store.put("a", 1)
    store.put("a", 2)
    store.put("b", 3)
    store.delete("b")
    assert log.end_offset("s-changelog") == 4

    fresh = ChangeLoggingKeyValueStore(
        InMemoryKeyValueStore("s"), log, "s-changelog"
    )
    assert fresh.restore() == 4
    assert fresh.get("a") == 2
    assert fresh.get("b") is None
    # Restore itself must not have re-appended.
    assert log.end_offset("s-changelog") == 4


def test_caching_store_batches_changelog_until_flush():
    log = RecordLog()
    logged = ChangeLoggingKeyValueStore(InMemoryKeyValueStore("s"), log, "cl")
    cached = CachingKeyValueStore(logged)
    cached.put("a", 1)
    cached.put("a", 2)
    cached.put("b", 5)
    cached.delete("b")
    assert log.end_offset("cl") == 0  # nothing pushed down yet
    assert cached.get("a") == 2
    assert cached.get("b") is None
    assert dict(cached.items()) == {"a": 2}
    cached.flush()
    # One changelog record per dirty key, not per write.
    assert log.end_offset("cl") == 2
    assert logged.get("a") == 2


# ------------------------------------------------------------ store builders
def test_query_store_builders_toggles_and_naming():
    qsb = QueryStoreBuilders("My Query", letters_pattern())
    assert qsb.nfa.name == "myquery-streamscep-states"
    assert qsb.buffer.name == "myquery-streamscep-matched"
    assert qsb.aggregates.name == "myquery-streamscep-aggregates"
    assert changelog_topic("app1", qsb.nfa.name) == (
        "app1-myquery-streamscep-states-changelog"
    )

    log = RecordLog()
    # Logging on (default): the KV stack carries a changelog layer.
    nfa_store = qsb.nfa.build(log, app_id="app1")
    assert isinstance(nfa_store._kv, ChangeLoggingKeyValueStore)
    # Logging off: plain memory store.
    qsb.nfa.with_logging_disabled()
    assert isinstance(qsb.nfa.build(log)._kv, InMemoryKeyValueStore)
    # Caching wraps outermost.
    qsb.nfa.with_logging_enabled().with_caching_enabled()
    stack = qsb.nfa.build(log)._kv
    assert isinstance(stack, CachingKeyValueStore)
    assert isinstance(stack.inner, ChangeLoggingKeyValueStore)


def test_store_changelog_roundtrip_via_processor():
    """Process through change-logged stores, replay the changelog into fresh
    stores, and verify the restored processor continues correctly."""
    from kafkastreams_cep_tpu import CEPProcessor

    log = RecordLog()
    qsb = QueryStoreBuilders("q", letters_pattern())
    stores = qsb.build_all(log, app_id="a")
    proc = CEPProcessor(
        "q",
        qsb.stages,
        nfa_store=stores[qsb.nfa.name],
        buffer=stores[qsb.buffer.name],
        aggregates=stores[qsb.aggregates.name],
    )
    for i, ch in enumerate("AB"):
        assert proc.process("K", ch, timestamp=i, topic="t", offset=i) == []

    # Fresh stores restored purely from the changelog.
    qsb2 = QueryStoreBuilders("q", letters_pattern())
    stores2 = qsb2.build_all(log, app_id="a")
    assert sum(restore_store(s) for s in stores2.values()) > 0
    proc2 = CEPProcessor(
        "q",
        qsb2.stages,
        nfa_store=stores2[qsb2.nfa.name],
        buffer=stores2[qsb2.buffer.name],
        aggregates=stores2[qsb2.aggregates.name],
    )
    matches = proc2.process("K", "C", timestamp=2, topic="t", offset=2)
    assert len(matches) == 1
    staged = matches[0].matched
    assert [s.stage for s in staged] == ["select-A", "select-B", "select-C"]
    assert [e.value for s in staged for e in s.events] == ["A", "B", "C"]


# ------------------------------------------------------------------- driver
def _build_topology(log):
    builder = ComplexStreamsBuilder(log=log, app_id="demo")
    out = builder.stream("letters").query("q", letters_pattern()).to("matches")
    topo = builder.build()
    return topo, out


def test_log_driver_end_to_end_with_sink():
    log = RecordLog()
    for i, ch in enumerate("XABC"):
        produce(log, "letters", "K", ch, timestamp=i)
    topo, out = _build_topology(log)
    driver = LogDriver(topo, group="g1")
    assert driver.poll() == 4
    assert len(out.records) == 1
    # Sink topic got the golden JSON shape.
    sunk = log.read("matches")
    assert len(sunk) == 1
    payload = json.loads(sunk[0].value.decode("utf-8"))
    assert payload == {
        "events": [
            {"name": "select-A", "events": ["A"]},
            {"name": "select-B", "events": ["B"]},
            {"name": "select-C", "events": ["C"]},
        ]
    }
    # Offsets committed; a second poll consumes nothing.
    assert driver.poll() == 0
    assert driver.position("letters") == 4


def test_log_driver_crash_resume_matches_unbroken_run(tmp_path):
    """Half the stream, 'crash' (drop every object), rebuild from the
    file-backed log, finish: matches equal the unbroken run."""
    stream = "ABACBABCAC"

    # Unbroken run for the expected match count.
    mem = RecordLog()
    for i, ch in enumerate(stream):
        produce(mem, "letters", "K", ch, timestamp=i)
    topo_u, out_u = _build_topology(mem)
    LogDriver(topo_u, group="g").poll()
    expected = [
        [e.value for s in r.value.matched for e in s.events] for r in out_u.records
    ]
    assert expected  # sanity: the stream does complete matches

    # Interrupted run against a durable log.
    path = str(tmp_path / "wal")
    log1 = RecordLog(path)
    for i, ch in enumerate(stream[:5]):
        produce(log1, "letters", "K", ch, timestamp=i)
    topo1, out1 = _build_topology(log1)
    driver1 = LogDriver(topo1, group="g")
    driver1.poll()
    first_half = [
        [e.value for s in r.value.matched for e in s.events] for r in out1.records
    ]
    log1.close()  # crash: all Python state dropped

    log2 = RecordLog(path)
    for i, ch in enumerate(stream[5:], start=5):
        produce(log2, "letters", "K", ch, timestamp=i)
    topo2, out2 = _build_topology(log2)
    driver2 = LogDriver(topo2, group="g")
    assert driver2.restored_records > 0
    driver2.poll()
    second_half = [
        [e.value for s in r.value.matched for e in s.events] for r in out2.records
    ]
    assert first_half + second_half == expected
    log2.close()


def test_log_driver_crash_between_process_and_commit_exactly_once(tmp_path):
    """A crash after records were processed (matches flushed to the sink)
    but before the offset commit used to replay the interval and re-emit:
    the emitted-match high-watermark (streams/emission.py) must make the
    sink stream exactly-once -- same records as the unbroken run, zero
    duplicates (ISSUE 6)."""
    from kafkastreams_cep_tpu.faults import (
        FaultInjector,
        FaultPoint,
        FaultSchedule,
        InjectedCrash,
        armed,
    )
    from kafkastreams_cep_tpu.streams.emission import decode_sink_key

    stream = "ABCXABCABC"

    # Unbroken run: the golden sink content.
    mem = RecordLog()
    for i, ch in enumerate(stream):
        produce(mem, "letters", "K", ch, timestamp=i)
    topo_u, _out_u = _build_topology(mem)
    LogDriver(topo_u, group="g").poll()
    golden = sorted(
        (decode_sink_key(r.key)[1], r.value) for r in mem.read("matches")
    )
    assert len(golden) == 3

    # Crash exactly between process and commit, twice, at different depths.
    path = str(tmp_path / "wal")
    log = RecordLog(path)
    for i, ch in enumerate(stream):
        produce(log, "letters", "K", ch, timestamp=i)
    log.flush()
    schedule = FaultSchedule(
        [FaultPoint("driver.pre_commit", 1), FaultPoint("driver.pre_commit", 2)]
    )
    crashes = 0
    with armed(FaultInjector(schedule)):
        while True:
            topo, _out = _build_topology(log)
            try:
                driver = LogDriver(topo, group="g")
                while driver.poll(max_records=4):
                    pass
                break
            except InjectedCrash:
                crashes += 1
                log.close()
                log = RecordLog(path)
    assert crashes == 2
    final = sorted(
        (decode_sink_key(r.key)[1], r.value) for r in log.read("matches")
    )
    assert final == golden  # zero losses AND zero duplicates
    log.close()


def test_log_driver_commit_offsets_topic():
    log = RecordLog()
    produce(log, "letters", "K", "A")
    topo, _out = _build_topology(log)
    driver = LogDriver(topo, group="g2")
    driver.poll()
    committed = log.read(OFFSETS_TOPIC)
    assert committed, "commit() must write to the offsets topic"


# ===================================================== wire transport (ISSUE 15)
# streams/transport.py: the same RecordLog contract over length-framed
# loopback sockets. Everything below is `transport`-marked (tier-1 at
# this CI sizing; `pytest -m transport` selects the suite); the chaos-
# flavored runs also ride `-m chaos`, and the long loopback soak plus
# the soak-CLI run are `slow`.
import socket  # noqa: E402
import struct  # noqa: E402
import time  # noqa: E402

from kafkastreams_cep_tpu.faults import (  # noqa: E402
    FaultInjector,
    FaultPoint,
    FaultSchedule,
    armed,
)
from kafkastreams_cep_tpu.obs import MetricsRegistry  # noqa: E402
from kafkastreams_cep_tpu.streams import transport as wire  # noqa: E402
from kafkastreams_cep_tpu.streams.transport import (  # noqa: E402
    RecordLogServer,
    SocketRecordLog,
    TransportError,
)

transport = pytest.mark.transport


@pytest.fixture
def loopback():
    """A started loopback RecordLogServer over an in-memory backing, a
    client factory sharing one private registry, and guaranteed
    teardown (clients first, then the server)."""
    reg = MetricsRegistry()
    server = RecordLogServer(RecordLog(), registry=reg).start()
    clients = []

    def connect(**kw):
        kw.setdefault("registry", reg)
        c = SocketRecordLog(server.address, **kw)
        clients.append(c)
        return c

    yield server, connect
    for c in clients:
        try:
            c.close()
        except Exception:
            pass
    server.stop()


@transport
def test_socket_record_log_contract_parity(loopback):
    """The client must satisfy the exact RecordLog L0 contract -- the
    same assertions as test_record_log_append_read_in_memory, over the
    wire: per-(topic, partition) offsets, None tombstones, start/max
    windows, end_offset, topics/partitions enumeration."""
    _server, connect = loopback
    log = connect()
    assert log.append("t", b"k1", b"v1", timestamp=5) == 0
    assert log.append("t", b"k2", None) == 1  # tombstone value
    assert log.append("t", None, None) == 2  # tombstone key AND value
    assert log.append("t", b"k3", b"v3", partition=2) == 0
    recs = log.read("t")
    assert [(r.offset, r.key, r.value, r.timestamp) for r in recs] == [
        (0, b"k1", b"v1", 5),
        (1, b"k2", None, 0),
        (2, None, None, 0),
    ]
    assert log.read("t", partition=2)[0].value == b"v3"
    assert log.end_offset("t") == 3
    assert log.topics() == ["t"]
    assert log.partitions("t") == [0, 2]
    assert log.read("t", start=1) == recs[1:]
    assert log.read("t", start=0, max_records=1) == recs[:1]
    log.flush()  # wire FLUSH must round-trip (fsync is a no-op in-memory)


@transport
def test_socket_driver_end_to_end_and_healthz(loopback):
    """LogDriver + EmissionGate + changelog stores run over the wire
    unchanged, and the client's freshness/window health surfaces through
    LogDriver.health() (the /healthz payload)."""
    server, connect = loopback
    log = connect(window=8, heartbeat_s=5.0)
    for i, ch in enumerate("XABC"):
        produce(log, "letters", "K", ch, timestamp=i)
    topo, out = _build_topology(log)
    driver = LogDriver(topo, group="g1")
    assert driver.poll() == 4
    assert len(out.records) == 1
    sunk = log.read("matches")
    assert len(sunk) == 1
    payload = json.loads(sunk[0].value.decode("utf-8"))
    assert [s["name"] for s in payload["events"]] == [
        "select-A", "select-B", "select-C",
    ]
    assert driver.poll() == 0
    h = driver.health()["transport"]
    assert h["mode"] == "socket"
    assert h["connected"] is True
    assert h["pending_appends"] == 0
    assert server.health()["peers"] == 1


@transport
def test_socket_windowed_appends_predicted_offsets_and_backpressure(loopback):
    """window>1 pipelines appends against client-predicted offsets (exact
    under one producer per partition) and a full window BLOCKS draining
    acks -- on_overflow=block propagated to the wire, never an unbounded
    client buffer."""
    _server, connect = loopback
    log = connect(window=4)
    offs = [log.append("t", b"k", b"v%d" % i) for i in range(24)]
    assert offs == list(range(24))
    log.flush()  # drains the FIFO: every append applied before the fsync
    assert log.end_offset("t") == 24
    assert [r.value for r in log.read("t")] == [b"v%d" % i for i in range(24)]
    h = log.health()
    assert h["backpressure_hits"] > 0
    assert h["pending_appends"] == 0
    assert h["window"] == 4


@transport
@pytest.mark.chaos
def test_wire_partial_write_torn_frame_resync_exactly_once(loopback):
    """The satellite pin: torn WIRE frames (half a frame on the socket,
    then a sever) must never corrupt the stream. The server discards the
    partial frames on CRC/EOF, the client reconnects on a clean boundary
    and replays, the (session, seq) identity dedups -- and the sink
    digests stay byte-equal to a fault-free in-memory run."""
    from kafkastreams_cep_tpu.streams.emission import decode_sink_key

    stream = "ABCXABCABCYABC"
    mem = RecordLog()
    for i, ch in enumerate(stream):
        produce(mem, "letters", "K", ch, timestamp=i)
    topo_u, _out = _build_topology(mem)
    LogDriver(topo_u, group="g").poll()
    golden = sorted(
        (decode_sink_key(r.key)[1], r.value) for r in mem.read("matches")
    )
    assert len(golden) == 4

    server, connect = loopback
    schedule = FaultSchedule(
        [FaultPoint("net.partial_write", h) for h in (2, 9, 17)]
    )
    with armed(FaultInjector(schedule)):
        log = connect(window=4, backoff_seed=1)
        for i, ch in enumerate(stream):
            produce(log, "letters", "K", ch, timestamp=i)
        topo, _out = _build_topology(log)
        driver = LogDriver(topo, group="g")
        while driver.poll(max_records=4):
            pass
    final = sorted(
        (decode_sink_key(r.key)[1], r.value) for r in log.read("matches")
    )
    assert final == golden  # zero losses AND zero duplicates
    # The damage was real: half-frames landed and were discarded server-
    # side, and the client reconnected to resync.
    assert server.health()["torn_frames"] >= 1
    assert log.health()["reconnects"] >= 1


@transport
def test_reconnect_backoff_budget_exhaustion_fail_stop(loopback):
    """A dead server must fail-stop after the seeded-backoff retry
    budget -- a TransportError, not a hang or silent drop (the same
    fail-stop contract as RecordLog.flush)."""
    server, connect = loopback
    log = connect(retry_budget=3, backoff_base_s=0.001, backoff_cap_s=0.01)
    assert log.append("t", b"k", b"v") == 0
    server.stop()
    with pytest.raises(TransportError, match="unrecoverable"):
        for _ in range(4):  # first sends may land in dead TCP buffers
            log.append("t", b"k", b"v")
    assert log.health()["backoff_retries"] >= 3


@transport
def test_seeded_backoff_jitter_is_deterministic(loopback):
    """Same backoff_seed => same jitter draws: chaos runs reproduce."""
    _server, connect = loopback
    a = connect(backoff_seed=42)
    b = connect(backoff_seed=42)
    assert [a._rng.random() for _ in range(8)] == [
        b._rng.random() for _ in range(8)
    ]


def _roundtrip(sock, payload):
    """Raw-socket request/response against a RecordLogServer."""
    sock.sendall(wire._seal(payload))
    hdr = wire._recv_exact(sock, wire._FRAME.size)
    length, _crc = wire._FRAME.unpack(hdr)
    return wire._recv_exact(sock, length)


@transport
def test_server_dedup_replayed_append_across_reconnects(loopback):
    """Wire-level exactly-once: a replayed APPEND with the same
    (session, seq) -- the ack-lost-in-a-disconnect case -- must return
    the SAME offset and append nothing, even from a brand-new
    connection (sessions outlive connections)."""
    server, _connect = loopback
    sid = b"\x01" * 16
    hello = (
        wire.OP_HELLO + wire._U64.pack(0) + sid
        + wire._U32.pack(wire.WIRE_VERSION)
    )
    app = (
        wire.OP_APPEND + wire._U64.pack(1) + wire._pack_str("t")
        + wire._I32.pack(0) + wire._I64.pack(7)
        + wire._pack_blob(b"k") + wire._pack_blob(b"v")
    )
    s = socket.create_connection(server.address, timeout=5.0)
    try:
        assert _roundtrip(s, hello)[:1] == wire.OP_OK
        first = _roundtrip(s, app)
        replay = _roundtrip(s, app)
        assert first == replay  # same OK frame, same offset
        assert struct.unpack_from("<q", first, 9)[0] == 0
    finally:
        s.close()
    s2 = socket.create_connection(server.address, timeout=5.0)
    try:
        resp = _roundtrip(s2, hello)
        # HELLO echoes the session's last acked seq for resync.
        assert struct.unpack_from("<Q", resp, 9)[0] == 1
        assert struct.unpack_from("<q", _roundtrip(s2, app), 9)[0] == 0
    finally:
        s2.close()
    assert server.backing.end_offset("t") == 1  # applied exactly once


@transport
@pytest.mark.chaos
def test_stall_detection_reconnect_exactly_once():
    """An injected server stall longer than the client IO deadline must
    be detected as a stall (not an error), trigger the reconnect path,
    and leave the stream exactly-once (the stalled apply races the
    replay; (session, seq) dedup must win either way)."""
    reg = MetricsRegistry()
    server = RecordLogServer(
        RecordLog(), registry=reg, stall_inject_s=1.2
    ).start()
    # Server frame hits: HELLO=1, APPEND v1=2, APPEND v2=3.
    schedule = FaultSchedule([FaultPoint("net.stall", 3)])
    log = None
    try:
        with armed(FaultInjector(schedule)):
            log = SocketRecordLog(
                server.address, registry=reg, io_timeout_s=0.25,
            )
            assert log.append("t", b"k", b"v1") == 0
            assert log.append("t", b"k", b"v2") == 1
        h = log.health()
        assert h["stalls"] >= 1
        assert h["disconnects"] >= 1
        assert h["connected"] is True
        # The stalled first apply and the post-reconnect replay must have
        # collapsed into ONE append.
        time.sleep(1.5)  # let the stalled peer thread finish its apply
        assert [r.value for r in log.read("t")] == [b"v1", b"v2"]
        assert log.end_offset("t") == 2
    finally:
        if log is not None:
            log.close()
        server.stop()


@transport
def test_heartbeat_keeps_idle_connection_fresh(loopback):
    """With heartbeat_s armed, an idle client pings: freshness stays
    bounded without any API traffic (the /healthz stall signal)."""
    _server, connect = loopback
    log = connect(heartbeat_s=0.1)
    log.append("t", b"k", b"v")
    time.sleep(0.6)
    h = log.health()
    assert h["connected"] is True
    assert h["last_ok_age_s"] is not None and h["last_ok_age_s"] < 0.5


@transport
def test_garbage_connection_is_isolated(loopback):
    """A peer speaking the wrong protocol (torn/garbage frames) must be
    dropped without disturbing other producers."""
    server, connect = loopback
    junk = socket.create_connection(server.address, timeout=5.0)
    junk.sendall(b"GET / HTTP/1.1\r\n\r\n")
    junk.close()
    log = connect()
    assert log.append("t", b"k", b"v") == 0
    assert [r.value for r in log.read("t")] == [b"v"]
    deadline = time.monotonic() + 2.0
    while server.health()["torn_frames"] < 1:
        assert time.monotonic() < deadline, "garbage frame never counted"
        time.sleep(0.01)


@transport
@pytest.mark.chaos
def test_broker_torn_append_restart_recovery(tmp_path):
    """A broker-side torn append (log.torn_append inside the server's
    file-backed log) kills the 'broker': the server restart-sims reopen
    the log (truncating the torn tail) while sessions survive, and the
    client's replay completes the stream exactly-once."""
    server = RecordLogServer(RecordLog(str(tmp_path / "broker"))).start()
    schedule = FaultSchedule([FaultPoint("log.torn_append", 3)])
    log = None
    try:
        with armed(FaultInjector(schedule)):
            log = SocketRecordLog(server.address, io_timeout_s=2.0)
            for i in range(6):
                assert log.append("t", b"k", b"v%d" % i) == i
        assert [r.value for r in log.read("t")] == [
            b"v%d" % i for i in range(6)
        ]
        assert server.health()["restarts"] == 1
        assert log.health()["reconnects"] >= 1
    finally:
        if log is not None:
            log.close()
        server.stop()


#: The wire chaos site set: driver crashes + broker torn appends +
#: client-observed wire damage. net.stall is exercised by its dedicated
#: test above (a seeded stall point would just add absorbed latency
#: here: the default stall_inject_s sits under these clients' deadline).
WIRE_CHAOS_SITES = (
    "driver.pre_commit", "driver.post_commit", "log.torn_append",
    "net.partial_write", "net.disconnect",
)


@transport
@pytest.mark.chaos
@pytest.mark.parametrize("seed", range(4))
def test_socket_pipeline_seeded_chaos_host(tmp_path, seed):
    """The acceptance pin, CI-sized: the full crash/rebuild chaos harness
    (tests/test_faults.py) with the durable log behind a loopback socket
    and wire damage in the schedule -- sink digests must equal the
    fault-free golden run."""
    from test_faults import _assert_stream_equal, _chaos, _golden, _stream

    stream = _stream(seed)
    golden = _golden(stream)
    assert golden, "seeded stream must complete matches"
    server = RecordLogServer(RecordLog(str(tmp_path / "broker"))).start()
    schedule = FaultSchedule.seeded(seed, sites=WIRE_CHAOS_SITES, n_points=4)
    try:
        chaos, _crashes = _chaos(
            tmp_path, schedule, stream,
            log_open=lambda: SocketRecordLog(
                server.address, backoff_seed=seed, io_timeout_s=2.0,
            ),
        )
        _assert_stream_equal(golden, chaos)
    finally:
        server.stop()


@transport
@pytest.mark.chaos
@pytest.mark.parametrize("seed", range(2))
def test_socket_pipeline_seeded_chaos_device(tmp_path, seed):
    """Same pin over the DEVICE runtime: the batched engine's restore/
    replay path must stay exactly-once when its durable log is a socket."""
    from test_faults import (
        DEVICE_OPTS,
        _assert_stream_equal,
        _chaos,
        _golden,
        _stream,
    )

    keys = ("k0", "k1")
    stream = _stream(seed)
    golden = _golden(stream, keys=keys, runtime="tpu", **DEVICE_OPTS)
    server = RecordLogServer(RecordLog(str(tmp_path / "broker"))).start()
    schedule = FaultSchedule.seeded(seed, sites=WIRE_CHAOS_SITES, n_points=3)
    try:
        chaos, _crashes = _chaos(
            tmp_path, schedule, stream, keys=keys, runtime="tpu",
            log_open=lambda: SocketRecordLog(
                server.address, backoff_seed=seed, io_timeout_s=2.0,
            ),
            **DEVICE_OPTS,
        )
        _assert_stream_equal(golden, chaos)
    finally:
        server.stop()


@transport
@pytest.mark.chaos
@pytest.mark.slow
def test_loopback_soak_flagship_sized(tmp_path):
    """The slow loopback soak: a longer device-runtime stream, windowed
    (pipelined) client, heartbeat armed, and a denser wire-damage
    schedule -- every fault recovered, digests byte-equal."""
    from test_faults import (
        DEVICE_OPTS,
        _assert_stream_equal,
        _chaos,
        _golden,
        _stream,
    )

    keys = ("k0", "k1")
    stream = _stream(7, n=120)
    golden = _golden(stream, keys=keys, runtime="tpu", **DEVICE_OPTS)
    assert golden
    server = RecordLogServer(RecordLog(str(tmp_path / "broker"))).start()
    schedule = FaultSchedule.seeded(7, sites=WIRE_CHAOS_SITES, n_points=8)
    try:
        chaos, crashes = _chaos(
            tmp_path, schedule, stream, keys=keys, runtime="tpu",
            max_crashes=48,
            log_open=lambda: SocketRecordLog(
                server.address, backoff_seed=7, io_timeout_s=2.0,
                window=8, heartbeat_s=2.0,
            ),
            **DEVICE_OPTS,
        )
        _assert_stream_equal(golden, chaos)
        assert crashes >= 1
    finally:
        server.stop()


@transport
@pytest.mark.slow
def test_soak_cli_socket_transport(tmp_path):
    """The soak CLI's --transport socket mode end to end: the verdict
    artifact must self-describe the transport, validate against the soak
    schema, and carry live wire-counter families."""
    from kafkastreams_cep_tpu.faults.soak import main as soak_main

    out = str(tmp_path / "SOAK_test.json")
    soak_main([
        "--quick", "--transport", "socket", "--out", out,
        "--dir", str(tmp_path / "wal"),
    ])
    with open(out) as f:
        doc = json.load(f)
    assert doc["soak"]["transport"] == "socket"
    assert doc["schema_ok"] is True
    assert "cep_transport_disconnects_total" in doc["faults"]


@transport
def test_dedup_eviction_replay_fences_session():
    """ISSUE 16 regression: a replayed APPEND whose seq was EVICTED from
    the bounded dedup map must fail the session loudly, never re-append.
    Before this fix the server re-ran such replays as fresh appends --
    a quiet exactly-once break invisible until the duplicate surfaced
    downstream. Subsequent appends on the fenced session also fail; a
    fresh session recovers."""
    reg = MetricsRegistry()
    server = RecordLogServer(RecordLog(), registry=reg, dedup_cache=4).start()

    def hello(sid):
        return (
            wire.OP_HELLO + wire._U64.pack(0) + sid
            + wire._U32.pack(wire.WIRE_VERSION)
        )

    def append(seq):
        return (
            wire.OP_APPEND + wire._U64.pack(seq) + wire._pack_str("t")
            + wire._I32.pack(0) + wire._I64.pack(0)
            + wire._pack_blob(b"k") + wire._pack_blob(b"v%d" % seq)
        )

    def err_text(resp):
        assert resp[:1] == wire.OP_ERR
        (n,) = struct.unpack_from("<I", resp, 9)
        return resp[13:13 + n].decode("utf-8")

    sid = b"\x07" * 16
    s = socket.create_connection(server.address, timeout=5.0)
    try:
        assert _roundtrip(s, hello(sid))[:1] == wire.OP_OK
        for seq in range(1, 9):  # cache of 4 keeps 5..8, evicts 1..4
            assert _roundtrip(s, append(seq))[:1] == wire.OP_OK
        # In-window replay still dedups (same offset, nothing appended).
        assert struct.unpack_from("<q", _roundtrip(s, append(6)), 9)[0] == 5
        # Evicted-range replay: explicit failure, session fenced.
        msg = err_text(_roundtrip(s, append(2)))
        assert "dedup" in msg and "fenced" in msg
        # The fence sticks: even a FRESH seq on this session now errors.
        assert "fenced" in err_text(_roundtrip(s, append(9)))
        assert server.backing.end_offset("t") == 8  # nothing re-appended
    finally:
        s.close()
    # A new session (the documented recovery) appends normally again.
    s2 = socket.create_connection(server.address, timeout=5.0)
    try:
        assert _roundtrip(s2, hello(b"\x08" * 16))[:1] == wire.OP_OK
        assert struct.unpack_from(
            "<q", _roundtrip(s2, append(1)), 9
        )[0] == 8
    finally:
        s2.close()
        server.stop()


@transport
@pytest.mark.chaos
def test_driver_restore_over_wire_under_disconnect_and_stall(tmp_path):
    """ISSUE 16 satellite: the bounded-retry changelog-restore path
    (LogDriver startup, site driver.restore) running against a SOCKET
    broker under seeded net.disconnect + net.stall chaos. The restore
    must absorb the wire damage (reconnect + replay under with_retry),
    resume from the committed offsets -- never from zero -- and keep the
    stream exactly-once vs the fault-free golden run."""
    from kafkastreams_cep_tpu.streams.emission import decode_sink_key

    def sink_digests(log):
        out = []
        for rec in log.read("matches"):
            _key, digest = decode_sink_key(rec.key)
            assert digest is not None
            out.append((digest, rec.value))
        return out

    events = list("XABCYABCXABC")
    mem = RecordLog()
    for i, ch in enumerate(events):
        produce(mem, "letters", "K", ch, timestamp=i)
    gtopo, _gout = _build_topology(mem)
    gdriver = LogDriver(gtopo, group="g")
    while gdriver.poll(max_records=3):
        pass
    golden = sink_digests(mem)
    assert golden

    reg = MetricsRegistry()
    server = RecordLogServer(
        RecordLog(str(tmp_path / "broker")), registry=reg,
        stall_inject_s=3.0,
    ).start()
    half = len(events) // 2
    try:
        log = SocketRecordLog(server.address, registry=reg, io_timeout_s=2.0)
        for i, ch in enumerate(events[:half]):
            produce(log, "letters", "K", ch, timestamp=i)
        topo, _out = _build_topology(log)
        driver = LogDriver(topo, group="g", registry=reg)
        while driver.poll(max_records=3):
            pass
        driver.close()  # final commit: changelogs + offsets durable
        log.close()

        # Rebuild over a fresh client with wire chaos armed: disconnects
        # land mid-restore-read, and a server stall overruns the client's
        # IO deadline during the replay.
        schedule = FaultSchedule([
            FaultPoint("driver.restore", 1),
            FaultPoint("net.disconnect", 3),
            FaultPoint("net.disconnect", 9),
            FaultPoint("net.stall", 1),
        ])
        with armed(FaultInjector(schedule, registry=reg)):
            log2 = SocketRecordLog(
                server.address, registry=reg, io_timeout_s=2.0,
            )
            topo2, _out2 = _build_topology(log2)
            driver2 = LogDriver(topo2, group="g", registry=reg)
            # The changelog replay really streamed state over the wire.
            assert driver2.restored_records > 0
            for i, ch in enumerate(events[half:], start=half):
                produce(log2, "letters", "K", ch, timestamp=i)
            while driver2.poll(max_records=3):
                pass
            driver2.close()
        injected = {p.site for p in schedule.points if p.fired}
        assert "net.disconnect" in injected, "chaos never landed"
        final = sink_digests(log2)
        assert sorted(final) == sorted(golden)
        assert len({d for d, _v in final}) == len(final), "duplicate emission"
        # The retry wrapper observed the injected restore transient.
        assert (
            reg._metrics["cep_retries_total"]
            .labels(site="driver.restore").value >= 1
        )
        log2.close()
    finally:
        server.stop()
