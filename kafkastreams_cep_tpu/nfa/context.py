"""Predicate evaluation context for the host NFA path.

Re-design of the reference evaluation context
(reference: core/.../cep/pattern/MatcherContext.java:31-83). Bundles the
read-only buffer view, the current Dewey version, previous/current stage and
event, and the fold-state view; also adapts itself into an expression `Env`
so declarative predicates evaluate identically on host and device.
"""
from __future__ import annotations

from typing import Any, Optional

from ..core.dewey import DeweyVersion
from ..core.event import Event
from ..core.sequence import Sequence
from ..pattern.expressions import Env
from ..pattern.stages import Stage
from ..state.aggregates import States
from ..state.buffer import ReadOnlySharedVersionBuffer


class MatcherContext:
    __slots__ = (
        "buffer",
        "version",
        "previous_stage",
        "current_stage",
        "previous_event",
        "current_event",
        "states",
        "previous_node",
    )

    def __init__(
        self,
        buffer: ReadOnlySharedVersionBuffer,
        version: DeweyVersion,
        previous_stage: Optional[Stage],
        current_stage: Stage,
        previous_event: Optional[Event],
        current_event: Event,
        states: States,
        previous_node: Optional[int] = None,
    ) -> None:
        self.buffer = buffer
        self.version = version
        self.previous_stage = previous_stage
        self.current_stage = current_stage
        self.previous_event = previous_event
        self.current_event = current_event
        self.states = states
        self.previous_node = previous_node

    def partial_sequence(self) -> Sequence:
        """Materialize the partial match for sequence predicates.

        Mirrors SequenceMatcher's default accept (SequenceMatcher.java:22-26):
        walks the run's lineage chain from its last stored node
        (ComputationStage.last_node); an exact parent walk, no version
        routing (see state/buffer.py).
        """
        if self.previous_node is None:
            return Sequence([])
        return self.buffer.get(self.previous_node)

    def env(self) -> "HostEventEnv":
        return HostEventEnv(self.current_event, self.states)


class HostEventEnv(Env):
    """Expression environment over a single host event + fold registers."""

    __slots__ = ("_event", "_states")

    def __init__(self, event: Event, states: Optional[States]) -> None:
        self._event = event
        self._states = states

    def field(self, name: str) -> Any:
        value = self._event.value
        if name == "":
            return value
        if isinstance(value, dict):
            return value[name]
        return getattr(value, name)

    def key(self) -> Any:
        return self._event.key

    def value(self) -> Any:
        return self._event.value

    def timestamp(self) -> Any:
        return self._event.timestamp

    def topic_is(self, topic: str) -> Any:
        return self._event.topic == topic

    def agg(self, name: str, default: Any = None) -> Any:
        if self._states is None:
            raise ValueError("aggregate reference outside a stateful context")
        if default is None:
            return self._states.get(name)
        return self._states.get_or_else(name, default)


class FoldEnv(HostEventEnv):
    """Environment for fold updates: agg(own-name) resolves to the current register."""

    __slots__ = ("_own_name", "_current")

    def __init__(
        self, event: Event, states: Optional[States], own_name: str, current: Any
    ) -> None:
        super().__init__(event, states)
        self._own_name = own_name
        self._current = current

    def agg(self, name: str, default: Any = None) -> Any:
        if name == self._own_name:
            if self._current is None:
                return default if default is not None else 0
            return self._current
        return super().agg(name, default)
