"""Chaos CLI: seeded fault sweeps and the SLO-gated production soak.

Two modes share this entry point:

    # the chaos SWEEP (default; the original CLI): per-seed golden-vs-
    # chaos digest equality over a crash/rebuild pipeline
    python -m kafkastreams_cep_tpu.faults --seeds 32 [--runtime tpu]

    # the production SOAK (faults/soak.py): scenario fleet + chaos +
    # self-scraped metrics time series + SLO verdict artifact
    python -m kafkastreams_cep_tpu.faults soak --quick --out SOAK.json

For each sweep seed it builds a fresh durable pipeline (letters query over
a file-backed RecordLog in a temp dir), computes the fault-free golden sink
stream, then replays the same stream under a seeded `FaultSchedule`,
rebuilding from disk after every simulated crash -- the same harness as
tests/test_faults.py, sized for soaking rather than CI. Any divergence
(lost or duplicated match) prints the seed and exits nonzero, so a failing
seed reproduces with `--seeds-from N --seeds 1`.
"""
from __future__ import annotations

import argparse
import os
import sys
import tempfile

# Keep the soak local: same backend pinning as tests/conftest.py (the axon
# PJRT plugin otherwise hangs the process when the TPU tunnel is down).
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    argv = list(argv)
    # Subcommand dispatch, backward compatible: bare flags keep running
    # the original sweep ("sweep" is accepted as its explicit name).
    if argv and argv[0] == "soak":
        from .soak import main as soak_main

        return soak_main(argv[1:])
    if argv and argv[0] == "sweep":
        argv = argv[1:]
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seeds", type=int, default=16, help="how many seeds")
    ap.add_argument("--seeds-from", type=int, default=0, help="first seed")
    ap.add_argument("--runtime", default="host", choices=["host", "tpu"])
    ap.add_argument("--events", type=int, default=48, help="stream length")
    ap.add_argument("--points", type=int, default=3, help="faults per seed")
    ap.add_argument(
        "--http-port", type=int, default=None, metavar="PORT",
        help="serve the live introspection plane (/metrics /snapshot "
        "/healthz /tracez) over the process-default registry while the "
        "soak runs; 0 binds an ephemeral port (printed)",
    )
    args = ap.parse_args(argv)

    import jax

    jax.config.update("jax_platforms", "cpu")

    sys.path.insert(
        0,
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), "tests",
        ),
    )
    from test_faults import (  # the CI harness, reused verbatim
        DRIVER_SITES,
        DEVICE_OPTS,
        _chaos,
        _golden,
        _stream,
    )

    from . import FaultSchedule

    sites = DRIVER_SITES + (
        ("engine.mid_drain",) if args.runtime == "tpu" else ()
    )
    opts = dict(DEVICE_OPTS) if args.runtime == "tpu" else {}
    keys = ("k0", "k1") if args.runtime == "tpu" else ("K",)
    failures = 0
    progress = {"seed": None, "done": 0, "failures": 0}
    server = None
    tracer = None
    if args.http_port is not None:
        # The soak's live plane (ISSUE 7): the chaos pipelines' drivers
        # report into the process-default registry, so /metrics shows the
        # driver layer moving mid-soak (polls/commits/restores/retries;
        # the harness arms its injector on a private registry, so
        # injected-fault totals stay out of this exposition); /healthz
        # reports soak progress + fault-arm state; /tracez carries the
        # soak's own per-seed spans (the harness-internal drivers keep
        # private tracers, so their restore/commit spans live in their
        # rings, not this server's).
        from ..obs import IntrospectionServer, SpanTracer, default_registry

        def _soak_health():
            return dict(progress, total_seeds=args.seeds,
                        runtime=args.runtime)

        tracer = SpanTracer(default_registry())
        server = IntrospectionServer(
            registry=default_registry(), tracer=tracer,
            health_fn=_soak_health, port=args.http_port,
        ).start()
        print(f"introspection plane: {server.url}")
    import contextlib

    for seed in range(args.seeds_from, args.seeds_from + args.seeds):
        stream = _stream(seed, n=args.events)
        golden = _golden(stream, keys=keys, runtime=args.runtime, **opts)
        schedule = FaultSchedule.seeded(seed, sites=sites,
                                        n_points=args.points)

        class _Tmp:
            def __truediv__(self, name):
                import pathlib

                return pathlib.Path(tempfile.mkdtemp()) / name

        span = (
            tracer.span(f"seed-{seed}")
            if tracer is not None else contextlib.nullcontext()
        )
        with span:
            chaos, crashes = _chaos(
                _Tmp(), schedule, stream, keys=keys,
                runtime=args.runtime, **opts
            )
        ok = sorted(chaos) == sorted(golden)
        print(
            f"seed {seed}: {len(golden)} matches, {crashes} crashes, "
            f"{'OK' if ok else 'DIVERGED'}"
        )
        if not ok:
            failures += 1
            print(f"  schedule: {schedule}")
        progress.update(seed=seed, done=progress["done"] + 1,
                        failures=failures)
    print(f"{args.seeds} seeds, {failures} divergent")
    if server is not None:
        server.stop()
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
