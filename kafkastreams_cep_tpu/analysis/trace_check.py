"""tracectx: wire trace-context propagation completeness (ISSUE 20).

The fleet tracing contract is only as strong as its weakest hop: a
record's `TraceContext` blob rides every durable append (producer ->
broker -> migration copy -> sink/DLQ), and ONE forwarding site that
drops the ``trace=`` keyword silently unstitches every end-to-end span
that crosses it -- no test fails, the trace file just loses its story
mid-record. This checker makes the omission structural, the same move
serde_check made for checkpoint fields:

- **Forwarding sites** (CEP-W01): in the trace-plumbing modules
  (`TRACE_FILES`), every ``*.append(...)`` call that forwards a record
  (>= 3 call arguments -- topic/key/value shaped; plain ``list.append``
  takes one) must pass ``trace=``. Control-plane appends that carry no
  record (offset commits, changelog snapshots) are audited in place
  with ``# cep: trace-ok(reason)``.
- **Plumbing bindings** (CEP-W02): the named functions that thread the
  blob (client/server append paths, ingest stamping, sink/DLQ
  forwarding, partition moves) must still exist and still mention
  ``trace`` -- a rename or a refactor that quietly severs the chain is
  reported against this checker's binding table, so the table and the
  plumbing move together.

Findings (W for "wire"; CEP-T* belongs to the threads checker):
    CEP-W01  record-forwarding append() that drops the trace blob
    CEP-W02  trace-plumbing binding missing or no longer threading trace

Findings anchor to the call/def line so a ``# cep: trace-ok(reason)``
pragma can audit the intentional cases exactly where they live.
"""
from __future__ import annotations

import ast
from typing import List, Sequence

from .core import Finding, SourceFile
from .zerosync import function_index

#: Modules whose record-forwarding appends must propagate the blob.
TRACE_FILES = (
    "kafkastreams_cep_tpu/streams/transport.py",
    "kafkastreams_cep_tpu/streams/partition.py",
    "kafkastreams_cep_tpu/streams/builder.py",
    "kafkastreams_cep_tpu/streams/driver.py",
    "kafkastreams_cep_tpu/streams/device_processor.py",
    "kafkastreams_cep_tpu/streams/rebalance.py",
)

#: (file, qualified function) pairs that ARE the trace plumbing: each
#: must exist and reference ``trace`` somewhere in its body. Update this
#: table when the plumbing moves -- CEP-W02 findings name the stale row.
TRACE_BINDINGS = (
    ("kafkastreams_cep_tpu/streams/transport.py", "SocketRecordLog.append"),
    ("kafkastreams_cep_tpu/streams/transport.py", "RecordLogServer._apply"),
    ("kafkastreams_cep_tpu/streams/transport.py", "_parse_records"),
    ("kafkastreams_cep_tpu/streams/partition.py",
     "PartitionedRecordLog.append"),
    ("kafkastreams_cep_tpu/streams/partition.py",
     "PartitionedRecordLog.move_partition"),
    ("kafkastreams_cep_tpu/streams/builder.py", "Topology.stamp_ingest"),
    ("kafkastreams_cep_tpu/streams/builder.py", "Topology._sink"),
    ("kafkastreams_cep_tpu/streams/driver.py", "produce"),
    ("kafkastreams_cep_tpu/streams/driver.py", "LogDriver._dead_letter"),
)

#: An append this long is a record-forwarding call (topic, key, value,
#: ...); list/deque appends take one argument and never trip it.
MIN_FORWARD_ARGS = 3

#: Positional arity at which the trace blob rides positionally
#: (topic, key, value, timestamp, partition, trace).
TRACE_POSITIONAL_ARITY = 6


def _propagates_trace(call: ast.Call) -> bool:
    if len(call.args) >= TRACE_POSITIONAL_ARITY:
        return True
    return any(kw.arg == "trace" for kw in call.keywords)


def _forwarding_appends(src: SourceFile) -> List[ast.Call]:
    out: List[ast.Call] = []
    for node in ast.walk(src.tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "append"
            and len(node.args) + len(node.keywords) >= MIN_FORWARD_ARGS
        ):
            out.append(node)
    return out


def _mentions_trace(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and node.id == "trace":
            return True
        if isinstance(node, ast.arg) and node.arg == "trace":
            return True
        if isinstance(node, ast.keyword) and node.arg == "trace":
            return True
        if isinstance(node, ast.Attribute) and node.attr == "trace":
            return True
        if isinstance(node, ast.Constant) and node.value == "trace":
            return True
    return False


def check(files: Sequence[SourceFile], root_dir: str) -> List[Finding]:
    by_path = {src.relpath: src for src in files}
    findings: List[Finding] = []

    for path in TRACE_FILES:
        src = by_path.get(path)
        if src is None:
            continue  # partial run without the module
        for call in _forwarding_appends(src):
            if _propagates_trace(call):
                continue
            findings.append(
                Finding(
                    "tracectx", "CEP-W01", path, call.lineno,
                    "record-forwarding append() without trace= -- the "
                    "wire trace context is dropped at this hop and every "
                    "end-to-end span crossing it unstitches (pass "
                    "trace=..., or audit a trace-free control-plane "
                    "append with # cep: trace-ok(reason))",
                    context=src.context_line(call.lineno),
                )
            )

    for path, qual in TRACE_BINDINGS:
        src = by_path.get(path)
        if src is None:
            continue
        fn = function_index(src).get(qual)
        if fn is None:
            findings.append(
                Finding(
                    "tracectx", "CEP-W02", path, 0,
                    f"trace plumbing binding names missing function "
                    f"{qual!r} -- the propagation chain moved; update "
                    "analysis/trace_check.py TRACE_BINDINGS",
                    context=f"binding:{qual}",
                )
            )
        elif not _mentions_trace(fn):
            findings.append(
                Finding(
                    "tracectx", "CEP-W02", path, fn.lineno,
                    f"{qual} no longer references `trace` -- this hop "
                    "stopped propagating the wire trace context (thread "
                    "the blob through, or update TRACE_BINDINGS if the "
                    "plumbing deliberately moved)",
                    context=f"plumbing:{qual}",
                )
            )
    return findings
