"""Test harness config: force JAX onto a virtual 8-device CPU mesh.

Device-path tests exercise the engine and multi-chip sharding on virtual
CPU devices so they are deterministic and independent of the TPU tunnel's
health; the real-TPU benchmark path is driven by bench.py instead (no
conftest there, so it keeps the ambient axon/TPU platform).

Setting JAX_PLATFORMS=cpu alone is not enough: the axon PJRT plugin is
registered by sitecustomize at interpreter start and `jax.backends()`
initializes every registered plugin, hanging all tests whenever the TPU
tunnel is down. Dropping the factory before the first backend init keeps
the test process purely local.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The axon register hook sets jax_platforms=axon via jax.config at
# interpreter start, so the env var alone no longer wins.
jax.config.update("jax_platforms", "cpu")
# Persistent compilation cache: the differential harness compiles ~100
# distinct programs; on a warm cache repeat suite runs skip nearly all of
# that (the cache key includes jaxlib version + flags, so it is safe).
jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.dirname(__file__), "..", ".jax_cache"),
)
# Cache EVERY program: the differential harness compiles hundreds of
# small (<0.5 s) programs whose compile walls only matter in aggregate
# -- on a single-core CI host they are most of the suite's wall.
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
try:  # private JAX API; guarded so a JAX upgrade degrades gracefully
    from jax._src import xla_bridge as _xb  # noqa: E402

    _xb._backend_factories.pop("axon", None)
except Exception:  # pragma: no cover - env-var path still forces cpu
    pass


def pytest_configure(config):
    # `chaos` rides tier-1 (it is NOT `slow`): the seeded fault schedules
    # are fast, deterministic and CPU-safe, and `pytest -m chaos` selects
    # just the fault-injection suite (tests/test_faults.py).
    config.addinivalue_line(
        "markers",
        "chaos: seeded fault-injection tests (fast, deterministic, CPU-safe)",
    )
    # `obs` mirrors `chaos`: rides tier-1, and `pytest -m obs` selects the
    # observability suite (registry/exposition/introspection-plane tests).
    config.addinivalue_line(
        "markers",
        "obs: observability-suite tests (fast, deterministic, CPU-safe)",
    )
    # `profiling` mirrors `obs`/`chaos`: rides tier-1, and
    # `pytest -m profiling` selects the performance-observability suite
    # (compile telemetry, sampled phase timing, trace export, perf ledger).
    config.addinivalue_line(
        "markers",
        "profiling: performance-observability tests (fast, CPU-safe)",
    )
    # `soak` mirrors the other suite markers: rides tier-1 (the --quick
    # soak is CI-sized by contract), and `pytest -m soak` selects the
    # production-soak suite (scenario fleet, scraper, verdict gating).
    config.addinivalue_line(
        "markers",
        "soak: production-soak suite (CI-sized --quick runs, CPU-safe)",
    )
    # `transport` mirrors the other suite markers: rides tier-1 at
    # --quick size, and `pytest -m transport` selects the wire-transport
    # suite (framed socket RecordLog, reconnect/backoff, exactly-once
    # over loopback; the long loopback soak is additionally `slow`).
    config.addinivalue_line(
        "markers",
        "transport: wire-transport suite (loopback sockets, CPU-safe)",
    )
    # `rebalance` mirrors `transport`: rides tier-1, and
    # `pytest -m rebalance` selects the partitioned-fleet/shard-migration
    # suite (broker routing, shard checkpoints, live migration).
    config.addinivalue_line(
        "markers",
        "rebalance: partitioned-fleet and shard-migration suite (CPU-safe)",
    )
    config.addinivalue_line("markers", "slow: excluded from tier-1")
    # `lint` selects the static-analysis gate (tests/test_lint.py):
    # ceplint over the full package, mutation fixtures, pragma/baseline
    # semantics, the jit-cache audit, and the lock-order monitor.
    config.addinivalue_line(
        "markers",
        "lint: static-analysis invariant gate (ceplint; fast, CPU-safe)",
    )


import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _lock_order_monitor(request):
    """Arm the instrumented-Lock monitor (analysis/lockmon.py) for the
    chaos, soak and transport suites -- the runs that exercise the obs
    serve/clock, scraper, driver, decode and transport threads together
    (ISSUE 13). Any lock-order cycle observed during the test is a
    potential deadlock and fails it, with the held->acquired graph in
    the report."""
    if (
        request.node.get_closest_marker("chaos") is None
        and request.node.get_closest_marker("soak") is None
        and request.node.get_closest_marker("transport") is None
    ):
        yield
        return
    from kafkastreams_cep_tpu.analysis.lockmon import (
        LockMonitor,
        active_monitor,
    )

    if active_monitor() is not None:  # nested arming (subprocess runs)
        yield
        return
    mon = LockMonitor().install()
    try:
        yield
    finally:
        mon.uninstall()
    cycles = mon.cycles()
    assert not cycles, (
        "lock-order cycle(s) observed (potential deadlock):\n"
        + mon.report()
    )
