// Native match decoder: pulled drain snapshots -> materialized Sequences.
//
// The reference materializes a match by walking the shared versioned
// buffer's pointers backwards per match (reference:
// core/.../cep/state/internal/SharedVersionedBufferStoreImpl.java:164-201);
// the TPU-native drain either (a) pulls the compacted node pools off the
// device once and walks every chain host-side (`decode_matches`, the
// original path) or (b) walks the chains ON DEVICE into a dense
// [match, hop] table (ops/engine.py build_chain_flatten) so the C side is
// a flat loop over rows with no pointer chasing (`decode_matches_flat`,
// the default drain path since the chain-flatten rewrite). The pure-Python
// walk + Sequence assembly costs ~30 us per match (PERF.md round-4 "Where
// the end-to-end time goes now") and dominates end-to-end throughput on
// match-heavy workloads; this CPython extension does the chain walk/read,
// stage grouping, normalization check and Staged/Sequence construction in
// one C call per drain.
//
// Semantics are exactly ops/runtime.py decode_chains + materialize_sequence
// (which remain the fallback and the semantic reference):
//   * chains walk predecessor indices oldest-first; nodes whose event id is
//     negative (GC-dropped puts under region overflow) are skipped while
//     the rest of the chain survives; all-dead chains decode to nothing;
//   * grouping is by stage NAME (ids are keyed by (name, type): a
//     begin-position one_or_more's BEGIN and NORMAL stages share one name
//     and must land in one group), first-occurrence order;
//   * a group already normalized under the Event contract (one
//     (topic, partition), strictly increasing offsets) skips Staged's
//     sorted(set(...)) -- the decode hot path; others fall back to the
//     Python constructor.
//
// Built on demand by native/__init__.py with g++ (no pybind11 in the
// image; plain CPython C API).

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <vector>

namespace {

struct Buf {
  Py_buffer buf{};
  bool held = false;

  ~Buf() {
    if (held) PyBuffer_Release(&buf);
  }
};

// Strided 2D int32 view: the drain pulls device arrays [N, K] and hands
// their [K, N] transposes here, so contiguity must not be required.
struct View2D {
  const char* data = nullptr;
  Py_ssize_t s0 = 0, s1 = 0;

  int32_t at(Py_ssize_t i, Py_ssize_t j) const {
    return *reinterpret_cast<const int32_t*>(data + i * s0 + j * s1);
  }
};

bool get_i32_2d(PyObject* obj, const char* what, Buf* b, View2D* v,
                Py_ssize_t* d0, Py_ssize_t* d1) {
  if (PyObject_GetBuffer(obj, &b->buf, PyBUF_STRIDES) < 0) return false;
  b->held = true;
  if (b->buf.ndim != 2 || b->buf.itemsize != 4) {
    PyErr_Format(PyExc_ValueError, "%s must be int32 [K, N]", what);
    return false;
  }
  if (*d0 < 0) *d0 = b->buf.shape[0];
  if (*d1 < 0) *d1 = b->buf.shape[1];
  if (b->buf.shape[0] != *d0 || b->buf.shape[1] != *d1) {
    PyErr_Format(PyExc_ValueError, "%s shape mismatch", what);
    return false;
  }
  v->data = static_cast<const char*>(b->buf.buf);
  v->s0 = b->buf.strides[0];
  v->s1 = b->buf.strides[1];
  return true;
}

// Strided 3D int32 view: the flat drain pulls one [3, M, C, K] table and
// hands per-plane [K, M, C] transposes here (numpy moveaxis views), so
// contiguity must not be required.
struct View3D {
  const char* data = nullptr;
  Py_ssize_t s0 = 0, s1 = 0, s2 = 0;

  int32_t at(Py_ssize_t i, Py_ssize_t j, Py_ssize_t c) const {
    return *reinterpret_cast<const int32_t*>(data + i * s0 + j * s1 +
                                             c * s2);
  }
};

bool get_i32_3d(PyObject* obj, const char* what, Buf* b, View3D* v,
                Py_ssize_t* d0, Py_ssize_t* d1, Py_ssize_t* d2) {
  if (PyObject_GetBuffer(obj, &b->buf, PyBUF_STRIDES) < 0) return false;
  b->held = true;
  if (b->buf.ndim != 3 || b->buf.itemsize != 4) {
    PyErr_Format(PyExc_ValueError, "%s must be int32 [K, M, C]", what);
    return false;
  }
  Py_ssize_t* dims[3] = {d0, d1, d2};
  for (int i = 0; i < 3; ++i) {
    if (*dims[i] < 0) *dims[i] = b->buf.shape[i];
    if (b->buf.shape[i] != *dims[i]) {
      PyErr_Format(PyExc_ValueError, "%s shape mismatch", what);
      return false;
    }
  }
  v->data = static_cast<const char*>(b->buf.buf);
  v->s0 = b->buf.strides[0];
  v->s1 = b->buf.strides[1];
  v->s2 = b->buf.strides[2];
  return true;
}

// A Staged/Sequence instance without running Python-level __init__
// (the C analog of cls.__new__(cls)).
PyObject* bare_instance(PyObject* type) {
  PyTypeObject* tp = reinterpret_cast<PyTypeObject*>(type);
  PyObject* empty = PyTuple_New(0);
  if (empty == nullptr) return nullptr;
  PyObject* obj = tp->tp_new(tp, empty, nullptr);
  Py_DECREF(empty);
  return obj;
}

// Shared chain -> Sequence materialization. Both decode entry points feed
// NEWEST-FIRST (name_id << 32 | gidx) chains here (the walk order);
// assembly iterates them reversed, so groups build oldest-first exactly as
// ops/runtime.py materialize_sequence does.
struct Materializer {
  PyObject* name_of_id = nullptr;     // borrowed
  PyObject* registry = nullptr;       // borrowed
  PyObject* staged_type = nullptr;    // borrowed
  PyObject* sequence_type = nullptr;  // borrowed
  const int32_t* qid_of_name = nullptr;
  Py_ssize_t n_qids = 0;
  Py_ssize_t n_names = 0;
  std::vector<int32_t> canon;
  PyObject* s_topic = nullptr;
  PyObject* s_partition = nullptr;
  PyObject* s_offset = nullptr;
  PyObject* s_stage = nullptr;
  PyObject* s_events_attr = nullptr;
  PyObject* s_matched = nullptr;
  PyObject* s_by_name = nullptr;

  struct Group {
    int32_t canon_id;
    PyObject* name;    // borrowed from name_of_id
    PyObject* events;  // owned list
  };
  std::vector<Group> groups;  // scratch reused across matches

  // `qid_b` is caller-owned so the qid buffer outlives this object.
  bool init(PyObject* name_of_id_, PyObject* registry_, PyObject* staged_,
            PyObject* sequence_, PyObject* qid_obj, Buf* qid_b) {
    if (!PyList_Check(name_of_id_) || !PyDict_Check(registry_) ||
        !PyType_Check(staged_) || !PyType_Check(sequence_)) {
      PyErr_SetString(PyExc_TypeError,
                      "name_of_id list, registry dict, Staged/Sequence types");
      return false;
    }
    name_of_id = name_of_id_;
    registry = registry_;
    staged_type = staged_;
    sequence_type = sequence_;

    if (qid_obj != Py_None) {
      if (PyObject_GetBuffer(qid_obj, &qid_b->buf, PyBUF_C_CONTIGUOUS) < 0) {
        return false;
      }
      qid_b->held = true;
      if (qid_b->buf.ndim != 1 || qid_b->buf.itemsize != 4) {
        PyErr_SetString(PyExc_ValueError, "qid_of_name_id must be int32 [N]");
        return false;
      }
      qid_of_name = static_cast<const int32_t*>(qid_b->buf.buf);
      n_qids = qid_b->buf.shape[0];
    }

    // name_id -> canonical group id: ids whose name strings compare equal
    // share a group (grouping is by NAME, not id).
    n_names = PyList_GET_SIZE(name_of_id);
    canon.assign(n_names, 0);
    for (Py_ssize_t i = 0; i < n_names; ++i) {
      canon[i] = static_cast<int32_t>(i);
      PyObject* ni = PyList_GET_ITEM(name_of_id, i);
      for (Py_ssize_t j = 0; j < i; ++j) {
        int eq =
            PyObject_RichCompareBool(ni, PyList_GET_ITEM(name_of_id, j), Py_EQ);
        if (eq < 0) return false;
        if (eq) {
          canon[i] = canon[j];
          break;
        }
      }
    }

    s_topic = PyUnicode_InternFromString("topic");
    s_partition = PyUnicode_InternFromString("partition");
    s_offset = PyUnicode_InternFromString("offset");
    s_stage = PyUnicode_InternFromString("stage");
    s_events_attr = PyUnicode_InternFromString("_events");
    s_matched = PyUnicode_InternFromString("matched");
    s_by_name = PyUnicode_InternFromString("_by_name");
    return s_topic && s_partition && s_offset && s_stage && s_events_attr &&
           s_matched && s_by_name;
  }

  void fini() {
    Py_XDECREF(s_topic);
    Py_XDECREF(s_partition);
    Py_XDECREF(s_offset);
    Py_XDECREF(s_stage);
    Py_XDECREF(s_events_attr);
    Py_XDECREF(s_matched);
    Py_XDECREF(s_by_name);
  }

  // Materialize one chain and append the Sequence (or (qid, Sequence)
  // pair) to per_key. Returns false with a Python error set.
  bool emit(const std::vector<int64_t>& chain, PyObject* per_key) {
    bool fail = false;
    // Oldest-first group assembly, first-occurrence stage order.
    groups.clear();
    for (size_t c = chain.size(); c-- > 0 && !fail;) {
      int32_t name_id = static_cast<int32_t>(chain[c] >> 32);
      int32_t gidx = static_cast<int32_t>(chain[c] & 0xffffffff);
      if (name_id < 0 || name_id >= n_names) {
        PyErr_Format(PyExc_ValueError, "bad stage name id %d", name_id);
        fail = true;
        break;
      }
      int32_t cid = canon[name_id];
      Group* grp = nullptr;
      for (auto& g2 : groups) {
        if (g2.canon_id == cid) {
          grp = &g2;
          break;
        }
      }
      if (grp == nullptr) {
        PyObject* lst = PyList_New(0);
        if (lst == nullptr) {
          fail = true;
          break;
        }
        groups.push_back(Group{cid, PyList_GET_ITEM(name_of_id, cid), lst});
        grp = &groups.back();
      }
      PyObject* g_obj = PyLong_FromLong(gidx);
      if (g_obj == nullptr) {
        fail = true;
        break;
      }
      PyObject* event = PyDict_GetItemWithError(registry, g_obj);  // borrowed
      Py_DECREF(g_obj);
      if (event == nullptr) {
        if (!PyErr_Occurred()) {
          PyErr_Format(PyExc_KeyError, "event registry missing gidx %d", gidx);
        }
        fail = true;
        break;
      }
      if (PyList_Append(grp->events, event) < 0) fail = true;
    }

    PyObject* matched = fail ? nullptr : PyList_New(0);
    if (matched == nullptr) fail = true;
    for (auto& grp : groups) {
      if (fail) {
        Py_XDECREF(grp.events);
        continue;
      }
      // Normalized exactly when all events share one (topic, partition)
      // and offsets strictly increase -- then Staged's sorted(set(...))
      // is the identity and can be skipped.
      Py_ssize_t ne = PyList_GET_SIZE(grp.events);
      bool normalized = true;
      PyObject* topic0 = nullptr;
      long long part0 = 0, prev_off = 0;
      for (Py_ssize_t i2 = 0; i2 < ne && normalized; ++i2) {
        PyObject* e = PyList_GET_ITEM(grp.events, i2);
        PyObject* topic = PyObject_GetAttr(e, s_topic);
        PyObject* part = topic ? PyObject_GetAttr(e, s_partition) : nullptr;
        PyObject* off = part ? PyObject_GetAttr(e, s_offset) : nullptr;
        if (off == nullptr) {
          Py_XDECREF(topic);
          Py_XDECREF(part);
          fail = true;
          break;
        }
        long long part_v = PyLong_AsLongLong(part);
        long long off_v = PyLong_AsLongLong(off);
        if ((part_v == -1 || off_v == -1) && PyErr_Occurred()) {
          // Non-int partition/offset: fall back to the Python ctor.
          PyErr_Clear();
          normalized = false;
        } else if (i2 == 0) {
          topic0 = topic;
          Py_INCREF(topic0);
          part0 = part_v;
          prev_off = off_v;
        } else {
          int teq = PyObject_RichCompareBool(topic, topic0, Py_EQ);
          if (teq < 0) {
            fail = true;
          } else if (!teq || part_v != part0 || off_v <= prev_off) {
            normalized = false;
          }
          prev_off = off_v;
        }
        Py_DECREF(topic);
        Py_DECREF(part);
        Py_DECREF(off);
      }
      Py_XDECREF(topic0);

      PyObject* staged = nullptr;
      if (!fail && normalized) {
        staged = bare_instance(staged_type);
        if (staged == nullptr || PyObject_SetAttr(staged, s_stage, grp.name) < 0 ||
            PyObject_SetAttr(staged, s_events_attr, grp.events) < 0) {
          fail = true;
        }
      } else if (!fail) {
        staged = PyObject_CallFunctionObjArgs(staged_type, grp.name, grp.events,
                                              nullptr);
        if (staged == nullptr) fail = true;
      }
      Py_DECREF(grp.events);
      if (!fail && PyList_Append(matched, staged) < 0) fail = true;
      Py_XDECREF(staged);
    }
    groups.clear();
    if (fail) {
      Py_XDECREF(matched);
      return false;
    }

    // Sequence.__init__ is matched + a stage->Staged dict; build both
    // here so no Python frame runs per match.
    PyObject* by_name = PyDict_New();
    PyObject* seq = by_name ? bare_instance(sequence_type) : nullptr;
    if (seq == nullptr) {
      Py_XDECREF(by_name);
      Py_DECREF(matched);
      return false;
    }
    Py_ssize_t n_groups = PyList_GET_SIZE(matched);
    for (Py_ssize_t i2 = 0; i2 < n_groups && !fail; ++i2) {
      PyObject* st = PyList_GET_ITEM(matched, i2);
      PyObject* nm = PyObject_GetAttr(st, s_stage);
      if (nm == nullptr || PyDict_SetItem(by_name, nm, st) < 0) fail = true;
      Py_XDECREF(nm);
    }
    if (!fail && (PyObject_SetAttr(seq, s_matched, matched) < 0 ||
                  PyObject_SetAttr(seq, s_by_name, by_name) < 0)) {
      fail = true;
    }
    Py_DECREF(by_name);
    Py_DECREF(matched);
    if (!fail && qid_of_name != nullptr) {
      // Stacked-query attribution: chains never span queries, so any
      // chain node's name id identifies the owner.
      int32_t nm0 = static_cast<int32_t>(chain[0] >> 32);
      long qid = (nm0 >= 0 && nm0 < n_qids) ? qid_of_name[nm0] : -1;
      PyObject* pair = Py_BuildValue("(lO)", qid, seq);
      if (pair == nullptr || PyList_Append(per_key, pair) < 0) fail = true;
      Py_XDECREF(pair);
    } else if (!fail && PyList_Append(per_key, seq) < 0) {
      fail = true;
    }
    Py_DECREF(seq);
    return !fail;
  }
};

// decode_matches(counts, pend, node_event, node_name, node_pred, name_of_id,
//                registry, staged_type, sequence_type[, qid_of_name_id])
//   -> [list[Sequence]] * K, or [list[(qid, Sequence)]] * K when the
//      optional per-name-id query-attribution table is given (stacked
//      multi-query decode, ops/tables.py compile_multi_query).
PyObject* decode_matches(PyObject*, PyObject* args) {
  PyObject *counts_obj, *pend_obj, *ev_obj, *nm_obj, *pr_obj;
  PyObject *name_of_id, *registry, *staged_type, *sequence_type;
  PyObject* qid_obj = Py_None;
  if (!PyArg_ParseTuple(args, "OOOOOOOOO|O", &counts_obj, &pend_obj, &ev_obj,
                        &nm_obj, &pr_obj, &name_of_id, &registry, &staged_type,
                        &sequence_type, &qid_obj)) {
    return nullptr;
  }

  Buf counts_b;
  if (PyObject_GetBuffer(counts_obj, &counts_b.buf, PyBUF_C_CONTIGUOUS) < 0) {
    return nullptr;
  }
  counts_b.held = true;
  if (counts_b.buf.ndim != 1 || counts_b.buf.itemsize != 4) {
    PyErr_SetString(PyExc_ValueError, "counts must be int32 [K]");
    return nullptr;
  }
  Py_ssize_t K = counts_b.buf.shape[0];
  Py_ssize_t M = -1, B = -1;
  Buf pend_b, ev_b, nm_b, pr_b;
  View2D pend, node_event, node_name, node_pred;
  if (!get_i32_2d(pend_obj, "pend", &pend_b, &pend, &K, &M)) return nullptr;
  if (!get_i32_2d(ev_obj, "node_event", &ev_b, &node_event, &K, &B)) {
    return nullptr;
  }
  if (!get_i32_2d(nm_obj, "node_name", &nm_b, &node_name, &K, &B)) {
    return nullptr;
  }
  if (!get_i32_2d(pr_obj, "node_pred", &pr_b, &node_pred, &K, &B)) {
    return nullptr;
  }

  const auto* counts = static_cast<const int32_t*>(counts_b.buf.buf);

  Buf qid_b;
  Materializer mat;
  if (!mat.init(name_of_id, registry, staged_type, sequence_type, qid_obj,
                &qid_b)) {
    mat.fini();
    return nullptr;
  }

  PyObject* out = PyList_New(K);
  bool fail = out == nullptr;

  // Scratch reused across matches: the chain as (name_id, gidx) pairs
  // (newest-first as walked, consumed oldest-first by the materializer).
  std::vector<int64_t> chain;

  for (Py_ssize_t k = 0; k < K && !fail; ++k) {
    PyObject* per_key = PyList_New(0);
    if (per_key == nullptr) {
      fail = true;
      break;
    }
    PyList_SET_ITEM(out, k, per_key);
    Py_ssize_t n = counts[k];
    if (n > M) n = M;
    for (Py_ssize_t j = 0; j < n && !fail; ++j) {
      int32_t cur = pend.at(k, j);
      chain.clear();
      // Walk newest -> oldest; a cycle (corrupt pool) cannot loop past B.
      for (Py_ssize_t hops = 0; cur >= 0 && cur < B && hops <= B; ++hops) {
        int32_t g = node_event.at(k, cur);
        if (g >= 0) {
          // Dropped puts (g < 0) skip the node, not the chain.
          chain.push_back((static_cast<int64_t>(node_name.at(k, cur)) << 32) |
                          static_cast<uint32_t>(g));
        }
        cur = node_pred.at(k, cur);
      }
      if (chain.empty()) continue;  // GC-dropped (node_drops counts it)
      if (!mat.emit(chain, per_key)) fail = true;
    }
  }

  mat.fini();
  if (fail) {
    Py_XDECREF(out);
    return nullptr;
  }
  return out;
}

// decode_matches_flat(counts, gidx, name, live, name_of_id, registry,
//                     staged_type, sequence_type[, qid_of_name_id])
//   -> same outputs as decode_matches, from the chain-flattened drain
//      table (ops/engine.py build_chain_flatten): gidx/name/live are
//      [K, M, C] int32 planes, hops newest-first; live == 0 ends a chain,
//      a live hop with gidx < 0 is a GC-dropped put (skipped while the
//      chain continues). The device already did the pointer walk, so this
//      is a flat loop over rows.
PyObject* decode_matches_flat(PyObject*, PyObject* args) {
  PyObject *counts_obj, *g_obj, *n_obj, *l_obj;
  PyObject *name_of_id, *registry, *staged_type, *sequence_type;
  PyObject* qid_obj = Py_None;
  if (!PyArg_ParseTuple(args, "OOOOOOOO|O", &counts_obj, &g_obj, &n_obj,
                        &l_obj, &name_of_id, &registry, &staged_type,
                        &sequence_type, &qid_obj)) {
    return nullptr;
  }

  Buf counts_b;
  if (PyObject_GetBuffer(counts_obj, &counts_b.buf, PyBUF_C_CONTIGUOUS) < 0) {
    return nullptr;
  }
  counts_b.held = true;
  if (counts_b.buf.ndim != 1 || counts_b.buf.itemsize != 4) {
    PyErr_SetString(PyExc_ValueError, "counts must be int32 [K]");
    return nullptr;
  }
  Py_ssize_t K = counts_b.buf.shape[0];
  Py_ssize_t M = -1, C = -1;
  Buf g_b, n_b, l_b;
  View3D gidx, name, live;
  if (!get_i32_3d(g_obj, "gidx", &g_b, &gidx, &K, &M, &C)) return nullptr;
  if (!get_i32_3d(n_obj, "name", &n_b, &name, &K, &M, &C)) return nullptr;
  if (!get_i32_3d(l_obj, "live", &l_b, &live, &K, &M, &C)) return nullptr;

  const auto* counts = static_cast<const int32_t*>(counts_b.buf.buf);

  Buf qid_b;
  Materializer mat;
  if (!mat.init(name_of_id, registry, staged_type, sequence_type, qid_obj,
                &qid_b)) {
    mat.fini();
    return nullptr;
  }

  PyObject* out = PyList_New(K);
  bool fail = out == nullptr;
  std::vector<int64_t> chain;

  for (Py_ssize_t k = 0; k < K && !fail; ++k) {
    PyObject* per_key = PyList_New(0);
    if (per_key == nullptr) {
      fail = true;
      break;
    }
    PyList_SET_ITEM(out, k, per_key);
    Py_ssize_t n = counts[k];
    if (n > M) n = M;
    for (Py_ssize_t j = 0; j < n && !fail; ++j) {
      chain.clear();
      for (Py_ssize_t c = 0; c < C; ++c) {
        if (!live.at(k, j, c)) break;  // chain ended
        int32_t g = gidx.at(k, j, c);
        if (g >= 0) {
          // Dropped puts (g < 0) skip the hop, not the chain.
          chain.push_back((static_cast<int64_t>(name.at(k, j, c)) << 32) |
                          static_cast<uint32_t>(g));
        }
      }
      if (chain.empty()) continue;  // GC-dropped (node_drops counts it)
      if (!mat.emit(chain, per_key)) fail = true;
    }
  }

  mat.fini();
  if (fail) {
    Py_XDECREF(out);
    return nullptr;
  }
  return out;
}

PyMethodDef methods[] = {
    {"decode_matches", decode_matches, METH_VARARGS,
     "Walk per-key match chains from pulled node pools and build Sequence "
     "objects; returns a list of K lists."},
    {"decode_matches_flat", decode_matches_flat, METH_VARARGS,
     "Build Sequence objects from a chain-flattened drain table "
     "([K, M, C] gidx/name/live planes); returns a list of K lists."},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef module = {
    PyModuleDef_HEAD_INIT, "_decoder",
    "Native match decoder (see decoder.cc).", -1, methods,
};

}  // namespace

PyMODINIT_FUNC PyInit__decoder() { return PyModule_Create(&module); }
