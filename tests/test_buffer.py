"""Shared buffer conformance (reference: SharedVersionedBufferTest.java:52-94).

The store is the exact-lineage redesign (state/buffer.py): chains are linked
by node id instead of Dewey-routed (stage, event) keys, so the reference
scenarios translate to parent-linked puts and head-id extraction. The
assertions -- extracted sequence content, stage order, shared-prefix reuse
across branches -- are the reference's.
"""
import pytest

from kafkastreams_cep_tpu import Event, SharedVersionedBuffer

TOPIC = "topic-test"

ev1 = Event("k1", "v1", 1000000001, TOPIC, 0, 0)
ev2 = Event("k2", "v2", 1000000002, TOPIC, 0, 1)
ev3 = Event("k3", "v3", 1000000003, TOPIC, 0, 2)
ev4 = Event("k4", "v4", 1000000004, TOPIC, 0, 3)
ev5 = Event("k5", "v5", 1000000005, TOPIC, 0, 4)


def test_extract_patterns_with_one_run():
    """Linear put/get (SharedVersionedBufferTest.java:52-66)."""
    buffer = SharedVersionedBuffer()
    n1 = buffer.put("first", ev1)
    n2 = buffer.put("second", ev2, n1)
    n3 = buffer.put("latest", ev3, n2)

    sequence = buffer.get(n3)
    assert sequence.size() == 3
    assert sequence.get_by_name("latest").events[0] == ev3
    assert sequence.get_by_name("second").events[0] == ev2
    assert sequence.get_by_name("first").events[0] == ev1


def test_extract_patterns_with_branching_run():
    """Two branches share the (first, second) prefix; each extracts its own
    lineage (SharedVersionedBufferTest.java:68-86)."""
    buffer = SharedVersionedBuffer()
    n1 = buffer.put("first", ev1)
    n2 = buffer.put("second", ev2, n1)
    head1 = buffer.put("latest", ev3, n2)

    # The branch forks off n2: prefix nodes are stored once.
    b3 = buffer.put("second", ev3, n2)
    b4 = buffer.put("second", ev4, b3)
    head2 = buffer.put("latest", ev5, b4)

    seq1 = buffer.get(head1)
    assert seq1.size() == 3
    assert seq1.get_by_name("latest").events[0] == ev3
    assert seq1.get_by_name("second").events[0] == ev2
    assert seq1.get_by_name("first").events[0] == ev1

    seq2 = buffer.get(head2)
    assert seq2.size() == 5
    assert len(seq2.get_by_name("latest").events) == 1
    assert len(seq2.get_by_name("second").events) == 3
    assert len(seq2.get_by_name("first").events) == 1

    # Shared prefix: 6 puts, 6 nodes -- the fork did not copy (first, ev1)
    # or (second, ev2).
    assert len(buffer) == 6


def test_stage_order_reversed_on_extract():
    buffer = SharedVersionedBuffer()
    n1 = buffer.put("first", ev1)
    n2 = buffer.put("second", ev2, n1)
    n3 = buffer.put("latest", ev3, n2)

    sequence = buffer.get(n3)
    assert [s.stage for s in sequence.matched] == ["first", "second", "latest"]


def test_put_requires_existing_parent():
    buffer = SharedVersionedBuffer()
    with pytest.raises(ValueError):
        buffer.put("second", ev2, 42)


def test_gc_reclaims_unreachable_chains_only():
    """Mark-sweep from live heads: a dead branch is reclaimed, the shared
    prefix survives as long as a live run reaches it (the lineage analog of
    refcount removal, SharedVersionedBufferStoreImpl.java:176-201)."""
    buffer = SharedVersionedBuffer()
    n1 = buffer.put("first", ev1)
    n2 = buffer.put("second", ev2, n1)
    head1 = buffer.put("latest", ev3, n2)
    head2 = buffer.put("second", ev4, n2)
    assert len(buffer) == 4

    # head1's run completed (extracted) -> only head2 is live.
    reclaimed = buffer.gc([head2])
    assert reclaimed == 1
    assert len(buffer) == 3
    seq = buffer.get(head2)
    assert seq.size() == 3
    assert seq.get_by_name("first").events[0] == ev1

    # No live heads: everything goes.
    assert buffer.gc([]) == 3
    assert len(buffer) == 0
