"""zerosync: host-sync constructs inside hot-path functions.

The NFA^b advance contract (PAPER.md; SASE NFA^b, Agrawal et al.
SIGMOD'08): every per-batch branch decision happens on device, so an
advance must dispatch without a host round-trip. A single stray
``float(traced)`` or ``np.asarray(device_array)`` turns the pipelined
advance into a lockstep one -- tests/test_obs.py pins the behavior at
runtime for one configuration; this checker pins the *construct* for
every hot-path function on every path.

Hot-path functions are declared two ways:

- the ``HOT_PATHS`` table below (fnmatch patterns over qualnames) -- the
  repo's own hot set, centrally auditable. A pattern that stops matching
  anything is itself a finding (CEP-S04), so the table cannot rot.
- a ``# cep: hot-path`` pragma on (or directly above) a ``def`` line --
  how out-of-tree and fixture code opts in.

Nested functions inherit hotness from their enclosing hot function.

Findings:
    CEP-S01  sync tell: .item()/.tolist()/block_until_ready/device_get,
             or np.asarray/np.array on a traced-looking value
    CEP-S02  host scalarization: float()/int()/bool() on a traced value
    CEP-S03  traced-value truthiness in if/while/assert/and/or/not
    CEP-S04  stale HOT_PATHS entry (pattern matches nothing)

"Traced-looking" is a local dataflow approximation: parameters with
array-carrying names (state, pool, xs, ...), results of jnp./jax.lax.
calls and of the engine's compiled-dispatch attributes, and anything
derived from them by arithmetic, subscripting, or method chaining.
``.shape``/``.dtype``/``.ndim``/``.size`` access exits the traced set
(static metadata is host-safe). Audited sites carry
``# cep: sync-ok(reason)``.
"""
from __future__ import annotations

import ast
from fnmatch import fnmatch
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, SourceFile, dotted_name as _dotted

#: repo-relative file -> qualname fnmatch patterns of hot-path functions.
HOT_PATHS: Dict[str, Tuple[str, ...]] = {
    "kafkastreams_cep_tpu/ops/engine.py": ("build_*",),
    "kafkastreams_cep_tpu/ops/pallas_step.py": ("build_*",),
    "kafkastreams_cep_tpu/ops/runtime.py": (
        "DeviceNFA.advance",
        "DeviceNFA._flush_group",
    ),
    "kafkastreams_cep_tpu/parallel/batched.py": (
        "BatchedDeviceNFA.pack",
        "BatchedDeviceNFA.advance",
        "BatchedDeviceNFA.advance_packed",
        "BatchedDeviceNFA._flush_group",
        "BatchedDeviceNFA._dispatch_pos_probe",
        "BatchedDeviceNFA._occupancy_bound",
    ),
    "kafkastreams_cep_tpu/parallel/key_shard.py": (
        "build_batched_*",
        "shard_state",
        "shard_xs",
    ),
}

#: parameter names seeded as traced (the engine's array-carrying names).
ARRAY_PARAMS = {
    "state", "pool", "xs", "ys", "xi", "xt", "xs_t", "pend", "carry",
    "leaf", "tree", "arrays",
}
#: attribute access that *exits* the traced set (static metadata).
META_ATTRS = {"shape", "dtype", "ndim", "size", "at"}
#: dotted-call prefixes whose results are traced values.
ARRAY_CALL_PREFIXES = (
    "jnp.", "jax.numpy.", "jax.lax.", "jax.nn.", "lax.",
)
#: substrings of ``self._X(...)`` callees that return device values
#: (the compiled-dispatch attributes: self._advance, self._append, ...).
DISPATCH_HINTS = ("advance", "append", "flush", "post", "step", "probe")
#: method calls that keep a traced receiver traced.
_CHAIN_METHODS = {
    "sum", "min", "max", "mean", "astype", "reshape", "ravel", "any",
    "all", "copy", "take", "dot", "cumsum", "argmax", "argmin", "clip",
    "transpose", "squeeze",
}
#: always a sync when called as a method in a hot function.
SYNC_METHODS = {"item", "tolist", "block_until_ready"}


class _FunctionIndex(ast.NodeVisitor):
    """qualname -> def node for every function in a module."""

    def __init__(self) -> None:
        self.functions: Dict[str, ast.AST] = {}
        self._stack: List[str] = []

    def _visit_def(self, node) -> None:
        self._stack.append(node.name)
        self.functions[".".join(self._stack)] = node
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_def
    visit_AsyncFunctionDef = _visit_def

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()


def function_index(src: SourceFile) -> Dict[str, ast.AST]:
    idx = _FunctionIndex()
    idx.visit(src.tree)
    return idx.functions


def hot_functions(src: SourceFile) -> Tuple[Dict[str, ast.AST], List[str]]:
    """(qualname -> def node of hot roots, stale HOT_PATHS patterns)."""
    funcs = function_index(src)
    hot: Dict[str, ast.AST] = {}
    stale: List[str] = []
    for pattern in HOT_PATHS.get(src.relpath, ()):
        matched = False
        for qual, node in funcs.items():
            if fnmatch(qual, pattern):
                hot[qual] = node
                matched = True
        if not matched:
            stale.append(pattern)
    for qual, node in funcs.items():
        line = node.lineno
        deco_first = min(
            [d.lineno for d in getattr(node, "decorator_list", [])] + [line]
        )
        if (
            src.has_marker(line, "hot-path")
            or src.has_marker(deco_first - 1, "hot-path")
        ):
            hot[qual] = node
    # Nested functions are visited through their parent; keep roots only.
    roots = {
        qual: node
        for qual, node in hot.items()
        if not any(qual != q and qual.startswith(q + ".") for q in hot)
    }
    return roots, stale


class _TracedEnv:
    """Forward-pass approximation of names bound to traced values."""

    def __init__(self, fn: ast.AST) -> None:
        self.names: Set[str] = set()
        # Seed from the root AND every nested def: inner jitted bodies
        # (build_* closures) carry the array params.
        for node in ast.walk(fn):
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                args = node.args
                for a in (
                    list(args.posonlyargs)
                    + list(args.args)
                    + list(args.kwonlyargs)
                ):
                    if a.arg in ARRAY_PARAMS:
                        self.names.add(a.arg)

    # ------------------------------------------------------------ expression
    def traced(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.names
        if isinstance(node, ast.Attribute):
            if node.attr in META_ATTRS:
                return False
            return self.traced(node.value)
        if isinstance(node, ast.Subscript):
            if self.traced(node.value):
                return True
            base = node.value
            return isinstance(base, ast.Name) and base.id in ARRAY_PARAMS
        if isinstance(node, ast.Call):
            dotted = _dotted(node.func)
            if dotted is not None:
                if dotted.startswith(ARRAY_CALL_PREFIXES):
                    return True
                if dotted.startswith("self._") and any(
                    h in dotted for h in DISPATCH_HINTS
                ):
                    return True
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _CHAIN_METHODS
            ):
                return self.traced(node.func.value)
            return False
        if isinstance(node, (ast.BinOp,)):
            return self.traced(node.left) or self.traced(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.traced(node.operand)
        if isinstance(node, ast.Compare):
            # Membership and identity tests on a columns dict are host
            # pytree-key operations, not device comparisons.
            if all(
                isinstance(op, (ast.In, ast.NotIn, ast.Is, ast.IsNot))
                for op in node.ops
            ):
                return False
            return self.traced(node.left) or any(
                self.traced(c) for c in node.comparators
            )
        if isinstance(node, ast.IfExp):
            return self.traced(node.body) or self.traced(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.traced(e) for e in node.elts)
        if isinstance(node, (ast.DictComp, ast.ListComp, ast.SetComp)):
            return any(
                self.traced(sub)
                for sub in ast.walk(node)
                if isinstance(sub, ast.Call)
            )
        return False

    def bind(self, target: ast.AST, traced: bool) -> None:
        if isinstance(target, ast.Name):
            if traced:
                self.names.add(target.id)
            else:
                self.names.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self.bind(elt, traced)

    def learn(self, fn: ast.AST) -> None:
        """Two forward passes over assignments (the second catches names
        first used above their traced re-binding inside loops)."""
        for _ in range(2):
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign):
                    traced = self.traced(node.value)
                    for t in node.targets:
                        self.bind(t, traced)
                elif isinstance(node, ast.AugAssign):
                    if self.traced(node.value):
                        self.bind(node.target, True)
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    self.bind(node.target, self.traced(node.value))


def _call_findings(
    src: SourceFile, fn: ast.AST, env: _TracedEnv, qual: str
) -> List[Finding]:
    out: List[Finding] = []

    def add(node: ast.AST, code: str, msg: str) -> None:
        out.append(
            Finding(
                "zerosync", code, src.relpath, node.lineno,
                f"{msg} in hot-path function {qual}",
                context=src.context_line(node.lineno),
            )
        )

    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            dotted = _dotted(node.func) or ""
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in SYNC_METHODS
            ):
                add(node, "CEP-S01", f"host sync .{node.func.attr}()")
            elif dotted in ("jax.block_until_ready", "jax.device_get"):
                add(node, "CEP-S01", f"host sync {dotted}()")
            elif dotted in (
                "np.asarray", "np.array", "numpy.asarray", "numpy.array",
                "np.copy",
            ):
                if node.args and env.traced(node.args[0]):
                    add(
                        node, "CEP-S01",
                        f"{dotted}() materializes a traced value on host",
                    )
            elif (
                isinstance(node.func, ast.Name)
                and node.func.id in ("float", "int", "bool")
                and node.args
                and env.traced(node.args[0])
            ):
                add(
                    node, "CEP-S02",
                    f"{node.func.id}() scalarizes a traced value "
                    "(device round-trip)",
                )
    return out


def _truthiness_findings(
    src: SourceFile, fn: ast.AST, env: _TracedEnv, qual: str
) -> List[Finding]:
    out: List[Finding] = []

    def check_test(expr: ast.AST) -> None:
        if isinstance(expr, ast.BoolOp):
            for v in expr.values:
                check_test(v)
            return
        if isinstance(expr, ast.UnaryOp) and isinstance(expr.op, ast.Not):
            check_test(expr.operand)
            return
        if env.traced(expr):
            out.append(
                Finding(
                    "zerosync", "CEP-S03", src.relpath, expr.lineno,
                    "traced-value truthiness forces a device sync "
                    f"in hot-path function {qual} (use jnp.where/lax.cond)",
                    context=src.context_line(expr.lineno),
                )
            )

    for node in ast.walk(fn):
        if isinstance(node, (ast.If, ast.While, ast.IfExp)):
            check_test(node.test)
        elif isinstance(node, ast.Assert):
            check_test(node.test)
    return out


def check(files: Sequence[SourceFile], root_dir: str) -> List[Finding]:
    findings: List[Finding] = []
    for src in files:
        roots, stale = hot_functions(src)
        for pattern in stale:
            findings.append(
                Finding(
                    "zerosync", "CEP-S04", src.relpath, 0,
                    f"stale HOT_PATHS pattern {pattern!r} matches no "
                    "function -- update analysis/zerosync.py",
                    context=f"hot-paths:{pattern}",
                )
            )
        for qual, fn in roots.items():
            env = _TracedEnv(fn)
            env.learn(fn)
            findings.extend(_call_findings(src, fn, env, qual))
            findings.extend(_truthiness_findings(src, fn, env, qual))
    return findings
