"""Exactly-once match emission: the per-query emitted-match watermark.

The reference's delivery guarantee to its sink topic is at-least-once:
a crash between the sink write and the consumer-offset commit replays the
interval and re-emits matches the sink already saw (Kafka Streams without
EOS transactions -- SURVEY §5.3). This module closes that window for the
embedded pipeline without needing a transaction coordinator, exploiting
the fact that the framework owns its transport:

  * every emitted match carries its **sequence identity** -- a digest of
    the (stage -> event (topic, partition, offset) set) structure, the same
    identity that distinguishes simultaneous runs (dewey-versioned run
    forks complete with distinct matched sets or distinct completing
    offsets), occurrence-qualified so two legitimately identical matches
    in one window stay distinct -- embedded in the sink record key;
  * at commit, the gate persists an `EmitWatermark` (each sink topic's end
    offset) through the changelogged store stack, ordered BEFORE the
    offsets append exactly like every other store flush;
  * on restore, the gate replays its watermark from the changelog and
    re-reads only the sink tail past it: whatever landed there during the
    crash window is exactly the set of matches the sink already saw, and
    replay dedupes against it.

Every window is bounded: committed offsets exceed the completing offsets
of every emitted match (the commit happens after processing), so the
processor-level offset HWMs guarantee a replay can never regenerate a
match from before the last commit -- the gate only ever tracks one
commit interval's emissions.
"""
from __future__ import annotations

import hashlib
import pickle
import struct
from typing import Any, Dict, List, Optional, Set, Tuple

from ..core.sequence import Sequence
from ..state.nfa_store import EmissionStore, EmitWatermark

#: Sink record key framing version tag (see `encode_sink_key`).
SINK_KEY_TAG = "kct-sink-v1"


def _put(out: bytearray, data: bytes) -> None:
    out += struct.pack("<I", len(data))
    out += data


def identity_prefix(query: str, key: Any) -> bytes:
    """The (query, canonical key) frames that open every sequence
    identity. The user key -- an arbitrary object -- is canonicalized
    through one serialize/deserialize round trip (see
    `sequence_identity`)."""
    out = bytearray()
    _put(out, query.encode("utf-8"))
    key_bytes = pickle.dumps(key, protocol=pickle.HIGHEST_PROTOCOL)
    _put(
        out,
        pickle.dumps(
            pickle.loads(key_bytes), protocol=pickle.HIGHEST_PROTOCOL
        ),
    )
    return bytes(out)


def sequence_ident_frames(seq: Sequence) -> bytes:
    """The per-stage identity frame suffix of `sequence_identity`: what
    the native sink-to-bytes decoder (decoder.cc emit_bytes) emits as
    `ident`, byte-for-byte -- `EmissionGate.admit_ident` hashes
    `identity_prefix + frames` and must equal `admit`'s digest."""
    out = bytearray()
    for staged in seq.matched:
        _put(out, b"\x01")
        _put(out, staged.stage.encode("utf-8"))
        for e in staged.events:
            _put(out, e.topic.encode("utf-8"))
            out += struct.pack("<qq", int(e.partition), int(e.offset))
    return bytes(out)


def sequence_identity(query: str, key: Any, seq: Sequence) -> bytes:
    """Canonical identity bytes of one match: query, record key, and the
    per-stage matched event identities ((topic, partition, offset) -- the
    Event identity contract, core/event.py).

    Encoded by hand, NOT by pickling the structure: pickle memoizes by
    object identity, so the same logical match serializes differently
    before and after a changelog restore (shared topic strings become
    distinct decoded copies) and the digest would stop being a stable
    identity across crash recovery. The user key -- an arbitrary object --
    is canonicalized through one serialize/deserialize round trip for the
    same reason."""
    h = hashlib.blake2b(digest_size=16)
    h.update(identity_prefix(query, key))
    h.update(sequence_ident_frames(seq))
    return h.digest()


def encode_sink_key(key: Any, digest: bytes) -> bytes:
    """Sink record key: pickled (tag, original key, emission digest).

    The digest rides the sink record itself so the sink topic is the
    source of truth for "what the sink already saw" -- crash recovery
    re-reads the tail and dedupes with zero cross-topic atomicity
    requirements (README "Failure semantics")."""
    from ..state.store import default_serializer

    return default_serializer((SINK_KEY_TAG, key, digest))


def decode_sink_key(data: Optional[bytes]) -> Tuple[Any, Optional[bytes]]:
    """(original key, digest) from a sink record key; (raw, None) for
    records predating the identity framing."""
    from ..state.store import default_deserializer

    if data is None:
        return None, None
    try:
        decoded = default_deserializer(data)
    except Exception:
        return data, None
    if (
        isinstance(decoded, tuple)
        and len(decoded) == 3
        and decoded[0] == SINK_KEY_TAG
    ):
        return decoded[1], decoded[2]
    return decoded, None


class EmissionGate:
    """Per-query exactly-once admission for the emission path.

    `admit(key, seq)` returns the occurrence-qualified digest when the
    match must be emitted, or None when the sink already saw it (counted
    in `cep_emit_deduped_total{query}`)."""

    def __init__(
        self,
        query_name: str,
        store: Optional[EmissionStore] = None,
        registry: Optional[Any] = None,
    ) -> None:
        from ..obs.registry import default_registry

        self.query = query_name
        self.store = store if store is not None else EmissionStore()
        self.metrics = registry if registry is not None else default_registry()
        self._m_deduped = self.metrics.counter(
            "cep_emit_deduped_total",
            "Replayed matches the sink already saw, skipped by the "
            "emission gate (exactly-once recovery)",
            labels=("query",),
        ).labels(query=self.query)
        #: digests emitted (or recovered from the sink tail) since the
        #: last commit; the commit clears it -- see the module docstring's
        #: bounded-window argument.
        self._emitted: Set[bytes] = set()
        #: occurrence counter per base identity within the window: two
        #: legitimately identical matches (same stages, same events --
        #: possible under branching selection) get distinct digests, so
        #: the fault-free path NEVER drops a real duplicate; regeneration
        #: during replay renumbers identically (deterministic order).
        self._occurrence: Dict[bytes, int] = {}
        #: per-key identity_prefix cache for the bytes path: the prefix
        #: pickles the key twice per match otherwise. Bounded; cleared
        #: wholesale on overflow (keys are usually few and stable).
        self._prefix_cache: Dict[Any, bytes] = {}

    # ------------------------------------------------------------- admission
    def admit(self, key: Any, seq: Sequence) -> Optional[bytes]:
        return self._qualify(sequence_identity(self.query, key, seq))

    def admit_ident(self, key: Any, ident: bytes) -> Optional[bytes]:
        """Bytes-path admission: `ident` is the per-stage identity frame
        suffix the native sink-to-bytes decoder emitted
        (`sequence_ident_frames`). The digest is bitwise-identical to
        `admit(key, seq)` on the same match -- the exactly-once window is
        shared across object- and bytes-mode emissions."""
        h = hashlib.blake2b(digest_size=16)
        h.update(self._key_prefix(key))
        h.update(ident)
        return self._qualify(h.digest())

    def _key_prefix(self, key: Any) -> bytes:
        try:
            cached = self._prefix_cache.get(key)
        except TypeError:  # unhashable key: compute every time
            return identity_prefix(self.query, key)
        if cached is None:
            if len(self._prefix_cache) >= 4096:
                self._prefix_cache.clear()
            cached = self._prefix_cache[key] = identity_prefix(
                self.query, key
            )
        return cached

    def _qualify(self, base: bytes) -> Optional[bytes]:
        n = self._occurrence.get(base, 0)
        self._occurrence[base] = n + 1
        digest = hashlib.blake2b(
            base + n.to_bytes(8, "little"), digest_size=16
        ).digest()
        if digest in self._emitted:
            self._m_deduped.inc()
            return None
        self._emitted.add(digest)
        return digest

    # ------------------------------------------------------------ durability
    def commit(self, log: Optional[Any], sink_topics: List[str]) -> None:
        """Roll the watermark forward at the commit boundary: record each
        sink topic's current end offset and clear the window (committed
        consumer offsets now exceed every emitted match's completing
        offset, so nothing in it can regenerate)."""
        if log is not None and sink_topics:
            self.store.put(
                EmitWatermark(
                    sink_pos={t: log.end_offset(t) for t in sink_topics}
                )
            )
        self._emitted.clear()
        self._occurrence.clear()

    def recover(self, log: Optional[Any], sink_topics: List[str]) -> int:
        """Seed the window from the sink tail past the persisted watermark:
        those records landed during the crash window (after the last
        commit), and replay will regenerate exactly them. Returns how many
        emitted digests were recovered."""
        self._emitted.clear()
        self._occurrence.clear()
        if log is None or not sink_topics:
            return 0
        wm = self.store.get()
        sink_pos = wm.sink_pos if wm is not None else {}
        n = 0
        for topic in sink_topics:
            for rec in log.read(topic, start=sink_pos.get(topic, 0)):
                _key, digest = decode_sink_key(rec.key)
                if digest is not None:
                    self._emitted.add(digest)
                    n += 1
        return n
