"""Seeded thread-shared-state violations.

Mutation fixture for tests/test_lint.py: an attribute written from two
thread roots with no lock (CEP-T01), and an anonymous thread root
(CEP-T03). NOT runnable production code.
"""
import threading


class LeakyWorker:
    def __init__(self) -> None:
        self.counter = 0
        self.ok = 0
        self._lock = threading.Lock()
        self._thread = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name="fixture-loop", daemon=True
        )
        self._thread.start()
        # CEP-T03: anonymous root.
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self) -> None:
        while True:
            self.counter += 1        # CEP-T01: also written from main
            with self._lock:
                self.ok += 1         # guarded everywhere: clean

    def bump(self) -> None:
        self.counter += 1            # CEP-T01: main-root write, no lock
        with self._lock:
            self.ok += 1
