#!/usr/bin/env python
"""Validate a bench.py or soak JSON artifact against the documented schema.

BENCH_r*.json artifacts must stay self-describing (PERF.md "v10 metrics
dictionary" documents every key): this checker fails on BOTH missing
documented keys AND undocumented extras, so a bench change that grows or
renames the JSON contract must update the dictionary (and this schema) in
the same PR. It also proves the two metric expositions agree: the
`metrics` section (the engine registry's JSON snapshot) is rebuilt into a
registry, rendered as Prometheus 0.0.4 text, parsed back, and compared
value-for-value.

SOAK_r*.json verdict artifacts (ISSUE 12, faults/soak.py) are validated
by the same both-ways rule via `validate_soak`: the SLO set is pinned to
`SOAK_SLOS` exactly, every SLO entry carries the documented verdict
shape, every scraped series summary carries min/max/last/slope, and the
`metrics`/`faults` sections reuse the bench contract (snapshot
round-trip + FAULT_SERIES key pinning). `main` dispatches on the
artifact shape, so one CLI checks both.

Usage:
    python scripts/check_bench_schema.py BENCH.json   # or - for stdin
    python scripts/check_bench_schema.py SOAK_r01.json
bench.py --smoke and the soak harness run validate()/validate_soak() on
their own output before printing.
"""
from __future__ import annotations

import json
import math
import sys
from typing import Any, Dict, List, Optional, Tuple

NUMBER = (int, float)
OPT_NUMBER = (int, float, type(None))

#: Top-level contract: key -> (required, allowed types). Every key bench
#: emits must appear here; every required key must be present in the
#: artifact. One line per key in PERF.md "v10 metrics dictionary".
TOP_LEVEL: Dict[str, Tuple[bool, tuple]] = {
    "metric": (True, (str,)),
    "value": (True, NUMBER),
    "unit": (True, (str,)),
    "vs_baseline": (True, OPT_NUMBER),
    "p99_match_emit_ms": (True, OPT_NUMBER),
    "components": (True, (dict, type(None))),
    "tunnel_mbps": (True, OPT_NUMBER),
    "tunnel_degraded": (True, (bool,)),
    "latency_p99_match_emit_ms": (True, OPT_NUMBER),
    "platform": (True, (str,)),
    "quick": (True, (bool,)),
    # Explicit bench mode: full | quick | smoke (ISSUE 16) -- the perf
    # ledger's mode_change excusal reads this; legacy artifacts derive
    # it from quick/schema_ok, so the key is optional.
    "mode": (False, (str,)),
    # Zero-knob capacity (ISSUE 18): True when every config armed at
    # EngineConfig() defaults and the autosizer settled the shapes
    # (--no-autosize records False). Optional: legacy artifacts predate
    # the autosizer, and perf_ledger excuses deltas across a flag flip.
    "autosized": (False, (bool,)),
    # The flagship config's settle record (CapacityAutosizer state +
    # rounds + warmup drops); None when the flagship did not run or
    # --no-autosize pinned the defaults.
    "autosize": (False, (dict, type(None))),
    "denominator": (True, (str,)),
    "configs": (True, (dict,)),
    "metrics": (True, (dict,)),
    "faults": (True, (dict,)),
    "latency": (True, (dict, type(None))),
    "observation": (True, (dict,)),
    "metrics_merged": (True, (dict, type(None))),
    "watermark": (True, (dict, type(None))),
    "transport": (True, (dict, type(None))),
    # Sink-to-bytes pass (ISSUE 17): objects vs json vs arrow decode eps
    # with parity booleans and the drain controller's chosen knobs; None
    # outside --smoke.
    "sink": (True, (dict, type(None))),
    "compile": (True, (dict,)),
    "regression": (True, (dict, type(None))),
    "schema_ok": (False, (bool,)),
}

#: The `watermark` block (ISSUE 10): the event-time pass's reorder-stage
#: overhead vs. the in-order baseline and watermark-lag percentiles; None
#: when the skip_any8 family did not run.
WATERMARK_KEYS: Dict[str, tuple] = {
    "inorder_eps": (int, float),
    "reorder_eps": (int, float),
    "overhead_pct": (int, float, type(None)),
    "lag_p50_ms": (int, float),
    "lag_p99_ms": (int, float),
    "released": (int, float),
    "late_dropped": (int, float),
    "occupancy_peak": (int, float),
    "inorder_matches": (int, float),
    "reorder_matches": (int, float),
    "n_expired_inorder": (int, float),
    "n_expired_reorder": (int, float),
    "keys": (int, float),
    "batch": (int, float),
}

#: The `transport` block (ISSUE 15): the smoke's wire-transport loopback
#: pass -- the durable pipeline over a socket RecordLog digest-pinned vs
#: an in-memory golden, plus the framing overhead figures; None outside
#: --smoke.
TRANSPORT_KEYS: Dict[str, tuple] = {
    "events": NUMBER,
    "matches": NUMBER,
    "digest_equal": (bool,),
    "window": NUMBER,
    "produce_eps": OPT_NUMBER,
    "e2e_eps": OPT_NUMBER,
    "frames": NUMBER,
    "wire_mb": NUMBER,
    "backpressure_hits": NUMBER,
    "reconnects": NUMBER,
    "retries": NUMBER,
    "torn_frames": NUMBER,
}

#: The `sink` block (ISSUE 17): the smoke's sink-to-bytes pass -- the
#: same stock stream through objects/json/arrow engines, byte + emission-
#: digest parity pinned against the object path, decode-path eps per
#: format, and the adaptive drain controller's chosen knobs.
SINK_KEYS: Dict[str, tuple] = {
    "events": NUMBER,
    "matches": NUMBER,
    "counts_equal": (bool,),
    "parity_json": (bool,),
    "parity_arrow": (bool,),
    "digest_parity": (bool,),
    "native": (bool,),
    "eps": (dict,),
    "sink_bytes": (dict,),
    "controller": (dict,),
}
SINK_EPS_KEYS: Dict[str, tuple] = {
    "objects": NUMBER,
    "json": NUMBER,
    "arrow": NUMBER,
}
SINK_BYTES_KEYS: Dict[str, tuple] = {
    "json": NUMBER,
    "arrow": NUMBER,
}
#: DrainController.state() (parallel/drain_sched.py): the knob/signal
#: snapshot embedded by both the bench `sink` block and the soak's
#: auto-cadence scenario; pinned both ways here AND consumed by
#: scripts/perf_ledger.py (SINK_CONTROLLER_KEYS there must match).
SINK_CONTROLLER_KEYS: Dict[str, tuple] = {
    "target_emit_ms": NUMBER,
    "gc_group": NUMBER,
    "suggest_t": NUMBER,
    "p99_ms": OPT_NUMBER,
    "rate_ev_s": NUMBER,
    "ticks": NUMBER,
    "adjustments": NUMBER,
    "gc_changes": NUMBER,
    "compile_budget": NUMBER,
    "compiles_seen": OPT_NUMBER,
}

#: CapacityAutosizer.state() (parallel/drain_sched.py, ISSUE 18): the
#: capacity law's snapshot -- chosen caps, resize/refusal counts, the
#: shrink floor, and the NESTED cadence state (SINK_CONTROLLER_KEYS).
#: Controller blocks dispatch on the `resizes` key: present means
#: autosizer, absent means a plain drain controller.
AUTOSIZER_STATE_KEYS: Dict[str, tuple] = {
    "lanes": NUMBER,
    "nodes": NUMBER,
    "matches": NUMBER,
    "matches_per_step": NUMBER,
    "suggest_t": NUMBER,
    "resizes": NUMBER,
    "refused": NUMBER,
    "ticks": NUMBER,
    "compile_budget": NUMBER,
    "floor": (dict,),
    "cadence": (dict,),
    "compiles_seen": OPT_NUMBER,
}

#: A bench `autosize` settle record (top-level for the flagship; each
#: batched config carries its own under configs.*.autosize).
AUTOSIZE_BLOCK_KEYS: Dict[str, tuple] = {
    "state": (dict,),
    "settle_rounds": NUMBER,
    "warmup_drops": (dict,),
}
AUTOSIZE_DROP_KEYS: Dict[str, tuple] = {
    "lane_drops": NUMBER,
    "node_drops": NUMBER,
    "match_drops": NUMBER,
}


def _check_controller_block(
    block: Optional[dict], where: str, errors: List[str]
) -> None:
    """A `controller` entry is either a CapacityAutosizer state (ISSUE
    18; discriminated by its `resizes` key) or a plain DrainController
    state -- validate whichever shape it claims, both ways."""
    if block is None:
        return
    if "resizes" in block:
        _check_flat_block(block, AUTOSIZER_STATE_KEYS, where, errors)
        if isinstance(block.get("cadence"), dict):
            _check_flat_block(
                block["cadence"], SINK_CONTROLLER_KEYS,
                f"{where}.cadence", errors,
            )
    else:
        _check_flat_block(block, SINK_CONTROLLER_KEYS, where, errors)


def _check_autosize_block(
    block: Optional[dict], where: str, errors: List[str]
) -> None:
    if block is None:
        return
    _check_flat_block(block, AUTOSIZE_BLOCK_KEYS, where, errors)
    if isinstance(block.get("state"), dict):
        _check_controller_block(block["state"], f"{where}.state", errors)
    if isinstance(block.get("warmup_drops"), dict):
        _check_flat_block(
            block["warmup_drops"], AUTOSIZE_DROP_KEYS,
            f"{where}.warmup_drops", errors,
        )

#: The `observation` block (ISSUE 7): what telemetry was armed while the
#: numbers were taken, so BENCH_r* artifacts self-describe the
#: observation overhead. http_* keys are None outside --smoke.
OBSERVATION_KEYS: Dict[str, tuple] = {
    "provenance_sample": NUMBER,
    "http_server": (bool,),
    "http_endpoints_ok": (bool, type(None)),
    "served_matches_snapshot": (bool, type(None)),
    "chrome_trace_ok": (bool, type(None)),
    "profilez_armed": (bool, type(None)),
}

#: The `compile` block (ISSUE 9): per-entry-point compile telemetry from
#: the flagship engine's compile watch (obs/compile.py). `fns` entries
#: carry compiles/seconds always; flops/bytes are None when the backend
#: offers no cost model for that lowering.
COMPILE_KEYS: Dict[str, tuple] = {
    "fns": (dict,),
    "total_compiles": NUMBER,
    "total_seconds": NUMBER,
}
COMPILE_FN_KEYS: Dict[str, tuple] = {
    "compiles": NUMBER,
    "seconds": NUMBER,
    "flops": OPT_NUMBER,
    "bytes": OPT_NUMBER,
}

#: The `regression` block (ISSUE 9): deltas vs a --compare prior
#: artifact; None without --compare. Per-config entries hold per-metric
#: {prev, cur, delta_pct, regressed} dicts.
REGRESSION_KEYS: Dict[str, tuple] = {
    "prior": (str,),
    "tolerance": NUMBER,
    "configs": (dict,),
    "missing_configs": (list,),
    "regressed": (bool,),
    "excused": (bool,),
    "tunnel_degraded_prev": (bool,),
    "tunnel_degraded_cur": (bool,),
    # Platform-change excusal (ISSUE 12): a round recorded on a
    # different backend (cpu vs tpu) is an environment delta, not a code
    # regression -- both sides' platforms ride the block so the excusal
    # is auditable. None when the prior predates self-described
    # platforms (truncated wrappers).
    "platform_prev": (str, type(None)),
    "platform_cur": (str, type(None)),
    # Bench-mode excusal (ISSUE 16): full vs --quick/--smoke rounds run
    # deliberately different workload sizes, so cross-mode deltas are
    # excused -- both sides' modes ride the block for auditability. None
    # when a truncated wrapper carries no mode marker.
    "mode_prev": (str, type(None)),
    "mode_cur": (str, type(None)),
    # Autosize excusal (ISSUE 18): a hand-tuned round vs a zero-knob
    # round measures deliberately different shapes; the flag flip is an
    # excuse, not a regression. None when a side predates the flag.
    "autosized_prev": (bool, type(None)),
    "autosized_cur": (bool, type(None)),
    # Controller-migration excusal (ISSUE 20): a round during which the
    # fleet controller executed rebalance actions spent wall clock on
    # fence->checkpoint->resume by design; the marker rides both sides
    # for auditability. None when a side predates the controller.
    "controller_migrations_prev": (bool, type(None)),
    "controller_migrations_cur": (bool, type(None)),
    # Which excusal actually fired (tunnel_degraded | platform_change |
    # mode_change | autosize_change | controller_migration |
    # salvaged_artifact); None when nothing regressed or nothing
    # excused it.
    "excuse": (str, type(None)),
}
REGRESSION_METRIC_KEYS: Dict[str, tuple] = {
    "prev": NUMBER,
    "cur": NUMBER,
    "delta_pct": OPT_NUMBER,
    "regressed": (bool,),
}

#: The `latency` block (ISSUE 7): the end-to-end match-latency histogram
#: (ingest stamp at driver poll -> sink emission) from the smoke
#: introspection pipeline. Percentiles are None until a match emitted.
LATENCY_KEYS: Dict[str, tuple] = {
    "query": (str,),
    "count": NUMBER,
    "sum_s": NUMBER,
    "p50_ms": OPT_NUMBER,
    "p99_ms": OPT_NUMBER,
    "buckets": (dict,),
}

#: The `faults` block (ISSUE 6): label-summed totals of every fault/
#: robustness counter family (obs/registry.py FAULT_SERIES). All keys
#: always present; all-zero in a healthy run.
FAULT_KEYS = (
    "cep_faults_injected_total",
    "cep_retries_total",
    "cep_overflow_backpressure_total",
    "cep_overflow_dropped_total",
    "cep_driver_dead_letters_total",
    "cep_driver_restore_failures_total",
    "cep_checkpoint_corrupt_total",
    "cep_emit_deduped_total",
    "cep_late_dropped_total",
    "cep_reorder_overflow_dropped_total",
    # Wire-transport families (ISSUE 15, streams/transport.py): nonzero
    # retries/disconnects/stalls/torn-frames/dedup/restarts in a bench or
    # soak artifact mean the wire itself took (or injected) damage.
    "cep_transport_retries_total",
    "cep_transport_disconnects_total",
    "cep_transport_stalls_total",
    "cep_transport_torn_frames_total",
    "cep_transport_dedup_total",
    "cep_transport_server_restarts_total",
)

#: The per-component breakdown (ops/profiling.py BatchTimings.components):
#: all keys always present; tunnel_mbps None until a drain pulled bytes.
COMPONENT_KEYS: Dict[str, tuple] = {
    "advance_ms": NUMBER,
    "post_ms": NUMBER,
    "drain_pull_ms": NUMBER,
    "decode_ms": NUMBER,
    "drain_bytes": NUMBER,
    "tunnel_mbps": OPT_NUMBER,
}

METRIC_KINDS = ("counter", "gauge", "histogram")

# ---------------------------------------------------------------- SOAK schema
#: Top-level contract of a SOAK_r*.json verdict (faults/soak.py). Same
#: both-ways rule as the bench artifact.
SOAK_TOP_LEVEL: Dict[str, Tuple[bool, tuple]] = {
    "soak": (True, (dict,)),
    "scenarios": (True, (dict,)),
    # Fleet tracing & SLO control plane (ISSUE 20): the burn-rate
    # controller's state + stitched-trace evidence. Optional so pre-v20
    # verdicts still validate; when present it is held to FLEET_KEYS.
    "fleet": (False, (dict,)),
    "slos": (True, (dict,)),
    "series": (True, (dict,)),
    "metrics": (True, (dict,)),
    "faults": (True, (dict,)),
    "passed": (True, (bool,)),
    "schema_ok": (False, (bool,)),
}

#: The `fleet` block (ISSUE 20, ops/controller.py FleetController.state
#: trimmed by the soak): burn/decision evidence when the controller was
#: armed. `enabled: false` blocks carry only the trace evidence.
FLEET_KEYS: Dict[str, tuple] = {
    "enabled": (bool,),
    "ticks": NUMBER,
    "actions": NUMBER,
    "burn": (dict,),
    "policy": (dict,),
    "decisions": (list,),
    "trace": (dict,),
}
#: Burn SLO names -- pinned exactly (a controller that silently stops
#: evaluating an SLO must fail the artifact's own schema).
FLEET_BURN_KEYS: Dict[str, tuple] = {
    "match_latency_p99": NUMBER,
    "emission_integrity": NUMBER,
    "pend_drift": NUMBER,
}
#: ControllerPolicy.as_dict() -- the thresholds the decisions were made
#: under ride the artifact so a judge can re-derive every breach.
FLEET_POLICY_KEYS: Dict[str, tuple] = {
    "latency_p99_budget_s": NUMBER,
    "drops_budget_per_s": NUMBER,
    "pend_slope_budget_per_s": NUMBER,
    "burn_threshold": NUMBER,
    "skew_ratio": NUMBER,
    "min_load": NUMBER,
    "dead_after_s": NUMBER,
    "cooldown_s": NUMBER,
}
#: One controller decision record (FleetController.tick()).
FLEET_DECISION_KEYS: Dict[str, tuple] = {
    "t_unix": NUMBER,
    "scraped": (list,),
    "shard_loads": (dict,),
    "burn": (dict,),
    "breached": (list,),
    "planned": (list,),
    "cooldown": (bool,),
    "executed": (list,),
}
#: The fleet block's stitched-trace evidence: span totals and the
#: Perfetto-loadable trace file the run wrote (None when tracing was
#: disabled or the workdir was unwritable).
FLEET_TRACE_KEYS: Dict[str, tuple] = {
    "spans": NUMBER,
    "stitched": NUMBER,
    "trace_file": (str, type(None)),
}


def _check_fleet_block(
    block: dict, where: str, errors: List[str]
) -> None:
    """Both-ways check of the soak's `fleet` block. A disabled block
    carries only {enabled, trace}; an enabled one carries the full
    controller state, with the burn names, policy knobs and decision
    shape each pinned exactly."""
    keys = (
        FLEET_KEYS
        if block.get("enabled")
        else {k: FLEET_KEYS[k] for k in ("enabled", "trace")}
    )
    _check_flat_block(block, keys, where, errors)
    if isinstance(block.get("trace"), dict):
        _check_flat_block(
            block["trace"], FLEET_TRACE_KEYS, f"{where}.trace", errors
        )
    if not block.get("enabled"):
        return
    if isinstance(block.get("burn"), dict):
        _check_flat_block(
            block["burn"], FLEET_BURN_KEYS, f"{where}.burn", errors
        )
    if isinstance(block.get("policy"), dict):
        _check_flat_block(
            block["policy"], FLEET_POLICY_KEYS, f"{where}.policy", errors
        )
    for i, dec in enumerate(block.get("decisions", ())):
        if not isinstance(dec, dict):
            errors.append(f"{where}.decisions[{i}]: expected object")
            continue
        _check_flat_block(
            dec, FLEET_DECISION_KEYS, f"{where}.decisions[{i}]", errors
        )
        if isinstance(dec.get("burn"), dict):
            _check_flat_block(
                dec["burn"], FLEET_BURN_KEYS,
                f"{where}.decisions[{i}].burn", errors,
            )

#: The `soak` run-description block.
SOAK_RUN_KEYS: Dict[str, tuple] = {
    "version": NUMBER,
    "seed": NUMBER,
    "quick": (bool,),
    "platform": (str,),
    "runtime": (str,),
    "transport": (str,),
    "violation": (str,),
    "duration_s": NUMBER,
    "wall_s": NUMBER,
    "events_produced": NUMBER,
    "events_processed": NUMBER,
    "matches": NUMBER,
    "eps": NUMBER,
    "crashes": NUMBER,
    "chaos_points": NUMBER,
    "churn_epochs": NUMBER,
    "scrapes": NUMBER,
    "scrape_errors": NUMBER,
    # Partitioned-fleet evidence (ISSUE 16): --brokers size, seeded
    # broker kills that landed, and the salvage-rebalance volume (all
    # zero-ish in single-broker runs).
    "brokers": NUMBER,
    "broker_kills": NUMBER,
    "rebalance_partitions_moved": NUMBER,
    "rebalance_records_moved": NUMBER,
    # Zero-knob capacity (ISSUE 18): True when the device scenarios ran
    # under the capacity autosizer + admission pacer (--auto-cadence).
    "autosized": (bool,),
}

#: The SLO name set -- pinned EXACTLY (a soak that silently stops gating
#: an SLO must fail its own schema).
SOAK_SLOS: Tuple[str, ...] = (
    "evidence",
    "drops",
    "p99_match_latency_ms",
    "watermark_lag_s",
    "leak_drift",
    "eps_regression",
    # Exactly-once across crashes, broker kills and shard rebalances
    # (ISSUE 16): every sink digest unique.
    "emission_integrity",
)

#: One SLO verdict entry: the machine-gateable shape.
SOAK_SLO_KEYS: Dict[str, tuple] = {
    "ok": (bool,),
    "value": OPT_NUMBER,
    "bound": OPT_NUMBER,
    "excused": (bool,),
    "detail": (dict, type(None)),
}

#: One scraped time-series summary (obs/scrape.py TimeSeries.summary):
#: min/max/last/slope let a judge tell a leak from a spike offline.
SOAK_SERIES_KEYS: Dict[str, tuple] = {
    "n": NUMBER,
    "min": NUMBER,
    "max": NUMBER,
    "last": NUMBER,
    "slope_per_s": NUMBER,
}

#: One scenario detail entry. `controller` carries the adaptive drain
#: controller's chosen knobs (DrainController.state()) for the
#: auto-cadence scenario (ISSUE 17); None for scenarios running without
#: the controller.
SOAK_SCENARIO_KEYS: Dict[str, tuple] = {
    "generator": (str,),
    "runtime": (str,),
    "topics": (list,),
    "events": NUMBER,
    "matches": NUMBER,
    "eps": NUMBER,
    "gated": (bool,),
    "controller": (dict, type(None)),
}


def looks_like_soak(doc: Any) -> bool:
    """Shape dispatch for main(): soak verdicts carry `soak` + `slos`."""
    return isinstance(doc, dict) and "soak" in doc and "slos" in doc


def validate_soak(out: Any) -> List[str]:
    """Schema violations for a SOAK_r*.json verdict (empty = valid)."""
    errors: List[str] = []
    if not isinstance(out, dict):
        return [f"soak artifact must be a JSON object, got {type(out).__name__}"]
    for key, (required, types) in SOAK_TOP_LEVEL.items():
        if key not in out:
            if required:
                errors.append(f"missing documented key {key!r}")
            continue
        if not isinstance(out[key], types):
            errors.append(
                f"{key}: expected {tuple(t.__name__ for t in types)}, "
                f"got {type(out[key]).__name__}"
            )
    for key in out:
        if key not in SOAK_TOP_LEVEL:
            errors.append(
                f"undocumented key {key!r} (document it in PERF.md and "
                "scripts/check_bench_schema.py SOAK_TOP_LEVEL)"
            )
    if isinstance(out.get("soak"), dict):
        _check_flat_block(out["soak"], SOAK_RUN_KEYS, "soak", errors)
    if isinstance(out.get("fleet"), dict):
        _check_fleet_block(out["fleet"], "fleet", errors)
    slos = out.get("slos")
    if isinstance(slos, dict):
        for name in SOAK_SLOS:
            if name not in slos:
                errors.append(f"slos: missing SLO {name!r}")
        for name, entry in slos.items():
            if name not in SOAK_SLOS:
                errors.append(f"slos: undocumented SLO {name!r}")
            if not isinstance(entry, dict):
                errors.append(f"slos.{name}: expected object")
                continue
            _check_flat_block(entry, SOAK_SLO_KEYS, f"slos.{name}", errors)
            # The regression SLO's detail is a perf_ledger
            # compare_artifacts block: hold it to that contract.
            if name == "eps_regression" and isinstance(
                entry.get("detail"), dict
            ):
                _check_flat_block(
                    entry["detail"], REGRESSION_KEYS,
                    "slos.eps_regression.detail", errors,
                )
    if isinstance(out.get("series"), dict):
        for name, summary in out["series"].items():
            if not isinstance(summary, dict):
                errors.append(f"series.{name}: expected object")
            else:
                _check_flat_block(
                    summary, SOAK_SERIES_KEYS, f"series.{name}", errors
                )
    if isinstance(out.get("scenarios"), dict):
        for name, sc in out["scenarios"].items():
            if not isinstance(sc, dict):
                errors.append(f"scenarios.{name}: expected object")
            else:
                _check_flat_block(
                    sc, SOAK_SCENARIO_KEYS, f"scenarios.{name}", errors
                )
                if isinstance(sc.get("controller"), dict):
                    _check_controller_block(
                        sc["controller"],
                        f"scenarios.{name}.controller", errors,
                    )
    if isinstance(out.get("metrics"), dict):
        _check_metrics_section(out["metrics"], errors)
    faults = out.get("faults")
    if isinstance(faults, dict):
        for k in FAULT_KEYS:
            if k not in faults:
                errors.append(f"faults: missing series {k!r}")
            elif not isinstance(faults[k], NUMBER):
                errors.append(f"faults.{k}: expected number")
        for k in faults:
            if k not in FAULT_KEYS:
                errors.append(
                    f"faults: undocumented series {k!r} (add it to "
                    "obs.registry.FAULT_SERIES, this schema, and PERF.md)"
                )
    return errors


def _check_components(c: Optional[dict], where: str, errors: List[str]) -> None:
    if c is None:
        return
    for k, types in COMPONENT_KEYS.items():
        if k not in c:
            errors.append(f"{where}: missing component key {k!r}")
        elif not isinstance(c[k], types):
            errors.append(
                f"{where}.{k}: expected {types}, got {type(c[k]).__name__}"
            )
    for k in c:
        if k not in COMPONENT_KEYS:
            errors.append(f"{where}: undocumented component key {k!r}")


def _check_flat_block(
    block: Optional[dict],
    keys: Dict[str, tuple],
    where: str,
    errors: List[str],
) -> None:
    """Documented-key check for a flat dict block (observation, latency)."""
    if block is None:
        return
    for k, types in keys.items():
        if k not in block:
            errors.append(f"{where}: missing documented key {k!r}")
        elif not isinstance(block[k], types):
            errors.append(
                f"{where}.{k}: expected {types}, got {type(block[k]).__name__}"
            )
    for k in block:
        if k not in keys:
            errors.append(f"{where}: undocumented key {k!r}")


def _check_metrics_section(
    snap: dict, errors: List[str], section: str = "metrics"
) -> None:
    """Structural check of a registry snapshot + prom-text round-trip.

    `section` names the artifact key being checked -- the same contract
    applies to the primary `metrics` snapshot and the merged
    cross-registry `metrics_merged` one (obs/merge.py output)."""
    # Section-local structural errors gate the round-trip below (a
    # malformed snapshot cannot be rebuilt); unrelated errors from other
    # sections must not suppress this check.
    local: List[str] = []
    for name, fam in snap.items():
        where = f"{section}.{name}"
        if not isinstance(fam, dict):
            local.append(f"{where}: expected object")
            continue
        kind = fam.get("type")
        if kind not in METRIC_KINDS:
            local.append(f"{where}: bad type {kind!r}")
            continue
        for req in ("help", "label_names", "values"):
            if req not in fam:
                local.append(f"{where}: missing {req!r}")
        for entry in fam.get("values", ()):
            if kind == "histogram":
                missing = {"labels", "count", "sum", "buckets"} - set(entry)
            else:
                missing = {"labels", "value"} - set(entry)
            if missing:
                local.append(f"{where}: value entry missing {sorted(missing)}")
    errors.extend(local)
    if local:
        return
    # Round-trip: snapshot -> registry -> prom text -> parsed samples must
    # carry the same values the snapshot holds.
    try:
        from kafkastreams_cep_tpu.obs.registry import (
            parse_prom_text,
            registry_from_snapshot,
        )
    except Exception as exc:  # pragma: no cover - missing package on PATH
        errors.append(f"{section}: cannot import obs registry ({exc})")
        return
    reg = registry_from_snapshot(snap)
    parsed = parse_prom_text(reg.to_prom_text())

    def close(a: float, b: float) -> bool:
        if math.isinf(a) or math.isinf(b):
            return a == b
        return math.isclose(a, b, rel_tol=1e-9, abs_tol=1e-12)

    for name, fam in snap.items():
        label_names = fam.get("label_names", [])
        for entry in fam["values"]:
            base = tuple(
                (ln, str(entry["labels"][ln])) for ln in label_names
            )
            if fam["type"] == "histogram":
                pairs = [
                    (f"{name}_sum", base, float(entry["sum"])),
                    (f"{name}_count", base, float(entry["count"])),
                ] + [
                    (
                        f"{name}_bucket",
                        base + (("le", le),),
                        float(cum),
                    )
                    for le, cum in entry["buckets"].items()
                ]
            else:
                pairs = [(name, base, float(entry["value"]))]
            for sample, labels, want in pairs:
                got = parsed.get(sample, {}).get(labels)
                if got is None:
                    errors.append(
                        f"{section} round-trip: {sample}{dict(labels)} "
                        "missing from prom text"
                    )
                elif not close(got, want):
                    errors.append(
                        f"{section} round-trip: {sample}{dict(labels)} "
                        f"prom={got} snapshot={want}"
                    )


def validate(out: Any) -> List[str]:
    """Return a list of schema violations (empty = valid)."""
    errors: List[str] = []
    if not isinstance(out, dict):
        return [f"artifact must be a JSON object, got {type(out).__name__}"]
    for key, (required, types) in TOP_LEVEL.items():
        if key not in out:
            if required:
                errors.append(f"missing documented key {key!r}")
            continue
        if not isinstance(out[key], types):
            errors.append(
                f"{key}: expected {tuple(t.__name__ for t in types)}, "
                f"got {type(out[key]).__name__}"
            )
    for key in out:
        if key not in TOP_LEVEL:
            errors.append(
                f"undocumented key {key!r} (document it in PERF.md's "
                "metrics dictionary and scripts/check_bench_schema.py)"
            )
    if isinstance(out.get("components"), (dict, type(None))):
        _check_components(out.get("components"), "components", errors)
    configs = out.get("configs")
    if isinstance(configs, dict):
        for name, cfg in configs.items():
            if not isinstance(cfg, dict):
                errors.append(f"configs.{name}: expected object")
                continue
            if isinstance(cfg.get("components"), dict):
                _check_components(
                    cfg["components"], f"configs.{name}.components", errors
                )
            if isinstance(cfg.get("autosize"), dict):
                _check_autosize_block(
                    cfg["autosize"], f"configs.{name}.autosize", errors
                )
    if isinstance(out.get("autosize"), dict):
        _check_autosize_block(out["autosize"], "autosize", errors)
    if isinstance(out.get("metrics"), dict):
        _check_metrics_section(out["metrics"], errors)
    if isinstance(out.get("metrics_merged"), dict):
        _check_metrics_section(
            out["metrics_merged"], errors, section="metrics_merged"
        )
    if isinstance(out.get("observation"), dict):
        _check_flat_block(
            out["observation"], OBSERVATION_KEYS, "observation", errors
        )
    if isinstance(out.get("latency"), (dict, type(None))):
        _check_flat_block(out.get("latency"), LATENCY_KEYS, "latency", errors)
    if isinstance(out.get("watermark"), (dict, type(None))):
        _check_flat_block(
            out.get("watermark"), WATERMARK_KEYS, "watermark", errors
        )
    if isinstance(out.get("transport"), (dict, type(None))):
        _check_flat_block(
            out.get("transport"), TRANSPORT_KEYS, "transport", errors
        )
    sink = out.get("sink")
    if isinstance(sink, dict):
        _check_flat_block(sink, SINK_KEYS, "sink", errors)
        if isinstance(sink.get("eps"), dict):
            _check_flat_block(sink["eps"], SINK_EPS_KEYS, "sink.eps", errors)
        if isinstance(sink.get("sink_bytes"), dict):
            _check_flat_block(
                sink["sink_bytes"], SINK_BYTES_KEYS, "sink.sink_bytes", errors
            )
        if isinstance(sink.get("controller"), dict):
            _check_controller_block(
                sink["controller"], "sink.controller", errors
            )
    compile_block = out.get("compile")
    if isinstance(compile_block, dict):
        _check_flat_block(compile_block, COMPILE_KEYS, "compile", errors)
        for fn, entry in (compile_block.get("fns") or {}).items():
            if not isinstance(entry, dict):
                errors.append(f"compile.fns.{fn}: expected object")
            else:
                _check_flat_block(
                    entry, COMPILE_FN_KEYS, f"compile.fns.{fn}", errors
                )
    regression = out.get("regression")
    if isinstance(regression, dict):
        _check_flat_block(regression, REGRESSION_KEYS, "regression", errors)
        for name, entry in (regression.get("configs") or {}).items():
            if not isinstance(entry, dict):
                errors.append(f"regression.configs.{name}: expected object")
                continue
            for metric, d in entry.items():
                if not isinstance(d, dict):
                    errors.append(
                        f"regression.configs.{name}.{metric}: expected object"
                    )
                else:
                    _check_flat_block(
                        d, REGRESSION_METRIC_KEYS,
                        f"regression.configs.{name}.{metric}", errors,
                    )
    faults = out.get("faults")
    if isinstance(faults, dict):
        for k in FAULT_KEYS:
            if k not in faults:
                errors.append(f"faults: missing series {k!r}")
            elif not isinstance(faults[k], NUMBER):
                errors.append(f"faults.{k}: expected number")
        for k in faults:
            if k not in FAULT_KEYS:
                errors.append(
                    f"faults: undocumented series {k!r} (add it to "
                    "obs.registry.FAULT_SERIES, this schema, and PERF.md)"
                )
    return errors


def main(argv: List[str]) -> int:
    if len(argv) != 2:
        print(__doc__, file=sys.stderr)
        return 2
    if argv[1] == "-":
        text = sys.stdin.read()
    else:
        with open(argv[1]) as f:
            text = f.read()
    # Whole-document first (soak verdicts are written indented); bench.py
    # prints exactly one JSON line on stdout, but a captured log may
    # carry stderr noise around it: fall back to the last line that
    # parses as an object.
    doc = None
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        for line in reversed([ln for ln in text.splitlines() if ln.strip()]):
            try:
                candidate = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(candidate, dict):
                doc = candidate
                break
    if doc is None:
        print("no JSON object found in input", file=sys.stderr)
        return 2
    is_soak = looks_like_soak(doc)
    errors = validate_soak(doc) if is_soak else validate(doc)
    if errors:
        for e in errors:
            print(f"SCHEMA: {e}", file=sys.stderr)
        return 1
    print("soak schema OK" if is_soak else "bench schema OK")
    return 0


if __name__ == "__main__":
    import os

    # Standalone runs must not touch the axon/TPU backend: the obs import
    # pulls the package root, which imports jax-heavy modules.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    sys.exit(main(sys.argv))
