"""Streams API: topology construction for CEP queries.

Re-design of the reference streams surface
(reference: core/.../cep/ComplexStreamsBuilder.java:61-107,
CEPStream.java:37-74, org/apache/kafka/.../CEPStreamImpl.java:41-95).
`ComplexStreamsBuilder.stream(topics)` returns a `CEPStream`; each
`query(name, pattern)` registers a processor node plus its three state
stores and returns a downstream stream of Sequences. Unlike the reference
-- which must reach into Kafka's internals -- the topology here is owned by
the framework, so wiring is direct.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Generic, List, Optional, Sequence as Seq, TypeVar, Union

from ..pattern.pattern import Pattern
from ..state.builders import QueryStoreBuilders, changelog_topic
from ..state.naming import (
    aggregates_store,
    device_state_store,
    emitted_store,
    event_buffer_store,
    nfa_states_store,
    normalize_query_name,
)
from ..state.nfa_store import EmissionStore
from .emission import EmissionGate, encode_sink_key
from .processor import CEPProcessor
from .serde import Queried

K = TypeVar("K")
V = TypeVar("V")


class Record:
    __slots__ = ("key", "value", "timestamp", "topic", "partition", "offset")

    def __init__(self, key, value, timestamp=0, topic="", partition=0, offset=0):
        self.key = key
        self.value = value
        self.timestamp = timestamp
        self.topic = topic
        self.partition = partition
        self.offset = offset


class QueryNode(Generic[K, V]):
    """One registered query: processor + stores + downstream sinks.

    runtime="host": the per-record oracle driver (streams/processor.py).
    runtime="tpu": the micro-batching batched device driver
    (streams/device_processor.py); matches surface when a batch fills or on
    `Topology.flush()`.

    runtime="auto": zero-knob routing (streams/auto_router.py) -- the
    query starts on the host runtime and promotes itself to the device
    engine when the observed distinct-key count crosses the scale where
    the batched kernel wins, replaying history so sink digests stay
    bitwise-identical to an all-device run.

    Pick by key cardinality: the device engine parallelizes over record
    keys, so "tpu" wins on many-key/high-volume topics while "host" wins
    below roughly 64 concurrently active keys (per-batch kernel latency is
    unamortized there -- PERF.md). The two runtimes share stores, serdes
    and topology wiring; switching is this one argument -- or let "auto"
    measure and decide.
    """

    def __init__(
        self,
        name: str,
        pattern: Pattern,
        queried: Optional[Queried],
        runtime: str = "host",
        log: Optional[Any] = None,
        app_id: str = "app",
        **device_opts: Any,
    ) -> None:
        self.name = normalize_query_name(name)
        self.pattern = pattern
        self.queried = queried
        self.runtime = runtime
        self.downstream: List[Callable] = []
        self.sink_topics: List[str] = []
        # The obs registry rides both runtimes (one telemetry spine per
        # topology when the caller passes one); the rest of device_opts is
        # tpu-only engine tuning.
        registry = device_opts.pop("registry", None)
        self.registry = registry
        self.device_opts = dict(device_opts)
        # Exactly-once emission gate (streams/emission.py): its watermark
        # store rides the same changelog durability stack as the reference
        # trio, for BOTH runtimes.
        emit_name = emitted_store(self.name)
        from ..state.store import (
            ChangeLoggingKeyValueStore,
            InMemoryKeyValueStore,
        )

        emit_kv: Any = InMemoryKeyValueStore(emit_name)
        if log is not None:
            emit_kv = ChangeLoggingKeyValueStore(
                emit_kv, log, changelog_topic(app_id, emit_name)
            )
        self.emission_store = EmissionStore(backing=emit_kv)
        self.gate = EmissionGate(
            self.name, store=self.emission_store, registry=registry
        )
        # End-to-end match latency (ISSUE 7): ingest wall stamp (driver
        # poll, Topology.stamp_ingest) -> sink emission, observed at the
        # emission point for BOTH runtimes. Host-side only: the stamp map
        # and the observe ride the existing emission path, never the
        # device.
        from ..obs.registry import default_registry
        from ..ops.profiling import LATENCY_BUCKETS

        self._m_match_latency = (
            registry if registry is not None else default_registry()
        ).histogram(
            "cep_match_latency_seconds",
            "Ingest (driver poll stamp) -> sink emission wall per match",
            labels=("query",),
            buckets=LATENCY_BUCKETS,
        ).labels(query=self.name)
        if runtime == "tpu":
            from .device_processor import DeviceCEPProcessor, DeviceStateStore

            self.store_builders = None
            self.processor: Any = DeviceCEPProcessor(
                name,
                pattern,
                schema=queried.schema if queried is not None else None,
                registry=registry,
                **device_opts,
            )
            # Device-runtime crash consistency: the engine checkpoint
            # changelog (snapshotted at every commit's flush) + the
            # emission watermark, both driven by flush/restore_stores.
            self.stores = {emit_name: self.emission_store}
            if log is not None:
                ds_name = device_state_store(self.name)
                self.stores[ds_name] = DeviceStateStore(
                    self, log, changelog_topic(app_id, ds_name),
                    registry=registry,
                )
            return
        if runtime not in ("host", "auto"):
            raise ValueError(f"unknown runtime {runtime!r} (host|tpu|auto)")
        # Compile once; the builders share the compiled stages with the
        # processor (QueryStoreBuilders.java:50-56).
        self.store_builders = QueryStoreBuilders(name, pattern)
        self.stores: Dict[str, Any] = self.store_builders.build_all(log, app_id)
        self.stores[emit_name] = self.emission_store
        # Event-time knobs for the HOST runtime ride the query kwargs
        # directly (the device runtime reads them from EngineConfig).
        # `on_overflow` is accepted as an alias for `reorder_overflow`
        # (it is the EngineConfig spelling README documents); an explicit
        # reorder_overflow wins when both are given.
        et_opts = {
            k: device_opts[k]
            for k in (
                "reorder_capacity", "lateness_ms", "late_policy",
                "reorder_overflow", "watermark_gen",
            )
            if k in device_opts
        }
        if "on_overflow" in device_opts:
            et_opts.setdefault(
                "reorder_overflow", device_opts["on_overflow"]
            )
        self.processor = CEPProcessor(
            name,
            self.store_builders.stages,
            nfa_store=self.stores[nfa_states_store(name)],
            buffer=self.stores[event_buffer_store(name)],
            aggregates=self.stores[aggregates_store(name)],
            registry=registry,
            **et_opts,
        )
        if runtime == "auto":
            # Zero-knob routing (ISSUE 18): start on the reference-parity
            # host runtime, promote to the device engine when the observed
            # distinct-key count crosses the threshold where the batched
            # kernel wins (streams/auto_router.py). Host event-time knobs
            # translate into the device EngineConfig at promotion so both
            # phases apply the same late/reorder policy.
            from dataclasses import replace as _dc_replace

            from ..ops.engine import EngineConfig
            from .auto_router import AutoRoutingProcessor

            auto_opts = {
                k: device_opts.pop(k)
                for k in ("promote_after", "buffer_max", "autosize")
                if k in device_opts
            }
            dev_opts = {
                k: v
                for k, v in device_opts.items()
                if k not in (
                    "reorder_capacity", "lateness_ms", "late_policy",
                    "reorder_overflow", "on_overflow", "watermark_gen",
                )
            }
            base_cfg = dev_opts.pop("config", None) or EngineConfig()
            et_cfg: Dict[str, Any] = {}
            for k in ("reorder_capacity", "lateness_ms", "late_policy"):
                if k in device_opts:
                    et_cfg[k] = device_opts[k]
            if "reorder_overflow" in device_opts:
                et_cfg["on_overflow"] = device_opts["reorder_overflow"]
            elif "on_overflow" in device_opts:
                et_cfg["on_overflow"] = device_opts["on_overflow"]
            if et_cfg:
                base_cfg = _dc_replace(base_cfg, **et_cfg)
            dev_opts["config"] = base_cfg
            if "watermark_gen" in device_opts:
                # A custom stateful watermark generator cannot be replayed
                # into the device gate without re-deciding late/admit: pin
                # the host runtime for this query's lifetime.
                auto_opts["promote_after"] = 1 << 62
            self.processor = AutoRoutingProcessor(
                name,
                pattern,
                self.processor,
                schema=queried.schema if queried is not None else None,
                registry=registry,
                device_opts=dev_opts,
                **auto_opts,
            )
        if log is not None and self.processor.gate is not None:
            from ..state.naming import event_time_store

            et_name = event_time_store(self.name)
            self.stores[et_name] = EventTimeStateStore(
                self, log, changelog_topic(app_id, et_name),
                registry=registry,
            )


class EventTimeStateStore:
    """Changelog durability for a HOST query's event-time gate.

    The host trio's changelogs restore through `restore_stores()`, but an
    EventTimeGate lives outside them -- and its arrival marks must never
    be MORE durable than the buffered records they dedup (a crash would
    then silently lose every buffered record: the mark rejects the replay
    while the buffer restored empty). This store snapshots the
    processor's combined event-time state (gate contents + arrival
    marks, `CEPProcessor.event_time_state()`) into
    `<app>-<query>-streamscep-eventtime-changelog` at every commit flush
    and restores the newest snapshot that validates, CRC-rejected tails
    counted in `cep_checkpoint_corrupt_total`.

    Commit atomicity caveat: like the reference trio itself (three
    separate changelogs per query), a commit's appends are not one
    atomic frame -- a torn flush can land the trio's records without
    this store's snapshot. The store is registered AFTER the trio, so
    iteration order makes the event-time snapshot the LAST append of a
    flush: a tear restores OLDER arrival marks over NEWER run state,
    which re-offers the window's records (duplicate-leaning,
    deduplicated at the sink by the emission gate) instead of the
    loss-leaning inverse. The device runtime sidesteps this class
    entirely with its single-blob snapshot."""

    def __init__(
        self, node: "QueryNode", log: Any, topic: str,
        registry: Optional[Any] = None,
    ) -> None:
        from ..obs.registry import default_registry
        from ..state.naming import event_time_store

        self.name = event_time_store(node.name)
        self.node = node
        self.log = log
        self.topic = topic
        self.metrics = registry if registry is not None else default_registry()
        self._m_corrupt = self.metrics.counter(
            "cep_checkpoint_corrupt_total",
            "Checkpoint payloads rejected by CRC/framing validation",
        )

    @property
    def persistent(self) -> bool:
        return True

    def flush(self) -> None:
        if self.log is None:
            return
        from ..state.serde import encode_event_time_state

        self.log.append(  # cep: trace-ok(event-time changelog snapshot: state flush, no record to trace)
            self.topic, None,
            encode_event_time_state(self.node.processor.event_time_state()),
        )

    def restore_from_changelog(self) -> int:
        if self.log is None:
            return 0
        from ..state.serde import CheckpointError, decode_event_time_state

        recs = self.log.read(self.topic)
        for rec in reversed(recs):
            if rec.value is None:
                continue
            try:
                state = decode_event_time_state(rec.value)
            except CheckpointError:
                # Corrupt bytes: walk back to the previous generation.
                self._m_corrupt.inc()
                continue
            try:
                self.node.processor.restore_event_time(state)
            except (ValueError, KeyError) as exc:
                # A CRC-valid snapshot that the CONFIGURED gate cannot
                # absorb is a configuration mismatch (changed watermark
                # generator), not corruption: restoring an empty gate
                # over committed consumer offsets would silently lose
                # every buffered record -- fail like the processor
                # restore paths do.
                raise ValueError(
                    f"{self.name}: event-time snapshot does not match the "
                    f"configured watermark generator ({exc}); restore with "
                    "the original event-time config"
                ) from exc
            return len(recs)
        return len(recs)


class CEPStream(Generic[K, V]):
    """A stream handle supporting `query(...)` (CEPStream.java:37-74)."""

    def __init__(self, builder: "ComplexStreamsBuilder", topics: Seq[str]) -> None:
        self._builder = builder
        self.topics = list(topics)

    def query(
        self,
        name: str,
        pattern: Pattern,
        queried: Optional[Queried] = None,
        runtime: str = "host",
        **device_opts: Any,
    ) -> "OutputStream":
        node = QueryNode(
            name,
            pattern,
            queried,
            runtime=runtime,
            log=self._builder.log,
            app_id=self._builder.app_id,
            **device_opts,
        )
        out = OutputStream(node)
        self._builder._register(self, node, out)
        return out


class OutputStream:
    """Downstream handle: collects matched sequences; supports peek/map sinks."""

    def __init__(self, node: QueryNode) -> None:
        self.node = node
        self.records: List[Record] = []

    def for_each(self, fn: Callable) -> "OutputStream":
        self.node.downstream.append(fn)
        return self

    def to(self, topic: str) -> "OutputStream":
        """Route matches to a sink topic of the builder's RecordLog
        (the reference's `.through("Matches")` egress,
        example/.../CEPStockDemo.java:84-99): key pickled, value the golden
        JSON shape (JsonSequenceSerde.java:26-85)."""
        self.node.sink_topics.append(topic)
        return self


class ComplexStreamsBuilder:
    """Framework entry object (ComplexStreamsBuilder.java:61-107).

    Pass `log` (a streams.log.RecordLog) to enable the durability stack:
    every query's stores are then change-logged to
    `<app_id>-<store-name>-changelog` topics, and outputs routed with
    `OutputStream.to(topic)` land in the log (the reference's sink-topic
    role)."""

    def __init__(self, log: Optional[Any] = None, app_id: str = "app") -> None:
        self._queries: List[tuple] = []
        self.log = log
        self.app_id = app_id

    def stream(self, topics: Union[str, Seq[str]]) -> CEPStream:
        if isinstance(topics, str):
            topics = [topics]
        return CEPStream(self, topics)

    def _register(self, stream: CEPStream, node: QueryNode, out: OutputStream) -> None:
        self._queries.append((stream, node, out))

    def build(self) -> "Topology":
        return Topology(self._queries, log=self.log)


class Topology:
    """The built processing graph, drivable record-by-record."""

    #: Ingest-stamp map bound: records that never complete a match would
    #: otherwise pin their stamp forever; past the bound the oldest stamps
    #: evict (their eventual matches simply skip the latency observation).
    INGEST_STAMPS_MAX = 1 << 16

    #: Bounded /explainz ring: one lineage entry per durably-admitted
    #: match, newest kept.
    EXPLAIN_RING = 256

    def __init__(self, queries: List[tuple], log: Optional[Any] = None) -> None:
        self.queries = queries
        self.log = log
        self._offsets: Dict[tuple, int] = {}
        # (topic, partition, key, offset) -> (ingest wall stamp
        # [time.perf_counter], trace-context blob or None, broker index or
        # None), written by the driver at poll time, read at sink emission
        # for the cep_match_latency_seconds{query} histogram, the stitched
        # match.emit span, and the /explainz lineage entry.
        # The full event-identity key: (key, offset) alone collides across
        # topics/partitions and would skew samples. A plain dict keeps
        # insertion order, so eviction below drops the oldest stamps.
        self._ingest_stamps: Dict[tuple, tuple] = {}
        #: Optional obs.trace.SpanTracer (attach_tracer): emitted matches
        #: whose completing event carried wire trace context land a
        #: "match.emit" child span here, stitching the consumer side into
        #: the record's end-to-end trace.
        self._tracer: Optional[Any] = None
        from collections import deque as _deque

        self._explain: Any = _deque(maxlen=self.EXPLAIN_RING)

    def attach_tracer(self, tracer: Any) -> None:
        """Attach a SpanTracer for stitched match-emission spans (the
        driver wires its own tracer here at construction)."""
        self._tracer = tracer

    def stamp_ingest(
        self,
        topic: str,
        partition: int,
        key,
        offset: int,
        t: float,
        trace: Optional[bytes] = None,
        broker: Optional[int] = None,
    ) -> None:
        """Record one record's ingest wall time (driver poll path), plus
        its wire trace-context blob and source broker when known."""
        stamps = self._ingest_stamps
        stamps[(topic, partition, key, offset)] = (t, trace, broker)
        # O(1) oldest-first eviction (dict preserves insertion order);
        # this runs per record on the poll path, so no list materializing.
        while len(stamps) > self.INGEST_STAMPS_MAX:
            del stamps[next(iter(stamps))]

    def _observe_match_latency(
        self,
        node: QueryNode,
        topic: str,
        partition: int,
        key,
        offset: int,
        seq: Any = None,
    ) -> Optional[bytes]:
        """Observe ingest -> emission latency for one emitted match, keyed
        by its completing event's identity; record the /explainz lineage
        entry; and, when the completing event carried wire trace context,
        land the stitched "match.emit" span. Returns the trace blob (for
        the sink append to forward) or None. The stamp stays: several
        matches may complete on one event, and replay dedup upstream
        already bounds re-observation."""
        stamp = self._ingest_stamps.get((topic, partition, key, offset))
        import time as _time

        latency: Optional[float] = None
        trace_blob: Optional[bytes] = None
        broker: Optional[int] = None
        ctx = None
        if stamp is not None:
            t0, trace_blob, broker = stamp
            latency = _time.perf_counter() - t0
            node._m_match_latency.observe(latency)
            if trace_blob is not None and self._tracer is not None:
                from ..obs.trace import TraceContext

                ctx = TraceContext.decode(trace_blob)
                if ctx is not None:
                    self._tracer.record("match.emit", latency, trace=ctx)
        entry: Dict[str, Any] = {
            "query": node.name,
            "key": str(key),
            "topic": topic,
            "partition": partition,
            "offset": offset,
            "latency_s": latency,
            "trace_id": ctx.trace_id if ctx is not None else None,
            "ingest_unix": ctx.ingest_unix if ctx is not None else None,
            "broker": broker,
        }
        lineage = self._match_lineage(seq)
        if lineage is not None:
            entry.update(lineage)
        self._explain.append(entry)
        return trace_blob

    @staticmethod
    def _match_lineage(seq: Any) -> Optional[Dict[str, Any]]:
        """The bounded lineage dict of one emitted match: pre-built by the
        bytes decode (SinkMatch.lineage), derived from the attached
        Sequence otherwise, last-event-only when neither is present."""
        from .serde import SinkMatch, match_lineage

        if seq is None:
            return None
        if isinstance(seq, SinkMatch):
            if seq.lineage is not None:
                return dict(seq.lineage)
            if seq.sequence is not None:
                return match_lineage(seq.sequence)
            last = seq.last_event
            if last is None:
                return None
            return {
                "events": [
                    {
                        "stage": None,
                        "topic": getattr(last, "topic", ""),
                        "partition": getattr(last, "partition", 0),
                        "offset": getattr(last, "offset", 0),
                        "timestamp": getattr(last, "timestamp", 0),
                    }
                ],
                "truncated_events": 0,
                "stage_path": [],
                "branch_depth": 0,
                "chain_depth": 1,
            }
        if getattr(seq, "matched", None) is not None:
            return match_lineage(seq)
        return None

    def explain(self, limit: int = 64) -> List[Dict[str, Any]]:
        """Recent emitted-match lineage entries, newest first (the
        /explainz surface): contributing event identities, run version
        path, trace id, source broker, and the observed latency."""
        snap = list(self._explain)
        return snap[::-1][: max(0, limit)]

    @property
    def source_topics(self) -> List[str]:
        seen: List[str] = []
        for stream, _node, _out in self.queries:
            for t in stream.topics:
                if t not in seen:
                    seen.append(t)
        return seen

    def process(
        self, topic: str, key, value, timestamp: int = 0, partition: int = 0, offset: Optional[int] = None
    ) -> List[Record]:
        """Drive one record through every query subscribed to `topic`."""
        if offset is None:
            offset = self._offsets.get((topic, partition), 0)
        # Keep the auto-offset counter ahead of explicit offsets too, so
        # later auto-assigned offsets never collide with used ones (event
        # identity is (topic, partition, offset)).
        self._offsets[(topic, partition)] = max(
            self._offsets.get((topic, partition), 0), offset + 1
        )
        outputs: List[Record] = []
        for stream, node, out in self.queries:
            if topic not in stream.topics:
                continue
            if node.runtime == "auto":
                # Auto-routed runtime: the wrapper speaks the keyed surface
                # for both phases, so matches route per-key exactly like the
                # gated-host and device branches (including the promotion
                # replay, whose duplicates the emission gate absorbs).
                keyed = node.processor.process_keyed(
                    key, value, timestamp=timestamp, topic=topic,
                    partition=partition, offset=offset,
                )
                outputs.extend(self._emit_device(node, out, keyed))
                continue
            if (
                node.runtime != "tpu"
                and getattr(node.processor, "gate", None) is not None
            ):
                # Gated host runtime: one arrival can release OTHER keys'
                # buffered records, so matches must be attributed (sink
                # key, emission digest, latency anchor) to THEIR key and
                # completing event -- the keyed path shares the device
                # branch's per-match routing.
                keyed = node.processor.process_keyed(
                    key, value, timestamp=timestamp, topic=topic,
                    partition=partition, offset=offset,
                )
                outputs.extend(self._emit_device(node, out, keyed))
                continue
            results = node.processor.process(
                key, value, timestamp=timestamp, topic=topic, partition=partition, offset=offset
            )
            if node.runtime == "tpu":
                # Device results span every key in the flushed micro-batch;
                # record metadata derives from each match's last event.
                outputs.extend(self._emit_device(node, out, results))
            else:
                for seq in results:
                    # Dedup gates the DURABLE sink only: in-memory
                    # consumers (out.records, for_each callbacks) did not
                    # survive the crash, so a replayed match must still be
                    # delivered to them -- their guarantee is
                    # at-least-once across restarts, the sink's is
                    # exactly-once (README "Failure semantics").
                    digest = node.gate.admit(key, seq)
                    record = Record(key, seq, timestamp, topic, partition, offset)
                    out.records.append(record)
                    outputs.append(record)
                    for fn in node.downstream:
                        fn(key, seq)
                    if digest is not None:
                        trace = self._observe_match_latency(
                            node, topic, partition, key, offset, seq
                        )
                        self._sink(node, record, digest, trace=trace)
        return outputs

    def flush(self) -> List[Record]:
        """Flush pending device micro-batches (no-op for host queries)."""
        outputs: List[Record] = []
        for _stream, node, out in self.queries:
            flush = getattr(node.processor, "flush", None)
            if flush is None:
                continue
            outputs.extend(self._emit_device(node, out, flush()))
        return outputs

    def tick_event_time(self, now_ms: int) -> List[Record]:
        """Wall-clock tick for event-time gates (idle-source watermark
        timeouts, ISSUE 10): both runtimes return [(key, Sequence)] for
        matches completed by records the advanced watermark released.
        No-op for queries without a gate."""
        outputs: List[Record] = []
        for _stream, node, out in self.queries:
            tick = getattr(node.processor, "tick_event_time", None)
            if tick is None:
                continue
            res = tick(now_ms)
            if res:
                outputs.extend(self._emit_device(node, out, res))
        return outputs

    def flush_event_time(self) -> List[Record]:
        """End-of-stream: force-release every gate's buffered records in
        event-time order and run them through the match loops."""
        outputs: List[Record] = []
        for _stream, node, out in self.queries:
            fet = getattr(node.processor, "flush_event_time", None)
            if fet is None:
                continue
            res = fet()
            if res:
                outputs.extend(self._emit_device(node, out, res))
        return outputs

    def _emit_device(
        self, node, out: "OutputStream", results, timestamp: Optional[int] = None
    ) -> List[Record]:
        """Route device-processor [(key, Sequence)] results downstream.

        Record metadata comes from the match's completing (last) event so
        host- and device-runtime outputs carry equivalent context.

        Bytes-mode engines (sink_format json/arrow) yield SinkMatch items
        instead of Sequences: admission digests over the native ident
        frames (`admit_ident` -- bitwise-equal to `admit` on the same
        match), Record metadata from the carried completing event, and
        the sink write reuses the pre-serialized payload."""
        from .serde import SinkMatch

        emitted: List[Record] = []
        for rkey, seq in results:
            # Dedup gates the durable sink only -- see Topology.process.
            if isinstance(seq, SinkMatch):
                digest = node.gate.admit_ident(rkey, seq.ident)
                last = seq.last_event
            else:
                digest = node.gate.admit(rkey, seq)
                last = seq.matched[-1].events[-1] if seq.matched else None
            record = Record(
                rkey,
                seq,
                timestamp if timestamp is not None else (last.timestamp if last else 0),
                last.topic if last else "",
                last.partition if last else 0,
                last.offset if last else 0,
            )
            out.records.append(record)
            emitted.append(record)
            for fn in node.downstream:
                fn(rkey, seq)
            if digest is not None:
                trace: Optional[bytes] = None
                if last is not None:
                    # Device matches complete at their last event: the
                    # ingest stamp of that event's identity anchors the
                    # end-to-end latency sample.
                    trace = self._observe_match_latency(
                        node, last.topic, last.partition, rkey, last.offset,
                        seq,
                    )
                self._sink(node, record, digest, trace=trace)
        return emitted

    def _sink(
        self,
        node: QueryNode,
        record: Record,
        digest: bytes,
        trace: Optional[bytes] = None,
    ) -> None:
        """Write a matched record to the node's sink topics in the log.

        The record key carries the match's emission digest
        (streams/emission.py `encode_sink_key`) so the sink topic itself
        is the durable record of what it saw -- crash recovery re-reads
        the tail and dedupes with no cross-topic atomicity. `trace`
        forwards the completing event's wire trace context, so a sink
        consumer can keep stitching the same end-to-end trace."""
        if self.log is None or not node.sink_topics:
            return
        from .serde import SinkMatch, sequence_to_json

        key_bytes = encode_sink_key(record.key, digest)
        if isinstance(record.value, SinkMatch):
            # Sink-to-bytes decode: the payload was serialized natively
            # off the chain table -- byte-identical to the line below on
            # the same match (the golden parity pin).
            value_bytes = record.value.payload
        else:
            value_bytes = sequence_to_json(record.value).encode("utf-8")
        for topic in node.sink_topics:
            self.log.append(
                topic, key_bytes, value_bytes, timestamp=record.timestamp,
                trace=trace,
            )

    def event_time_health(self) -> Dict[str, Any]:
        """Event-time plane liveness for /healthz (ISSUE 12 satellite):
        per-gated-query watermark lag and reorder-buffer occupancy, plus
        the fleet aggregates an operator gates on without parsing prom
        text. Queries without a gate are simply absent; a topology with
        none reports ``{"gated_queries": 0, ...}`` zeros."""
        per_query: Dict[str, Any] = {}
        occupancy = 0
        lag_max: Optional[float] = None
        for _stream, node, _out in self.queries:
            gate = getattr(node.processor, "gate", None)
            if gate is None:
                continue
            lag_ms = gate.watermark_lag_ms
            lag_s = None if lag_ms is None else lag_ms / 1e3
            per_query[node.name] = {
                "watermark_lag_s": lag_s,
                "reorder_occupancy": gate.occupancy,
            }
            occupancy += gate.occupancy
            if lag_s is not None:
                lag_max = lag_s if lag_max is None else max(lag_max, lag_s)
        return {
            "gated_queries": len(per_query),
            "reorder_occupancy": occupancy,
            "watermark_lag_s_max": lag_max,
            "queries": per_query,
        }

    def take_poisoned(self) -> List[tuple]:
        """Drain every processor's quarantined records ([(query, key,
        event, exception)]) -- the driver dead-letters them after each
        poll (streams/driver.py)."""
        out: List[tuple] = []
        for _stream, node, _o in self.queries:
            take = getattr(node.processor, "take_poisoned", None)
            if take is None:
                continue
            out.extend(
                (node.name, key, event, exc) for key, event, exc in take()
            )
        return out

    def flush_stores(self) -> None:
        """Flush every query's store stack (pushes cached writes down into
        the changelog; the reference's commit-interval flush). The
        emission gate's watermark rolls forward LAST: a crash between the
        state appends and the watermark append then leaves NEW state with
        an OLD watermark -- recovery's sink-tail scan over-covers and the
        gate harmlessly dedupes. The reverse order (new watermark, old
        state) would let replay regenerate matches the scan no longer
        sees, re-opening the duplicate window this gate exists to close."""
        for _stream, node, _out in self.queries:
            for store in node.stores.values():
                store.flush()
            node.gate.commit(self.log, node.sink_topics)
            node.emission_store.flush()

    def restore_stores(self) -> int:
        """Replay each store's changelog from the log into the store
        (the reference's restore-consumer path on rebalance/restart), then
        recover each query's emission gate from its watermark + the sink
        tail. Returns total changelog records applied."""
        from ..state.builders import restore_store

        n = sum(
            restore_store(store)
            for _stream, node, _out in self.queries
            for store in node.stores.values()
        )
        for _stream, node, _out in self.queries:
            node.gate.recover(self.log, node.sink_topics)
        return n
