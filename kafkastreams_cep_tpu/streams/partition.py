"""Partitioned broker fleet: the RecordLog contract across N brokers.

The reference delegates partition assignment to the Kafka Streams task
layer: each task owns a topic-partition set on one broker, and the group
coordinator moves ownership when a broker dies or load skews (SURVEY §1,
L0). This module owns that layer for the embedded pipeline:

  * `PartitionedRecordLog` -- a client view over an ordered list of
    brokers (anything satisfying the RecordLog contract, typically
    `SocketRecordLog` clients of PR 12's `RecordLogServer`). Every
    (topic, partition) routes to exactly one broker -- deterministically
    by a stable hash until `assign()`/`move_partition()` pins it -- so
    `LogDriver`, the changelog store stack, and the EmissionGate run
    unchanged on top: offsets stay per (topic, partition, broker) and
    commit ordering is per-broker exactly as on one log.
  * `move_partition` -- the data-plane half of a rebalance: copy one
    (topic, partition)'s records to the target broker from its current
    end offset (idempotent resume: a re-run move appends nothing), then
    flip the route. When the owner is dead, a salvage log (the broker's
    durable file-backed segments reopened in-process) stands in as the
    read side -- the embedded stand-in for reading a replica.
  * `BrokerFleet` -- test/soak harness that spawns N file-backed
    `RecordLogServer`s, hands out clients, and can kill one broker and
    reopen its segments for salvage.

The control-plane half (when to move, fencing, shard checkpoint handoff)
lives in streams/rebalance.py.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..state.serde import crc32c
from .log import LogRecord


class PartitionedRecordLog:
    """RecordLog-contract router over an ordered broker list.

    The broker ORDER is the fleet topology: every client view of the same
    fleet must list the same brokers in the same order, or their default
    routes diverge. Explicit assignments (`assign`, `move_partition`)
    override the hash route and are the unit of rebalance."""

    def __init__(
        self,
        brokers: Sequence[Any],
        registry: Optional[Any] = None,
        assignment: Optional[Dict[Tuple[str, int], int]] = None,
    ) -> None:
        from ..obs.registry import default_registry

        if not brokers:
            raise ValueError("PartitionedRecordLog needs at least one broker")
        self.brokers: List[Any] = list(brokers)
        self.path = None  # contract parity: not itself file-backed
        self.metrics = registry if registry is not None else default_registry()
        self._lock = threading.Lock()
        self._assignment: Dict[Tuple[str, int], int] = dict(assignment or {})
        #: Default-route redirects for downed brokers: (topic, partition)s
        #: materialized BEFORE the death are re-homed explicitly by the
        #: rebalance layer (move_partition), but a topic first touched
        #: AFTER it would still hash onto the corpse -- mark_down() sends
        #: those future defaults to a survivor instead.
        self._down: Dict[int, int] = {}
        for idx in self._assignment.values():
            self._check_idx(idx)
        m = self.metrics
        n = len(self.brokers)
        self._m_up = m.gauge(
            "cep_transport_broker_up",
            "1 while the broker's last routed request succeeded, 0 after "
            "a routed request raised (reset by the next success)",
            labels=("broker",),
        )
        _m_reqs = m.counter(
            "cep_transport_broker_requests_total",
            "Requests routed to each broker of the partitioned fleet",
            labels=("broker", "op"),
        )
        _m_errs = m.counter(
            "cep_transport_broker_errors_total",
            "Routed requests that raised, per broker (the health signal "
            "the rebalance controller watches alongside last_ok age)",
            labels=("broker",),
        )
        # Children bound once per broker: routing is the append/read hot
        # path and labels() resolution locks per call.
        self._up = [self._m_up.labels(broker=str(i)) for i in range(n)]
        self._reqs_append = [
            _m_reqs.labels(broker=str(i), op="append") for i in range(n)
        ]
        self._reqs_read = [
            _m_reqs.labels(broker=str(i), op="read") for i in range(n)
        ]
        self._errs = [_m_errs.labels(broker=str(i)) for i in range(n)]
        for up in self._up:
            up.set(1.0)

    # ------------------------------------------------------------- routing
    def _check_idx(self, idx: int) -> None:
        if not 0 <= idx < len(self.brokers):
            raise ValueError(
                f"broker index {idx} out of range (fleet of "
                f"{len(self.brokers)})"
            )

    def _default_route(self, topic: str, partition: int) -> int:
        # Stable across processes and fleet restarts (no PYTHONHASHSEED
        # dependence): the same (topic, partition) always lands on the
        # same broker of an equally-ordered fleet.
        return (crc32c(topic.encode("utf-8")) + partition) % len(self.brokers)

    def broker_for(self, topic: str, partition: int = 0) -> int:
        """The owning broker index for one (topic, partition)."""
        with self._lock:
            key = (topic, int(partition))
            idx = self._assignment.get(key)
            if idx is None:
                idx = self._default_route(topic, partition)
                # Follow down-redirects (bounded: a redirect chain longer
                # than the fleet means a cycle -- a config bug, not a
                # reachable route).
                for _ in range(len(self.brokers)):
                    if idx not in self._down:
                        break
                    idx = self._down[idx]
                else:
                    raise ValueError(
                        f"down-broker redirect cycle resolving "
                        f"({topic}, {partition})"
                    )
                self._assignment[key] = idx
            return idx

    def mark_down(self, broker: int, redirect_to: int) -> None:
        """Route future default assignments away from a dead broker.
        Existing assignments are untouched (the rebalance layer moves
        those explicitly, data first)."""
        self._check_idx(broker)
        self._check_idx(redirect_to)
        if broker == redirect_to:
            raise ValueError("cannot redirect a downed broker to itself")
        with self._lock:
            self._down[broker] = redirect_to

    def assign(self, topic: str, partition: int, broker: int) -> None:
        """Pin one (topic, partition) to a broker (no data movement --
        use `move_partition` to rebalance a populated partition)."""
        self._check_idx(broker)
        with self._lock:
            self._assignment[(topic, int(partition))] = broker

    def assignment(self) -> Dict[Tuple[str, int], int]:
        """Snapshot of every materialized (topic, partition) -> broker
        route (defaults materialize on first touch)."""
        with self._lock:
            return dict(self._assignment)

    def partitions_on(self, broker: int) -> List[Tuple[str, int]]:
        """Every materialized (topic, partition) currently routed to one
        broker -- the move list when that broker dies."""
        with self._lock:
            return sorted(
                tp for tp, idx in self._assignment.items() if idx == broker
            )

    def _routed(self, topic: str, partition: int) -> Tuple[Any, int]:
        idx = self.broker_for(topic, partition)
        return self.brokers[idx], idx

    # ----------------------------------------------------------- contract
    def append(
        self,
        topic: str,
        key: Optional[bytes],
        value: Optional[bytes],
        timestamp: int = 0,
        partition: int = 0,
        trace: Optional[bytes] = None,
    ) -> int:
        broker, idx = self._routed(topic, partition)
        self._reqs_append[idx].inc()
        try:
            off = broker.append(
                topic, key, value, timestamp=timestamp, partition=partition,
                trace=trace,
            )
        except Exception:
            self._errs[idx].inc()
            self._up[idx].set(0.0)
            raise
        self._up[idx].set(1.0)
        return off

    def read(
        self,
        topic: str,
        partition: int = 0,
        start: int = 0,
        max_records: Optional[int] = None,
    ) -> List[LogRecord]:
        broker, idx = self._routed(topic, partition)
        self._reqs_read[idx].inc()
        try:
            records = broker.read(
                topic, partition=partition, start=start,
                max_records=max_records,
            )
        except Exception:
            self._errs[idx].inc()
            self._up[idx].set(0.0)
            raise
        self._up[idx].set(1.0)
        return records

    def end_offset(self, topic: str, partition: int = 0) -> int:
        broker, _idx = self._routed(topic, partition)
        return broker.end_offset(topic, partition=partition)

    def topics(self) -> List[str]:
        seen = set()
        for idx, broker in enumerate(self.brokers):
            if idx in self._down:
                continue  # evacuated corpse: survivors hold its metadata
            seen.update(broker.topics())
        return sorted(seen)

    def partitions(self, topic: str) -> List[int]:
        seen = set()
        for idx, broker in enumerate(self.brokers):
            if idx in self._down:
                continue  # evacuated corpse: survivors hold its metadata
            seen.update(broker.partitions(topic))
        return sorted(seen)

    def flush(self) -> None:
        """Flush every broker that owns at least one materialized route
        (all of them before any route exists). Fail-stop on the first
        failure, matching the embedded log's fsyncgate stance: commit()
        must never record offsets over changelog/sink appends whose
        durability is unknown. Ownerless brokers are skipped so a dead,
        fully-evacuated broker cannot wedge the survivors' commits."""
        with self._lock:
            owners = set(self._assignment.values())
        for idx, broker in enumerate(self.brokers):
            if owners and idx not in owners:
                continue
            broker.flush()

    def close(self) -> None:
        first: Optional[BaseException] = None
        for broker in self.brokers:
            try:
                broker.close()
            except Exception as exc:  # close the rest before raising
                if first is None:
                    first = exc
        if first is not None:
            raise first

    # ---------------------------------------------------------- rebalance
    def move_partition(
        self,
        topic: str,
        partition: int,
        target: int,
        source_log: Optional[Any] = None,
    ) -> int:
        """Copy one (topic, partition) to broker `target` and flip its
        route; returns how many records were appended.

        The copy resumes from the target's current end offset, so a move
        interrupted and re-run appends only the missing suffix (offsets
        are record ordinals on both sides -- the single-owner invariant
        means the target's prefix IS the source's prefix). `source_log`
        substitutes the read side when the owner is unreachable: the
        dead broker's durable segments reopened as a salvage RecordLog."""
        self._check_idx(target)
        with self._lock:
            src_idx = self._assignment.get(
                (topic, int(partition)),
                self._default_route(topic, partition),
            )
        if src_idx == target and source_log is None:
            return 0
        src = source_log if source_log is not None else self.brokers[src_idx]
        dst = self.brokers[target]
        already = dst.end_offset(topic, partition=partition)
        records = src.read(topic, partition=partition, start=already)
        for rec in records:
            dst.append(
                topic, rec.key, rec.value,
                timestamp=rec.timestamp, partition=partition,
                trace=getattr(rec, "trace", None),
            )
        dst.flush()
        self.assign(topic, partition, target)
        return len(records)

    # ------------------------------------------------------------- health
    def health(self) -> Dict[str, Any]:
        with self._lock:
            assignment = {
                f"{t}:{p}": idx for (t, p), idx in sorted(self._assignment.items())
            }
        per_broker = []
        for i, broker in enumerate(self.brokers):
            fn = getattr(broker, "health", None)
            per_broker.append(fn() if callable(fn) else None)
        with self._lock:
            down = {str(b): t for b, t in sorted(self._down.items())}
        return {
            "mode": "partitioned",
            "brokers": len(self.brokers),
            "broker_health": per_broker,
            "assignment": assignment,
            "down": down,
        }


class BrokerFleet:
    """N file-backed socket brokers under one base directory.

    The soak/test harness half of the fleet: spawn servers, hand out
    `SocketRecordLog` clients (one per broker, shared registry), kill a
    broker under traffic, and reopen its durable segments for salvage
    (`move_partition(source_log=...)`) -- the embedded stand-in for a
    replica read. Restart brings the broker back on its old segments
    (RecordLog reload truncates any torn tail)."""

    def __init__(
        self,
        base_dir: str,
        n_brokers: int = 2,
        registry: Optional[Any] = None,
        **server_opts: Any,
    ) -> None:
        import os

        from .log import RecordLog
        from .transport import RecordLogServer

        if n_brokers < 1:
            raise ValueError("fleet needs at least one broker")
        self.base_dir = base_dir
        self.registry = registry
        self.server_opts = dict(server_opts)
        self.paths = [
            os.path.join(base_dir, f"broker{i}") for i in range(n_brokers)
        ]
        self.servers: List[Optional[RecordLogServer]] = []
        for path in self.paths:
            os.makedirs(path, exist_ok=True)
            self.servers.append(
                RecordLogServer(
                    RecordLog(path), registry=registry, **self.server_opts
                ).start()
            )

    @property
    def n_brokers(self) -> int:
        return len(self.servers)

    def addresses(self) -> List[Optional[Tuple[str, int]]]:
        return [s.address if s is not None else None for s in self.servers]

    def clients(self, registry: Optional[Any] = None, **client_opts: Any):
        """One `SocketRecordLog` per live broker, fleet order preserved
        (dead brokers get a non-connecting placeholder client so routing
        indices stay stable; requests to them fail loudly)."""
        from .transport import SocketRecordLog

        out = []
        for i, server in enumerate(self.servers):
            opts = dict(client_opts)
            # Distinct per-broker backoff streams from one seed.
            if "backoff_seed" in opts:
                opts["backoff_seed"] = opts["backoff_seed"] + i
            if server is None:
                out.append(
                    SocketRecordLog(
                        ("127.0.0.1", 9), registry=registry,
                        connect=False, retry_budget=0, **opts,
                    )
                )
            else:
                out.append(
                    SocketRecordLog(
                        server.address, registry=registry, **opts
                    )
                )
        return out

    def kill(self, broker: int) -> None:
        """Stop one broker's server (its durable segments stay on disk).
        Clients see disconnects; salvage_log() reads what it flushed."""
        server = self.servers[broker]
        if server is not None:
            server.stop()
            self.servers[broker] = None

    def salvage_log(self, broker: int):
        """The dead broker's durable segments reopened in-process -- the
        read side of a salvage `move_partition`."""
        from .log import RecordLog

        return RecordLog(self.paths[broker])

    def restart(self, broker: int):
        """Bring a killed broker back on its old segments."""
        from .log import RecordLog
        from .transport import RecordLogServer

        if self.servers[broker] is not None:
            raise RuntimeError(f"broker {broker} is already running")
        self.servers[broker] = RecordLogServer(
            RecordLog(self.paths[broker]), registry=self.registry,
            **self.server_opts,
        ).start()
        return self.servers[broker]

    def stop(self) -> None:
        for i, server in enumerate(self.servers):
            if server is not None:
                server.stop()
                self.servers[i] = None
