#!/usr/bin/env python
"""ceplint: invariant-enforcing static analysis for the CEP engine.

Thin entry-point shim; the implementation lives in
kafkastreams_cep_tpu/analysis/ (importable without jax -- only the
optional --jit-audit touches the device stack).

    python scripts/ceplint.py --all            # full gate (tier-1 runs this)
    python scripts/ceplint.py --all --json     # machine-readable
    python scripts/ceplint.py path/to/file.py  # partial scan
    python scripts/ceplint.py --all --jit-audit  # + churn-replay audit

Exit 0 clean, 1 on unbaselined findings, 2 on usage/internal error.
"""
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from kafkastreams_cep_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
