"""Declarative predicate / fold expressions.

The reference evaluates predicates as opaque Java closures
(reference: core/.../cep/pattern/Matcher.java:30-38), which cannot run on an
accelerator. The TPU-native design instead expresses predicates and fold
updates as small expression trees over:

  * event fields            -> ``field("price")``
  * the raw event value/key -> ``value()`` / ``key()``
  * event metadata          -> ``timestamp()``, ``topic_is("t")``
  * per-run fold registers  -> ``agg("avg")``

An expression evaluates against an *environment* (a duck-typed object with
``field/key/value/timestamp/topic_id/agg`` accessors). The same tree
therefore runs in two worlds:

  * host interpreter: env wraps a single Event + aggregate store lookups
    (nfa/context.py), producing Python scalars;
  * device kernel: env wraps structure-of-arrays jnp columns + the register
    file (ops/engine.py), producing vectorized jnp masks, traced under jit.

This is the design lever that turns the reference's per-edge virtual call
(NFA.java:371-384) into one fused vector op per predicate per micro-batch.
"""
from __future__ import annotations

import operator
from typing import Any, Callable, FrozenSet, Optional, Union

Number = Union[int, float, bool]


class Expr:
    """Base expression node. Immutable; overloads build the tree."""

    def evaluate(self, env: "Env") -> Any:
        raise NotImplementedError

    # --- metadata used by the device compiler -------------------------------
    def fields(self) -> FrozenSet[str]:
        """Names of event fields referenced anywhere in the tree."""
        return frozenset()

    def aggs(self) -> FrozenSet[str]:
        """Names of fold registers referenced anywhere in the tree."""
        return frozenset()

    # --- operator overloads -------------------------------------------------
    def _bin(self, other: Any, op: Callable, sym: str) -> "Expr":
        return BinOp(self, _lift(other), op, sym)

    def _rbin(self, other: Any, op: Callable, sym: str) -> "Expr":
        return BinOp(_lift(other), self, op, sym)

    def __add__(self, o): return self._bin(o, operator.add, "+")
    def __radd__(self, o): return self._rbin(o, operator.add, "+")
    def __sub__(self, o): return self._bin(o, operator.sub, "-")
    def __rsub__(self, o): return self._rbin(o, operator.sub, "-")
    def __mul__(self, o): return self._bin(o, operator.mul, "*")
    def __rmul__(self, o): return self._rbin(o, operator.mul, "*")
    def __truediv__(self, o): return self._bin(o, operator.truediv, "/")
    def __rtruediv__(self, o): return self._rbin(o, operator.truediv, "/")
    def __floordiv__(self, o): return self._bin(o, operator.floordiv, "//")
    def __rfloordiv__(self, o): return self._rbin(o, operator.floordiv, "//")
    def __mod__(self, o): return self._bin(o, operator.mod, "%")
    def __rmod__(self, o): return self._rbin(o, operator.mod, "%")

    def __gt__(self, o): return self._bin(o, operator.gt, ">")
    def __ge__(self, o): return self._bin(o, operator.ge, ">=")
    def __lt__(self, o): return self._bin(o, operator.lt, "<")
    def __le__(self, o): return self._bin(o, operator.le, "<=")
    def __eq__(self, o): return self._bin(o, operator.eq, "==")  # type: ignore[override]
    def __ne__(self, o): return self._bin(o, operator.ne, "!=")  # type: ignore[override]

    def __and__(self, o): return BoolOp(self, _lift(o), "and")
    def __rand__(self, o): return BoolOp(_lift(o), self, "and")
    def __or__(self, o): return BoolOp(self, _lift(o), "or")
    def __ror__(self, o): return BoolOp(_lift(o), self, "or")
    def __invert__(self): return NotOp(self)

    __hash__ = object.__hash__


def _lift(v: Any) -> Expr:
    return v if isinstance(v, Expr) else Const(v)


class Const(Expr):
    __slots__ = ("value",)

    def __init__(self, value: Number) -> None:
        self.value = value

    def evaluate(self, env: "Env") -> Any:
        return self.value

    def __repr__(self) -> str:
        return repr(self.value)


class Field(Expr):
    """A named field of the event value (dict key / attribute / column)."""

    __slots__ = ("name",)

    def __init__(self, name: str) -> None:
        self.name = name

    def evaluate(self, env: "Env") -> Any:
        return env.field(self.name)

    def fields(self) -> FrozenSet[str]:
        return frozenset({self.name})

    def __repr__(self) -> str:
        return f"field({self.name!r})"


class Value(Expr):
    """The raw event value (for scalar-valued streams, e.g. the Letters demo)."""

    def evaluate(self, env: "Env") -> Any:
        return env.value()

    def fields(self) -> FrozenSet[str]:
        return frozenset({""})

    def __repr__(self) -> str:
        return "value()"


class Key(Expr):
    def evaluate(self, env: "Env") -> Any:
        return env.key()

    def __repr__(self) -> str:
        return "key()"


class Timestamp(Expr):
    def evaluate(self, env: "Env") -> Any:
        return env.timestamp()

    def __repr__(self) -> str:
        return "timestamp()"


class TopicIs(Expr):
    """True when the event originates from the given topic.

    The reference ANDs a TopicPredicate into stage predicates when a
    per-stage source topic is selected (StagesFactory.java:95-99); on device
    this becomes a comparison against a tokenized topic-id column.
    """

    __slots__ = ("topic",)

    def __init__(self, topic: str) -> None:
        self.topic = topic

    def evaluate(self, env: "Env") -> Any:
        return env.topic_is(self.topic)

    def __repr__(self) -> str:
        return f"topic_is({self.topic!r})"


class AggRef(Expr):
    """The current run's fold register (reference States.get, States.java:56-60)."""

    __slots__ = ("name", "default")

    def __init__(self, name: str, default: Optional[Number] = None) -> None:
        self.name = name
        self.default = default

    def evaluate(self, env: "Env") -> Any:
        return env.agg(self.name, self.default)

    def aggs(self) -> FrozenSet[str]:
        return frozenset({self.name})

    def __repr__(self) -> str:
        if self.default is None:
            return f"agg({self.name!r})"
        return f"agg({self.name!r}, default={self.default!r})"


class BinOp(Expr):
    __slots__ = ("left", "right", "op", "sym")

    def __init__(self, left: Expr, right: Expr, op: Callable, sym: str) -> None:
        self.left = left
        self.right = right
        self.op = op
        self.sym = sym

    def evaluate(self, env: "Env") -> Any:
        return self.op(self.left.evaluate(env), self.right.evaluate(env))

    def fields(self) -> FrozenSet[str]:
        return self.left.fields() | self.right.fields()

    def aggs(self) -> FrozenSet[str]:
        return self.left.aggs() | self.right.aggs()

    def __repr__(self) -> str:
        return f"({self.left!r} {self.sym} {self.right!r})"


class BoolOp(Expr):
    __slots__ = ("left", "right", "kind")

    def __init__(self, left: Expr, right: Expr, kind: str) -> None:
        self.left = left
        self.right = right
        self.kind = kind

    def evaluate(self, env: "Env") -> Any:
        lhs = self.left.evaluate(env)
        rhs = self.right.evaluate(env)
        if isinstance(lhs, bool) and isinstance(rhs, bool):
            return (lhs and rhs) if self.kind == "and" else (lhs or rhs)
        # jnp path: element-wise logical ops keep everything traceable.
        return (lhs & rhs) if self.kind == "and" else (lhs | rhs)

    def fields(self) -> FrozenSet[str]:
        return self.left.fields() | self.right.fields()

    def aggs(self) -> FrozenSet[str]:
        return self.left.aggs() | self.right.aggs()

    def __repr__(self) -> str:
        return f"({self.left!r} {self.kind} {self.right!r})"


class NotOp(Expr):
    __slots__ = ("inner",)

    def __init__(self, inner: Expr) -> None:
        self.inner = inner

    def evaluate(self, env: "Env") -> Any:
        v = self.inner.evaluate(env)
        if isinstance(v, bool):
            return not v
        return ~v

    def fields(self) -> FrozenSet[str]:
        return self.inner.fields()

    def aggs(self) -> FrozenSet[str]:
        return self.inner.aggs()

    def __repr__(self) -> str:
        return f"(not {self.inner!r})"


class TrueExpr(Expr):
    def evaluate(self, env: "Env") -> Any:
        return env.true()

    def __repr__(self) -> str:
        return "true()"


class Env:
    """Duck-typed evaluation environment contract (documented, not enforced)."""

    def field(self, name: str) -> Any: ...
    def key(self) -> Any: ...
    def value(self) -> Any: ...
    def timestamp(self) -> Any: ...
    def topic_is(self, topic: str) -> Any: ...
    def agg(self, name: str, default: Optional[Number]) -> Any: ...
    def true(self) -> Any:
        return True


# Public factory helpers -- the DSL surface.
def field(name: str) -> Field:
    return Field(name)


def value() -> Value:
    return Value()


def key() -> Key:
    return Key()


def timestamp() -> Timestamp:
    return Timestamp()


def topic_is(topic: str) -> TopicIs:
    return TopicIs(topic)


def agg(name: str, default: Optional[Number] = None) -> AggRef:
    return AggRef(name, default)


def const(v: Number) -> Const:
    return Const(v)
