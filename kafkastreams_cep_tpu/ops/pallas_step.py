"""Fused Pallas TPU kernel for the batched NFA step (PERF.md round-3 lever 1).

One kernel advances 8 keys x R run lanes through a full [T]-event micro-batch:
grid (K/8, T) with T innermost, the engine state carried in the *output*
refs across T (Mosaic elides the re-fetch/flush while the block index is
unchanged, so the multi-step carry lives entirely in VMEM), and one
(1, 8, cap) node/match output block streamed to HBM per step.

This replaces the vmapped XLA scan step (ops/engine.py:build_step) whose
per-event cost was spread across ~100s of small fusions plus scratch-space
staging copies between them (profiled on the real chip, PERF.md "v4"): the
kernel computes the identical transition relation -- the same unrolled
epsilon descent, slot table, DFS emission order, counters and drop policy --
so the two paths are interchangeable and bitwise-comparable.

TPU-native forms used here (none exist in the reference, which is a
per-record JVM loop, NFA.java:134-397):

  * per-lane stage-table lookups are unrolled selects over the static stage
    count (the kernel analog of engine.py's one-hot contractions);
  * the lane-axis exclusive cumsum that locates each surviving slot's
    compaction rank is a matmul against a strictly-lower-triangular
    constant (MXU, Precision.HIGHEST -- exact for integer payloads);
  * slot compaction itself is a batched one-hot matmul: for each of the
    3L emission slots, out[k, f, j] += field[k, f, r] * (rank[k, r] == j),
    an (8, F, R) @ (8, R, R) MXU contraction per slot. Integer fields ride
    f32 lanes exactly (one-hot rows select a single value, so no rounding
    can occur below 2^24); `seq`/`ts`/`node` split into 16-bit halves so
    the full i32 range survives;
  * match-id and buffer-node emission reuse the same rank/one-hot machinery
    with j ranging over matches_per_step / nodes_per_step.

Sentinel encoding: -1-valued fields (eps, node, ts) are biased by +1 before
the 16-bit split and unbiased after selection.

The kernel is engaged by BatchedDeviceNFA(engine="pallas"|"auto"); the XLA
scan step remains the fallback (mesh-sharded runs, unsupported configs, and
non-TPU platforms) and the conformance oracle for this kernel's tests.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..pattern.expressions import Env
from .engine import EngineConfig
from .tables import (
    OP_BEGIN,
    OP_NONE,
    OP_TAKE,
    PR_NONE,
    PR_PROCEED,
    PR_SKIP,
    CompiledQuery,
)

_I32_MAX = np.int64(2**31 - 1)
# HIGHEST (f32-emulating bf16 passes) is required for exact integer
# transport through the selection matmuls: DEFAULT rounds the 16-bit
# planes (bf16 has an 8-bit significand) -- measured on hardware as
# corrupted run ids (seq_collisions) at production shapes.
HI = jax.lax.Precision.HIGHEST

#: per-lane i32 state fields, in the stacked-lanes array order.
LANE_FIELDS = (
    "active", "src", "eps", "vlen", "seq", "node", "ts", "branching",
    "ignored", "root",
)
#: per-key scalar counters, in the stacked-counters array order.
COUNTER_FIELDS = (
    "runs", "n_events", "n_branches", "n_expired",
    "lane_drops", "node_drops", "match_drops", "seq_collisions",
)


def supports_pallas(query: CompiledQuery, config: EngineConfig) -> Optional[str]:
    """None if the fused kernel can run this query/config, else the reason."""
    R = config.lanes
    L = query.max_depth
    p_cap = config.nodes_per_step if config.nodes_per_step > 0 else R * L
    if p_cap > 512:
        return f"nodes_per_step window {p_cap} > 512 (VMEM budget)"
    if config.matches_per_step > 512:
        return f"matches_per_step {config.matches_per_step} > 512"
    # Node ids must survive a single f32 one-hot lane (< 2^24); the window
    # base grows with the batch length, checked per-advance in the builder.
    if config.nodes >= (1 << 24):
        return f"node pool {config.nodes} >= 2^24 (f32-exact id transport)"
    return None


class PallasEnv(Env):
    """Expression environment inside the kernel: (8, 1) per-key event
    scalars broadcasting against (8, R) fold-register planes."""

    def __init__(
        self,
        event: Dict[str, jnp.ndarray],
        regs: List[jnp.ndarray],
        regs_set: List[jnp.ndarray],
        agg_slots: Dict[str, int],
        defaults: Dict[str, float],
    ) -> None:
        self._event = event
        self._regs = regs
        self._regs_set = regs_set
        self._agg_slots = agg_slots
        self._defaults = defaults

    def field(self, name: str) -> Any:
        return self._event[f"f:{name}"]

    def value(self) -> Any:
        return self._event["f:"]

    def key(self) -> Any:
        raise NotImplementedError("key() is not available in device predicates")

    def timestamp(self) -> Any:
        return self._event["ts"]

    def topic_is(self, topic_code: Any) -> Any:
        return self._event["topic"] == topic_code

    def agg(self, name: str, default: Any = None) -> Any:
        slot = self._agg_slots.get(name)
        fallback = default if default is not None else self._defaults.get(name, 0)
        if slot is None:
            return jnp.float32(fallback)
        return jnp.where(
            self._regs_set[slot] != 0, self._regs[slot], jnp.float32(fallback)
        )

    def true(self) -> Any:
        return True


def _split16(v: jnp.ndarray, bias: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(lo, hi) f32 halves of a biased i32 (v + bias must be >= 0)."""
    u = v + bias
    return (u & 0xFFFF).astype(jnp.float32), (u >> 16).astype(jnp.float32)


def _join16(lo: jnp.ndarray, hi: jnp.ndarray, bias: int) -> jnp.ndarray:
    return (
        (hi.astype(jnp.int32) << 16) | lo.astype(jnp.int32)
    ) - bias


def _key_axis_spec(leaf, axis: int):
    """PartitionSpec sharding `axis` over the key mesh axis."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.key_shard import KEY_AXIS

    dims = [None] * leaf.ndim
    dims[axis] = KEY_AXIS
    return P(*dims)


def build_pallas_batched_advance(
    query: CompiledQuery,
    config: EngineConfig,
    interpret: bool = False,
    mesh: Optional[Any] = None,
):
    """jit advance(state, xs) -> (state, ys) running the fused kernel.

    Contract-identical to key_shard.build_batched_advance except ys leaves
    are [T, K, cap] (key axis second) -- pair with
    build_pallas_batched_post. K must be a multiple of 8 (per shard).

    With `mesh`, the whole advance runs under `shard_map` over the key
    axis: every device executes the kernel on its own key slice and no
    collective touches the per-event hot path (per-key NFAs are
    independent; SURVEY.md section 2.8 scale-out stance) -- only the
    drivers' stats reduction all-reduces.
    """
    R = config.lanes
    D = config.dewey_width(query)
    A = query.n_aggs
    B = config.nodes
    M_STEP = config.matches_per_step
    L = query.max_depth
    P = query.n_preds
    SLOTS = 3 * L
    P_CAP = config.nodes_per_step if config.nodes_per_step > 0 else R * L
    NF = len(LANE_FIELDS)
    NC = len(COUNTER_FIELDS)
    reason = supports_pallas(query, config)
    if reason is not None:
        raise ValueError(f"pallas step unsupported: {reason}")

    # -- static stage tables (host numpy; unrolled into selects) -----------
    n_consume_op = np.asarray(query.consume_op)
    n_consume_pred = np.asarray(query.consume_pred)
    n_consume_target = np.asarray(query.consume_target)
    n_ignore_pred = np.asarray(query.ignore_pred)
    n_proceed_kind = np.asarray(query.proceed_kind)
    n_proceed_pred = np.asarray(query.proceed_pred)
    n_proceed_target = np.asarray(query.proceed_target)
    n_window = np.where(
        query.window_ms < 0, -1, np.minimum(query.window_ms, _I32_MAX - 1)
    ).astype(np.int32)
    n_name_id = np.asarray(query.name_id)
    n_pure_name = np.asarray(query.pure_name_id)
    n_is_begin = np.asarray(query.is_begin)
    n_is_final = np.asarray(query.is_final)
    n_is_fwd = np.asarray(query.is_fwd)
    n_fwd_final = np.asarray(query.fwd_final)
    N_ST = len(n_consume_op)
    n_pure_of_ptgt = n_pure_name[n_proceed_target.clip(0)]
    n_isfin_of_ctgt = n_is_final[n_consume_target.clip(0)] & (n_consume_target >= 0)
    stateful = [bool(f) for f in query.pred_stateful]

    flat_folds: List[Tuple[int, int, Callable]] = []
    for stage_i, stage_folds in enumerate(query.folds):
        for slot, fn in stage_folds:
            flat_folds.append((stage_i, slot, fn))

    int_fields = [
        name for name, dt in query.schema.fields.items()
        if np.dtype(dt) != np.dtype(np.float32)
    ]
    f32_fields = [
        name for name, dt in query.schema.fields.items()
        if np.dtype(dt) == np.dtype(np.float32)
    ]
    # xi column order: ts, topic, gidx, valid, ints..., spred..., gc_phase,
    # wm (the group's step offset and the per-step watermark ride the event
    # columns so the kernel needs no extra input ref; gc_phase is the same
    # for every row of a batch, read per key block as an (8, 1) scalar
    # plane; wm is the event-time watermark in force when the record was
    # released -- WM_NONE when no event-time gate is armed, making the
    # expiry clock bitwise-equal to the event timestamp).
    XI_BASE = 4
    PH_COL = XI_BASE + len(int_fields) + P
    WM_COL = PH_COL + 1
    CI = WM_COL + 1
    CF = len(f32_fields)

    # Per-lane stage lookups are unrolled selects over the static stage
    # count. The `ids == i` compare masks are memoized per distinct stage-id
    # array (trace-level, keyed by object identity): the step performs
    # ~10 lookups against each of a handful of id arrays, and the kernel is
    # VPU-bound, so sharing the N_ST compares across lookups is a measured
    # win over recomparing inside every lut.
    def make_luts():
        cache: Dict[int, Any] = {}

        def masks_for(ids: jnp.ndarray) -> List[jnp.ndarray]:
            got = cache.get(id(ids))
            if got is None:
                got = (ids, [ids == i for i in range(N_ST)])
                cache[id(ids)] = got
            return got[1]

        def lut_i(ids: jnp.ndarray, table: np.ndarray) -> jnp.ndarray:
            """Unrolled per-lane table lookup (ids -1 -> 0)."""
            eq = masks_for(ids)
            acc = jnp.zeros_like(ids)
            for i in range(N_ST):
                v = int(table[i])
                if v != 0:
                    acc = jnp.where(eq[i], jnp.int32(v), acc)
            return acc

        def lut_b(ids: jnp.ndarray, table: np.ndarray) -> jnp.ndarray:
            """Unrolled boolean lookup (ids -1 -> False)."""
            eq = masks_for(ids)
            acc = jnp.zeros(ids.shape, bool)
            for i in range(N_ST):
                if bool(table[i]):
                    acc = acc | eq[i]
            return acc

        return masks_for, lut_i, lut_b

    # Triangular matrix for lane-axis exclusive cumsums (tri[r', r] = 1 iff
    # r' < r, so  counts @ tri  is the exclusive scan). Built with iota
    # inside the kernel: pallas kernels cannot capture traced constants.
    def make_tri() -> jnp.ndarray:
        ii = jax.lax.broadcasted_iota(jnp.int32, (R, R), 0)
        jj = jax.lax.broadcasted_iota(jnp.int32, (R, R), 1)
        return (ii < jj).astype(jnp.float32)

    def excl_lane_cumsum(cnt_f: jnp.ndarray, tri: jnp.ndarray) -> jnp.ndarray:
        """[8, R] f32 counts -> [8, R] exclusive cumsum along lanes (exact)."""
        return jax.lax.dot_general(
            cnt_f, tri, (((1,), (0,)), ((), ())), precision=HI
        )

    def select_slots(
        masks: List[jnp.ndarray],
        ranks: List[jnp.ndarray],
        fields_fns: List[Callable[[], List[jnp.ndarray]]],
        n_out: int,
        n_fields: int,
    ) -> jnp.ndarray:
        """DFS-order one-hot compaction: output [8, F, n_out] f32 where
        out[k, :, j] = the slot fields at the j-th set mask bit in
        (lane-major, slot-minor) rank order. Unselected j stay 0.

        The output axis is processed in 128-wide chunks, slot-outermost so
        each slot's one-hot transients die before the next slot's are
        built -- without chunking, large (lanes, slots, caps) configs blow
        the 16 MB VMEM scoped-allocation limit (seen at lanes>=192 with
        9 slots).

        Each slot's whole contribution (field materialization, one-hot
        build, matmul) sits behind a scalar `lax.cond` on its occupancy:
        an empty slot's contribution is exactly zero, so skipping it is
        bitwise-neutral -- and on a typical event step most of the 3L
        emission slots ARE empty (clone slots occupy only on branching
        events, re-add slots only when a begin root consumed), so the
        runtime branch removes the kernel's dominant VPU term (the
        [8, R, chunk] one-hot compares scale with slots x lanes x n_out)."""
        # Escape hatch for A/B perf work: KCT_SLOT_SKIP=0 inlines every
        # slot's contribution unconditionally (the round-4 form). Measured
        # on v5e (skip_any8, lanes=256): cond-skipped 0.23 s/batch vs 0.90
        # inline -- most slots are empty on most steps.
        import os

        use_cond = os.environ.get("KCT_SLOT_SKIP", "1") != "0"
        offsets = list(range(0, n_out, 128))
        acc: List[jnp.ndarray] = [
            jnp.zeros((8, n_fields, min(128, n_out - j0)), jnp.float32)
            for j0 in offsets
        ]
        for mask, rank, ffn in zip(masks, ranks, fields_fns):
            any_occ = jnp.any(mask)

            def contrib(accs, ffn=ffn, mask=mask, rank=rank):
                ft = jnp.stack(ffn(), axis=1)  # (8, F, R)
                mi = mask.astype(jnp.int32)[:, :, None] != 0
                rk = rank[:, :, None]
                out = []
                for a, j0 in zip(accs, offsets):
                    w = min(128, n_out - j0)
                    jiota = (
                        jax.lax.broadcasted_iota(jnp.int32, (1, 1, w), 2) + j0
                    )
                    oh = ((rk == jiota) & mi).astype(jnp.float32)  # (8, R, w)
                    out.append(
                        a
                        + jax.lax.dot_general(
                            ft, oh, (((2,), (1,)), ((0,), (0,))), precision=HI
                        )
                    )
                return out

            if use_cond:
                acc = jax.lax.cond(any_occ, contrib, lambda a: list(a), acc)
            else:
                acc = contrib(acc)
        return acc[0] if len(acc) == 1 else jnp.concatenate(acc, axis=2)

    def kernel(
        xi_ref, xf_ref, lanes_ref, ver_ref, regs_ref, rset_ref, ctr_ref,
        lanes_o, ver_o, regs_o, rset_o, ctr_o, wev_o, wnm_o, wpr_o, wmt_o,
        wmr_o,
    ):
        t = pl.program_id(1)
        masks_for, lut_i, lut_b = make_luts()

        @pl.when(t == 0)
        def _():
            lanes_o[...] = lanes_ref[...]
            ver_o[...] = ver_ref[...]
            regs_o[...] = regs_ref[...]
            rset_o[...] = rset_ref[...]
            ctr_o[...] = ctr_ref[...]

        # -- load carried state (8, R) planes -------------------------------
        st = {name: lanes_o[i] for i, name in enumerate(LANE_FIELDS)}
        ver0 = [ver_o[d] for d in range(D)]
        regs0 = [regs_o[a] for a in range(A)]
        rset0 = [rset_o[a] for a in range(A)]
        ctr = ctr_o[...]  # (8, NC) i32

        xi = xi_ref[0]  # (8, CI) i32
        xf = xf_ref[0]  # (8, max(CF,1)) f32
        ev_ts = xi[:, 0:1]
        topic = xi[:, 1:2]
        gidx = xi[:, 2:3]
        valid = xi[:, 3:4] != 0  # (8, 1) bool
        # Expiry clock (engine.py build_step): max(ts, watermark); the fill
        # WM_NONE reduces it to ts exactly (arrival-order parity).
        ev_clk = jnp.maximum(ev_ts, xi[:, WM_COL : WM_COL + 1])
        event: Dict[str, jnp.ndarray] = {"ts": ev_ts, "topic": topic}
        for ci, name in enumerate(int_fields):
            event[f"f:{name}"] = xi[:, XI_BASE + ci : XI_BASE + ci + 1]
        for cf, name in enumerate(f32_fields):
            event[f"f:{name}"] = xf[:, cf : cf + 1]

        active = st["active"] != 0
        src = st["src"]
        eps = st["eps"]
        lane_node = st["node"]
        lane_root = st["root"]
        lane_ts = st["ts"]
        lane_seq = st["seq"]
        runs = ctr[:, 0:1]

        # -- predicate plane list (stateless from xi, stateful in-kernel) ---
        env = PallasEnv(event, regs0, rset0, query.agg_slots, query.agg_defaults)
        pred_vals: List[jnp.ndarray] = []
        for p in range(P):
            if stateful[p]:
                v = query.predicates[p](env)
                pred_vals.append(
                    jnp.broadcast_to(jnp.asarray(v, bool), (8, R))
                )
            else:
                sp = xi[:, XI_BASE + len(int_fields) + p :
                        XI_BASE + len(int_fields) + p + 1]
                pred_vals.append(jnp.broadcast_to(sp != 0, (8, R)))

        def lut_pred(ids: jnp.ndarray, pid_table: np.ndarray) -> jnp.ndarray:
            eq = masks_for(ids)
            acc = jnp.zeros(ids.shape, bool)
            for i in range(N_ST):
                pid = int(pid_table[i])
                if pid >= 0:
                    acc = acc | (eq[i] & pred_vals[pid])
            return acc

        # -- window expiry (engine.py:330-352) -------------------------------
        root_begin = lut_b(src, n_is_begin)
        w_src = lut_i(src, n_window)
        if config.strict_windows:
            w_eps = lut_i(eps, n_window)
            w_eps = jnp.where(w_eps >= 0, w_eps, w_src)
            eff_window = jnp.where(eps >= 0, w_eps, w_src)
            expired = (
                active & (lane_ts >= 0) & (eff_window >= 0)
                & ((ev_clk - lane_ts) > eff_window)
            )
        else:
            eff_window = jnp.where(eps >= 0, -1, w_src)
            expired = (
                active & ~root_begin & (eff_window >= 0)
                & ((ev_clk - lane_ts) > eff_window)
            )
        active = active & ~expired

        root_fwd = (eps >= 0) | lut_b(src, n_is_fwd)
        start_ts = jnp.where(root_begin, jnp.broadcast_to(ev_ts, (8, R)), lane_ts)
        state_match = ((eps >= 0) & lut_b(eps, n_is_final)) | (
            (eps < 0) & lut_b(src, n_fwd_final)
        )

        # ==== downward pass: unrolled epsilon descent (engine.py:362-424) ===
        alive = active
        cs = src
        is_eps = eps >= 0
        ceps = eps
        ver = ver0
        vlen = st["vlen"]
        br = st["branching"] != 0
        ig = st["ignored"] != 0
        ps = jnp.full((8, R), -1, jnp.int32)

        levels: List[Dict[str, Any]] = []
        for _l in range(L):
            c_op = jnp.where(is_eps, OP_NONE, lut_i(cs, n_consume_op))
            c_m = (
                alive & ~is_eps & (c_op != OP_NONE)
                & lut_pred(cs, n_consume_pred)
            )
            take_m = c_m & (c_op == OP_TAKE)
            begin_m = c_m & (c_op == OP_BEGIN)
            ig_m = alive & ~is_eps & lut_pred(cs, n_ignore_pred)
            pk = jnp.where(is_eps, PR_PROCEED, lut_i(cs, n_proceed_kind))
            ptgt = jnp.where(is_eps, ceps, lut_i(cs, n_proceed_target))
            p_m = alive & (pk != PR_NONE) & (is_eps | lut_pred(cs, n_proceed_pred))
            p_strict = p_m & (pk == PR_PROCEED)
            branch_m = (p_strict & take_m) | (ig_m & (c_m | p_strict))

            ptgt_c = jnp.maximum(ptgt, 0)
            pure_tgt = lut_i(cs, n_pure_of_ptgt)
            if _l == 0:
                pure_tgt = jnp.where(is_eps, lut_i(ceps, n_pure_name), pure_tgt)
            fwd_next = (
                p_m & (pure_tgt != lut_i(cs, n_pure_name)) & ~br & ~ig
            )

            levels.append(
                dict(
                    alive=alive, cs=cs, is_eps=is_eps, ver=ver, vlen=vlen,
                    br=br, ig=ig, ps=ps, c_m=c_m, take_m=take_m,
                    begin_m=begin_m, ig_m=ig_m, p_m=p_m, pk=pk, ptgt=ptgt_c,
                    branch_m=branch_m,
                )
            )

            vlen = jnp.where(fwd_next, vlen + 1, vlen)
            br = br & ~fwd_next
            ig = ig & ~fwd_next
            ps = jnp.where(pk == PR_SKIP, ps, cs).astype(jnp.int32)
            alive = p_m
            cs = ptgt_c
            is_eps = jnp.zeros((8, R), bool)
            ceps = jnp.full((8, R), -1, jnp.int32)

        # ==== fold-register chain (deepest first, engine.py:426-444) =======
        def apply_folds(v, regs, rset):
            regs, rset = list(regs), list(rset)
            for stage_i, slot, fn in flat_folds:
                mask = v["c_m"] & (v["cs"] == stage_i)
                fenv = PallasEnv(
                    event, regs, rset, query.agg_slots, query.agg_defaults
                )
                val = jnp.broadcast_to(
                    jnp.asarray(fn(fenv), jnp.float32), (8, R)
                )
                regs[slot] = jnp.where(mask, val, regs[slot])
                rset[slot] = rset[slot] | mask
            return regs, rset

        cur_regs = regs0
        cur_set = [r != 0 for r in rset0]
        clone_regs: List[Any] = [None] * L
        for l in reversed(range(L)):
            clone_regs[l] = (cur_regs, cur_set)
            if flat_folds:
                cur_regs, cur_set = apply_folds(levels[l], cur_regs, cur_set)
        final_regs, final_set = cur_regs, cur_set

        # -- fold-divergence detector (engine.py: consuming lane sharing a
        # run id with ANY other live lane; see the rationale there) --------
        if flat_folds:
            consuming = jnp.zeros((8, R), bool)
            for l in range(L):
                consuming = consuming | levels[l]["c_m"]
            seq_i = lane_seq[:, :, None]
            pair = (
                (seq_i == lane_seq[:, None, :])
                & (consuming.astype(jnp.int32)[:, :, None] != 0)
                & (active.astype(jnp.int32)[:, None, :] != 0)
                & (
                    jax.lax.broadcasted_iota(jnp.int32, (1, R, R), 1)
                    != jax.lax.broadcasted_iota(jnp.int32, (1, R, R), 2)
                )
            )
            collide = jnp.any(
                jnp.any(pair, axis=2), axis=1, keepdims=True
            )  # (8, 1)
        else:
            collide = jnp.zeros((8, 1), bool)

        # ==== buffer puts: rank + one-hot emit (engine.py:454-482) ==========
        tri = make_tri()
        put_masks = [levels[l]["c_m"] for l in range(L)]
        put_cnt = jnp.zeros((8, R), jnp.int32)
        for m in put_masks:
            put_cnt = put_cnt + m.astype(jnp.int32)
        put_off = excl_lane_cumsum(put_cnt.astype(jnp.float32), tri).astype(jnp.int32)
        put_ranks: List[jnp.ndarray] = []
        partial = jnp.zeros((8, R), jnp.int32)
        for m in put_masks:
            put_ranks.append(put_off + partial)
            partial = partial + m.astype(jnp.int32)
        n_put = jnp.sum(put_cnt, axis=1, keepdims=True)  # (8, 1)

        # Window base for this step's node ids: the group-phase step offset
        # (an (8, 1) plane from xi; identical across keys) shifts this
        # advance's segment past earlier advances' in the accumulated
        # group window (EngineConfig.gc_group).
        base = B + (xi[:, PH_COL : PH_COL + 1] + t) * P_CAP
        put_idx = [
            jnp.where(
                put_masks[l] & (put_ranks[l] < P_CAP),
                base + put_ranks[l],
                -1,
            ).astype(jnp.int32)
            for l in range(L)
        ]
        # w_event is gidx for every real put slot -- rank order makes it a
        # prefix, no selection needed.
        put_j = jax.lax.broadcasted_iota(jnp.int32, (8, P_CAP), 1)
        put_jok = put_j < jnp.minimum(n_put, P_CAP)
        w_event = jnp.where(
            put_jok & valid, jnp.broadcast_to(gidx, (8, P_CAP)), -1
        ).astype(jnp.int32)
        psel = select_slots(
            put_masks,
            put_ranks,
            [
                (
                    lambda l=l: [
                        lut_i(levels[l]["cs"], n_name_id).astype(jnp.float32),
                        (lane_node + 1).astype(jnp.float32),  # bias -1 -> 0
                    ]
                )
                for l in range(L)
            ],
            P_CAP,
            2,
        )
        w_name = jnp.where(put_jok & valid, psel[:, 0, :].astype(jnp.int32), -1)
        w_pred = jnp.where(
            put_jok & valid, psel[:, 1, :].astype(jnp.int32) - 1, -1
        )
        step_node_drops = jnp.maximum(n_put - P_CAP, 0)

        # ==== upward pass (engine.py:484-507) ===============================
        desc_any = jnp.zeros((8, R), bool)
        up: List[Optional[Dict[str, Any]]] = [None] * L
        for l in reversed(range(L)):
            v = levels[l]
            ignore_emit = v["ig_m"] & ~v["branch_m"]
            clone_m = v["branch_m"] & v["c_m"]
            rootcopy_m = v["branch_m"] & ~v["c_m"] & ~desc_any
            readd_cond = root_begin & ~root_fwd & v["alive"]
            readd_fresh = readd_cond & v["c_m"]
            readd_root = readd_cond & ~v["c_m"]
            ns_before = v["c_m"] | ignore_emit | desc_any | clone_m | rootcopy_m
            add_mask = readd_fresh & ns_before
            idx1 = v["vlen"] - 1  # addRun offset 1
            readd_ver = [
                v["ver"][d] + (add_mask & (idx1 == d)).astype(jnp.int32)
                for d in range(D)
            ]
            up[l] = dict(
                ignore_emit=ignore_emit, clone_m=clone_m, rootcopy_m=rootcopy_m,
                readd_fresh=readd_fresh, readd_root=readd_root,
                readd_ver=readd_ver,
            )
            desc_any = ns_before | readd_fresh | readd_root

        # ==== output slot table in oracle DFS order (engine.py:509-620) =====
        zero = jnp.zeros((8, R), jnp.int32)
        false2 = jnp.zeros((8, R), bool)
        f32z = jnp.zeros((8, R), jnp.float32)

        slots: List[Dict[str, Any]] = []
        for l in range(L):
            v = levels[l]
            c_eps = jnp.where(
                v["take_m"], v["cs"], lut_i(v["cs"], n_consume_target)
            )
            ign = up[l]["ignore_emit"]
            c_m = v["c_m"]
            match_consume = (v["take_m"] & lut_b(v["cs"], n_is_final)) | (
                ~v["take_m"] & lut_b(v["cs"], n_isfin_of_ctgt)
            )
            slots.append(
                dict(
                    occ=c_m | ign,
                    src=jnp.where(c_m, v["cs"], src),
                    eps=jnp.where(c_m, c_eps, eps),
                    ver=v["ver"],
                    vlen=v["vlen"],
                    seq=lane_seq,
                    node=jnp.where(c_m, put_idx[l], lane_node),
                    ts=jnp.where(c_m, start_ts, lane_ts),
                    br=false2,
                    ig=~c_m,
                    newseq=false2,
                    regs=final_regs,
                    regs_set=final_set,
                    match=(c_m & match_consume) | (~c_m & state_match),
                )
            )

        for l in reversed(range(L)):
            v = levels[l]
            u = up[l]
            has_ps = v["ps"] >= 0
            cl_src = jnp.where(has_ps, v["ps"], v["cs"])
            ps_begin = ~has_ps | lut_b(v["ps"], n_is_begin)
            off = jnp.where(ps_begin & (v["vlen"] >= 2), 2, 1).astype(jnp.int32)
            idx = v["vlen"] - off
            m_clone = u["clone_m"]
            cl_ver = [
                v["ver"][d] + (m_clone & (idx == d)).astype(jnp.int32)
                for d in range(D)
            ]
            cl_node = jnp.where(v["ig_m"], lane_node, put_idx[l])
            m_copy = u["rootcopy_m"]
            cr, cr_set = clone_regs[l]
            slots.append(
                dict(
                    occ=m_clone | m_copy,
                    src=jnp.where(m_clone, cl_src, src),
                    eps=jnp.where(m_clone, v["cs"], eps),
                    ver=[
                        jnp.where(m_clone, cl_ver[d], ver0[d]) for d in range(D)
                    ],
                    vlen=jnp.where(m_clone, v["vlen"], st["vlen"]),
                    seq=jnp.where(m_clone, zero, lane_seq),
                    node=jnp.where(m_clone, cl_node, lane_node),
                    ts=jnp.where(m_clone, start_ts, lane_ts),
                    br=m_clone | (st["branching"] != 0),
                    ig=~m_clone & (st["ignored"] != 0),
                    newseq=m_clone,
                    regs=[
                        jnp.where(m_clone, cr[a], final_regs[a]) for a in range(A)
                    ],
                    regs_set=[
                        (m_clone & cr_set[a]) | (~m_clone & final_set[a])
                        for a in range(A)
                    ],
                    match=(m_clone & lut_b(v["cs"], n_is_final))
                    | (~m_clone & state_match),
                )
            )

            m_fresh = u["readd_fresh"]
            m_root = u["readd_root"]
            slots.append(
                dict(
                    occ=m_fresh | m_root,
                    src=src,
                    eps=eps,
                    ver=[
                        jnp.where(m_fresh, u["readd_ver"][d], ver0[d])
                        for d in range(D)
                    ],
                    vlen=jnp.where(m_fresh, v["vlen"], st["vlen"]),
                    seq=jnp.where(m_fresh, zero, lane_seq),
                    node=jnp.where(m_fresh, -1, lane_node),
                    ts=jnp.where(m_fresh, -1, lane_ts),
                    br=~m_fresh & (st["branching"] != 0),
                    ig=~m_fresh & (st["ignored"] != 0),
                    newseq=m_fresh,
                    regs=[
                        jnp.where(m_fresh, f32z, final_regs[a]) for a in range(A)
                    ],
                    regs_set=[~m_fresh & final_set[a] for a in range(A)],
                    match=state_match,
                )
            )

        # Chain root per slot: a lane with a chain passes its root to every
        # slot; a chainless lane's slot chain starts at the slot's own node
        # (engine.py o_root -- the root >= 0 iff node >= 0 invariant).
        has_root = lane_root >= 0
        for s in slots:
            s["root"] = jnp.where(has_root, lane_root, s["node"])

        # ==== fresh run ids in (lane, slot) DFS order (engine.py:636-643) ===
        ns_masks = [s["occ"] & s["newseq"] for s in slots]
        ns_cnt = jnp.zeros((8, R), jnp.int32)
        for m in ns_masks:
            ns_cnt = ns_cnt + m.astype(jnp.int32)
        ns_off = excl_lane_cumsum(ns_cnt.astype(jnp.float32), tri).astype(jnp.int32)
        partial = jnp.zeros((8, R), jnp.int32)
        n_new = jnp.sum(ns_cnt, axis=1, keepdims=True)
        for s, m in zip(slots, ns_masks):
            s["seq"] = jnp.where(m, runs + 1 + ns_off + partial, s["seq"])
            partial = partial + m.astype(jnp.int32)

        # ==== match extraction + lane compaction (engine.py:645-679) ========
        match_masks = [s["occ"] & s["match"] for s in slots]
        keep_masks = [s["occ"] & ~s["match"] for s in slots]

        def dfs_ranks(masks):
            cnt = jnp.zeros((8, R), jnp.int32)
            for m in masks:
                cnt = cnt + m.astype(jnp.int32)
            off = excl_lane_cumsum(cnt.astype(jnp.float32), tri).astype(jnp.int32)
            ranks = []
            part = jnp.zeros((8, R), jnp.int32)
            for m in masks:
                ranks.append(off + part)
                part = part + m.astype(jnp.int32)
            return ranks, jnp.sum(cnt, axis=1, keepdims=True)

        m_ranks, n_match = dfs_ranks(match_masks)
        k_ranks, n_keep = dfs_ranks(keep_masks)

        msel = select_slots(
            match_masks, m_ranks,
            [
                (
                    lambda s=s: [
                        (s["node"] + 1).astype(jnp.float32),
                        (s["root"] + 1).astype(jnp.float32),
                    ]
                )
                for s in slots
            ],
            M_STEP,
            2,
        )
        mj = jax.lax.broadcasted_iota(jnp.int32, (8, M_STEP), 1)
        mok = mj < jnp.minimum(n_match, M_STEP)
        w_match = jnp.where(
            mok & valid, msel[:, 0, :].astype(jnp.int32) - 1, -1
        )
        w_mroot = jnp.where(
            mok & valid, msel[:, 1, :].astype(jnp.int32) - 1, -1
        )
        step_match_drops = jnp.maximum(n_match - M_STEP, 0)
        lane_drop_count = jnp.maximum(n_keep - R, 0)

        # Field packing for the state compaction matmul. Integer payloads
        # ride one f32 lane each (exact below 2^24); seq (run ids), ts and
        # node get 16-bit splits for full i32 range.
        def slot_fields(s) -> List[jnp.ndarray]:
            seq_lo, seq_hi = _split16(s["seq"], 0)
            ts_lo, ts_hi = _split16(s["ts"], 1)
            nd_lo, nd_hi = _split16(s["node"], 1)
            rt_lo, rt_hi = _split16(s["root"], 1)
            out = [
                s["src"].astype(jnp.float32),
                (s["eps"] + 1).astype(jnp.float32),
                s["vlen"].astype(jnp.float32),
                s["br"].astype(jnp.float32),
                s["ig"].astype(jnp.float32),
                seq_lo, seq_hi, ts_lo, ts_hi, nd_lo, nd_hi, rt_lo, rt_hi,
            ]
            out.extend(s["ver"][d].astype(jnp.float32) for d in range(D))
            out.extend(s["regs"])
            out.extend(s["regs_set"][a].astype(jnp.float32) for a in range(A))
            return out

        F_FIX = 13
        ksel = select_slots(
            keep_masks, k_ranks,
            [(lambda s=s: slot_fields(s)) for s in slots],
            R,
            F_FIX + D + 2 * A,
        )
        jr = jax.lax.broadcasted_iota(jnp.int32, (8, R), 1)
        lane_ok = jr < jnp.minimum(n_keep, R)

        def pick_i(i: int, fill: int) -> jnp.ndarray:
            return jnp.where(lane_ok, ksel[:, i, :].astype(jnp.int32), fill)

        n_src = pick_i(0, 0)
        n_eps = jnp.where(lane_ok, ksel[:, 1, :].astype(jnp.int32) - 1, -1)
        n_vlen = pick_i(2, 0)
        n_br = pick_i(3, 0)
        n_ig = pick_i(4, 0)
        n_seq = jnp.where(lane_ok, _join16(ksel[:, 5, :], ksel[:, 6, :], 0), 0)
        n_ts = jnp.where(lane_ok, _join16(ksel[:, 7, :], ksel[:, 8, :], 1), -1)
        n_node = jnp.where(lane_ok, _join16(ksel[:, 9, :], ksel[:, 10, :], 1), -1)
        n_root = jnp.where(lane_ok, _join16(ksel[:, 11, :], ksel[:, 12, :], 1), -1)
        n_ver = [
            jnp.where(lane_ok, ksel[:, F_FIX + d, :].astype(jnp.int32), 0)
            for d in range(D)
        ]
        n_regs = [
            jnp.where(lane_ok, ksel[:, F_FIX + D + a, :], 0.0) for a in range(A)
        ]
        n_rset = [
            jnp.where(lane_ok, ksel[:, F_FIX + D + A + a, :].astype(jnp.int32), 0)
            for a in range(A)
        ]

        # ==== counters + masked write-back ==================================
        n_branch = jnp.zeros((8, R), jnp.int32)
        for u in up:
            n_branch = n_branch + u["clone_m"].astype(jnp.int32)
        deltas = [
            n_new,                                                  # runs
            jnp.ones((8, 1), jnp.int32),                            # n_events
            jnp.sum(n_branch, axis=1, keepdims=True),               # n_branches
            jnp.sum(expired.astype(jnp.int32), axis=1, keepdims=True),
            lane_drop_count,
            step_node_drops,
            step_match_drops,
            collide.astype(jnp.int32),
        ]
        vmask = valid  # (8, 1)
        new_ctr = ctr + jnp.where(
            vmask, jnp.concatenate(deltas, axis=1), 0
        )
        ctr_o[...] = new_ctr

        vm = jnp.broadcast_to(vmask, (8, R))
        new_lanes = {
            "active": ((vm & lane_ok) | (~vm & active)).astype(jnp.int32),
            "src": jnp.where(vm, n_src, src),
            "eps": jnp.where(vm, n_eps, eps),
            "vlen": jnp.where(vm, n_vlen, st["vlen"]),
            "seq": jnp.where(vm, n_seq, lane_seq),
            "node": jnp.where(vm, n_node, lane_node),
            "ts": jnp.where(vm, n_ts, lane_ts),
            "branching": jnp.where(vm, n_br, st["branching"]),
            "ignored": jnp.where(vm, n_ig, st["ignored"]),
            "root": jnp.where(vm, n_root, lane_root),
        }
        for i, name in enumerate(LANE_FIELDS):
            lanes_o[i] = new_lanes[name].astype(jnp.int32)
        for d in range(D):
            ver_o[d] = jnp.where(vm, n_ver[d], ver0[d])
        for a in range(A):
            regs_o[a] = jnp.where(vm, n_regs[a], regs0[a])
            rset_o[a] = jnp.where(vm, n_rset[a], rset0[a])

        wev_o[0] = w_event
        wnm_o[0] = w_name
        wpr_o[0] = w_pred
        wmt_o[0] = w_match
        wmr_o[0] = w_mroot

    G = max(int(config.gc_group), 1)

    def advance_impl(state, xs):
        T, K = xs["valid"].shape
        if K % 8 != 0:
            raise ValueError(f"pallas advance needs K % 8 == 0, got {K}")
        if B + G * T * P_CAP >= (1 << 24):
            raise ValueError(
                "node-id window exceeds f32-exact range; shrink the batch, "
                f"nodes_per_step or gc_group (B={B}, T={T}, cap={P_CAP}, "
                f"G={G})"
            )
        # -- pack xi [T, K, CI] / xf [T, K, max(CF,1)] -----------------------
        spred = xs["spred"]  # [T, K, P]
        # Group-phase step offset: replicated into every (t, k) slot (the
        # drivers keep all keys' phases in lockstep).
        phase = jnp.broadcast_to(
            state["gc_phase"].astype(jnp.int32)[None, :], (T, K)
        )
        # Per-step watermark column (ISSUE 10): absent when no event-time
        # gate is armed -- the WM_NONE fill keeps the kernel's expiry
        # clock bitwise-equal to the event timestamp.
        if "wm" in xs:
            wm = xs["wm"].astype(jnp.int32)
        else:
            from .engine import WM_NONE

            wm = jnp.full((T, K), WM_NONE, jnp.int32)
        xi_cols = [
            xs["ts"].astype(jnp.int32),
            xs["topic"].astype(jnp.int32),
            xs["gidx"].astype(jnp.int32),
            xs["valid"].astype(jnp.int32),
        ]
        xi_cols += [xs[f"f:{n}"].astype(jnp.int32) for n in int_fields]
        xi = jnp.concatenate(
            [c[:, :, None] for c in xi_cols]
            + [spred.astype(jnp.int32), phase[:, :, None], wm[:, :, None]],
            axis=2,
        )
        if CF:
            xf = jnp.stack([xs[f"f:{n}"] for n in f32_fields], axis=2)
        else:
            xf = jnp.zeros((T, K, 1), jnp.float32)

        # -- state -> kernel layouts ----------------------------------------
        lanes = jnp.stack(
            [jnp.transpose(state[n].astype(jnp.int32)) for n in LANE_FIELDS],
            axis=0,
        )  # [NF, K, R]
        ver = jnp.transpose(state["ver"], (1, 2, 0))        # [D, K, R]
        regs = jnp.transpose(state["regs"], (1, 2, 0))      # [A, K, R]
        rset = jnp.transpose(state["regs_set"], (1, 2, 0)).astype(jnp.int32)
        ctr = jnp.stack(
            [state[c].astype(jnp.int32) for c in COUNTER_FIELDS], axis=1
        )  # [K, NC]

        grid = (K // 8, T)
        outs = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 8, CI), lambda kb, t: (t, kb, 0)),
                pl.BlockSpec((1, 8, max(CF, 1)), lambda kb, t: (t, kb, 0)),
                pl.BlockSpec((NF, 8, R), lambda kb, t: (0, kb, 0)),
                pl.BlockSpec((D, 8, R), lambda kb, t: (0, kb, 0)),
                pl.BlockSpec((A, 8, R), lambda kb, t: (0, kb, 0)),
                pl.BlockSpec((A, 8, R), lambda kb, t: (0, kb, 0)),
                pl.BlockSpec((8, NC), lambda kb, t: (kb, 0)),
            ],
            out_specs=[
                pl.BlockSpec((NF, 8, R), lambda kb, t: (0, kb, 0)),
                pl.BlockSpec((D, 8, R), lambda kb, t: (0, kb, 0)),
                pl.BlockSpec((A, 8, R), lambda kb, t: (0, kb, 0)),
                pl.BlockSpec((A, 8, R), lambda kb, t: (0, kb, 0)),
                pl.BlockSpec((8, NC), lambda kb, t: (kb, 0)),
                pl.BlockSpec((1, 8, P_CAP), lambda kb, t: (t, kb, 0)),
                pl.BlockSpec((1, 8, P_CAP), lambda kb, t: (t, kb, 0)),
                pl.BlockSpec((1, 8, P_CAP), lambda kb, t: (t, kb, 0)),
                pl.BlockSpec((1, 8, M_STEP), lambda kb, t: (t, kb, 0)),
                pl.BlockSpec((1, 8, M_STEP), lambda kb, t: (t, kb, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((NF, K, R), jnp.int32),
                jax.ShapeDtypeStruct((D, K, R), jnp.int32),
                jax.ShapeDtypeStruct((A, K, R), jnp.float32),
                jax.ShapeDtypeStruct((A, K, R), jnp.int32),
                jax.ShapeDtypeStruct((K, NC), jnp.int32),
                jax.ShapeDtypeStruct((T, K, P_CAP), jnp.int32),
                jax.ShapeDtypeStruct((T, K, P_CAP), jnp.int32),
                jax.ShapeDtypeStruct((T, K, P_CAP), jnp.int32),
                jax.ShapeDtypeStruct((T, K, M_STEP), jnp.int32),
                jax.ShapeDtypeStruct((T, K, M_STEP), jnp.int32),
            ],
            # Pre-0.7 jax names this TPUCompilerParams; fall back so the
            # kernel builds on both (the CI image ships the old name).
            compiler_params=getattr(
                pltpu, "CompilerParams",
                getattr(pltpu, "TPUCompilerParams", None),
            )(
                # Large (lanes, slots, caps) configs need more than the
                # 16 MB default scoped-VMEM budget for the selection
                # transients; v5e has headroom above the default.
                vmem_limit_bytes=100 * 1024 * 1024,
            ),
            interpret=interpret,
        )(xi, xf, lanes, ver, regs, rset, ctr)
        lanes_o, ver_o, regs_o, rset_o, ctr_o, wev, wnm, wpr, wmt, wmr = outs

        new_state = dict(state)
        for i, name in enumerate(LANE_FIELDS):
            leaf = jnp.transpose(lanes_o[i])  # [R, K]
            if name in ("active", "branching", "ignored"):
                leaf = leaf.astype(bool)
            new_state[name] = leaf
        new_state["ver"] = jnp.transpose(ver_o, (2, 0, 1))
        new_state["regs"] = jnp.transpose(regs_o, (2, 0, 1))
        new_state["regs_set"] = jnp.transpose(rset_o, (2, 0, 1)).astype(bool)
        for i, c in enumerate(COUNTER_FIELDS):
            new_state[c] = ctr_o[:, i].astype(jnp.int32)
        ys = {
            "w_event": wev, "w_name": wnm, "w_pred": wpr, "w_match": wmt,
            "w_mroot": wmr,
        }
        return new_state, ys

    if mesh is None:
        return jax.jit(advance_impl)

    from jax.experimental.shard_map import shard_map

    @jax.jit
    def advance_sharded(state, xs):
        state_spec = jax.tree.map(
            lambda l: _key_axis_spec(l, l.ndim - 1), state
        )
        xs_spec = jax.tree.map(lambda l: _key_axis_spec(l, 1), xs)
        ys_spec = {
            k: _key_axis_spec(jnp.zeros((1, 1, 1)), 1)
            for k in ("w_event", "w_name", "w_pred", "w_match", "w_mroot")
        }
        return shard_map(
            advance_impl,
            mesh=mesh,
            in_specs=(state_spec, xs_spec),
            out_specs=(state_spec, ys_spec),
            check_rep=False,
        )(state, xs)

    return advance_sharded


def build_pallas_batched_append(
    config: EngineConfig,
    mesh: Optional[Any] = None,
):
    """Per-advance light post (dense scatter-append + group-phase bump) for
    pallas-layout ys ([T, K, cap]). The mark/sweep GC is deferred to the
    group flush (build_pallas_batched_flush); the append stays per-advance
    so capacity guards keep observing true pending counts.

    With `mesh`, runs under `shard_map` over the key axis like the advance
    (the append offset is per-key; no collectives)."""
    from .engine import build_pend_append

    append = build_pend_append(config)

    def append_impl(state, pool, ys):
        # w_match arrives [T, K, M_STEP]; the append wants the key axis
        # last ([T, M_STEP, K]) so its page reshape stays t-major.
        state, pool, page_roots = append(
            state,
            pool,
            jnp.transpose(ys["w_match"], (0, 2, 1)),
            jnp.transpose(ys["w_mroot"], (0, 2, 1)),
        )
        state = {
            **state,
            "gc_phase": (
                state["gc_phase"] + jnp.int32(ys["w_event"].shape[0])
            ).astype(jnp.int32),
        }
        return state, pool, page_roots

    if mesh is None:
        return jax.jit(append_impl)

    from jax.experimental.shard_map import shard_map

    @jax.jit
    def append_sharded(state, pool, ys):
        state_spec = jax.tree.map(
            lambda l: _key_axis_spec(l, l.ndim - 1), state
        )
        pool_spec = jax.tree.map(
            lambda l: _key_axis_spec(l, l.ndim - 1), pool
        )
        ys_spec = jax.tree.map(lambda l: _key_axis_spec(l, 1), ys)
        roots_spec = _key_axis_spec(jnp.zeros((1, 1)), 1)
        return shard_map(
            append_impl,
            mesh=mesh,
            in_specs=(state_spec, pool_spec, ys_spec),
            out_specs=(state_spec, pool_spec, roots_spec),
            check_rep=False,
        )(state, pool, ys)

    return append_sharded


def build_pallas_batched_flush(
    query: CompiledQuery,
    config: EngineConfig,
    mesh: Optional[Any] = None,
):
    """Group flush (pin-seeded mark/sweep + compaction) for pallas-layout
    ys node planes concatenated over the group's advances ([T_group, K,
    cap]; page_roots [TM_group, K]). Resets the group-phase scalar. The
    ring remap runs as a dynamic block loop over the occupied prefix
    (engine.remap_pend_blocks).

    With `mesh`, runs under `shard_map` over the key axis like the
    advance (the GC is per-key; no collectives)."""
    from .engine import build_gc, remap_pend_blocks

    gc = jax.vmap(
        build_gc(query, config, defer_pend_remap=True),
        in_axes=(-1, -1, 1, -1), out_axes=(-1, -1, -1),
    )

    def flush_impl(state, pool, ys, page_roots):
        state, pool, remap_full = gc(state, pool, ys, page_roots)
        pool = {
            **pool,
            "pend": remap_pend_blocks(
                pool["pend"], remap_full, pool["pend_pos"]
            ),
        }
        state = {**state, "gc_phase": jnp.zeros_like(state["gc_phase"])}
        return state, pool

    if mesh is None:
        return jax.jit(flush_impl)

    from jax.experimental.shard_map import shard_map

    @jax.jit
    def flush_sharded(state, pool, ys, page_roots):
        state_spec = jax.tree.map(
            lambda l: _key_axis_spec(l, l.ndim - 1), state
        )
        pool_spec = jax.tree.map(
            lambda l: _key_axis_spec(l, l.ndim - 1), pool
        )
        ys_spec = jax.tree.map(lambda l: _key_axis_spec(l, 1), ys)
        roots_spec = _key_axis_spec(page_roots, 1)
        return shard_map(
            flush_impl,
            mesh=mesh,
            in_specs=(state_spec, pool_spec, ys_spec, roots_spec),
            out_specs=(state_spec, pool_spec),
            check_rep=False,
        )(state, pool, ys, page_roots)

    return flush_sharded


def build_pallas_batched_post(
    query: CompiledQuery,
    config: EngineConfig,
    mesh: Optional[Any] = None,
):
    """Every-advance post pass (dense scatter-append + GC) for pallas-layout
    ys ([T, K, cap]): the G=1 composition kept for tests and one-shot
    callers; the batched driver runs build_pallas_batched_append/
    build_pallas_batched_flush at the group cadence
    (EngineConfig.gc_group).

    With `mesh`, runs under `shard_map` over the key axis like the advance
    (the append offset and GC are per-key; no collectives). The ring
    remap runs as a dynamic block loop over the occupied prefix
    (engine.remap_pend_blocks)."""
    from .engine import build_gc, build_pend_append, remap_pend_blocks

    append = build_pend_append(config)
    gc = jax.vmap(
        build_gc(query, config, defer_pend_remap=True),
        in_axes=(-1, -1, 1, -1), out_axes=(-1, -1, -1),
    )

    def post_impl(state, pool, ys):
        # w_match arrives [T, K, M_STEP]; the append wants the key axis
        # last ([T, M_STEP, K]) so its page reshape stays t-major.
        state, pool, page_roots = append(
            state,
            pool,
            jnp.transpose(ys["w_match"], (0, 2, 1)),
            jnp.transpose(ys["w_mroot"], (0, 2, 1)),
        )
        state, pool, remap_full = gc(state, pool, ys, page_roots)
        pool = {
            **pool,
            "pend": remap_pend_blocks(
                pool["pend"], remap_full, pool["pend_pos"]
            ),
        }
        return state, pool

    if mesh is None:
        return jax.jit(post_impl)

    from jax.experimental.shard_map import shard_map

    @jax.jit
    def post_sharded(state, pool, ys):
        state_spec = jax.tree.map(
            lambda l: _key_axis_spec(l, l.ndim - 1), state
        )
        pool_spec = jax.tree.map(
            lambda l: _key_axis_spec(l, l.ndim - 1), pool
        )
        ys_spec = jax.tree.map(lambda l: _key_axis_spec(l, 1), ys)
        return shard_map(
            post_impl,
            mesh=mesh,
            in_specs=(state_spec, pool_spec, ys_spec),
            out_specs=(state_spec, pool_spec),
            check_rep=False,
        )(state, pool, ys)

    return post_sharded
