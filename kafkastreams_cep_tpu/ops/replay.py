"""Exact-replay bridge: device engine state <-> host oracle state.

The device engine stores fold registers per run LANE with copy-on-emit;
the reference keys aggregate state per RUN and writes through sequentially
per queue item (reference: core/.../cep/state/internal/
AggregatesStoreImpl.java:55-75, nfa/NFA.java:319-321,362-369). When a
consuming lane shares its run id with another live lane, the per-lane
copies diverge from the shared cell -- the engine detects every such event
(`seq_collisions`, ops/engine.py) and this module makes the divergence
RECOVERABLE instead of merely counted:

  * `device_to_oracle` rebuilds a host `NFA` from a per-key device state
    snapshot. Sound exactly when no collision has fired since the snapshot:
    then every group of same-run-id lanes carries registers equal to the
    oracle's per-run cell (one-sided fold writes are what break this, and
    each one bumps the counter), so the per-lane -> per-run collapse loses
    nothing. The node pool maps 1:1 onto the host exact-lineage buffer
    (state/buffer.py mirrors ops/engine.py's pool by design).
  * `oracle_to_device` lowers the post-replay oracle back into the per-key
    lane/pool arrays, so the device continues from a reference-exact state
    and the next collision replays only its own interval.

The drivers (ops/runtime.py, parallel/batched.py) snapshot per-key state at
drain boundaries -- a snapshot is just a reference to the immutable device
arrays, pulled lazily only when a replay actually fires -- and on a per-key
counter increment replay that key's interval events through the oracle,
substituting its matches and resyncing the device.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..core.dewey import DeweyVersion
from ..core.event import Event
from ..nfa.nfa import NFA, ComputationStage
from ..pattern.stages import Stage
from ..state.aggregates import AggregatesStore
from ..state.buffer import BufferNode, SharedVersionedBuffer
from .engine import EngineConfig
from .tables import CompiledQuery


def supports_replay(query: CompiledQuery) -> bool:
    """Replay applies only when divergence is possible (the query folds)
    and the host stage graph was retained by compile_query."""
    return bool(query.agg_slots) and query.host_stages is not None


def _new_epsilon(query: CompiledQuery, config: EngineConfig, src: int, tgt: int) -> Stage:
    """The oracle's synthesized forwarding stage for a consumed run at
    (src, eps) -- mirrors NFA._new_epsilon including the strict-windows
    window inheritance."""
    cur = query.stage_list[src]
    target = query.stage_list[tgt]
    eps = Stage.new_epsilon(cur, target)
    if config.strict_windows:
        eps.window_ms = target.window_ms if target.window_ms != -1 else cur.window_ms
    return eps


def device_to_oracle(
    query: CompiledQuery,
    config: EngineConfig,
    state: Dict[str, np.ndarray],
    pool: Dict[str, np.ndarray],
    registry: Dict[int, Event],
    ts_base: int,
    key: Any,
) -> Tuple[NFA, Dict[Event, int]]:
    """Rebuild a host oracle from one key's device state (numpy slices).

    Returns (oracle, event->gidx map for the buffer's events). Raises
    KeyError if a chain event was pruned from the registry (the drivers
    pin snapshot-referenced events precisely to prevent that).
    """
    assert query.host_stages is not None, "compile_query retains host stages"
    buffer: SharedVersionedBuffer = SharedVersionedBuffer()
    n_nodes = int(pool["node_count"])
    node_event = pool["node_event"]
    node_name = pool["node_name"]
    node_pred = pool["node_pred"]
    ev_gidx: Dict[Event, int] = {}
    for i in range(n_nodes):
        g = int(node_event[i])
        ev = registry[g]
        parent = int(node_pred[i])
        buffer._nodes[i] = BufferNode(
            query.name_of_id[int(node_name[i])], ev, parent if parent >= 0 else None
        )
        ev_gidx[ev] = g
    buffer._next_id = n_nodes

    store = AggregatesStore()
    runs: List[ComputationStage] = []
    R = state["active"].shape[0]
    seen_seq: set = set()
    for i in range(R):
        if not bool(state["active"][i]):
            continue
        src = int(state["src"][i])
        eps = int(state["eps"][i])
        stage = (
            _new_epsilon(query, config, src, eps)
            if eps >= 0
            else query.stage_list[src]
        )
        vlen = int(state["vlen"][i])
        version = DeweyVersion(tuple(int(d) for d in state["ver"][i][:vlen]))
        seq = int(state["seq"][i])
        node = int(state["node"][i])
        ts = int(state["ts"][i])
        runs.append(
            ComputationStage(
                stage=stage,
                version=version,
                sequence=seq,
                last_event=(
                    buffer._nodes[node].event if node >= 0 else None
                ),
                timestamp=ts + ts_base if ts >= 0 else -1,
                is_branching=bool(state["branching"][i]),
                is_ignored=bool(state["ignored"][i]),
                last_node=node if node >= 0 else None,
            )
        )
        # Per-run aggregate cells from the lane registers: same-run lanes
        # hold equal copies while no collision has fired (the snapshot
        # contract), so the first lane of each run id is authoritative.
        if seq not in seen_seq:
            seen_seq.add(seq)
            for name, slot in query.agg_slots.items():
                if bool(state["regs_set"][i][slot]):
                    store.put(key, name, seq, float(state["regs"][i][slot]))

    return (
        NFA(
            store,
            buffer,
            query.host_stages.defined_states(),
            runs,
            runs=int(state["runs"]),
            strict_windows=config.strict_windows,
        ),
        ev_gidx,
    )


def oracle_to_device(
    query: CompiledQuery,
    config: EngineConfig,
    oracle: NFA,
    key: Any,
    ev_gidx: Dict[Event, int],
    ts_base: int,
    old_state: Dict[str, np.ndarray],
) -> Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray]]:
    """Lower a (post-replay) host oracle into per-key device state arrays.

    `ev_gidx` must cover every event in the oracle's buffer (the caller
    extends the conversion-time map with the replayed interval's events).
    `old_state` supplies the observability counters, carried through.
    Raises ValueError when the oracle outgrew the engine's lane/node
    capacities (the caller degrades to detection-only for the key).
    """
    index_of: Dict[Tuple[int, Any], int] = {
        (s.id, s.type): i for i, s in enumerate(query.stage_list)
    }
    ident_of: Dict[int, int] = {id(s): i for i, s in enumerate(query.stage_list)}

    R = config.lanes
    B = config.nodes
    D = config.dewey_width(query)
    A = query.n_aggs

    live = list(oracle.computation_stages)
    if len(live) > R:
        raise ValueError(f"oracle queue {len(live)} exceeds lanes {R}")

    # -- node pool: renumber the buffer densely, parents first -------------
    ids = sorted(oracle.buffer._nodes)
    if len(ids) > B:
        raise ValueError(f"oracle buffer {len(ids)} exceeds nodes {B}")
    remap = {old: new for new, old in enumerate(ids)}
    node_event = np.full(B, -1, np.int32)
    node_name = np.full(B, -1, np.int32)
    node_pred = np.full(B, -1, np.int32)
    name_id_of = {  # (name, StateType) -> buffer name id, as compile_query
        nm: i for i, nm in enumerate(query.name_of_id)
    }
    for old in ids:
        node = oracle.buffer._nodes[old]
        new = remap[old]
        g = ev_gidx.get(node.event)
        if g is None:
            raise ValueError("buffer event missing from gidx map")
        node_event[new] = g
        nid = name_id_of.get(node.stage_name)
        if nid is None:
            raise ValueError(f"unknown stage name {node.stage_name!r}")
        node_name[new] = nid
        node_pred[new] = remap[node.parent] if node.parent is not None else -1

    # Fresh empty ring: the replay interval's matches were just returned by
    # the oracle, and the drivers only resync at drain boundaries (ring
    # drained). Pins start empty -- nothing is pending.
    from .engine import _PEND_MIN_NONE

    pool = {
        "node_event": node_event,
        "node_name": node_name,
        "node_pred": node_pred,
        "node_count": np.asarray(len(ids), np.int32),
        "pend": np.full(config.matches, -1, np.int32),
        "pend_count": np.asarray(0, np.int32),
        "pend_pos": np.asarray(0, np.int32),
        "pinned": np.zeros(B, bool),
        "pend_min": np.asarray(_PEND_MIN_NONE, np.int32),
    }

    # -- lane table --------------------------------------------------------
    state = {
        "active": np.zeros(R, bool),
        "src": np.zeros(R, np.int32),
        "eps": np.full(R, -1, np.int32),
        "ver": np.zeros((R, D), np.int32),
        "vlen": np.zeros(R, np.int32),
        "seq": np.zeros(R, np.int32),
        "node": np.full(R, -1, np.int32),
        "ts": np.full(R, -1, np.int32),
        "branching": np.zeros(R, bool),
        "ignored": np.zeros(R, bool),
        "regs": np.zeros((R, A), np.float32),
        "regs_set": np.zeros((R, A), bool),
        "runs": np.asarray(int(oracle.runs), np.int32),
    }
    for i, comp in enumerate(live):
        stage = comp.stage
        if stage.is_epsilon() and id(stage) not in ident_of:
            tgt = stage.edges[0].target
            src_i = index_of.get((stage.id, stage.type))
            tgt_i = ident_of.get(id(tgt))
            if src_i is None or tgt_i is None:
                raise ValueError(f"cannot map epsilon stage {stage!r}")
            state["src"][i] = src_i
            state["eps"][i] = tgt_i
        else:
            src_i = ident_of.get(id(stage))
            if src_i is None:
                src_i = index_of.get((stage.id, stage.type))
            if src_i is None:
                raise ValueError(f"cannot map stage {stage!r}")
            state["src"][i] = src_i
            state["eps"][i] = -1
        digits = comp.version.digits
        if len(digits) > D:
            raise ValueError(f"dewey width {len(digits)} exceeds {D}")
        state["active"][i] = True
        state["ver"][i, : len(digits)] = digits
        state["vlen"][i] = len(digits)
        state["seq"][i] = comp.sequence
        state["node"][i] = (
            remap[comp.last_node] if comp.last_node is not None else -1
        )
        state["ts"][i] = (
            comp.timestamp - ts_base if comp.timestamp >= 0 else -1
        )
        state["branching"][i] = comp.is_branching
        state["ignored"][i] = comp.is_ignored
        for name, slot in query.agg_slots.items():
            val = oracle.aggregates_store.find(key, name, comp.sequence)
            if val is not None:
                state["regs"][i, slot] = np.float32(val)
                state["regs_set"][i, slot] = True

    # Per-lane chain roots: follow the freshly built predecessor pointers
    # (the dense renumbering is creation-ordered, preserving the interval-
    # pinning invariant that a chain's root is its smallest id).
    from ..state.serde import _chain_roots

    state["root"] = _chain_roots(state["node"], node_pred)

    # Observability counters carry through from the device state.
    for ctr in (
        "n_events", "n_branches", "n_expired",
        "lane_drops", "node_drops", "match_drops", "seq_collisions",
    ):
        state[ctr] = np.asarray(old_state[ctr], np.int32)
    # Resyncs happen at drain boundaries, after the group flush: the
    # group-phase scalar is 0 there (the renumbered pool has no window).
    state["gc_phase"] = np.asarray(0, np.int32)
    return state, pool
