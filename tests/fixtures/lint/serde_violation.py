"""Seeded serde-completeness violations (the PR 9 bug class).

Encode/decode pairs that drop fields; bound to serde_structs.py by
tests/test_lint.py via monkeypatched STRUCT_BINDINGS/DICT_BINDINGS.
NOT runnable production code.
"""
from typing import Any, Dict

from .serde_structs import Record


def encode_record(w, rec: Record) -> None:
    w.i64(rec.a)
    w.i64(rec.b)  # rec.c never written: CEP-D01


def decode_record(r) -> Record:
    return Record(a=r.i64(), b=r.i64(), c=0, skipme=0)  # c supplied; fine


def encode_gate_state(state: Dict[str, Any]) -> bytes:
    # reads x and y; 'z' from snapshot_state is dropped: CEP-D01
    return b"%d,%d" % (state["x"], state["y"])


def decode_gate_state(data: bytes) -> Dict[str, Any]:
    x, y = data.split(b",")
    out: Dict[str, Any] = {"x": int(x), "y": int(y)}
    out["q"] = 0  # never encoded: CEP-D03; 'y' never consumed: CEP-D03
    return out
