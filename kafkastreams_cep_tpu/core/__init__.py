from .dewey import DeweyVersion
from .event import Event
from .sequence import Sequence, SequenceBuilder, Staged

__all__ = ["DeweyVersion", "Event", "Sequence", "SequenceBuilder", "Staged"]
