"""Chaos CLI: seeded fault sweeps and the SLO-gated production soak.

Two modes share this entry point:

    # the chaos SWEEP (default; the original CLI): per-seed golden-vs-
    # chaos digest equality over a crash/rebuild pipeline
    python -m kafkastreams_cep_tpu.faults --seeds 32 [--runtime tpu]

    # the production SOAK (faults/soak.py): scenario fleet + chaos +
    # self-scraped metrics time series + SLO verdict artifact
    python -m kafkastreams_cep_tpu.faults soak --quick --out SOAK.json

    # WIRE TRANSPORT (ISSUE 15, streams/transport.py) -- terminal A
    # serves a RecordLog over a socket, terminal B runs a seeded chaos
    # pipeline against it (partial writes + disconnects injected client-
    # side) and pins digest equality vs a local fault-free golden run:
    python -m kafkastreams_cep_tpu.faults --listen 9092 --listen-dir /tmp/wal
    python -m kafkastreams_cep_tpu.faults --connect 127.0.0.1:9092

For each sweep seed it builds a fresh durable pipeline (letters query over
a file-backed RecordLog in a temp dir), computes the fault-free golden sink
stream, then replays the same stream under a seeded `FaultSchedule`,
rebuilding from disk after every simulated crash -- the same harness as
tests/test_faults.py, sized for soaking rather than CI. Any divergence
(lost or duplicated match) prints the seed and exits nonzero, so a failing
seed reproduces with `--seeds-from N --seeds 1`.
"""
from __future__ import annotations

import argparse
import os
import sys
import tempfile

# Keep the soak local: same backend pinning as tests/conftest.py (the axon
# PJRT plugin otherwise hangs the process when the TPU tunnel is down).
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    argv = list(argv)
    # Subcommand dispatch, backward compatible: bare flags keep running
    # the original sweep ("sweep" is accepted as its explicit name).
    if argv and argv[0] == "soak":
        from .soak import main as soak_main

        return soak_main(argv[1:])
    if argv and argv[0] == "sweep":
        argv = argv[1:]
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seeds", type=int, default=16, help="how many seeds")
    ap.add_argument("--seeds-from", type=int, default=0, help="first seed")
    ap.add_argument("--runtime", default="host", choices=["host", "tpu"])
    ap.add_argument("--events", type=int, default=48, help="stream length")
    ap.add_argument("--points", type=int, default=3, help="faults per seed")
    ap.add_argument(
        "--http-port", type=int, default=None, metavar="PORT",
        help="serve the live introspection plane (/metrics /snapshot "
        "/healthz /tracez) over the process-default registry while the "
        "soak runs; 0 binds an ephemeral port (printed)",
    )
    ap.add_argument(
        "--listen", default=None, metavar="[HOST:]PORT",
        help="serve a RecordLog over the wire (streams/transport.py) "
        "until Ctrl-C (or --listen-for), instead of sweeping",
    )
    ap.add_argument(
        "--listen-dir", default=None, metavar="DIR",
        help="file-backed segment dir for --listen (default: in-memory)",
    )
    ap.add_argument(
        "--listen-for", type=float, default=None, metavar="SECONDS",
        help="stop the --listen server after this many seconds",
    )
    ap.add_argument(
        "--connect", default=None, metavar="HOST:PORT",
        help="run one seeded chaos pipeline (seed = --seeds-from) over a "
        "remote --listen RecordLogServer: net.partial_write and "
        "net.disconnect join the schedule, and the sink digests must "
        "equal a local fault-free golden run (needs a FRESH server log)",
    )
    args = ap.parse_args(argv)

    if args.listen is not None:
        return _serve(args)

    import jax

    jax.config.update("jax_platforms", "cpu")

    sys.path.insert(
        0,
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), "tests",
        ),
    )
    from test_faults import (  # the CI harness, reused verbatim
        DRIVER_SITES,
        DEVICE_OPTS,
        _chaos,
        _golden,
        _stream,
    )

    from . import FaultSchedule

    if args.connect is not None:
        return _connect_run(args, FaultSchedule)

    sites = DRIVER_SITES + (
        ("engine.mid_drain",) if args.runtime == "tpu" else ()
    )
    opts = dict(DEVICE_OPTS) if args.runtime == "tpu" else {}
    keys = ("k0", "k1") if args.runtime == "tpu" else ("K",)
    failures = 0
    progress = {"seed": None, "done": 0, "failures": 0}
    server = None
    tracer = None
    if args.http_port is not None:
        # The soak's live plane (ISSUE 7): the chaos pipelines' drivers
        # report into the process-default registry, so /metrics shows the
        # driver layer moving mid-soak (polls/commits/restores/retries;
        # the harness arms its injector on a private registry, so
        # injected-fault totals stay out of this exposition); /healthz
        # reports soak progress + fault-arm state; /tracez carries the
        # soak's own per-seed spans (the harness-internal drivers keep
        # private tracers, so their restore/commit spans live in their
        # rings, not this server's).
        from ..obs import IntrospectionServer, SpanTracer, default_registry

        def _soak_health():
            return dict(progress, total_seeds=args.seeds,
                        runtime=args.runtime)

        tracer = SpanTracer(default_registry())
        server = IntrospectionServer(
            registry=default_registry(), tracer=tracer,
            health_fn=_soak_health, port=args.http_port,
        ).start()
        print(f"introspection plane: {server.url}")
    import contextlib

    for seed in range(args.seeds_from, args.seeds_from + args.seeds):
        stream = _stream(seed, n=args.events)
        golden = _golden(stream, keys=keys, runtime=args.runtime, **opts)
        schedule = FaultSchedule.seeded(seed, sites=sites,
                                        n_points=args.points)

        class _Tmp:
            def __truediv__(self, name):
                import pathlib

                return pathlib.Path(tempfile.mkdtemp()) / name

        span = (
            tracer.span(f"seed-{seed}")
            if tracer is not None else contextlib.nullcontext()
        )
        with span:
            chaos, crashes = _chaos(
                _Tmp(), schedule, stream, keys=keys,
                runtime=args.runtime, **opts
            )
        ok = sorted(chaos) == sorted(golden)
        print(
            f"seed {seed}: {len(golden)} matches, {crashes} crashes, "
            f"{'OK' if ok else 'DIVERGED'}"
        )
        if not ok:
            failures += 1
            print(f"  schedule: {schedule}")
        progress.update(seed=seed, done=progress["done"] + 1,
                        failures=failures)
    print(f"{args.seeds} seeds, {failures} divergent")
    if server is not None:
        server.stop()
    return 1 if failures else 0


def _parse_addr(spec: str, default_host: str = "127.0.0.1"):
    host, _, port_s = spec.rpartition(":")
    return (host or default_host, int(port_s))


def _serve(args) -> int:
    """--listen: broker a RecordLog over the wire for remote --connect
    runs (or any SocketRecordLog). No jax import -- this is a pure
    host-side broker process."""
    import time

    from ..streams.log import RecordLog
    from ..streams.transport import RecordLogServer

    host, port = _parse_addr(args.listen)
    server = RecordLogServer(
        RecordLog(args.listen_dir), host=host, port=port
    ).start()
    addr = server.address
    where = args.listen_dir or "in-memory"
    print(f"RecordLogServer on {addr[0]}:{addr[1]} (backing: {where}); "
          "Ctrl-C to stop")
    try:
        if args.listen_for is not None:
            time.sleep(args.listen_for)
        else:
            while True:
                time.sleep(1.0)
    except KeyboardInterrupt:
        pass
    server.stop()
    server.backing.close()
    return 0


def _connect_run(args, fault_schedule_cls) -> int:
    """--connect: the sweep harness once (seed = --seeds-from), with the
    durable log on the far side of a socket and the wire fault sites in
    the schedule. Digest equality vs the local fault-free golden run is
    the same exactly-once pin the CI suite enforces."""
    import pathlib
    import tempfile

    from test_faults import DEVICE_OPTS, _chaos, _golden, _stream

    from ..streams.transport import SocketRecordLog

    host, port = _parse_addr(args.connect)
    probe = SocketRecordLog((host, port))
    dirty = probe.end_offset("letters") or probe.end_offset("matches")
    probe.close()
    if dirty:
        print(f"--connect: the server log at {host}:{port} already has "
              "letters/matches records; exactly-once digests need a "
              "fresh --listen server", file=sys.stderr)
        return 2
    opts = dict(DEVICE_OPTS) if args.runtime == "tpu" else {}
    keys = ("k0", "k1") if args.runtime == "tpu" else ("K",)
    seed = args.seeds_from
    stream = _stream(seed, n=args.events)
    golden = _golden(stream, keys=keys, runtime=args.runtime, **opts)
    # log.torn_append lives in the REMOTE process (it is not armed
    # there), so the wire sweep schedules driver crashes + client-side
    # wire damage only.
    sites = ("driver.pre_commit", "driver.post_commit",
             "net.partial_write", "net.disconnect")
    schedule = fault_schedule_cls.seeded(
        seed, sites=sites, n_points=args.points
    )

    class _Tmp:
        def __truediv__(self, name):
            return pathlib.Path(tempfile.mkdtemp()) / name

    chaos, crashes = _chaos(
        _Tmp(), schedule, stream, keys=keys, runtime=args.runtime,
        log_open=lambda: SocketRecordLog(
            (host, port), backoff_seed=seed, io_timeout_s=2.0,
        ),
        **opts,
    )
    ok = sorted(chaos) == sorted(golden)
    print(f"connect {host}:{port} seed {seed}: {len(golden)} matches, "
          f"{crashes} crashes, {'OK' if ok else 'DIVERGED'}")
    if not ok:
        print(f"  schedule: {schedule}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
