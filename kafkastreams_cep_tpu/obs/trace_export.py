"""Timeline export: SpanTracer rings + match exemplars as Chrome-trace JSON.

The SpanTracer's recent-span ring (restore / poll-commit / device_trace
walls) and the engines' sampled match-provenance exemplars could only be
read as JSON lists until ISSUE 9 -- no timeline view. This module renders
both into the Chrome Trace Event format (the JSON Perfetto and
chrome://tracing load natively), so "what did this process just spend
time on" becomes a zoomable timeline instead of a scrollback of dicts:

- **Host spans** become complete (``"ph": "X"``) events on the wall-clock
  timebase: ``ts`` is the span's start in microseconds since the Unix
  epoch, ``dur`` its wall duration. One timeline row per span name (the
  ``tid`` is a stable small index per name) so poll/commit/restore
  cadence reads at a glance.
- **Match exemplars** become complete events on the EVENT-TIME timebase
  (the window's first..last event timestamp): a match's provenance
  carries no host wall stamp, so mixing it into the span rows would lie
  about simultaneity. They land under their own process row
  (``pid`` MATCH_PID, one row per query) with the full provenance dict
  in ``args`` -- clicking a match in Perfetto shows its lineage.

`chrome_trace` returns the JSON-object flavor (``{"traceEvents": [...]}``
plus metadata); the event array alone is also a valid trace. Serving
lives in obs/http.py (``/tracez?format=chrome``); bench.py can write the
same document to disk (``--trace-out``).

Everything here is a pure host-side read of already-recorded rings --
rendering a timeline can never sync the device or touch the data path.
"""
from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List, Mapping, Optional

from .trace import SpanTracer

__all__ = [
    "MATCH_PID",
    "SPAN_PID",
    "chrome_trace",
    "match_events",
    "span_events",
    "write_chrome_trace",
]

#: Chrome-trace process ids: host wall spans vs event-time match rows.
#: Two timebases must never share a row (see module docstring).
SPAN_PID = 1
MATCH_PID = 2


def span_events(
    spans: Iterable[Mapping[str, Any]],
    pid: int = SPAN_PID,
) -> List[Dict[str, Any]]:
    """Render SpanTracer ring entries (``recent()`` dicts: span /
    end_unix / duration_s) as Chrome complete events, one ``tid`` row per
    span name. Input order is free; output carries whatever was given
    (trace viewers sort by ``ts`` themselves)."""
    rows: Dict[str, int] = {}
    out: List[Dict[str, Any]] = []
    for s in spans:
        name = str(s.get("span", "span"))
        tid = rows.setdefault(name, len(rows) + 1)
        dur_s = float(s.get("duration_s", 0.0))
        end_unix = float(s.get("end_unix", 0.0))
        out.append(
            {
                "name": name,
                "cat": "host_span",
                "ph": "X",
                # Microseconds since the epoch: Perfetto renders absolute
                # wall clocks fine, and two exports from two processes
                # line up without a shared t0 handshake.
                "ts": (end_unix - dur_s) * 1e6,
                "dur": dur_s * 1e6,
                "pid": pid,
                "tid": tid,
                "args": {"end_unix": end_unix},
            }
        )
    return out


def match_events(
    matches: Iterable[Mapping[str, Any]],
    pid: int = MATCH_PID,
) -> List[Dict[str, Any]]:
    """Render match-provenance exemplars (provenance_exemplars() dicts)
    as Chrome complete events on the event-time axis: ts..ts+dur is the
    match window's first..last event timestamp (ms -> us), with the full
    provenance in ``args``. Zero-width windows (single-event matches)
    still render: viewers draw a minimal sliver for dur=0."""
    rows: Dict[str, int] = {}
    out: List[Dict[str, Any]] = []
    for m in matches:
        query = str(m.get("query", "q"))
        tid = rows.setdefault(query, len(rows) + 1)
        t0_ms = float(m.get("first_timestamp", -1))
        t1_ms = float(m.get("last_timestamp", t0_ms))
        out.append(
            {
                "name": query,
                "cat": "match_event_time",
                "ph": "X",
                "ts": t0_ms * 1e3,
                "dur": max(t1_ms - t0_ms, 0.0) * 1e3,
                "pid": pid,
                "tid": tid,
                "args": dict(m),
            }
        )
    return out


def _process_metadata(pid: int, name: str) -> Dict[str, Any]:
    return {
        "name": "process_name",
        "ph": "M",
        "pid": pid,
        "tid": 0,
        "args": {"name": name},
    }


def chrome_trace(
    tracer: Optional[SpanTracer] = None,
    spans: Optional[Iterable[Mapping[str, Any]]] = None,
    match_exemplars: Optional[Iterable[Mapping[str, Any]]] = None,
    limit: int = 1024,
) -> Dict[str, Any]:
    """The full Chrome-trace document: host spans (from `tracer.recent`
    or an explicit `spans` iterable) + optional match exemplars, with
    process-name metadata rows naming the two timebases."""
    if spans is None:
        spans = tracer.recent(limit) if tracer is not None else []
    events: List[Dict[str, Any]] = [
        _process_metadata(SPAN_PID, "host spans (wall clock)"),
    ]
    events.extend(span_events(spans))
    if match_exemplars is not None:
        events.append(_process_metadata(MATCH_PID, "matches (event time)"))
        events.extend(match_events(match_exemplars))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"exporter": "kafkastreams_cep_tpu.obs.trace_export"},
    }


def write_chrome_trace(path: str, doc: Mapping[str, Any]) -> None:
    """Write a chrome_trace() document to disk (load it in Perfetto via
    "Open trace file" or chrome://tracing)."""
    with open(path, "w") as f:
        json.dump(doc, f)
