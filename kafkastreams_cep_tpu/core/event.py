"""Stream event record.

TPU-native re-design of the reference's uniquely-identified stream record
(reference: core/.../cep/Event.java:1-123). Identity and ordering are
(topic, partition, offset); cross-partition ordering falls back to the
event timestamp (Event.java:88-99,113-117).

On the device path events are never represented as objects: they are packed
into structure-of-arrays columns (see ops/schema.py). This class is the host
ingress/egress view.
"""
from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Any, Generic, TypeVar

K = TypeVar("K")
V = TypeVar("V")


@functools.total_ordering
@dataclass(frozen=True)
class Event(Generic[K, V]):
    key: K
    value: V
    timestamp: int
    topic: str = ""
    partition: int = 0
    offset: int = 0

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, Event):
            return NotImplemented
        return (
            self.topic == other.topic
            and self.partition == other.partition
            and self.offset == other.offset
        )

    def __hash__(self) -> int:
        return hash((self.topic, self.partition, self.offset))

    def __lt__(self, other: "Event") -> bool:
        # Mirrors the reference ordering contract: same (topic, partition)
        # orders by offset, otherwise by timestamp.
        if self.topic != other.topic or self.partition != other.partition:
            return self.timestamp < other.timestamp
        return self.offset < other.offset

    def __repr__(self) -> str:
        return (
            f"Event(key={self.key!r}, value={self.value!r}, ts={self.timestamp}, "
            f"topic={self.topic!r}, partition={self.partition}, offset={self.offset})"
        )
