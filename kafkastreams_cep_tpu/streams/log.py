"""Append-only record log: the framework's Kafka-role transport.

The reference's only communication backend is the Kafka broker: source and
sink topics carry records, and one compacted changelog topic per state
store carries durability writes
(reference: README.md:350-355, ComplexStreamsBuilder.java:61-100,
AbstractStoreBuilder.java:36,52-71 -- SURVEY.md §2.8 row 2). This module is
the TPU-native framework's equivalent: an embedded, optionally file-backed
log of (topic, partition) streams with monotonically increasing offsets.
It is a transport shim, not a broker -- the contract the rest of the
framework needs is exactly append/read/end_offset per (topic, partition),
which is also the contract a real Kafka client would be adapted to (zero
egress in this environment, so no client library is shipped; `RecordLog`
is the seam where one would plug in).

Framing (file-backed segments, one file per topic-partition):
  [u8 flags][i64 timestamp][i32 klen][key][i32 vlen][value]
with klen/vlen = -1 encoding None (a None value is a tombstone, as in a
compacted changelog topic). Offsets are implicit record ordinals.
"""
from __future__ import annotations

import os
import struct
import threading
from typing import Dict, Iterator, List, NamedTuple, Optional, Tuple

_HEADER = struct.Struct("<bq")  # flags, timestamp
_LEN = struct.Struct("<i")


class LogRecord(NamedTuple):
    offset: int
    timestamp: int
    key: Optional[bytes]
    value: Optional[bytes]
    #: Opaque wire trace-context blob (obs.trace.TraceContext.encode()).
    #: Observability only: carried in memory and over the socket transport,
    #: NOT persisted in the file framing -- a reloaded segment yields
    #: trace=None and every consumer must already tolerate that (decode()
    #: returns None for absent blobs).
    trace: Optional[bytes] = None


def _topic_filename(topic: str, partition: int) -> str:
    # Topics may contain characters unfit for filenames; escape conservatively.
    safe = "".join(c if c.isalnum() or c in "._-" else f"%{ord(c):02x}" for c in topic)
    return f"{safe}-{partition}.log"


class RecordLog:
    """An embedded multi-topic append-only log.

    In-memory by default; pass `path` for durable file-backed segments that
    reload on reopen (the crash/restart story the reference delegates to the
    Kafka cluster)."""

    def __init__(self, path: Optional[str] = None) -> None:
        self.path = path
        self._lock = threading.Lock()
        self._records: Dict[Tuple[str, int], List[LogRecord]] = {}
        self._files: Dict[Tuple[str, int], object] = {}
        if path is not None:
            os.makedirs(path, exist_ok=True)
            self._load()

    # ------------------------------------------------------------------ io
    def _load(self) -> None:
        assert self.path is not None
        for fname in sorted(os.listdir(self.path)):
            if not fname.endswith(".log"):
                continue
            stem = fname[: -len(".log")]
            topic_esc, _, part_s = stem.rpartition("-")
            try:
                partition = int(part_s)
            except ValueError:
                continue
            topic = _unescape(topic_esc)
            records: List[LogRecord] = []
            fpath = os.path.join(self.path, fname)
            with open(fpath, "rb") as f:
                data = f.read()
            pos = 0
            while pos + _HEADER.size <= len(data):
                # A crash mid-append leaves a torn trailing record; stop at
                # the first incomplete frame and truncate it away so the
                # next append starts on a clean boundary.
                try:
                    _flags, ts = _HEADER.unpack_from(data, pos)
                    key, after_key = _read_blob(data, pos + _HEADER.size)
                    value, end = _read_blob(data, after_key)
                except _TornRecord:
                    break
                records.append(LogRecord(len(records), ts, key, value))
                pos = end
            if pos < len(data):
                with open(fpath, "r+b") as f:
                    f.truncate(pos)
            self._records[(topic, partition)] = records

    def _file_for(self, tp: Tuple[str, int]):
        if self.path is None:
            return None
        f = self._files.get(tp)
        if f is None:
            f = open(
                os.path.join(self.path, _topic_filename(tp[0], tp[1])), "ab"
            )
            self._files[tp] = f
        return f

    # ----------------------------------------------------------------- API
    def append(
        self,
        topic: str,
        key: Optional[bytes],
        value: Optional[bytes],
        timestamp: int = 0,
        partition: int = 0,
        trace: Optional[bytes] = None,
    ) -> int:
        """Append one record; returns its offset."""
        from ..faults import injection as _flt

        tp = (topic, partition)
        with self._lock:
            f = self._file_for(tp)
            if _flt.ACTIVE is not None and f is not None:
                # `log.torn_append` crash site: the injector lands half the
                # frame durably and dies BEFORE the in-memory append, so
                # the reload path (torn-tail truncation above) owns
                # recovery -- the caller never saw this offset.
                frame = bytearray(_HEADER.pack(0, timestamp))
                for blob in (key, value):
                    if blob is None:
                        frame += _LEN.pack(-1)
                    else:
                        frame += _LEN.pack(len(blob)) + blob
                _flt.ACTIVE.fire(
                    "log.torn_append", file=f, payload=bytes(frame)
                )
            records = self._records.setdefault(tp, [])
            offset = len(records)
            records.append(LogRecord(offset, timestamp, key, value, trace))
            if f is not None:
                f.write(_HEADER.pack(0, timestamp))
                _write_blob(f, key)
                _write_blob(f, value)
        return offset

    def read(
        self, topic: str, partition: int = 0, start: int = 0, max_records: Optional[int] = None
    ) -> List[LogRecord]:
        with self._lock:
            records = self._records.get((topic, partition), [])
            end = len(records) if max_records is None else min(len(records), start + max_records)
            return records[start:end]

    def end_offset(self, topic: str, partition: int = 0) -> int:
        with self._lock:
            return len(self._records.get((topic, partition), []))

    def topics(self) -> List[str]:
        with self._lock:
            return sorted({t for (t, _p) in self._records})

    def partitions(self, topic: str) -> List[int]:
        with self._lock:
            return sorted(p for (t, p) in self._records if t == topic)

    def flush(self) -> None:
        """Make every buffered append durable.

        Deliberately NOT wrapped in the transient-retry helper: on Linux a
        failed fsync marks the dirty pages clean, so a retry "succeeds"
        while the bytes never reached disk (fsyncgate) -- and commit()
        would then durably record offsets covering lost changelog/sink
        records. A flush failure here is fail-stop by design; the caller
        crashes before the offset append and replay recovers."""
        with self._lock:
            for f in self._files.values():
                f.flush()
                os.fsync(f.fileno())

    def close(self) -> None:
        with self._lock:
            for f in self._files.values():
                f.close()
            self._files.clear()


def _write_blob(f, data: Optional[bytes]) -> None:
    if data is None:
        f.write(_LEN.pack(-1))
    else:
        f.write(_LEN.pack(len(data)))
        f.write(data)


class _TornRecord(Exception):
    """A frame extends past the end of the segment file (torn write)."""


def _read_blob(data: bytes, pos: int) -> Tuple[Optional[bytes], int]:
    if pos + _LEN.size > len(data):
        raise _TornRecord
    (n,) = _LEN.unpack_from(data, pos)
    pos += _LEN.size
    if n < 0:
        return None, pos
    if pos + n > len(data):
        raise _TornRecord
    return data[pos : pos + n], pos + n


def _unescape(escaped: str) -> str:
    out = []
    i = 0
    while i < len(escaped):
        c = escaped[i]
        if c == "%" and i + 2 < len(escaped):
            out.append(chr(int(escaped[i + 1 : i + 3], 16)))
            i += 3
        else:
            out.append(c)
            i += 1
    return "".join(out)
