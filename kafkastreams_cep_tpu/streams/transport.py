"""Wire transport: the RecordLog contract over length-framed sockets.

`streams/log.py` documents `RecordLog` as "the seam where one would plug
in" a real Kafka client; until this module, every byte the engine ever
moved went through that file-backed shim in-process. This is the seam
filled in: a stdlib-`socket` server (`RecordLogServer`) fronting any
`RecordLog`, and a client (`SocketRecordLog`) that implements the exact
same contract -- `append`/`read`/`end_offset` per (topic, partition) with
None-tombstone framing preserved -- so `LogDriver`, `EmissionGate`, and
the changelog stores run over real connections unchanged.

Wire framing (one frame per request and per response)::

    +----------------+----------------+----------------------------------+
    | u32 len        | u32 crc32c     | payload (len bytes)              |
    +----------------+----------------+----------------------------------+
    payload := [u8 op][u64 seq][op-specific body]

Every frame is CRC-sealed (the same crc32c as the checkpoint codec,
state/serde.py). A torn frame -- mid-frame EOF, oversized length, or CRC
mismatch -- is never partially applied: the receiver discards it, counts
`cep_transport_torn_frames_total{role}`, and drops the connection, so
resync always happens on a clean frame boundary (the wire analog of
`RecordLog._load`'s truncate-at-torn-tail recovery).

Robustness model:

- **Reconnect/backoff.** Connection loss is transient: the client closes
  the socket, then retries with seeded-jitter exponential backoff under a
  retry budget (`cep_transport_retries_total{site}`; the raw connect also
  runs under `faults.with_retry`). Budget exhaustion raises
  `TransportError` -- fail-stop, like `RecordLog.flush`.
- **Exactly-once appends.** The client holds every unacknowledged request
  in a FIFO and replays it verbatim after reconnect. Appends carry a
  (16-byte session id, monotone u64 seq) identity; the server keeps a
  bounded per-session seq->offset map and suppresses replayed appends
  (`cep_transport_dedup_total`) -- the Kafka idempotent-producer model.
  Reads/end_offset/flush are idempotent and simply re-execute. Combined
  with the `EmissionGate` digests + committed sink watermark (PR 6),
  sink emission stays exactly-once across mid-emit disconnects.
- **Propagated backpressure.** `window` > 1 pipelines appends but bounds
  them: when the in-flight window is full, `append()` BLOCKS draining
  acks (`cep_transport_backpressure_total`), never buffering unboundedly.
  Server-side, requests are applied inline on the peer's reader thread,
  so a stalled apply stops socket reads and the kernel's TCP buffers
  backpressure the producer -- `on_overflow=block` end to end. Windowed
  offsets are client-predicted and ack-verified; exact prediction assumes
  the idempotent-producer deployment (one producer per partition).
- **Heartbeat/stall detection.** With `heartbeat_s` set, an idle client
  pings; a peer that stops answering within `io_timeout_s` is a stall
  (`cep_transport_stalls_total`) and triggers the reconnect path. Client
  `health()` (freshness, window occupancy, reconnect counts) is surfaced
  through `LogDriver.health()` into `/healthz`.
- **Broker death.** An `InjectedCrash` inside the backing log (the
  `log.torn_append` site) kills the "broker": the server drops every
  connection and reopens its file-backed log -- the reload truncates the
  torn tail -- while producer sessions survive (the idempotent-producer
  state a real broker keeps replicated in the log), so client replays
  still dedup. Clients just see a disconnect and recover.

Fault sites (faults/injection.py): `net.partial_write` lands half a frame
on the socket then severs, `net.disconnect` severs between frames,
`net.stall` freezes the server's apply loop past the client's IO deadline.

All threads are named daemons (`kct-transport-accept`,
`kct-transport-peer-N`, `kct-transport-heartbeat`) and all shared maps are
lock-guarded, per the ceplint `threads` checker.
"""
from __future__ import annotations

import itertools
import socket
import struct
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from ..faults import injection as _flt
from ..faults.injection import InjectedCrash, TransientFault, with_retry
from ..state.serde import crc32c
from .log import LogRecord, RecordLog

__all__ = [
    "MAX_FRAME",
    "RecordLogServer",
    "SocketRecordLog",
    "TransportError",
    "WIRE_VERSION",
]

WIRE_VERSION = 1
#: Frame header: payload length, crc32c(payload).
_FRAME = struct.Struct("<II")
#: Hard cap on one frame's payload: a torn/garbage length field must fail
#: fast as a torn frame, not allocate gigabytes.
MAX_FRAME = 64 * 1024 * 1024

_U64 = struct.Struct("<Q")
_I64 = struct.Struct("<q")
_I32 = struct.Struct("<i")
_U32 = struct.Struct("<I")
_U16 = struct.Struct("<H")

# Request ops.
OP_HELLO = b"h"
OP_APPEND = b"a"
OP_READ = b"r"
OP_END = b"e"
OP_TOPICS = b"t"
OP_PARTS = b"p"
OP_FLUSH = b"f"
OP_PING = b"g"
# Response ops.
OP_OK = b"k"
OP_ERR = b"!"

_SESSION_LEN = 16


class TransportError(RuntimeError):
    """Fail-stop transport failure: retry budget exhausted, protocol
    violation, or a server-side application error."""


class _Lost(Exception):
    """Internal: the connection is damaged; reconnect + replay owns it."""

    def __init__(self, cause: str) -> None:
        super().__init__(cause)
        self.cause = cause


class _WireEOF(Exception):
    """Internal: the peer closed the stream. `partial` marks a mid-read
    EOF (torn frame) vs a clean close on a frame boundary."""

    def __init__(self, partial: bool) -> None:
        super().__init__("eof")
        self.partial = partial


# ------------------------------------------------------------------ framing
def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise _WireEOF(partial=bool(buf))
        buf += chunk
    return bytes(buf)


def _seal(payload: bytes) -> bytes:
    return _FRAME.pack(len(payload), crc32c(payload)) + payload


def _pack_str(s: str) -> bytes:
    b = s.encode("utf-8")
    return _U16.pack(len(b)) + b


def _pack_blob(b: Optional[bytes]) -> bytes:
    if b is None:
        return _I32.pack(-1)
    return _I32.pack(len(b)) + b


class _Reader:
    """Cursor over a payload; short reads raise (the CRC already vouched
    for integrity, so a short body is a protocol bug, not line noise)."""

    def __init__(self, data: bytes, pos: int = 0) -> None:
        self.data = data
        self.pos = pos

    def take(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise ValueError("truncated payload")
        out = self.data[self.pos : self.pos + n]
        self.pos += n
        return out

    def op(self) -> bytes:
        return self.take(1)

    def u16(self) -> int:
        return _U16.unpack(self.take(_U16.size))[0]

    def u32(self) -> int:
        return _U32.unpack(self.take(_U32.size))[0]

    def u64(self) -> int:
        return _U64.unpack(self.take(_U64.size))[0]

    def i32(self) -> int:
        return _I32.unpack(self.take(_I32.size))[0]

    def i64(self) -> int:
        return _I64.unpack(self.take(_I64.size))[0]

    def str(self) -> str:
        return self.take(self.u16()).decode("utf-8")

    def blob(self) -> Optional[bytes]:
        n = self.i32()
        if n < 0:
            return None
        return self.take(n)


# ---------------------------------------------------------- response parses
def _parse_i64(rd: _Reader) -> int:
    return rd.i64()


def _parse_records(rd: _Reader) -> List[LogRecord]:
    n = rd.u32()
    records = [
        LogRecord(rd.i64(), rd.i64(), rd.blob(), rd.blob()) for _ in range(n)
    ]
    # ISSUE 20 trace propagation: servers that carry trace context append a
    # trailing per-record blob section AFTER the classic record section, so
    # an older client parses the same frame unchanged (it never looks past
    # record n-1) and an older server's frame leaves traces at None here.
    if n and rd.pos < len(rd.data):
        records = [
            r._replace(trace=rd.blob()) for r in records
        ]
    return records


def _parse_strs(rd: _Reader) -> List[str]:
    return [rd.str() for _ in range(rd.u32())]


def _parse_i32s(rd: _Reader) -> List[int]:
    return [rd.i32() for _ in range(rd.u32())]


# ------------------------------------------------------------------- server
class RecordLogServer:
    """Serve a `RecordLog` over a loopback/LAN socket.

    One named daemon accept thread plus one reader thread per peer;
    requests are applied inline on the peer thread (that inline apply IS
    the backpressure: a slow backing log stops socket reads and TCP
    flow-controls the producer). Producer sessions and the peer map are
    lock-guarded shared state."""

    def __init__(
        self,
        backing: Optional[RecordLog] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        registry: Optional[Any] = None,
        io_timeout_s: float = 30.0,
        stall_inject_s: float = 0.75,
        dedup_cache: int = 4096,
        tracer: Optional[Any] = None,
    ) -> None:
        from ..obs.registry import default_registry

        self.backing = backing if backing is not None else RecordLog()
        self.host = host
        self.port = port
        #: Optional obs.trace.SpanTracer: when set, every trace-bearing
        #: append also lands a "broker.append" child span in THIS broker's
        #: ring, so the fleet export stitches the hop into the record's
        #: end-to-end trace.
        self.tracer = tracer
        self.io_timeout_s = io_timeout_s
        #: How long an injected `net.stall` freezes the apply loop. Pick
        #: it ABOVE the clients' `io_timeout_s` to force stall-detection
        #: reconnects; below it, stalls are absorbed as latency.
        self.stall_inject_s = stall_inject_s
        self.dedup_cache = dedup_cache
        self.metrics = registry if registry is not None else default_registry()
        self._lock = threading.Lock()
        self._sessions: Dict[bytes, "OrderedDict[int, int]"] = {}
        #: Per-session high-water mark of seqs EVICTED from the bounded
        #: dedup map: a replayed append at or below it can no longer be
        #: verified against its original offset, so it must fail the
        #: session rather than silently re-append (exactly-once would
        #: break on the quiet duplicate).
        self._evicted: Dict[bytes, int] = {}
        #: Sessions failed after an evicted-range replay: every further
        #: append on them errors until the producer starts a new session.
        self._fenced: set = set()
        self._peers: Dict[int, socket.socket] = {}
        self._peer_ids = itertools.count(1)
        self._threads: List[threading.Thread] = []
        self._listener: Optional[socket.socket] = None
        self._addr: Tuple[str, int] = (host, port)
        self._stopping = False
        self._n_restarts = 0
        self._n_torn = 0
        m = self.metrics
        self._m_frames = m.counter(
            "cep_transport_frames_total",
            "Wire frames by endpoint role and direction",
            labels=("role", "dir"),
        )
        self._m_bytes = m.counter(
            "cep_transport_bytes_total",
            "Wire bytes (frame headers included) by role and direction",
            labels=("role", "dir"),
        )
        self._m_conns = m.gauge(
            "cep_transport_connections",
            "Open transport connections (server: live peers; client: 0/1)",
            labels=("role",),
        )
        self._m_torn = m.counter(
            "cep_transport_torn_frames_total",
            "Torn wire frames discarded (CRC/length/mid-frame EOF)",
            labels=("role",),
        )
        self._m_dedup = m.counter(
            "cep_transport_dedup_total",
            "Replayed appends suppressed by (session, seq) identity",
        )
        self._m_sessions = m.gauge(
            "cep_transport_sessions",
            "Producer sessions tracked for idempotent-append dedup",
        )
        self._m_restarts = m.counter(
            "cep_transport_server_restarts_total",
            "Simulated broker crash-restarts (injected backing-log deaths)",
        )

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "RecordLogServer":
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.bind((self.host, self.port))
        sock.listen(64)
        # Short accept timeout so stop() is noticed promptly.
        sock.settimeout(0.2)
        with self._lock:
            self._listener = sock
            self._addr = sock.getsockname()
        t = threading.Thread(
            target=self._accept_loop, name="kct-transport-accept", daemon=True
        )
        with self._lock:
            self._threads.append(t)
        t.start()
        return self

    @property
    def address(self) -> Tuple[str, int]:
        with self._lock:
            return self._addr

    def stop(self) -> None:
        with self._lock:
            self._stopping = True
            listener, self._listener = self._listener, None
            peers = list(self._peers.values())
            self._peers.clear()
            threads = list(self._threads)
            self._m_conns.labels(role="server").set(0.0)
        if listener is not None:
            try:
                listener.close()
            except OSError:
                pass
        for conn in peers:
            try:
                conn.close()
            except OSError:
                pass
        for t in threads:
            t.join(timeout=2.0)
        self.backing.flush()

    def health(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "mode": "socket-server",
                "address": f"{self._addr[0]}:{self._addr[1]}",
                "peers": len(self._peers),
                "sessions": len(self._sessions),
                "restarts": self._n_restarts,
                "torn_frames": self._n_torn,
            }

    # ---------------------------------------------------------- peer loops
    def _accept_loop(self) -> None:
        while True:
            with self._lock:
                listener = self._listener
            if listener is None:
                return
            try:
                conn, _addr = listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            pid = next(self._peer_ids)
            conn.settimeout(self.io_timeout_s)
            try:
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            t = threading.Thread(
                target=self._serve_peer,
                args=(conn, pid),
                name=f"kct-transport-peer-{pid}",
                daemon=True,
            )
            with self._lock:
                self._peers[pid] = conn
                self._threads.append(t)
                self._m_conns.labels(role="server").set(float(len(self._peers)))
            t.start()

    def _torn_frame(self) -> None:
        self._m_torn.labels(role="server").inc()
        with self._lock:
            self._n_torn += 1

    def _serve_peer(self, conn: socket.socket, pid: int) -> None:
        peer: Dict[str, Any] = {"session": None}
        frames_in = self._m_frames.labels(role="server", dir="in")
        frames_out = self._m_frames.labels(role="server", dir="out")
        bytes_in = self._m_bytes.labels(role="server", dir="in")
        bytes_out = self._m_bytes.labels(role="server", dir="out")
        try:
            while not self._stopping:
                try:
                    hdr = _recv_exact(conn, _FRAME.size)
                except _WireEOF as eof:
                    if eof.partial:
                        self._torn_frame()
                    return
                except socket.timeout:
                    continue
                except OSError:
                    return
                length, crc = _FRAME.unpack(hdr)
                if length > MAX_FRAME:
                    self._torn_frame()
                    return
                try:
                    payload = _recv_exact(conn, length)
                except (socket.timeout, OSError, _WireEOF):
                    # Mid-frame loss: never apply a torn frame; resync is
                    # the peer's reconnect, on a clean boundary.
                    self._torn_frame()
                    return
                if crc32c(payload) != crc:
                    self._torn_frame()
                    return
                frames_in.inc()
                bytes_in.inc(len(payload) + _FRAME.size)
                if _flt.ACTIVE is not None:
                    try:
                        _flt.ACTIVE.fire("net.stall")
                    except TransientFault:
                        # Injected consumer stall: stop reading/answering.
                        # Kernel socket buffers fill, producers block (or
                        # hit their IO deadline and reconnect).
                        time.sleep(self.stall_inject_s)
                try:
                    resp = self._apply(payload, peer)
                except InjectedCrash:
                    # The backing log "process" died (log.torn_append):
                    # simulate the broker restart and drop this peer.
                    self._restart_backing()
                    return
                except Exception as exc:
                    seq = 0
                    if len(payload) >= 1 + _U64.size:
                        seq = _U64.unpack_from(payload, 1)[0]
                    resp = (
                        OP_ERR
                        + _U64.pack(seq)
                        + _pack_str(f"{type(exc).__name__}: {exc}")
                    )
                out = _seal(resp)
                try:
                    conn.sendall(out)
                except OSError:
                    return
                frames_out.inc()
                bytes_out.inc(len(out))
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._lock:
                self._peers.pop(pid, None)
                self._m_conns.labels(role="server").set(float(len(self._peers)))

    # -------------------------------------------------------------- apply
    def _apply(self, payload: bytes, peer: Dict[str, Any]) -> bytes:
        rd = _Reader(payload)
        op = rd.op()
        seq = rd.u64()

        def ok(body: bytes = b"") -> bytes:
            return OP_OK + _U64.pack(seq) + body

        if op == OP_HELLO:
            sid = rd.take(_SESSION_LEN)
            ver = rd.u32()
            if ver != WIRE_VERSION:
                raise ValueError(f"wire version {ver} != {WIRE_VERSION}")
            with self._lock:
                sess = self._sessions.setdefault(sid, OrderedDict())
                peer["session"] = sid
                self._m_sessions.set(float(len(self._sessions)))
                last = next(reversed(sess)) if sess else 0
            return ok(_U64.pack(last))
        if op == OP_APPEND:
            t0 = time.perf_counter()
            topic = rd.str()
            part = rd.i32()
            ts = rd.i64()
            key = rd.blob()
            value = rd.blob()
            # Optional trailing trace-context blob (ISSUE 20): absent from
            # older clients' frames, so only read it when bytes remain.
            trace = rd.blob() if rd.pos < len(rd.data) else None
            sid = peer["session"]
            with self._lock:
                sess = self._sessions.get(sid) if sid is not None else None
                if sid is not None and sid in self._fenced:
                    raise ValueError(
                        "session fenced after an evicted-range replay; "
                        "start a new session"
                    )
                if sess is not None and seq in sess:
                    # Replayed append (the ack was lost in a disconnect):
                    # same (session, seq) -> same offset, applied once.
                    self._m_dedup.inc()
                    return ok(_I64.pack(sess[seq]))
                if sess is not None and seq <= self._evicted.get(sid, 0):
                    # Replay from BELOW the dedup window: its original
                    # offset was evicted, so applied-once can no longer be
                    # proven. Re-appending here would be a silent duplicate
                    # -- fail the session explicitly instead (the client
                    # surfaces a TransportError, never a quiet re-append).
                    self._fenced.add(sid)
                    raise ValueError(
                        f"replayed append seq {seq} predates the dedup "
                        f"window (evicted through seq "
                        f"{self._evicted.get(sid, 0)}): exactly-once "
                        "cannot be verified; session fenced"
                    )
                off = self.backing.append(
                    topic, key, value, timestamp=ts, partition=part,
                    trace=trace,
                )
                if sess is not None:
                    sess[seq] = off
                    while len(sess) > self.dedup_cache:
                        gone, _off = sess.popitem(last=False)
                        if gone > self._evicted.get(sid, 0):
                            self._evicted[sid] = gone
            if self.tracer is not None and trace is not None:
                # Stitch the broker hop into the record's trace: a child
                # span of the producer's append span. The STORED blob stays
                # the producer's context byte-for-byte -- re-encoding per
                # hop would make the same record read back differently from
                # different brokers.
                from ..obs.trace import TraceContext

                ctx = TraceContext.decode(trace)
                if ctx is not None:
                    self.tracer.record(
                        "broker.append",
                        time.perf_counter() - t0,
                        trace=ctx,
                    )
            return ok(_I64.pack(off))
        if op == OP_READ:
            topic = rd.str()
            part = rd.i32()
            start = rd.i64()
            maxr = rd.i64()
            records = self.backing.read(
                topic,
                partition=part,
                start=start,
                max_records=None if maxr < 0 else maxr,
            )
            body = bytearray(_U32.pack(len(records)))
            for r in records:
                body += _I64.pack(r.offset)
                body += _I64.pack(r.timestamp)
                body += _pack_blob(r.key)
                body += _pack_blob(r.value)
            # Trailing trace section (ISSUE 20): one blob per record, after
            # the classic section so pre-trace clients parse unchanged.
            # Only emitted when at least one record carries context --
            # trace-free traffic pays zero bytes.
            if any(getattr(r, "trace", None) is not None for r in records):
                for r in records:
                    body += _pack_blob(r.trace)
            return ok(bytes(body))
        if op == OP_END:
            topic = rd.str()
            part = rd.i32()
            return ok(_I64.pack(self.backing.end_offset(topic, partition=part)))
        if op == OP_TOPICS:
            names = self.backing.topics()
            return ok(
                _U32.pack(len(names)) + b"".join(_pack_str(n) for n in names)
            )
        if op == OP_PARTS:
            parts = self.backing.partitions(rd.str())
            return ok(
                _U32.pack(len(parts)) + b"".join(_I32.pack(p) for p in parts)
            )
        if op == OP_FLUSH:
            self.backing.flush()
            return ok()
        if op == OP_PING:
            return ok()
        raise ValueError(f"unknown wire op {op!r}")

    def _restart_backing(self) -> None:
        """Simulated broker death: drop every connection and reopen the
        file-backed log (the reload truncates the torn tail, exactly as
        `RecordLog._load` promises). Sessions survive -- the idempotent-
        producer state a real broker keeps replicated in the log -- so
        post-restart replays still dedup."""
        with self._lock:
            self._m_restarts.inc()
            self._n_restarts += 1
            for conn in self._peers.values():
                try:
                    conn.close()
                except OSError:
                    pass
            self._peers.clear()
            self._m_conns.labels(role="server").set(0.0)
            if self.backing.path is not None:
                self.backing.close()
                self.backing = RecordLog(self.backing.path)


# ------------------------------------------------------------------- client
class SocketRecordLog:
    """`RecordLog` contract over a socket, with reconnect/backoff, bounded
    in-flight appends, idempotent replay, and heartbeat stall detection.

    Thread-safe: every public method serializes on one RLock (the
    heartbeat daemon uses the same lock), matching `RecordLog`'s locking
    discipline. `window=1` (default) keeps appends synchronous -- exact
    server offsets returned. `window>1` pipelines appends and returns
    client-predicted offsets (exact under one-producer-per-partition,
    ack-verified and resynced otherwise); a full window BLOCKS, which is
    `on_overflow=block` propagated to the wire."""

    def __init__(
        self,
        address: Tuple[str, int],
        registry: Optional[Any] = None,
        window: int = 1,
        io_timeout_s: float = 5.0,
        retry_budget: int = 8,
        backoff_base_s: float = 0.01,
        backoff_cap_s: float = 0.5,
        backoff_seed: int = 0,
        heartbeat_s: Optional[float] = None,
        connect: bool = True,
        session: Optional[bytes] = None,
        start_seq: int = 0,
    ) -> None:
        import os as _os
        import random as _random

        from ..obs.registry import default_registry

        self.address = (str(address[0]), int(address[1]))
        self.path = None  # RecordLog-contract parity: not file-backed here
        self.window = max(1, int(window))
        self.io_timeout_s = io_timeout_s
        self.retry_budget = max(0, int(retry_budget))
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self.heartbeat_s = heartbeat_s
        self.metrics = registry if registry is not None else default_registry()
        self._rng = _random.Random(backoff_seed)
        # A migrated shard's successor adopts the source's producer
        # session AND its seq cursor (streams/rebalance.py handoff): the
        # server's (session, seq) dedup then spans the move. Resuming a
        # session with a REWOUND seq would collide with the server's
        # table, so the two travel together in the shard checkpoint.
        if session is not None and len(session) != _SESSION_LEN:
            raise ValueError(
                f"session id must be {_SESSION_LEN} bytes, got {len(session)}"
            )
        self._session = (
            bytes(session) if session is not None else _os.urandom(_SESSION_LEN)
        )
        self._lock = threading.RLock()
        self._sock: Optional[socket.socket] = None
        self._seq = max(0, int(start_seq))
        self._inflight: Deque[Dict[str, Any]] = deque()
        self._next_off: Dict[Tuple[str, int], int] = {}
        self._closed = False
        self._connects = 0
        self._server_last_seq = 0
        self._last_ok = 0.0
        self._n_reconnects = 0
        self._n_disconnects = 0
        self._n_stalls = 0
        self._n_retries = 0
        self._n_backpressure = 0
        m = self.metrics
        self._m_frames = m.counter(
            "cep_transport_frames_total",
            "Wire frames by endpoint role and direction",
            labels=("role", "dir"),
        )
        self._m_bytes = m.counter(
            "cep_transport_bytes_total",
            "Wire bytes (frame headers included) by role and direction",
            labels=("role", "dir"),
        )
        self._m_conns = m.gauge(
            "cep_transport_connections",
            "Open transport connections (server: live peers; client: 0/1)",
            labels=("role",),
        )
        self._m_torn = m.counter(
            "cep_transport_torn_frames_total",
            "Torn wire frames discarded (CRC/length/mid-frame EOF)",
            labels=("role",),
        )
        self._m_retries = m.counter(
            "cep_transport_retries_total",
            "Reconnect/backoff attempts by call site",
            labels=("site",),
        )
        self._m_reconnects = m.counter(
            "cep_transport_reconnects_total",
            "Successful reconnections after a connection loss",
        )
        self._m_disconnects = m.counter(
            "cep_transport_disconnects_total",
            "Connection losses observed by the client, by cause",
            labels=("cause",),
        )
        self._m_stalls = m.counter(
            "cep_transport_stalls_total",
            "Idle/stall timeouts (no response within the IO deadline)",
        )
        self._m_backpressure = m.counter(
            "cep_transport_backpressure_total",
            "Windowed appends that blocked on the bounded in-flight window",
        )
        self._m_inflight = m.gauge(
            "cep_transport_inflight_appends",
            "Client unacknowledged appends currently in the window",
        )
        self._m_last_ok = m.gauge(
            "cep_transport_last_ok_age_seconds",
            "Seconds since the client last heard the server",
        )
        self._hb_thread: Optional[threading.Thread] = None
        if connect:
            with self._lock:
                self._reconnect(site="connect")
        if heartbeat_s is not None:
            t = threading.Thread(
                target=self._heartbeat_loop,
                name="kct-transport-heartbeat",
                daemon=True,
            )
            self._hb_thread = t
            t.start()

    # -------------------------------------------------------- connection
    # Every helper below re-enters self._lock (an RLock; all callers --
    # public methods and the heartbeat daemon -- already hold it), so the
    # shared-state writes are syntactically lock-guarded, not just
    # guarded-by-convention.
    def _close_socket(self) -> None:
        with self._lock:
            sock, self._sock = self._sock, None
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
            self._m_conns.labels(role="client").set(0.0)

    def _connect_and_hello(self) -> None:
        # Immediate double-tap on the raw connect via the shared transient
        # retry helper (counts cep_retries_total{site="net.connect"});
        # the seeded exponential backoff lives one level up in _reconnect.
        sock = with_retry(
            lambda: socket.create_connection(
                self.address, timeout=self.io_timeout_s
            ),
            site="net.connect",
            attempts=2,
            backoff_s=0.0,
            registry=self.metrics,
        )
        sock.settimeout(self.io_timeout_s)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        # The handshake bypasses the fault hooks: the net.* sites target
        # steady-state traffic, and a fault here would only re-enter the
        # same reconnect loop that is already running.
        hello = OP_HELLO + _U64.pack(0) + self._session + _U32.pack(WIRE_VERSION)
        sock.sendall(_seal(hello))
        hdr = _recv_exact(sock, _FRAME.size)
        length, crc = _FRAME.unpack(hdr)
        if length > MAX_FRAME:
            raise _Lost("torn")
        payload = _recv_exact(sock, length)
        if crc32c(payload) != crc:
            raise _Lost("torn")
        rd = _Reader(payload)
        if rd.op() != OP_OK or rd.u64() != 0:
            raise TransportError("bad HELLO response (not a RecordLogServer?)")
        with self._lock:
            self._sock = sock
            self._server_last_seq = rd.u64()
            self._last_ok = time.monotonic()
            self._connects += 1
            self._m_conns.labels(role="client").set(1.0)
            if self._connects > 1:
                self._m_reconnects.inc()
                self._n_reconnects += 1

    def _reconnect(self, site: str) -> None:
        """(Re)connect with seeded-jitter exponential backoff under the
        retry budget, then replay every in-flight frame in FIFO order.
        Attempt 0 is immediate; budget exhaustion is fail-stop."""
        for attempt in range(self.retry_budget + 1):
            if attempt > 0:
                with self._lock:
                    self._m_retries.labels(site=site).inc()
                    self._n_retries += 1
                span = min(
                    self.backoff_cap_s,
                    self.backoff_base_s * (2 ** (attempt - 1)),
                )
                time.sleep(span * (0.5 + 0.5 * self._rng.random()))
            try:
                self._connect_and_hello()
                for entry in self._inflight:
                    self._send_frame(entry["frame"])
                return
            except (_Lost, _WireEOF, OSError, socket.timeout):
                self._close_socket()
        raise TransportError(
            f"transport to {self.address[0]}:{self.address[1]} unrecoverable "
            f"after {self.retry_budget} backoff retries (site={site})"
        )

    def _recover(self, lost: _Lost, site: str) -> None:
        self._close_socket()
        with self._lock:
            self._m_disconnects.labels(cause=lost.cause).inc()
            self._n_disconnects += 1
            if lost.cause == "stall":
                self._m_stalls.inc()
                self._n_stalls += 1
        self._reconnect(site)

    # ------------------------------------------------------------ wire IO
    def _send_frame(self, frame: bytes) -> None:
        sock = self._sock
        if sock is None:
            raise _Lost("closed")
        try:
            if _flt.ACTIVE is not None:
                _flt.ACTIVE.fire("net.partial_write", sock=sock, payload=frame)
                _flt.ACTIVE.fire("net.disconnect")
            sock.sendall(frame)
        except TransientFault as fault:
            cause = (
                "partial_write"
                if fault.site == "net.partial_write"
                else "injected"
            )
            raise _Lost(cause) from fault
        except socket.timeout:
            raise _Lost("stall") from None
        except OSError as exc:
            raise _Lost("send") from exc
        self._m_frames.labels(role="client", dir="out").inc()
        self._m_bytes.labels(role="client", dir="out").inc(len(frame))

    def _recv_frame(self) -> bytes:
        sock = self._sock
        if sock is None:
            raise _Lost("closed")
        if _flt.ACTIVE is not None:
            try:
                _flt.ACTIVE.fire("net.disconnect")
            except TransientFault as fault:
                raise _Lost("injected") from fault
        try:
            hdr = _recv_exact(sock, _FRAME.size)
            length, crc = _FRAME.unpack(hdr)
            if length > MAX_FRAME:
                self._m_torn.labels(role="client").inc()
                raise _Lost("torn")
            payload = _recv_exact(sock, length)
        except socket.timeout:
            raise _Lost("stall") from None
        except _WireEOF as eof:
            if eof.partial:
                self._m_torn.labels(role="client").inc()
                raise _Lost("torn") from eof
            raise _Lost("eof") from eof
        except OSError as exc:
            raise _Lost("recv") from exc
        if crc32c(payload) != crc:
            self._m_torn.labels(role="client").inc()
            raise _Lost("torn")
        with self._lock:
            self._m_frames.labels(role="client", dir="in").inc()
            self._m_bytes.labels(role="client", dir="in").inc(
                len(payload) + _FRAME.size
            )
            self._last_ok = time.monotonic()
        return payload

    # ------------------------------------------------------- request FIFO
    def _appends_inflight(self) -> int:
        return sum(1 for e in self._inflight if e["kind"] == "append")

    def _submit(
        self,
        op: bytes,
        body: bytes,
        parse: Optional[Callable[[_Reader], Any]],
        kind: str,
        tp: Optional[Tuple[str, int]] = None,
        predicted: Optional[int] = None,
    ) -> Dict[str, Any]:
        with self._lock:
            self._seq += 1
            payload = op + _U64.pack(self._seq) + body
            entry: Dict[str, Any] = {
                "seq": self._seq,
                "frame": _seal(payload),
                "parse": parse,
                "kind": kind,
                "tp": tp,
                "predicted": predicted,
                "done": False,
                "result": None,
                "site": "append" if kind == "append" else kind,
            }
            if self._sock is None:
                self._reconnect(site=entry["site"])
            self._inflight.append(entry)
        try:
            self._send_frame(entry["frame"])
        except _Lost as lost:
            # The entry is already in the FIFO: reconnect replays it.
            self._recover(lost, site=entry["site"])
        self._m_inflight.set(float(self._appends_inflight()))
        return entry

    def _pump_one(self) -> None:
        """Receive and apply exactly one response (FIFO order)."""
        payload = self._recv_frame()
        rd = _Reader(payload)
        op = rd.op()
        seq = rd.u64()
        if not self._inflight:
            raise _Lost("torn")  # unsolicited frame: desync; resync clean
        entry = self._inflight[0]
        if seq != entry["seq"]:
            raise TransportError(
                f"response seq {seq} != expected {entry['seq']}: "
                "request/response FIFO violated"
            )
        self._inflight.popleft()
        self._m_inflight.set(float(self._appends_inflight()))
        if op == OP_ERR:
            raise TransportError(
                f"server error for {entry['kind']}: {rd.str()}"
            )
        if op != OP_OK:
            raise TransportError(f"unknown response op {op!r}")
        entry["result"] = rd if entry["parse"] is None else entry["parse"](rd)
        entry["done"] = True
        if entry["kind"] == "append":
            self._on_append_ack(entry)

    def _on_append_ack(self, entry: Dict[str, Any]) -> None:
        with self._lock:
            tp = entry["tp"]
            off = entry["result"]
            predicted = entry["predicted"]
            if predicted is not None and off != predicted:
                # Another producer interleaved on this partition: resync
                # the predictor past our still-unacked appends to it.
                waiting = sum(1 for e in self._inflight if e["tp"] == tp)
                self._next_off[tp] = off + 1 + waiting
            else:
                self._next_off[tp] = max(self._next_off.get(tp, 0), off + 1)

    def _await(self, entry: Dict[str, Any]) -> Any:
        while not entry["done"]:
            try:
                self._pump_one()
            except _Lost as lost:
                self._recover(lost, site=entry["site"])
        return entry["result"]

    def _request(
        self,
        op: bytes,
        body: bytes,
        parse: Optional[Callable[[_Reader], Any]],
        kind: str,
    ) -> Any:
        entry = self._submit(op, body, parse, kind=kind)
        return self._await(entry)

    def _check_open(self) -> None:
        if self._closed:
            raise TransportError("transport is closed")

    # ------------------------------------------------- RecordLog contract
    def append(
        self,
        topic: str,
        key: Optional[bytes],
        value: Optional[bytes],
        timestamp: int = 0,
        partition: int = 0,
        trace: Optional[bytes] = None,
    ) -> int:
        with self._lock:
            self._check_open()
            tp = (topic, partition)
            predicted: Optional[int] = None
            if self.window > 1:
                if tp not in self._next_off:
                    self._next_off[tp] = self._request(
                        OP_END,
                        _pack_str(topic) + _I32.pack(partition),
                        _parse_i64,
                        kind="end_offset",
                    )
                predicted = self._next_off[tp]
                self._next_off[tp] = predicted + 1
            body = (
                _pack_str(topic)
                + _I32.pack(partition)
                + _I64.pack(timestamp)
                + _pack_blob(key)
                + _pack_blob(value)
            )
            if trace is not None:
                # Trailing optional blob: a pre-trace server never reads
                # past `value`, so the frame stays WIRE_VERSION 1 and the
                # context rides replays untouched (the _inflight entry
                # keeps the sealed body, so reconnect replay re-sends it).
                body += _pack_blob(trace)
            entry = self._submit(
                OP_APPEND, body, _parse_i64, kind="append",
                tp=tp, predicted=predicted,
            )
            if self.window <= 1:
                return self._await(entry)
            if self._appends_inflight() >= self.window:
                # Bounded in-flight window: BLOCK draining acks -- this is
                # on_overflow=block propagated to the wire, never an
                # unbounded client-side buffer.
                self._m_backpressure.inc()
                self._n_backpressure += 1
                while self._appends_inflight() >= self.window:
                    try:
                        self._pump_one()
                    except _Lost as lost:
                        self._recover(lost, site="append")
            return predicted

    def read(
        self,
        topic: str,
        partition: int = 0,
        start: int = 0,
        max_records: Optional[int] = None,
    ) -> List[LogRecord]:
        with self._lock:
            self._check_open()
            body = (
                _pack_str(topic)
                + _I32.pack(partition)
                + _I64.pack(start)
                + _I64.pack(-1 if max_records is None else max_records)
            )
            return self._request(OP_READ, body, _parse_records, "read")

    def end_offset(self, topic: str, partition: int = 0) -> int:
        with self._lock:
            self._check_open()
            body = _pack_str(topic) + _I32.pack(partition)
            return self._request(OP_END, body, _parse_i64, "end_offset")

    def topics(self) -> List[str]:
        with self._lock:
            self._check_open()
            return self._request(OP_TOPICS, b"", _parse_strs, "topics")

    def partitions(self, topic: str) -> List[int]:
        with self._lock:
            self._check_open()
            return self._request(
                OP_PARTS, _pack_str(topic), _parse_i32s, "partitions"
            )

    def flush(self) -> None:
        """Drain the in-flight window, then fsync the server's backing
        log. The FIFO guarantees every prior append was applied before
        the server sees the FLUSH, so commit-before-offsets ordering
        (streams/driver.py) holds over the wire too."""
        with self._lock:
            self._check_open()
            self._request(OP_FLUSH, b"", None, "flush")

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            try:
                while self._inflight and self._sock is not None:
                    self._pump_one()  # best-effort drain; no reconnects
            except (_Lost, TransportError):
                pass
            self._closed = True
            self._close_socket()
        t = self._hb_thread
        if t is not None:
            t.join(timeout=2.0)

    def session_state(self) -> Tuple[bytes, int]:
        """(session id, last issued seq): the idempotent-producer identity
        a shard checkpoint carries so a migrated shard's successor client
        resumes the SAME dedup horizon on the broker (pass both back as
        `session=`/`start_seq=`)."""
        with self._lock:
            return self._session, self._seq

    # ----------------------------------------------------------- health
    def health(self) -> Dict[str, Any]:
        with self._lock:
            idle = (
                round(time.monotonic() - self._last_ok, 3)
                if self._last_ok
                else None
            )
            return {
                "mode": "socket",
                "server": f"{self.address[0]}:{self.address[1]}",
                "connected": self._sock is not None,
                "session": self._session.hex(),
                "last_ok_age_s": idle,
                "pending_appends": self._appends_inflight(),
                "window": self.window,
                "reconnects": self._n_reconnects,
                "disconnects": self._n_disconnects,
                "stalls": self._n_stalls,
                "backoff_retries": self._n_retries,
                "backpressure_hits": self._n_backpressure,
            }

    def _heartbeat_loop(self) -> None:
        period = max(0.01, (self.heartbeat_s or 1.0) / 4.0)
        while True:
            time.sleep(period)
            if self._closed:
                return
            if not self._lock.acquire(timeout=period):
                continue  # a long windowed drain owns the wire; skip
            try:
                if self._closed:
                    return
                idle = time.monotonic() - self._last_ok
                self._m_last_ok.set(idle)
                if self._sock is None or idle < self.heartbeat_s:
                    continue
                try:
                    self._request(OP_PING, b"", None, "heartbeat")
                except TransportError:
                    # Budget exhausted: leave the socket down; the next
                    # API call retries with a fresh budget.
                    pass
            finally:
                self._lock.release()
