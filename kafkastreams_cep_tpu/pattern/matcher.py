"""Predicates evaluated on NFA edges.

Host-side counterpart of the reference predicate hierarchy
(reference: core/.../cep/pattern/Matcher.java:30-131, SimpleMatcher.java:32-48,
StatefulMatcher.java:29-46, SequenceMatcher.java:16-26). Predicates come in
two families:

  * ``ExprPredicate`` wraps a declarative ``Expr`` -- runs on both the host
    interpreter and the TPU kernel (the recommended form);
  * callable predicates (``simple``/``stateful``/``sequence``) accept
    arbitrary Python functions -- host-only, mirroring the reference's
    closure-based matchers for full parity.

Combinators (not/and/or) mirror Matcher.not/and/or (Matcher.java:40-50).
"""
from __future__ import annotations

from typing import Any, Callable, Optional, TYPE_CHECKING

from .expressions import Expr, TrueExpr

if TYPE_CHECKING:
    from ..nfa.context import MatcherContext


class Predicate:
    """Base predicate: boolean test against a MatcherContext."""

    #: True when this predicate (and all children) can compile to the device.
    device_compilable: bool = False

    def accept(self, ctx: "MatcherContext") -> bool:
        raise NotImplementedError

    def expr(self) -> Optional[Expr]:
        """The underlying expression tree, if device-compilable."""
        return None


class ExprPredicate(Predicate):
    """A predicate defined by a declarative expression tree."""

    device_compilable = True

    def __init__(self, expression: Expr) -> None:
        self.expression = expression

    def accept(self, ctx: "MatcherContext") -> bool:
        return bool(self.expression.evaluate(ctx.env()))

    def expr(self) -> Optional[Expr]:
        return self.expression

    def __repr__(self) -> str:
        return f"ExprPredicate({self.expression!r})"


class SimplePredicate(Predicate):
    """Stateless closure over the current event (SimpleMatcher.java:32-48)."""

    def __init__(self, fn: Callable[[Any], bool]) -> None:
        self.fn = fn

    def accept(self, ctx: "MatcherContext") -> bool:
        return bool(self.fn(ctx.current_event))


class StatefulPredicate(Predicate):
    """Closure over (event, fold states) (StatefulMatcher.java:29-46)."""

    def __init__(self, fn: Callable[[Any, Any], bool]) -> None:
        self.fn = fn

    def accept(self, ctx: "MatcherContext") -> bool:
        return bool(self.fn(ctx.current_event, ctx.states))


class SequencePredicate(Predicate):
    """Closure over (event, partial-match sequence, fold states).

    The reference materializes the whole partial match from the shared
    buffer on *every* evaluation (SequenceMatcher.java:22-26); the host path
    reproduces that observable behavior. Device queries should prefer fold
    registers (running reductions) instead -- see SURVEY.md section 7.
    """

    def __init__(self, fn: Callable[[Any, Any, Any], bool]) -> None:
        self.fn = fn

    def accept(self, ctx: "MatcherContext") -> bool:
        sequence = ctx.partial_sequence()
        return bool(self.fn(ctx.current_event, sequence, ctx.states))


class TruePredicate(Predicate):
    """Always true (Matcher.TruePredicate, Matcher.java:122-131)."""

    device_compilable = True

    def accept(self, ctx: "MatcherContext") -> bool:
        return True

    def expr(self) -> Optional[Expr]:
        return TrueExpr()

    def __repr__(self) -> str:
        return "TruePredicate()"


class TopicPredicate(Predicate):
    """Event originates from a topic (Matcher.TopicPredicate, Matcher.java:104-120)."""

    device_compilable = True

    def __init__(self, topic: str) -> None:
        if topic is None:
            raise ValueError("topic cannot be None")
        self.topic = topic

    def accept(self, ctx: "MatcherContext") -> bool:
        return ctx.current_event.topic == self.topic

    def expr(self) -> Optional[Expr]:
        from .expressions import TopicIs

        return TopicIs(self.topic)


class NotPredicate(Predicate):
    def __init__(self, inner: Predicate) -> None:
        self.inner = inner
        self.device_compilable = inner.device_compilable

    def accept(self, ctx: "MatcherContext") -> bool:
        return not self.inner.accept(ctx)

    def expr(self) -> Optional[Expr]:
        e = self.inner.expr()
        return None if e is None else ~e


class AndPredicate(Predicate):
    def __init__(self, left: Predicate, right: Predicate) -> None:
        self.left = left
        self.right = right
        self.device_compilable = left.device_compilable and right.device_compilable

    def accept(self, ctx: "MatcherContext") -> bool:
        return self.left.accept(ctx) and self.right.accept(ctx)

    def expr(self) -> Optional[Expr]:
        le, re_ = self.left.expr(), self.right.expr()
        if le is None or re_ is None:
            return None
        return le & re_


class OrPredicate(Predicate):
    def __init__(self, left: Predicate, right: Predicate) -> None:
        self.left = left
        self.right = right
        self.device_compilable = left.device_compilable and right.device_compilable

    def accept(self, ctx: "MatcherContext") -> bool:
        return self.left.accept(ctx) or self.right.accept(ctx)

    def expr(self) -> Optional[Expr]:
        le, re_ = self.left.expr(), self.right.expr()
        if le is None or re_ is None:
            return None
        return le | re_


def not_(p: Predicate) -> Predicate:
    return NotPredicate(p)


def and_(left: Predicate, right: Predicate) -> Predicate:
    return AndPredicate(left, right)


def or_(left: Predicate, right: Predicate) -> Predicate:
    return OrPredicate(left, right)


def coerce_predicate(p: Any) -> Predicate:
    """Accept an Expr, a Predicate, or a callable (arity decides the family)."""
    if isinstance(p, Predicate):
        return p
    if isinstance(p, Expr):
        return ExprPredicate(p)
    if callable(p):
        import inspect

        try:
            arity = len(inspect.signature(p).parameters)
        except (TypeError, ValueError):
            arity = 1
        if arity <= 1:
            return SimplePredicate(p)
        if arity == 2:
            return StatefulPredicate(p)
        return SequencePredicate(p)
    raise TypeError(f"Cannot interpret {p!r} as a predicate")
