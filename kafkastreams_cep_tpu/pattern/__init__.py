from .builder import QueryBuilder
from .compiler import InvalidPatternException, compile_pattern
from .expressions import agg, const, field, key, timestamp, topic_is, value
from .matcher import (
    AndPredicate, ExprPredicate, NotPredicate, OrPredicate, Predicate,
    SequencePredicate, SimplePredicate, StatefulPredicate, TopicPredicate,
    TruePredicate, and_, coerce_predicate, not_, or_,
)
from .aggregator import StateAggregator
from .pattern import Cardinality, Pattern, Selected, Strategy
from .stages import Edge, EdgeOperation, Stage, Stages, StateType
