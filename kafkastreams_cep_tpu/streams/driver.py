"""Log pump driver: consume source topics, drive the topology, commit.

The Kafka-Streams-runtime role the reference delegates to its platform
(reference: the poll/process/commit loop of Kafka Streams' StreamThread
driving CEPProcessor.java:111-160, with changelog restore on start and
consumer-group offset commits). Here the transport is the embedded
`RecordLog` (streams/log.py): the driver restores every query store from
its changelog topic, resumes from the committed consumer offsets (stored in
the log's `__consumer_offsets` topic), and pumps records through
`Topology.process`, committing after each poll.

Records in source topics carry pickled keys/values by default; pass
`key_deserializer`/`value_deserializer` for custom wire formats.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Tuple

from ..obs.registry import MetricsRegistry, default_registry
from ..state.store import default_deserializer, default_serializer
from .builder import Topology
from .log import RecordLog

OFFSETS_TOPIC = "__consumer_offsets"


def produce(
    log: RecordLog,
    topic: str,
    key: Any,
    value: Any,
    timestamp: int = 0,
    partition: int = 0,
) -> int:
    """Producer-side helper: append one (key, value) record, default serde."""
    return log.append(
        topic,
        default_serializer(key),
        default_serializer(value),
        timestamp=timestamp,
        partition=partition,
    )


class LogDriver:
    """Drives one topology from a RecordLog: restore, poll, commit.

    The Kafka-Streams-metrics surface the reference delegates to the
    framework lives here too: poll/record/commit counters and the restore
    wall land in `registry` (the process default when none is passed).
    `report_every_s` arms a periodic reporter: after a poll, once the
    interval has elapsed since the last report, `reporter` is called with
    the registry's prom-text exposition (default: the
    `kafkastreams_cep_tpu.obs` logger at INFO)."""

    def __init__(
        self,
        topology: Topology,
        log: Optional[RecordLog] = None,
        group: str = "default",
        key_deserializer: Callable[[bytes], Any] = default_deserializer,
        value_deserializer: Callable[[bytes], Any] = default_deserializer,
        restore: bool = True,
        registry: Optional[MetricsRegistry] = None,
        report_every_s: Optional[float] = None,
        reporter: Optional[Callable[[str], None]] = None,
    ) -> None:
        self.topology = topology
        self.log = log if log is not None else topology.log
        if self.log is None:
            raise ValueError("LogDriver needs a RecordLog (topology built without one)")
        self.group = group
        self.key_de = key_deserializer
        self.value_de = value_deserializer
        self.metrics = registry if registry is not None else default_registry()
        # Children bound once to this driver's group (labels() locks per
        # resolution; poll() is the cadence path).
        self._m_polls = self.metrics.counter(
            "cep_driver_polls_total", "poll() calls", labels=("group",)
        ).labels(group=self.group)
        self._m_records = self.metrics.counter(
            "cep_driver_records_total", "Records polled and processed",
            labels=("group",),
        ).labels(group=self.group)
        self._m_commits = self.metrics.counter(
            "cep_driver_commits_total", "Offset commits (dirty positions only)",
            labels=("group",),
        ).labels(group=self.group)
        self._m_restore_s = self.metrics.gauge(
            "cep_driver_restore_seconds", "Changelog restore wall at startup",
            labels=("group",),
        ).labels(group=self.group)
        self._m_restored = self.metrics.gauge(
            "cep_driver_restored_records", "Changelog records replayed at startup",
            labels=("group",),
        ).labels(group=self.group)
        self._m_reports = self.metrics.counter(
            "cep_driver_reports_total", "Periodic metric reports emitted",
            labels=("group",),
        ).labels(group=self.group)
        self.report_every_s = report_every_s
        self.reporter = reporter
        self._last_report_t = time.perf_counter()
        self._positions: Dict[Tuple[str, int], int] = {}
        #: positions as last durably committed -- commit() appends only the
        #: deltas, so the offsets topic grows with progress, not with the
        #: commit count (the last-write-wins read tolerates either).
        self._committed: Dict[Tuple[str, int], int] = {}
        self.restored_records = 0
        if restore:
            t0 = time.perf_counter()
            self.restored_records = self.topology.restore_stores()
            self._m_restore_s.set(time.perf_counter() - t0)
            self._m_restored.set(self.restored_records)
        self._load_committed()

    # ------------------------------------------------------------- offsets
    def _load_committed(self) -> None:
        """Latest committed position per (group, topic, partition)."""
        for rec in self.log.read(OFFSETS_TOPIC):
            if rec.key is None or rec.value is None:
                continue
            group, topic, partition = default_deserializer(rec.key)
            if group != self.group:
                continue
            pos = default_deserializer(rec.value)
            self._positions[(topic, partition)] = pos
            self._committed[(topic, partition)] = pos

    def commit(self) -> None:
        """Durably record consumer positions after making the state they
        cover durable (the reference commits offsets and flushes stores
        together at the commit interval).

        Order matters for at-least-once: the changelog/sink appends are
        fsynced BEFORE the offset record is appended and fsynced, so a crash
        between the two replays the interval (deduped by the HWM) instead of
        silently skipping records whose effects were lost."""
        self.topology.flush_stores()
        self.log.flush()  # changelog + sink records durable first
        dirty = {
            tp: pos
            for tp, pos in self._positions.items()
            if self._committed.get(tp) != pos
        }
        if not dirty:
            return
        for (topic, partition), pos in dirty.items():
            self.log.append(
                OFFSETS_TOPIC,
                default_serializer((self.group, topic, partition)),
                default_serializer(pos),
            )
        self.log.flush()
        self._committed.update(dirty)
        self._m_commits.inc()

    def position(self, topic: str, partition: int = 0) -> int:
        return self._positions.get((topic, partition), 0)

    # ---------------------------------------------------------------- poll
    def poll(self, max_records: Optional[int] = None, commit: bool = True) -> int:
        """Consume available records from every source topic, in offset
        order per partition; returns how many were processed."""
        processed = 0
        budget = max_records
        for topic in self.topology.source_topics:
            partitions = self.log.partitions(topic) or [0]
            for partition in partitions:
                start = self._positions.get((topic, partition), 0)
                records = self.log.read(topic, partition, start, budget)
                for rec in records:
                    self.topology.process(
                        topic,
                        self.key_de(rec.key) if rec.key is not None else None,
                        self.value_de(rec.value) if rec.value is not None else None,
                        timestamp=rec.timestamp,
                        partition=partition,
                        offset=rec.offset,
                    )
                    processed += 1
                if records:
                    self._positions[(topic, partition)] = records[-1].offset + 1
                if budget is not None:
                    budget -= len(records)
                    if budget <= 0:
                        break
            if budget is not None and budget <= 0:
                break
        self.topology.flush()  # flush device micro-batches
        if commit and processed:
            self.commit()
        self._m_polls.inc()
        self._m_records.inc(processed)
        self._maybe_report()
        return processed

    # ---------------------------------------------------------- reporting
    def _maybe_report(self) -> None:
        """Periodic reporter hook: emit the registry's prom-text exposition
        once `report_every_s` has elapsed since the last report (checked
        after each poll -- the driver's natural cadence point)."""
        if self.report_every_s is None:
            return
        now = time.perf_counter()
        if now - self._last_report_t < self.report_every_s:
            return
        self._last_report_t = now
        import logging

        # Best-effort: a failing reporter (push gateway blip) must never
        # break the data path -- records were already processed and
        # offsets committed by the time we get here.
        try:
            text = self.metrics.to_prom_text()
            if self.reporter is not None:
                self.reporter(text)
            else:
                logging.getLogger("kafkastreams_cep_tpu.obs").info(
                    "metrics report (group=%s)\n%s", self.group, text
                )
            self._m_reports.inc()
        except Exception:
            logging.getLogger("kafkastreams_cep_tpu.obs").warning(
                "metrics reporter failed (group=%s)", self.group, exc_info=True
            )
