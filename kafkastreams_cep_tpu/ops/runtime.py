"""Host runtime around the device engine: packing, decode, pool GC.

The device kernel (ops/engine.py) runs the transition relation; this module
owns everything that stays host-side in the TPU-native design
(SURVEY.md section 7 build plan, steps 4-5):

  * event ingestion: packing a micro-batch of `Event`s into SoA columns via
    the query's EventSchema and keeping a (global index -> Event) registry
    for match materialization;
  * match construction: walking the device node pool's predecessor indices
    backwards and assembling `Sequence` objects in the oracle's order
    (the host analog of SharedVersionedBufferStoreImpl.peek,
    reference: core/.../state/internal/SharedVersionedBufferStoreImpl.java:176-201);
  * buffer GC: mark-sweep compaction of the node pool at batch boundaries,
    replacing the reference's per-traversal refcount decrements
    (the "deferred refcount deltas + periodic compaction" design,
    SURVEY.md section 7 "Refcounted buffer GC without pointers").
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

import jax.numpy as jnp

from ..core.event import Event
from ..core.sequence import Sequence, SequenceBuilder
from ..pattern.stages import Stages
from .engine import EngineConfig, build_batch_fn, eval_stateless_preds, init_state
from .schema import EventSchema
from .tables import CompiledQuery, compile_query


class DeviceNFA:
    """Single-key device NFA: the accelerator counterpart of nfa/nfa.py.

    Drives the jit-compiled scan batch-by-batch while keeping the run/buffer
    state device-resident between batches; only match descriptors and (at GC
    points) the node pool cross back to the host.
    """

    def __init__(
        self,
        stages_or_query: Any,
        schema: Optional[EventSchema] = None,
        config: Optional[EngineConfig] = None,
    ) -> None:
        if isinstance(stages_or_query, CompiledQuery):
            self.query = stages_or_query
        else:
            assert isinstance(stages_or_query, Stages)
            self.query = compile_query(stages_or_query, schema)
        self.config = config if config is not None else EngineConfig()
        self._advance = build_batch_fn(self.query, self.config)
        self.state = init_state(self.query, self.config)
        self._events: Dict[int, Event] = {}
        self._next_gidx = 0
        self._ts_base: Optional[int] = None

    # ------------------------------------------------------------------ API
    @property
    def runs(self) -> int:
        """Run counter -- parity with NFA.runs for conformance asserts."""
        return int(self.state["runs"])

    @property
    def n_live(self) -> int:
        """Live lane count -- parity with len(NFA.computation_stages)."""
        return int(np.sum(np.asarray(self.state["active"])))

    @property
    def stats(self) -> Dict[str, int]:
        keys = (
            "n_events", "n_branches", "n_expired",
            "lane_drops", "node_drops", "match_drops", "seq_collisions",
        )
        return {k: int(self.state[k]) for k in keys}

    def match_pattern(self, event: Event) -> List[Sequence]:
        """Single-event convenience API mirroring NFA.match_pattern."""
        return self.advance([event])

    def live_runs(self) -> List[Dict[str, Any]]:
        """Queue snapshot in order: (stage name, run id, last event, version).

        The device analog of inspecting NFA.computation_stages in tests
        (reference: NFATest.assertNFA, NFATest.java:836-840).
        """
        active = np.asarray(self.state["active"])
        src = np.asarray(self.state["src"])
        seq = np.asarray(self.state["seq"])
        node = np.asarray(self.state["node"])
        ver = np.asarray(self.state["ver"])
        vlen = np.asarray(self.state["vlen"])
        node_event = np.asarray(self.state["node_event"])
        out = []
        for i in range(len(active)):
            if not active[i]:
                continue
            name = self.query.name_of_id[int(self.query.name_id[src[i]])]
            last = None
            if node[i] >= 0:
                last = self._events.get(int(node_event[node[i]]))
            out.append(
                dict(
                    stage=name,
                    sequence=int(seq[i]),
                    last_event=last,
                    version=".".join(str(d) for d in ver[i][: vlen[i]]),
                )
            )
        return out

    def advance(self, events: List[Event]) -> List[Sequence]:
        """Process a micro-batch; returns completed matches in oracle order."""
        if not events:
            return []
        xs = self._pack(events)
        self.state = self._advance(self.state, xs)
        matches = self._decode_matches()
        self._compact()
        return matches

    # ------------------------------------------------------------ internals
    def _pack(self, events: List[Event]) -> Dict[str, jnp.ndarray]:
        if self._ts_base is None:
            self._ts_base = int(events[0].timestamp)
        schema = self.query.schema
        cols = schema.pack(
            [e.value for e in events],
            [e.timestamp for e in events],
            topics=[e.topic for e in events],
            ts_base=self._ts_base,
        )
        T = len(events)
        gidx = np.arange(self._next_gidx, self._next_gidx + T, dtype=np.int32)
        for i, e in enumerate(events):
            self._events[int(gidx[i])] = e
        self._next_gidx += T
        xs = {k: jnp.asarray(v) for k, v in cols.items()}
        xs["spred"] = eval_stateless_preds(self.query, cols)
        xs["gidx"] = jnp.asarray(gidx)
        xs["valid"] = jnp.ones(T, bool)
        return xs

    def _decode_matches(self) -> List[Sequence]:
        count = int(self.state["match_count"])
        if count == 0:
            return []
        match_node = np.asarray(self.state["match_node"])[:count]
        node_event = np.asarray(self.state["node_event"])
        node_name = np.asarray(self.state["node_name"])
        node_pred = np.asarray(self.state["node_pred"])
        names = self.query.name_of_id

        out: List[Sequence] = []
        for node in match_node:
            builder: SequenceBuilder = SequenceBuilder()
            idx = int(node)
            while idx >= 0:
                builder.add(names[int(node_name[idx])], self._events[int(node_event[idx])])
                idx = int(node_pred[idx])
            out.append(builder.build(reversed_=True))

        # Drain the ring.
        self.state["match_count"] = jnp.asarray(0, np.int32)
        self.state["match_node"] = jnp.full_like(self.state["match_node"], -1)
        return out

    def _compact(self) -> None:
        """Mark-sweep the node pool: keep chains reachable from live lanes."""
        count = int(self.state["node_count"])
        if count == 0:
            return
        active = np.asarray(self.state["active"])
        lane_node = np.asarray(self.state["node"])
        node_pred = np.asarray(self.state["node_pred"])[: count]
        node_event = np.asarray(self.state["node_event"])[: count]
        node_name = np.asarray(self.state["node_name"])[: count]

        marked = np.zeros(count, bool)
        for i in range(len(active)):
            if not active[i]:
                continue
            idx = int(lane_node[i])
            while idx >= 0 and not marked[idx]:
                marked[idx] = True
                idx = int(node_pred[idx])
        kept = np.flatnonzero(marked)
        if len(kept) == count:
            return
        remap = np.full(count + 1, -1, np.int32)
        remap[kept] = np.arange(len(kept), dtype=np.int32)

        B = len(np.asarray(self.state["node_pred"])) - 1
        new_event = np.full(B + 1, -1, np.int32)
        new_name = np.full(B + 1, -1, np.int32)
        new_pred = np.full(B + 1, -1, np.int32)
        new_event[: len(kept)] = node_event[kept]
        new_name[: len(kept)] = node_name[kept]
        # Predecessors of kept nodes are kept too (chains are marked whole).
        pred_of_kept = node_pred[kept]
        new_pred[: len(kept)] = np.where(
            pred_of_kept >= 0, remap[pred_of_kept.clip(0)], -1
        )
        new_lane_node = np.where(lane_node >= 0, remap[lane_node.clip(0, count)], -1)

        self.state["node_event"] = jnp.asarray(new_event)
        self.state["node_name"] = jnp.asarray(new_name)
        self.state["node_pred"] = jnp.asarray(new_pred)
        self.state["node_count"] = jnp.asarray(len(kept), np.int32)
        self.state["node"] = jnp.asarray(new_lane_node.astype(np.int32))

        # Prune the event registry to events still referenced by the pool.
        live_gidx = set(int(g) for g in new_event[: len(kept)] if g >= 0)
        self._events = {g: e for g, e in self._events.items() if g in live_gidx}
