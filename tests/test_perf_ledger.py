"""Perf ledger (ISSUE 9): trajectory ingestion, regression flagging,
tunnel-degraded excusal, truncated-tail salvage, bench --compare block.

Pins the acceptance contracts:
- the ledger ingests every artifact shape a round has shipped in (raw
  bench JSON, driver wrapper with `parsed`, wrapper with a truncated
  `tail`) and renders a full trajectory table over the repo's real
  BENCH_r01..r05 artifacts;
- a synthetic >=15% eps drop is flagged as a regression, while the same
  drop under `tunnel_degraded` (either side) is excused -- environment
  noise must not fail the check;
- `compare_artifacts` (the bench.py --compare `regression` block) emits
  the documented shape and check_bench_schema accepts it.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(__file__)), "scripts")
)
from check_bench_schema import validate as validate_bench_schema  # noqa: E402
from perf_ledger import (  # noqa: E402
    build_ledger,
    compare_artifacts,
    find_regressions,
    parse_artifact,
    render_table,
    salvage_configs,
)

pytestmark = pytest.mark.profiling

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _round(name, configs, tunnel_degraded=False):
    rec = parse_artifact(
        {"configs": configs, "tunnel_degraded": tunnel_degraded}
    )
    rec["round"] = name
    return rec


def _cfg(eps, e2e=None):
    out = {"events": 1000, "seconds": 1.0, "eps": eps}
    if e2e is not None:
        out["e2e_eps"] = e2e
    return out


# ---------------------------------------------------------------- regression
def test_flags_synthetic_15pct_eps_regression():
    rounds = [
        _round("r1", {"skip_any8_batched": _cfg(100_000.0, 90_000.0)}),
        _round("r2", {"skip_any8_batched": _cfg(84_000.0, 89_000.0)}),
    ]
    ledger = build_ledger(rounds)
    regs = find_regressions(ledger, rounds, tolerance=0.15)
    assert len(regs) == 1
    r = regs[0]
    assert (r["config"], r["metric"], r["round"]) == (
        "skip_any8_batched", "eps", "r2"
    )
    assert r["excused"] is False
    assert r["delta_pct"] == pytest.approx(-16.0)
    # The same trajectory under a looser tolerance stays quiet.
    assert find_regressions(ledger, rounds, tolerance=0.20) == []
    # A drop inside the tolerance never flags.
    rounds_ok = [
        _round("r1", {"c": _cfg(100.0)}),
        _round("r2", {"c": _cfg(90.0)}),
    ]
    assert find_regressions(
        build_ledger(rounds_ok), rounds_ok, tolerance=0.15
    ) == []


def test_tunnel_degraded_round_is_excused_either_side():
    # Degraded CURRENT round: the drop is reported but excused.
    rounds = [
        _round("r1", {"c": _cfg(100_000.0)}),
        _round("r2", {"c": _cfg(10_000.0)}, tunnel_degraded=True),
    ]
    regs = find_regressions(build_ledger(rounds), rounds, tolerance=0.15)
    assert len(regs) == 1 and regs[0]["excused"] is True
    # Degraded PREVIOUS round: the "recovery baseline" is noise too.
    rounds = [
        _round("r1", {"c": _cfg(100_000.0)}, tunnel_degraded=True),
        _round("r2", {"c": _cfg(50_000.0)}),
    ]
    regs = find_regressions(build_ledger(rounds), rounds, tolerance=0.15)
    assert len(regs) == 1 and regs[0]["excused"] is True
    # Both healthy -> not excused.
    rounds = [
        _round("r1", {"c": _cfg(100_000.0)}),
        _round("r2", {"c": _cfg(50_000.0)}),
    ]
    regs = find_regressions(build_ledger(rounds), rounds, tolerance=0.15)
    assert len(regs) == 1 and regs[0]["excused"] is False


def test_platform_change_excuses_regressions_both_directions():
    """Rounds that self-describe DIFFERENT platforms (a cpu round after
    a tpu round) report drops as excused -- an environment change is not
    a code regression. Unknown platforms (legacy truncated wrappers)
    never excuse themselves."""
    rounds = [
        _round("r1", {"c": _cfg(100_000.0)}),
        _round("r2", {"c": _cfg(5_000.0)}),
    ]
    rounds[0]["platform"] = "tpu"
    rounds[1]["platform"] = "cpu"
    regs = find_regressions(build_ledger(rounds), rounds, tolerance=0.15)
    assert len(regs) == 1
    assert regs[0]["excused"] is True
    assert regs[0]["excuse"] == "platform_change"
    # One side unknown -> NOT excused.
    rounds[0]["platform"] = None
    regs = find_regressions(build_ledger(rounds), rounds, tolerance=0.15)
    assert regs[0]["excused"] is False and regs[0]["excuse"] is None
    # Same platform both sides -> NOT excused.
    rounds[0]["platform"] = "cpu"
    regs = find_regressions(build_ledger(rounds), rounds, tolerance=0.15)
    assert regs[0]["excused"] is False


def test_compare_artifacts_platform_fields_and_excusal():
    prev = {"configs": {"c": _cfg(100_000.0)}, "platform": "tpu",
            "tunnel_degraded": False}
    cur = {"configs": {"c": _cfg(5_000.0)}, "platform": "cpu",
           "tunnel_degraded": False}
    block = compare_artifacts(prev, cur, tolerance=0.15)
    assert block["regressed"] is True and block["excused"] is True
    assert block["platform_prev"] == "tpu"
    assert block["platform_cur"] == "cpu"
    # Unknown prior platform (legacy wrapper): reported, not excused.
    prev2 = {"configs": {"c": _cfg(100_000.0)}, "tunnel_degraded": False}
    block2 = compare_artifacts(prev2, cur, tolerance=0.15)
    assert block2["regressed"] is True and block2["excused"] is False
    assert block2["platform_prev"] is None
    # The augmented block still passes the artifact schema.
    from test_obs import _valid_artifact

    art = _valid_artifact()
    art["regression"] = block
    assert validate_bench_schema(art) == []


def test_salvage_recovers_platform_from_truncated_tail():
    tail = '"tunnel_degraded": false, "platform": "tpu", "configs": {'
    _configs, top = salvage_configs(tail)
    assert top["platform"] == "tpu"
    rec = parse_artifact({"n": 1, "rc": 0, "tail": tail, "parsed": None})
    assert rec["platform"] == "tpu"


def test_host_suite_configs_tracked_via_nested_metrics():
    """Host-suite configs ({"host": {...}, "device_single": {...}}) show
    in the trajectory as host_eps/serde_eps/device_eps context columns --
    but never flag regressions (CPython denominator noise)."""
    rounds = [
        _round("r1", {"skip_any8": {
            "host": {"eps": 4000.0, "serde_eps": 2400.0},
            "device_single": {"eps": 480.0},
        }}),
        _round("r2", {"skip_any8": {
            "host": {"eps": 1000.0, "serde_eps": 600.0},
            "device_single": {"eps": 470.0},
        }}),
    ]
    ledger = build_ledger(rounds)
    assert ledger["table"]["skip_any8"]["host_eps"] == [4000.0, 1000.0]
    assert ledger["table"]["skip_any8"]["serde_eps"] == [2400.0, 600.0]
    assert ledger["table"]["skip_any8"]["device_eps"] == [480.0, 470.0]
    # A 75% host drop is context, not a flag.
    assert find_regressions(ledger, rounds, tolerance=0.15) == []
    text = render_table(ledger, rounds, [])
    assert "host_eps" in text and "4,000" in text


def test_compare_reports_configs_missing_from_current_run():
    """A config the prior carried but the current run lacks is surfaced
    in missing_configs -- a vanished benchmark must not read as a clean
    comparison (though subset runs do not flag `regressed`)."""
    prev = {"configs": {
        "skip_any8_batched": _cfg(100_000.0),
        "multi_query": _cfg(50_000.0),
    }}
    cur = {"configs": {"multi_query": _cfg(49_000.0)}}
    block = compare_artifacts(prev, cur, tolerance=0.15)
    assert block["missing_configs"] == ["skip_any8_batched"]
    assert block["regressed"] is False
    # Nothing missing -> empty list, and prior configs without eps
    # numbers (host dicts, introspection detail) never count as missing.
    prev2 = {"configs": {
        "multi_query": _cfg(50_000.0),
        "introspection": {"http_endpoints_ok": True},
    }}
    assert compare_artifacts(prev2, cur)["missing_configs"] == []


def test_regression_compares_against_last_round_carrying_the_config():
    # A round missing the config (empty artifact) must not break the
    # chain: r3 compares against r1.
    rounds = [
        _round("r1", {"c": _cfg(100.0)}),
        _round("r2", {}),
        _round("r3", {"c": _cfg(50.0)}),
    ]
    regs = find_regressions(build_ledger(rounds), rounds, tolerance=0.15)
    assert len(regs) == 1
    assert regs[0]["prev_round"] == "r1" and regs[0]["round"] == "r3"


# ------------------------------------------------------------------ salvage
def test_salvage_recovers_complete_configs_from_truncated_tail():
    full = {
        "skip_any8_batched": _cfg(1000.0, 1100.0),
        "highcard_letters_batched": _cfg(2000.0),
    }
    line = json.dumps({"tunnel_degraded": False, "configs": full})
    # Truncate the front mid-way through the first config object: the
    # whole first config is lost, the second survives.
    cut = line.index('"highcard_letters_batched"') - 20
    configs, top = salvage_configs(line[cut:])
    assert "highcard_letters_batched" in configs
    assert configs["highcard_letters_batched"]["eps"] == 2000.0
    assert "skip_any8_batched" not in configs  # truncated mid-object
    # Inner dicts of a COMPLETE config are claimed by it, not leaked as
    # configs; unlisted names are ignored.
    line2 = json.dumps(
        {"configs": {"skip_any8": {"host": _cfg(5.0), "device_single": _cfg(6.0)}}}
    )
    configs2, _ = salvage_configs(line2)
    assert list(configs2) == ["skip_any8"]
    assert configs2["skip_any8"]["host"]["eps"] == 5.0


def test_parse_artifact_all_three_shapes():
    raw = {"configs": {"c": _cfg(10.0)}, "tunnel_degraded": True}
    rec = parse_artifact(raw)
    assert rec["configs"]["c"]["eps"] == 10.0
    assert rec["tunnel_degraded"] is True and rec["salvaged"] is False
    # Wrapper with parsed takes parsed.
    rec = parse_artifact({"n": 1, "rc": 0, "tail": "", "parsed": raw})
    assert rec["configs"]["c"]["eps"] == 10.0
    # Wrapper without parsed salvages the tail.
    tail = json.dumps(raw)[5:]  # clip the front
    rec = parse_artifact({"n": 1, "rc": 0, "tail": tail, "parsed": None})
    assert rec["empty"] or rec["salvaged"]
    # Empty wrapper (rounds 1-2's shape) is an empty round, not an error.
    rec = parse_artifact({"n": 1, "rc": 0, "tail": "", "parsed": None})
    assert rec["empty"] is True and rec["configs"] == {}


# ----------------------------------------------------- real BENCH_r* corpus
def test_ledger_over_repo_bench_rounds_prints_full_table():
    """The acceptance path: the CLI over BENCH_r01..r05.json prints a
    trajectory table covering every salvageable round and config."""
    paths = [
        os.path.join(REPO, f"BENCH_r0{i}.json") for i in range(1, 6)
    ]
    for p in paths:
        assert os.path.exists(p), p
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "perf_ledger.py")]
        + paths,
        capture_output=True, text=True, timeout=120,
    )
    tab = proc.stdout
    # All five rounds appear as columns; the salvaged configs as rows.
    for i in range(1, 6):
        assert f"BENCH_r0{i}" in tab
    assert "skip_any8_batched" in tab
    assert "eps" in tab and "p99_match_emit_ms" in tab
    # Rounds 1-2 shipped empty tails: the table says so instead of
    # silently rendering them as zero.
    assert "no data" in tab
    assert "salvaged from truncated tail" in tab
    # rc mirrors the verdict: the real corpus carries unexcused drops
    # (r05's degraded-tunnel flag predates the self-describing artifact,
    # so its truncated tail cannot excuse itself).
    assert proc.returncode in (0, 1)
    if "REGRESSIONS" in tab:
        assert proc.returncode == 1


def test_ledger_json_mode_and_excused_exit_code(tmp_path):
    a = tmp_path / "r1.json"
    b = tmp_path / "r2.json"
    a.write_text(json.dumps(
        {"configs": {"c": _cfg(100.0)}, "tunnel_degraded": False}
    ))
    b.write_text(json.dumps(
        {"configs": {"c": _cfg(10.0)}, "tunnel_degraded": True}
    ))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "perf_ledger.py"),
         "--json", str(a), str(b)],
        capture_output=True, text=True, timeout=60,
    )
    # Excused-only regressions exit 0 (the check must not fail on noise).
    assert proc.returncode == 0, proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["ledger"]["table"]["c"]["eps"] == [100.0, 10.0]
    assert len(doc["regressions"]) == 1
    assert doc["regressions"][0]["excused"] is True


# ------------------------------------------------------- bench --compare block
def test_compare_artifacts_block_shape_and_schema():
    prev = {"configs": {"skip_any8_batched": _cfg(100_000.0, 90_000.0)},
            "tunnel_degraded": False}
    cur = {"configs": {"skip_any8_batched": _cfg(50_000.0, 88_000.0)},
           "tunnel_degraded": False}
    block = compare_artifacts(prev, cur, tolerance=0.15, prior_name="prev.json")
    assert block["regressed"] is True and block["excused"] is False
    entry = block["configs"]["skip_any8_batched"]
    assert entry["eps"]["regressed"] is True
    assert entry["eps"]["delta_pct"] == pytest.approx(-50.0)
    assert entry["e2e_eps"]["regressed"] is False
    # tunnel_degraded on the CURRENT side excuses the verdict.
    cur_deg = dict(cur, tunnel_degraded=True)
    block2 = compare_artifacts(prev, cur_deg, tolerance=0.15)
    assert block2["regressed"] is True and block2["excused"] is True
    # The block passes the artifact schema as bench.py embeds it.
    from test_obs import _valid_artifact

    art = _valid_artifact()
    art["regression"] = block
    assert validate_bench_schema(art) == []


def test_render_table_marks_flags():
    rounds = [
        _round("r1", {"c": _cfg(100.0)}),
        _round("r2", {"c": _cfg(10.0)}, tunnel_degraded=True),
        _round("r3", {"c": _cfg(100.0)}),
        _round("r4", {"c": _cfg(50.0)}),
    ]
    ledger = build_ledger(rounds)
    regs = find_regressions(ledger, rounds, tolerance=0.15)
    text = render_table(ledger, rounds, regs)
    assert "10.0~" in text   # excused cell
    assert "50.0!" in text   # flagged cell
    assert "REGRESSIONS" in text
    assert "excused (tunnel_degraded)" in text
