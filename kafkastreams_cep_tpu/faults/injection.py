"""Deterministic fault injection: seeded schedules over named crash sites.

The reference delegates its entire failure story to Kafka Streams
(changelog restore, task reassignment, offset commits -- SURVEY §5.3,
CEPProcessor.java:111-160); the layers this framework adds on top of that
L0 contract -- the device engine, the async flat-drain decode thread, the
checkpoint codec -- need their failure modes *provoked*, not awaited. This
module is the provoker: a `FaultSchedule` (seeded RNG -> ordered fault
points) armed process-globally, with injection hooks compiled into the
production code at named crash sites. Every hook is a no-op unless armed:
the production path pays exactly one module-attribute check
(`ACTIVE is not None`), pinned by tests/test_faults.py alongside the PR 5
zero-extra-syncs contract.

Named sites (the full set is `ALL_SITES`):

  driver.pre_commit       LogDriver.poll, after processing, before commit()
  driver.post_commit      LogDriver.poll, after commit() returned
  driver.restore          LogDriver startup changelog restore (transient)
  engine.mid_drain        batched drain: ring pulled + cleared, decode
                          worker not yet joined (matches in flight)
  engine.device_step      the device advance dispatch (transient -- the
                          retry wrapper recovers it)
  store.checkpoint_write  CheckpointFile.save mid-write (torn bytes land
                          on the final path; CRC + last-good recover)
  log.torn_append         RecordLog.append: half a frame reaches the
                          segment file before the crash (reload truncates)
  time.reorder_overflow   EventTimeGate.offer admission (transient -- the
                          gate catches it and treats the reorder buffer
                          as full NOW, so chaos schedules exercise the
                          overflow policy path without filling a buffer)
  net.partial_write       SocketRecordLog frame send: half the frame lands
                          on the socket, then the connection is damaged
                          (transient -- the peer discards the torn frame
                          and the client's reconnect path re-delivers)
  net.disconnect          SocketRecordLog send/recv between frames
                          (transient -- reconnect + idempotent replay)
  net.stall               RecordLogServer apply loop: the server freezes
                          past the client's IO deadline (transient -- the
                          client's heartbeat/stall detection reconnects)

Crashes raise `InjectedCrash`, a BaseException subclass so no quarantine /
best-effort `except Exception` in the pipeline can accidentally swallow a
simulated process death. Transient sites raise `TransientFault` (an
Exception), which `with_retry` recovers.
"""
from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "ALL_SITES",
    "CRASH_SITES",
    "TRANSIENT_SITES",
    "CEPOverflowError",
    "FaultInjector",
    "FaultPoint",
    "FaultSchedule",
    "InjectedCrash",
    "PoisonRecords",
    "TransientFault",
    "armed",
    "arm",
    "disarm",
    "with_retry",
]

#: Crash sites: the process "dies" here (InjectedCrash propagates).
CRASH_SITES: Tuple[str, ...] = (
    "driver.pre_commit",
    "driver.post_commit",
    "engine.mid_drain",
    "store.checkpoint_write",
    "log.torn_append",
)
#: Transient sites: the fault is recoverable in-process (TransientFault,
#: caught at the site -- by the retry wrapper, or by the event-time
#: gate's overflow hook, which reinterprets it as forced buffer pressure).
TRANSIENT_SITES: Tuple[str, ...] = (
    "engine.device_step",
    "driver.restore",
    "time.reorder_overflow",
    # Wire-transport sites (streams/transport.py): connection damage is
    # recoverable by design -- reconnect/backoff + idempotent replay.
    "net.partial_write",
    "net.disconnect",
    "net.stall",
)
ALL_SITES: Tuple[str, ...] = CRASH_SITES + TRANSIENT_SITES


class InjectedCrash(BaseException):
    """A simulated process death at a named crash site.

    BaseException on purpose: poison quarantine and best-effort reporters
    catch `Exception`, and a simulated crash must never be quarantined."""

    def __init__(self, site: str) -> None:
        super().__init__(site)
        self.site = site


class TransientFault(Exception):
    """A recoverable injected fault (device-step blip, log IO hiccup)."""

    def __init__(self, site: str) -> None:
        super().__init__(site)
        self.site = site


class CEPOverflowError(RuntimeError):
    """Engine capacity overflow escalated by `EngineConfig.on_overflow`.

    Raised (policy "raise", and "block" when backpressure could not keep
    the run loss-free) instead of the default loud-drop accounting. When
    raised from a drain boundary, `.matches` carries the successfully
    drained matches (the ring was already pulled), so callers can still
    deliver them. Lives here so host-only layers (streams/driver.py) can
    catch it without importing the jax-heavy ops package."""

    #: Matches drained before the escalation (set at drain boundaries).
    matches = None


class PoisonRecords(Exception):
    """One or more records failed inside the engine's pack/predicate path.

    Carries [(key, Event, original exception)] so the driver can quarantine
    exactly the poison records while the batch's healthy remainder has
    already been processed."""

    def __init__(self, poisoned: List[Tuple[Any, Any, Exception]]) -> None:
        super().__init__(f"{len(poisoned)} poison record(s)")
        self.poisoned = poisoned


@dataclass
class FaultPoint:
    """One scheduled fault: fires on the `hit`-th call to its site."""

    site: str
    hit: int  # 1-based cumulative fire() count at this site
    fired: bool = field(default=False, compare=False)

    def __post_init__(self) -> None:
        if self.site not in ALL_SITES:
            raise ValueError(
                f"unknown fault site {self.site!r} (known: {ALL_SITES})"
            )
        if self.hit < 1:
            raise ValueError("hit counts are 1-based")


class FaultSchedule:
    """An ordered set of fault points, optionally generated from a seed.

    `seeded(seed)` draws `n_points` (site, hit) pairs with a deterministic
    RNG so a failing chaos run reproduces from its seed alone. Hit counts
    are cumulative per site across the whole run -- they keep counting
    through simulated crashes, so one schedule can kill a pipeline several
    times at different depths."""

    def __init__(self, points: Iterable[FaultPoint]) -> None:
        self.points: List[FaultPoint] = list(points)

    @classmethod
    def seeded(
        cls,
        seed: int,
        sites: Sequence[str] = CRASH_SITES,
        n_points: int = 2,
        max_hit: int = 6,
    ) -> "FaultSchedule":
        rng = random.Random(seed)
        points = [
            FaultPoint(rng.choice(list(sites)), rng.randint(1, max_hit))
            for _ in range(n_points)
        ]
        # Two points on the same (site, hit) collapse to one fault.
        uniq = {(p.site, p.hit): p for p in points}
        return cls(sorted(uniq.values(), key=lambda p: (p.site, p.hit)))

    def pending(self) -> List[FaultPoint]:
        return [p for p in self.points if not p.fired]

    def __repr__(self) -> str:
        return f"FaultSchedule({self.points!r})"


class FaultInjector:
    """Arms a schedule: counts `fire()` calls per site, raises on matches.

    The injector outlives simulated crashes (it is test-side state, not
    pipeline state), so hit counts keep accumulating across restarts --
    exactly how a flaky environment behaves. `cep_faults_injected_total`
    lands in `registry` (the process default when none is passed)."""

    def __init__(
        self, schedule: FaultSchedule, registry: Optional[Any] = None
    ) -> None:
        from ..obs.registry import default_registry

        self.schedule = schedule
        self.hits: dict = {}
        self.fired: List[FaultPoint] = []
        self.metrics = registry if registry is not None else default_registry()
        self._m_injected = self.metrics.counter(
            "cep_faults_injected_total",
            "Faults fired by the injection harness",
            labels=("site",),
        )

    def fire(self, site: str, **ctx: Any) -> None:
        """Count one pass through `site`; raise if a point is due.

        `ctx` carries site-specific handles (the torn-append site gets the
        open segment file + frame bytes so it can land half a frame before
        the crash)."""
        n = self.hits.get(site, 0) + 1
        self.hits[site] = n
        for p in self.schedule.points:
            if p.fired or p.site != site or p.hit != n:
                continue
            p.fired = True
            self.fired.append(p)
            self._m_injected.labels(site=site).inc()
            if site == "log.torn_append":
                self._tear(ctx)
            if site == "store.checkpoint_write":
                self._corrupt_checkpoint(ctx)
            if site == "net.partial_write":
                self._partial_send(ctx)
            if site in TRANSIENT_SITES:
                raise TransientFault(site)
            raise InjectedCrash(site)

    @staticmethod
    def _tear(ctx: dict) -> None:
        """Land the first half of the frame durably, then die: the reload
        path must truncate exactly the torn tail (streams/log.py)."""
        f, payload = ctx.get("file"), ctx.get("payload", b"")
        if f is not None and payload:
            import os

            f.write(payload[: max(1, len(payload) // 2)])
            f.flush()
            os.fsync(f.fileno())

    @staticmethod
    def _partial_send(ctx: dict) -> None:
        """Land the first half of the wire frame on the socket, then
        sever: the peer reads a torn frame (mid-frame EOF or CRC reject),
        discards it without applying, and drops the connection -- the
        client's reconnect path owns re-delivery on a clean frame
        boundary (streams/transport.py)."""
        sock, payload = ctx.get("sock"), ctx.get("payload", b"")
        if sock is not None and payload:
            try:
                sock.sendall(payload[: max(1, len(payload) // 2)])
            except OSError:
                pass  # an already-dead socket IS the disconnect

    @staticmethod
    def _corrupt_checkpoint(ctx: dict) -> None:
        """Land half the checkpoint bytes on the FINAL path (simulating a
        non-atomic writer / disk corruption), then die: load must reject
        the CRC and fall back to last-good (state/store.py)."""
        path, data = ctx.get("path"), ctx.get("data", b"")
        if path is not None and data:
            with open(path, "wb") as f:
                f.write(data[: max(1, len(data) // 2)])
                f.flush()
                import os

                os.fsync(f.fileno())


#: The process-global armed injector. Hooks check `ACTIVE is not None`
#: (one module-attribute read) and call `ACTIVE.fire(site)` only when a
#: harness armed one -- the production path is a no-op.
ACTIVE: Optional[FaultInjector] = None


def arm(injector: FaultInjector) -> FaultInjector:
    global ACTIVE
    ACTIVE = injector
    return injector


def disarm() -> None:
    global ACTIVE
    ACTIVE = None


class armed:
    """Context manager: arm an injector (or a schedule) for the block."""

    def __init__(self, injector_or_schedule, registry: Optional[Any] = None):
        if isinstance(injector_or_schedule, FaultSchedule):
            injector_or_schedule = FaultInjector(
                injector_or_schedule, registry=registry
            )
        self.injector: FaultInjector = injector_or_schedule

    def __enter__(self) -> FaultInjector:
        return arm(self.injector)

    def __exit__(self, *exc) -> None:
        disarm()


def with_retry(
    fn: Callable[[], Any],
    site: str,
    attempts: int = 3,
    backoff_s: float = 0.001,
    retry_on: Tuple[type, ...] = (TransientFault, OSError),
    registry: Optional[Any] = None,
) -> Any:
    """Run `fn`, retrying transient failures with linear backoff.

    Retries only `retry_on` exceptions (never InjectedCrash -- a simulated
    process death must not be survivable in-process), caps at `attempts`
    total tries, and counts every retry in `cep_retries_total{site}`. The
    final failure re-raises."""
    from ..obs.registry import default_registry

    metrics = registry if registry is not None else default_registry()
    counter = metrics.counter(
        "cep_retries_total",
        "Transient-fault retries by site",
        labels=("site",),
    )
    last: Optional[BaseException] = None
    for attempt in range(max(1, attempts)):
        if attempt > 0:
            counter.labels(site=site).inc()
            if backoff_s > 0:
                time.sleep(backoff_s * attempt)
        try:
            return fn()
        except retry_on as exc:
            last = exc
    assert last is not None
    raise last
