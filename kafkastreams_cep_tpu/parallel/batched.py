"""Multi-key batched device driver: thousands of per-key NFAs per chip.

The reference scales by Kafka partitioning -- one stream task per partition,
one NFA object per record key, advanced record-at-a-time
(reference: core/.../cep/processor/CEPProcessor.java:111-124,139). The
TPU-native design packs K keys' event columns into [T, K] micro-batches and
drives the vmapped transition kernel (parallel/key_shard.py) so one chip
advances every key's NFA in lockstep; the key axis shards across a
`jax.sharding.Mesh` for multi-chip scale-out with no collectives on the
per-event hot path (SURVEY.md section 2.8).

Host responsibilities mirror the single-key runtime (ops/runtime.py): SoA
packing through the query's EventSchema, a global (gidx -> Event) registry,
vectorized match decode across all keys at once, and on-device mark-sweep
pool GC at a configurable cadence.
"""
from __future__ import annotations

from collections import deque
from typing import Any, Dict, List, Mapping, Optional, Sequence as Seq, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..core.event import Event
from ..core.sequence import Sequence
from ..ops.engine import EngineConfig, drain_pend, eval_stateless_preds
from ..ops.runtime import decode_chains, materialize_sequence
from ..ops.schema import EventSchema
from ..ops.tables import CompiledQuery, compile_query
from ..pattern.stages import Stages
from .key_shard import (
    build_batched_advance,
    build_batched_post,
    init_batched_pool,
    init_batched_state,
    shard_state,
    shard_xs,
)

#: Rebase margin: keys first seen after the base is fixed may start up to
#: this much earlier and still rebase non-negative (~17 minutes; i32
#: timestamps span ~24 days either side of the base).
TS_REBASE_MARGIN_MS = 1 << 20


class BatchedDeviceNFA:
    """K independent per-key NFAs advanced as one [T, K] device program.

    `keys` fixes the lane->key mapping for the instance's lifetime (the
    driver layer above assigns keys to lanes; see streams/device_processor).
    With `mesh` set, engine state and event columns shard along the key axis
    over the mesh's devices.

    `engine` selects the transition kernel: "auto" (default) runs the fused
    Pallas kernel (ops/pallas_step.py) on single-chip TPU and the vmapped
    XLA scan step everywhere else (mesh-sharded, CPU, configs outside the
    kernel envelope -- the reason lands in `engine_fallback_reason`);
    "xla" / "pallas" force a path; "pallas_interpret" runs the kernel in
    the Pallas interpreter (conformance tests on CPU).
    """

    def __init__(
        self,
        stages_or_query: Any,
        keys: Seq[Any],
        schema: Optional[EventSchema] = None,
        config: Optional[EngineConfig] = None,
        mesh: Optional[Any] = None,
        events_prune_threshold: int = 1 << 16,
        engine: str = "auto",
        auto_drain: bool = True,
    ) -> None:
        if isinstance(stages_or_query, CompiledQuery):
            self.query = stages_or_query
        else:
            assert isinstance(stages_or_query, Stages)
            self.query = compile_query(stages_or_query, schema)
        self.config = config if config is not None else EngineConfig()
        self.mesh = mesh
        self.keys: List[Any] = list(keys)
        if not self.keys:
            raise ValueError("BatchedDeviceNFA needs at least one key")
        self.engine, self.engine_fallback_reason = self._pick_engine(engine)
        # Pad the key axis to a multiple of the mesh extent so the shard is
        # even (and of the pallas kernel's 8-key block); padding lanes never
        # receive valid events.
        self.K = len(self.keys)
        self.K_padded = self._padded_extent(self.K)
        self.key_index: Dict[Any, int] = {k: i for i, k in enumerate(self.keys)}

        self.state = init_batched_state(self.query, self.config, self.K_padded)
        self.pool = init_batched_pool(self.query, self.config, self.K_padded)
        if mesh is not None:
            self.state = shard_state(self.state, mesh)
            self.pool = shard_state(self.pool, mesh)
        if self.engine.startswith("pallas"):
            from ..ops.pallas_step import (
                build_pallas_batched_advance,
                build_pallas_batched_post,
            )

            self._advance = build_pallas_batched_advance(
                self.query, self.config,
                interpret=(self.engine == "pallas_interpret"),
            )
            self._post = build_pallas_batched_post(self.query, self.config)
        else:
            self._advance = build_batched_advance(self.query, self.config)
            self._post = build_batched_post(self.query, self.config)
        self._drain_pend = jax.jit(drain_pend)
        # post (pend-append + GC) runs every advance: node ids are only
        # stable across advances through its remap.
        #: Capacity guard against silent match loss (the reference never
        #: drops a match, SharedVersionedBufferStoreImpl.java:101-126): a
        #: non-decoding advance can append at most T * matches_per_step ids
        #: per key, so draining whenever the worst-case running total would
        #: exceed the pend ring keeps overflow impossible -- with zero
        #: device syncs until a drain is actually forced. Auto-drained
        #: matches are buffered host-side and handed out by the next
        #: explicit drain()/decoding advance.
        self.auto_drain = auto_drain
        self._pend_accum = 0
        self._auto_buffer: Dict[Any, List[Sequence]] = {}
        self._compact_pend_fn = None
        self.events_prune_threshold = events_prune_threshold
        self._events: Dict[int, Event] = {}
        self._next_gidx = 0
        #: highest gidx already advanced through the engine; events above it
        #: were packed ahead (pipelined ingest) and must survive pruning.
        #: Maintained host-side via a FIFO of per-pack watermarks (batches
        #: must be advanced in pack order -- stream semantics).
        self._processed_gidx = -1
        self._pack_hwms: deque = deque()
        self._ts_base: Optional[int] = None
        self._batches = 0
        self._stats_fn = None
        from ..ops.profiling import BatchTimings

        #: Per-batch dispatch/drain timings + match-emit latency histogram
        #: (SURVEY.md §5.5; semantics in ops/profiling.py).
        self.timings = BatchTimings()

    def _pick_engine(self, engine: str) -> Tuple[str, Optional[str]]:
        """Resolve "auto" to the fused pallas kernel when it applies.

        The kernel runs single-chip only (a mesh shards the XLA path);
        "auto" keeps the XLA scan step for meshes, non-TPU platforms and
        configs outside the kernel's envelope, recording why in
        `engine_fallback_reason`.
        """
        from ..ops.pallas_step import supports_pallas

        if engine in ("xla", "pallas", "pallas_interpret"):
            if engine.startswith("pallas"):
                reason = supports_pallas(self.query, self.config)
                if reason is not None:
                    raise ValueError(f"pallas engine unsupported: {reason}")
                if self.mesh is not None:
                    raise ValueError(
                        "pallas engine does not shard over a mesh yet; "
                        "use engine='xla' with mesh"
                    )
            return engine, None
        if engine != "auto":
            raise ValueError(f"unknown engine {engine!r}")
        if self.mesh is not None:
            return "xla", "mesh-sharded run"
        platform = jax.devices()[0].platform
        if platform != "tpu":
            return "xla", f"platform {platform!r} (pallas kernel is TPU-only)"
        reason = supports_pallas(self.query, self.config)
        if reason is not None:
            return "xla", reason
        return "pallas", None

    def _padded_extent(self, k: int) -> int:
        mult = 1
        if self.mesh is not None:
            mult = int(np.prod([self.mesh.shape[a] for a in self.mesh.axis_names]))
        if self.engine.startswith("pallas"):
            mult = max(mult, 8)  # kernel key-block granularity
        return ((k + mult - 1) // mult) * mult

    # ------------------------------------------------------------------ API
    def add_keys(self, new_keys: Seq[Any]) -> None:
        """Grow the key axis: fresh per-key engine state for each new key.

        The jitted advance/GC retrace for the new [K] extent (shape change),
        so callers should grow geometrically (see streams/device_processor).
        """
        for k in new_keys:
            if k in self.key_index:
                raise KeyError(f"key {k!r} already assigned")
        self.keys.extend(new_keys)
        self.K = len(self.keys)
        k_pad = self._padded_extent(self.K)
        delta = k_pad - self.K_padded
        self.key_index = {k: i for i, k in enumerate(self.keys)}
        if delta > 0:
            cat = lambda old, new: jnp.concatenate([old, new], axis=-1)
            self.state = jax.tree.map(
                cat, self.state, init_batched_state(self.query, self.config, delta)
            )
            self.pool = jax.tree.map(
                cat, self.pool, init_batched_pool(self.query, self.config, delta)
            )
            self.K_padded = k_pad
            if self.mesh is not None:
                self.state = shard_state(self.state, self.mesh)
                self.pool = shard_state(self.pool, self.mesh)

    @property
    def stats(self) -> Dict[str, int]:
        """Cross-key counter totals: one fused reduction + one host pull
        (key_shard.global_stats; an ICI all-reduce when sharded)."""
        from .key_shard import global_stats

        if self._stats_fn is None:
            self._stats_fn = jax.jit(global_stats)
        pulled = jax.device_get(self._stats_fn(self.state))
        keys = (
            "n_events", "n_branches", "n_expired",
            "lane_drops", "node_drops", "match_drops", "seq_collisions",
        )
        return {k: int(pulled[k]) for k in keys}

    def runs(self, key: Any) -> int:
        return int(np.asarray(self.state["runs"])[self.key_index[key]])

    def n_live(self, key: Any) -> int:
        return int(
            np.sum(np.asarray(self.state["active"])[:, self.key_index[key]])
        )

    def pack(
        self, events_by_key: Mapping[Any, Seq[Event]]
    ) -> Dict[str, jnp.ndarray]:
        """Pack per-key event lists into time-major [T, K] device columns.

        Ragged keys are padded at the tail with valid=False steps; keys
        absent from the mapping are all-padding for this batch. Work (and
        global event-id allocation) is O(real events): padding slots are
        numpy fills carrying gidx -1, never Python-per-slot loops.
        """
        lists: List[Seq[Event]] = [() for _ in range(self.K_padded)]
        T = 0
        min_first: Optional[int] = None
        for key, evs in events_by_key.items():
            idx = self.key_index.get(key)
            if idx is None:
                raise KeyError(f"unknown key {key!r} (fixed at construction)")
            lists[idx] = evs
            T = max(T, len(evs))
            if evs:
                ts0 = int(evs[0].timestamp)
                min_first = ts0 if min_first is None else min(min_first, ts0)
        if T == 0 or min_first is None:
            raise ValueError("empty batch")
        gidx_before = self._next_gidx
        ts_base_before = self._ts_base
        if self._ts_base is None:
            # Shared rebase across ALL keys: take the min first-timestamp in
            # this batch minus a margin, so a key whose stream starts
            # (boundedly) earlier than the first-seen key still rebases to a
            # non-negative i32 -- negative rebased times collide with the
            # engine's -1 "unstarted" sentinel and silently disable window
            # expiry for those runs (found by the multikey differential
            # harness, seeds 8/10).
            self._ts_base = min_first - TS_REBASE_MARGIN_MS

        K = self.K_padded
        schema = self.query.schema
        cols: Dict[str, np.ndarray] = {
            f"f:{name}": np.zeros((T, K), dtype)
            for name, dtype in schema.fields.items()
        }
        cols["ts"] = np.zeros((T, K), np.int32)
        cols["topic"] = np.zeros((T, K), np.int32)
        valid = np.zeros((T, K), bool)
        gidx = np.full((T, K), -1, np.int32)

        native = self._native_packer()
        if native is not None:
            # One C call packs every (lane, event, field): extraction,
            # tokenization, ts rebase, validity, gidx and registry update
            # (native/packer.cc; the Python loop below stays the semantic
            # reference and the fallback).
            field_names = tuple(schema.fields.keys())
            is_float = tuple(
                np.dtype(dt) == np.float32 for dt in schema.fields.values()
            )
            self._next_gidx = native.pack_batch(
                [list(evs) for evs in lists],
                field_names,
                is_float,
                schema._vocab,
                schema._rev_vocab,
                schema._topic_vocab,
                int(self._ts_base),
                tuple(cols[f"f:{n}"] for n in field_names),
                cols["ts"],
                cols["topic"],
                valid,
                gidx,
                int(self._next_gidx),
                self._events,
            )
        else:
            for k, evs in enumerate(lists):
                if not evs:
                    continue
                n = len(evs)
                key_cols = schema.pack(
                    [e.value for e in evs],
                    [e.timestamp for e in evs],
                    topics=[e.topic for e in evs],
                    ts_base=self._ts_base,
                )
                for name, arr in key_cols.items():
                    cols[name][:n, k] = arr
                ids = np.arange(self._next_gidx, self._next_gidx + n, dtype=np.int32)
                gidx[:n, k] = ids
                self._next_gidx += n
                for g, e in zip(ids, evs):
                    self._events[int(g)] = e
                valid[:n, k] = True

        # Complete rebase-underflow guard: covers out-of-order events deep
        # inside a batch and late batches alike (one vectorized pass;
        # padding slots hold 0 and cannot mask a real negative). The
        # registry/gidx/base mutations above are rolled back so a caller
        # that catches and skips the bad batch leaks nothing (interned
        # schema vocab tokens may leak ids -- append-only and harmless).
        if int(cols["ts"].min()) < 0:
            for g in range(gidx_before, self._next_gidx):
                self._events.pop(g, None)
            self._next_gidx = gidx_before
            self._ts_base = ts_base_before
            raise ValueError(
                f"event timestamp rebases negative (base {self._ts_base}, "
                f"margin {TS_REBASE_MARGIN_MS} ms): an event arrived more "
                "than the margin earlier than the first batch's earliest "
                "event; negative rebased times would collide with the "
                "engine's -1 sentinel and silently disable window expiry"
            )
        xs = {k: jnp.asarray(v) for k, v in cols.items()}
        xs["spred"] = eval_stateless_preds(self.query, cols)
        xs["gidx"] = jnp.asarray(gidx)
        xs["valid"] = jnp.asarray(valid)
        if self.mesh is not None:
            xs = shard_xs(xs, self.mesh)
        self._pack_hwms.append(self._next_gidx - 1)
        return xs

    def advance(
        self, events_by_key: Mapping[Any, Seq[Event]]
    ) -> Dict[Any, List[Sequence]]:
        """Pack, advance all keys one micro-batch, decode per-key matches."""
        return self.advance_packed(self.pack(events_by_key))

    def advance_packed(
        self, xs: Dict[str, jnp.ndarray], decode: bool = True
    ) -> Dict[Any, List[Sequence]]:
        """Advance with pre-packed columns (the bench/pipelined ingest path).

        With decode=False the call is fully asynchronous -- no device sync,
        matches accumulate in the (padded) ring until `drain()` or the next
        decoding advance. Size `EngineConfig.matches` for the accumulation
        window; overflow shows up in `stats["match_drops"]`.
        """
        T = int(xs["valid"].shape[0])
        step_cap = T * self.config.matches_per_step
        raw = None
        # The capacity guard only applies in the paged-append regime
        # (step_cap <= matches): there the worst-case cursor growth is
        # exactly one page per matching advance and a pre-advance drain
        # makes ring overflow impossible. With step_cap > matches the
        # engine's compact append places what fits and counts the rest in
        # match_drops (loud) -- size EngineConfig.matches to at least one
        # page (T * matches_per_step) for loss-free deferred decode.
        if (
            self.auto_drain
            and step_cap <= self.config.matches
            and self._pend_accum + step_cap > self.config.matches
        ):
            # Ring would overflow in the worst case: pull the pending
            # matches off the device and clear the ring NOW, but decode
            # them host-side only after the next advance is dispatched --
            # the Python materialization then overlaps device compute.
            # Applies to decoding advances too: their own drain only runs
            # after the advance has already appended to the ring.
            raw = self._pull_raw()
            self._pend_accum = 0
        if self._pack_hwms:
            self._processed_gidx = max(
                self._processed_gidx, self._pack_hwms.popleft()
            )
        import time as _time

        t0 = _time.perf_counter()
        self.state, ys = self._advance(self.state, xs)
        self.state, self.pool = self._post(self.state, self.pool, ys)
        self._batches += 1
        self._pend_accum += step_cap
        # Slot count from shape only -- counting true valids would pull the
        # device array and break the zero-sync advance path (exact event
        # totals live in the engine's n_events counter).
        self.timings.record_advance(
            _time.perf_counter() - t0, int(np.prod(xs["valid"].shape))
        )
        if raw is not None:
            for k, v in self._decode_raw(raw).items():
                self._auto_buffer.setdefault(k, []).extend(v)
        out: Dict[Any, List[Sequence]] = {}
        if decode:
            out = self.drain()
        return out

    def drain(self) -> Dict[Any, List[Sequence]]:
        """Decode and clear all pending matches (a device sync point).

        Pending ids are GC roots, remapped on every post pass, so draining
        after any number of non-decoding advances is id-consistent."""
        import time as _time

        t0 = _time.perf_counter()
        self._pend_accum = 0
        buffered = self._auto_buffer
        self._auto_buffer = {}
        raw = self._pull_raw()
        out = buffered
        if raw is not None:
            for k, v in self._decode_raw(raw).items():
                out.setdefault(k, []).extend(v)
        # Prune AFTER decoding: the raw snapshot's chains reference events
        # by gidx, and materialized Sequences hold the Event objects.
        self._prune_events()  # registry must stay bounded on match-free streams
        self.timings.record_drain(
            _time.perf_counter() - t0, sum(len(v) for v in out.values())
        )
        return out

    # --------------------------------------------------------- checkpointing
    def snapshot(self) -> bytes:
        """Serialize the [K]-stacked engine state + key list + registry."""
        import pickle

        from ..state.serde import (
            _Writer,
            MAGIC,
            encode_array_tree,
            encode_event_registry,
        )

        w = _Writer()
        w._buf.write(MAGIC)
        w.blob(pickle.dumps(self.keys, protocol=pickle.HIGHEST_PROTOCOL))
        w.blob(encode_array_tree({k: np.asarray(v) for k, v in self.state.items()}))
        w.blob(encode_array_tree({k: np.asarray(v) for k, v in self.pool.items()}))
        w.blob(encode_event_registry(self._events))
        w.i64(self._next_gidx)
        w.i64(self._ts_base if self._ts_base is not None else -1)
        w.i64(self._batches)
        return w.getvalue()

    @classmethod
    def restore(
        cls,
        stages_or_query: Any,
        data: bytes,
        schema: Optional[EventSchema] = None,
        config: Optional[EngineConfig] = None,
        mesh: Optional[Any] = None,
        engine: str = "auto",
    ) -> "BatchedDeviceNFA":
        import pickle

        from ..state.serde import (
            _Reader,
            MAGIC,
            decode_array_tree,
            decode_event_registry,
        )

        r = _Reader(data)
        if r._read(4) != MAGIC:
            raise ValueError("bad checkpoint magic")
        keys = pickle.loads(r.blob())
        bat = cls(
            stages_or_query, keys=keys, schema=schema, config=config,
            mesh=mesh, engine=engine,
        )
        tree = decode_array_tree(r.blob())
        state = {k: jnp.asarray(v) for k, v in tree.items()}
        pool_tree = decode_array_tree(r.blob())
        pool = {k: jnp.asarray(v) for k, v in pool_tree.items()}
        if mesh is not None:
            state = shard_state(state, mesh)
            pool = shard_state(pool, mesh)
        bat.state = state
        bat.pool = pool
        bat.K_padded = int(tree["active"].shape[-1])
        # A checkpoint taken under a different engine may carry a key-axis
        # extent off this engine's granularity (pallas advances 8-key
        # blocks); grow with fresh padding state, never shrink.
        want = bat._padded_extent(bat.K_padded)
        if want > bat.K_padded:
            delta = want - bat.K_padded
            cat = lambda old, new: jnp.concatenate([old, new], axis=-1)
            bat.state = jax.tree.map(
                cat, bat.state, init_batched_state(bat.query, bat.config, delta)
            )
            bat.pool = jax.tree.map(
                cat, bat.pool, init_batched_pool(bat.query, bat.config, delta)
            )
            bat.K_padded = want
            if mesh is not None:
                bat.state = shard_state(bat.state, mesh)
                bat.pool = shard_state(bat.pool, mesh)
        bat._events = decode_event_registry(r.blob())
        bat._next_gidx = r.i64()
        bat._processed_gidx = bat._next_gidx - 1  # no pre-packed xs survive
        # The restored pool may hold pending undrained matches: seed the
        # capacity guard with the ring cursor (page occupancy, holes
        # included) so auto-drain cannot undercount after a restore.
        bat._pend_accum = int(np.asarray(bat.pool["pend_pos"]).max())
        ts_base = r.i64()
        bat._ts_base = None if ts_base < 0 else ts_base
        bat._batches = r.i64()
        return bat

    # ------------------------------------------------------------ internals
    def _native_packer(self):
        """The C packer module, or None (cached; dtype-gated)."""
        cached = getattr(self, "_native_mod", False)
        if cached is not False:
            return cached
        mod = None
        try:
            from ..native import load_packer

            if all(
                np.dtype(dt) in (np.dtype(np.int32), np.dtype(np.float32))
                for dt in self.query.schema.fields.values()
            ):
                mod = load_packer()
        except Exception:
            mod = None
        self._native_mod = mod
        return mod

    def _pull_raw(self) -> Optional[Dict[str, np.ndarray]]:
        """Pull pending matches + the node pools off the device and clear
        the ring (a sync point). Decode happens separately (`_decode_raw`)
        so callers can overlap the Python materialization with the next
        dispatched batch. Returns None when nothing is pending.

        Bucketed pulls: the compacted region only holds `node_count` live
        nodes per key (post-GC ids are dense from 0), so the dominant D2H
        transfer is sliced to the max live count, rounded up to a power of
        two to bound the number of distinct sliced programs to O(log B)
        (PERF.md round-3 lever 3: decode pull width).
        """
        counts = np.asarray(self.pool["pend_count"])  # [K]
        self.last_match_counts = counts
        if counts.sum() == 0:
            if int(np.asarray(self.pool["pend_pos"]).max()) > 0:
                self.pool = self._drain_pend(self.pool)  # reclaim hole pages
            return None
        max_nodes = int(np.asarray(self.pool["node_count"]).max())
        full_b = self.pool["node_event"].shape[0]
        full_m = self.pool["pend"].shape[0]
        Bb = 1
        while Bb < max(max_nodes, 1):
            Bb <<= 1
        Bb = min(Bb, full_b)
        # The paged ring is mostly holes (-1): compact valid ids to a
        # per-key prefix on-device (one stable sort) so the D2H transfer
        # is pow2(max per-key count) wide, not pend_pos wide -- the pull
        # rides a ~100 MB/s tunnel, so bytes are the cost (PERF.md).
        if self._compact_pend_fn is None:
            self._compact_pend_fn = jax.jit(
                lambda p: jnp.take_along_axis(
                    p, jnp.argsort(p < 0, axis=0, stable=True), axis=0
                )
            )
        compacted = self._compact_pend_fn(self.pool["pend"])
        Mb = 1
        while Mb < max(int(counts.max()), 1):
            Mb <<= 1
        Mb = min(Mb, full_m)
        raw = {
            "counts": counts,
            "pend": np.asarray(compacted[:Mb]).T,                    # [K, Mb]
            "node_event": np.asarray(self.pool["node_event"][:Bb]).T,  # [K, Bb]
            "node_name": np.asarray(self.pool["node_name"][:Bb]).T,
            "node_pred": np.asarray(self.pool["node_pred"][:Bb]).T,
        }
        self.pool = self._drain_pend(self.pool)
        return raw

    def _decode_raw(self, raw: Dict[str, np.ndarray]) -> Dict[Any, List[Sequence]]:
        """Materialize a pulled snapshot into per-key Sequence lists."""
        pend = raw["pend"]
        node_event = raw["node_event"]
        node_name = raw["node_name"]
        node_pred = raw["node_pred"]
        K, B = node_event.shape

        # Flatten per-key pools into one index space so every chain across
        # every key walks in the same vectorized pass.
        key_base = (np.arange(K, dtype=np.int64) * B)[:, None]
        flat_pred = np.where(node_pred >= 0, node_pred + key_base, -1).reshape(-1)
        flat_event = node_event.reshape(-1)
        flat_name = node_name.reshape(-1)

        # Vectorized starts: row-major nonzero keeps per-key emission order.
        # GC-nulled entries (region overflow remapped the id to -1;
        # node_drops counts them) survive as -1 after compaction and decode
        # to dead chains.
        counts = np.asarray(raw["counts"], np.int64)
        jmask = np.arange(pend.shape[1])[None, :] < counts[:, None]
        ks, js = np.nonzero(jmask)
        vals = pend[ks, js].astype(np.int64)
        starts = np.where(vals >= 0, vals + ks * B, -1)
        match_key = ks
        chains = decode_chains(
            np.asarray(starts, np.int64), flat_name, flat_event, flat_pred
        )
        out: Dict[Any, List[Sequence]] = {}
        for k_idx, chain in zip(match_key, chains):
            if not chain:
                continue  # GC-dropped under overflow (node_drops counts it)
            key = self.keys[k_idx]
            out.setdefault(key, []).append(
                materialize_sequence(chain, self.query.name_of_id, self._events)
            )
        return out

    def _prune_events(self) -> None:
        """Bound the host event registry: keep pool-referenced events plus
        anything packed ahead of the processed watermark (pipelined ingest
        registers events before their batch is advanced)."""
        if len(self._events) <= self.events_prune_threshold:
            return
        live = np.asarray(self.pool["node_event"])
        live_gidx = set(int(g) for g in live[live >= 0])
        hwm = self._processed_gidx
        self._events = {
            g: e for g, e in self._events.items() if g > hwm or g in live_gidx
        }


