"""Compiled NFA graph: stages and edges.

Re-design of the reference compiled-automaton model
(reference: core/.../cep/nfa/Stage.java:40-252, Stages.java:33-72,
EdgeOperation.java:20-46). A compiled query is an ordered list of stages;
each stage has typed edges (BEGIN/TAKE/PROCEED/SKIP_PROCEED/IGNORE) carrying
a predicate and a target stage. The device compiler (ops/tables.py) packs
this graph into fixed transition tables.
"""
from __future__ import annotations

import enum
from typing import List, Optional, Set

from .aggregator import StateAggregator
from .matcher import Predicate, TruePredicate


class EdgeOperation(enum.Enum):
    """Edge kinds (EdgeOperation.java:20-46)."""

    BEGIN = "begin"            # forward transition, consumes the event
    TAKE = "take"              # self loop, consumes the event
    PROCEED = "proceed"        # epsilon forward transition
    SKIP_PROCEED = "skip_proceed"  # epsilon forward for optional stages
    IGNORE = "ignore"          # self loop, does not consume


class StateType(enum.Enum):
    BEGIN = "begin"
    NORMAL = "normal"
    FINAL = "final"


class Edge:
    __slots__ = ("operation", "predicate", "target")

    def __init__(self, operation: EdgeOperation, predicate: Predicate, target: Optional["Stage"]) -> None:
        if predicate is None:
            raise ValueError("predicate cannot be None")
        self.operation = operation
        self.predicate = predicate
        self.target = target

    def is_op(self, op: EdgeOperation) -> bool:
        return self.operation == op

    def __repr__(self) -> str:
        tgt = self.target.name if self.target is not None else None
        return f"Edge({self.operation.name} -> {tgt})"


class Stage:
    """One compiled NFA state: id, name, type, window, folds, edge list."""

    def __init__(self, stage_id: int, name: str, state_type: StateType) -> None:
        self.id = stage_id
        self.name = name
        self.type = state_type
        self.window_ms: int = -1
        self.aggregates: List[StateAggregator] = []
        self.edges: List[Edge] = []

    def add_edge(self, edge: Edge) -> "Stage":
        self.edges.append(edge)
        return self

    @property
    def is_begin(self) -> bool:
        return self.type == StateType.BEGIN

    @property
    def is_final(self) -> bool:
        return self.type == StateType.FINAL

    def is_epsilon(self) -> bool:
        return len(self.edges) == 1 and self.edges[0].operation == EdgeOperation.PROCEED

    def get_target(self, op: EdgeOperation) -> Optional["Stage"]:
        target = None
        for edge in self.edges:
            if edge.operation == op:
                target = edge.target
        return target

    def __repr__(self) -> str:
        return f"Stage(id={self.id}, name={self.name!r}, type={self.type.name}, edges={self.edges})"

    @staticmethod
    def new_epsilon(current: "Stage", target: "Stage") -> "Stage":
        """A runtime forwarding state: current's identity, one PROCEED->target.

        Mirrors Stage.newEpsilonState (Stage.java:247-251); the device engine
        removes the need for these synthesized objects by storing
        (eval-stage, prev-stage, pending-version-extension) per run lane.
        """
        eps = Stage(current.id, current.name, current.type)
        eps.add_edge(Edge(EdgeOperation.PROCEED, TruePredicate(), target))
        return eps


class Stages:
    """The compiled stage list for one query (Stages.java:33-72)."""

    def __init__(self, stages: List[Stage]) -> None:
        self.stages = stages

    def begin_stage(self) -> Stage:
        for stage in self.stages:
            if stage.is_begin:
                return stage
        raise ValueError("compiled query has no begin stage")

    def defined_states(self) -> Set[str]:
        names: Set[str] = set()
        for stage in self.stages:
            for aggregate in stage.aggregates:
                names.add(aggregate.name)
        return names

    def __iter__(self):
        return iter(self.stages)

    def __len__(self) -> int:
        return len(self.stages)
